#!/usr/bin/env python3
"""Validate semmerge observability artifacts against the documented
schema (runbook.md, "Observability").

Checks a ``.semmerge-trace.json`` trace artifact, (optionally) a
``.semmerge-events.jsonl`` span/event stream, and (optionally) a BENCH
JSON record emitted by ``bench.py``. Run standalone::

    python scripts/check_trace_schema.py .semmerge-trace.json \
        [.semmerge-events.jsonl] [--bench BENCH_JSON]

Subcommand modes for the request-tracing artifacts::

    python scripts/check_trace_schema.py validate_postmortem \
        .semmerge-postmortem/<trace_id>.json [...]
    python scripts/check_trace_schema.py validate_request_traces \
        TRACE_JSON TRACE_JSON [...]
    python scripts/check_trace_schema.py validate_slo \
        STATUS_OR_TRACE_JSON [...]
    python scripts/check_trace_schema.py validate_conflicts \
        .semmerge-conflicts.json [...]
    python scripts/check_trace_schema.py validate_fleet \
        STATUS_OR_TRACE_JSON [...]
    python scripts/check_trace_schema.py validate_transport \
        STATUS_OR_TRACE_JSON [...]
    python scripts/check_trace_schema.py validate_fleet_trace \
        SEMMERGE_FLEET_TRACE_DIR/<trace_id>.json [...]
    python scripts/check_trace_schema.py validate_export \
        OTLP_PAYLOAD_JSON [...]
    python scripts/check_trace_schema.py validate_sampling \
        STATUS_OR_KEPT_TRACE_JSON [...]
    python scripts/check_trace_schema.py validate_window \
        STATUS_JSON [...]
    python scripts/check_trace_schema.py validate_triage \
        .semmerge-postmortem/<trace_id>.json [...]

Exit 0 when everything conforms, 1 with one line per violation
otherwise. The tier-1 suite imports :func:`validate_trace` /
:func:`validate_events` / :func:`validate_bench` / :func:`validate_batch`
/ :func:`validate_request_traces` / :func:`validate_postmortem` /
:func:`validate_slo` / :func:`validate_conflicts` /
:func:`validate_fleet` / :func:`validate_transport` /
:func:`validate_fleet_trace` / :func:`validate_export` /
:func:`validate_sampling` / :func:`validate_window` /
:func:`validate_triage` directly (``tests/test_trace_schema.py``), so
trace-format drift fails CI before it reaches a consumer.

Dependency-free on purpose: the schema IS this file plus the runbook
table, not a jsonschema document that could drift separately.
"""
from __future__ import annotations

import json
import sys
from typing import Any, List

SPAN_STATUS = ("ok", "error")

#: Required keys of the trace artifact (``Tracer.to_dict``).
TRACE_REQUIRED = ("schema", "phases", "counters", "total_seconds", "device")

#: Required keys of one span row (trace ``spans[]`` / events ``type: span``).
SPAN_REQUIRED = ("name", "t_start", "seconds", "depth", "span_id",
                 "parent_id", "thread", "status", "meta")

#: Required keys of the ``device`` telemetry block.
DEVICE_REQUIRED = ("jax_imported", "platform", "device_count",
                   "transfer_bytes", "transfer_count")

#: Span names of the apply layer (runtime/applier.py). ``apply_ops``
#: wraps every apply; ``apply_columnar`` is the columnar dispatch walk;
#: ``apply_plan`` is the bench's tree-less consumption of the same
#: columns. A CLI ``--trace`` of a fused merge must contain the first
#: two — renaming them is schema drift (tests pin this).
APPLY_PHASE_SPANS = ("apply_ops", "apply_columnar", "apply_plan")

#: Meta keys every ``degradation`` span must carry (the ladder record:
#: which rung failed, which rung the merge moved to, and the fault).
DEGRADATION_META = ("from", "to", "fault", "stage")

#: Label keys of the fault-containment metric series (cli.py ladder /
#: backends/subproc.py supervision). Series of these names carrying
#: other label sets are schema drift.
FAULT_METRIC_LABELS = {
    "merge_degradations_total": ("fault", "from", "to"),
    "merge_faults_total": ("fault", "stage"),
    "subprocess_retries_total": ("method",),
    "subprocess_deadline_kills_total": ("method",),
    "resolutions_total": ("category", "outcome"),
}

#: Meta keys every ``service.*`` span must carry (which verb the
#: request was for — the daemon's per-request span contract).
SERVICE_SPAN_META = ("verb",)

#: Span names of the service layer (service/daemon.py request path).
SERVICE_SPANS = ("service.accept", "service.queue_wait", "service.execute")

#: Label keys of the service-layer metric series. Series of these
#: names carrying other label sets are schema drift.
SERVICE_METRIC_LABELS = {
    "service_requests_total": ("outcome", "verb"),
    "declcache_hits_total": (),
    "declcache_misses_total": (),
    "declcache_evictions_total": (),
}

#: Span names every co-batched merge records, mesh or not: the window
#: span is leader-side; pack/dispatch/scatter wrap one batched fused
#: dispatch each.
BATCH_CORE_SPANS = ("batch.window", "batch.pack", "batch.dispatch",
                    "batch.scatter")

#: All known batch-layer span names. ``batch.mesh_build`` records the
#: dispatch-mesh planning choice and only fires when a mesh forms
#: (posture ``auto``/``require`` on a multi-chip host).
BATCH_SPANS = BATCH_CORE_SPANS + ("batch.mesh_build",)

#: Meta keys every ``batch.*`` span must carry (how many valid requests
#: the window/round held).
BATCH_SPAN_META = ("requests",)

#: Mesh meta of the sharded dispatch path: required on
#: ``batch.mesh_build``, validated-when-present on ``batch.dispatch``
#: (the single-device program carries neither).
MESH_SPAN_META = ("mesh_shape", "rows_per_chip")

#: Label keys of the batching metric series. ``batch_requests_total``
#: is the per-request outcome counter; ``batch_size`` is a plain
#: histogram; ``batch_padding_waste_ratio`` and
#: ``batch_mesh_occupancy_ratio`` plain gauges in [0, 1].
BATCH_METRIC_LABELS = {
    "batch_requests_total": ("outcome",),
    "batch_mesh_fallbacks_total": ("reason",),
}

#: Documented ``batch_mesh_fallbacks_total`` reasons
#: (batch/dispatcher.py): 1-chip host, mesh construction failure,
#: mesh program dispatch failure, injected/real ``batch:mesh``
#: request-side fault.
BATCH_MESH_FALLBACK_REASONS = ("single-device", "build-error",
                               "dispatch-error", "fault")

#: Label keys of the resilience-layer metric series (admission control
#: and load shedding in service/daemon.py, circuit breakers in
#: service/resilience.py, supervised restart in service/supervisor.py
#: and backends/subproc.py, bounded program caches in ops/fused.py).
#: Series of these names carrying other label sets are schema drift.
RESILIENCE_METRIC_LABELS = {
    "service_shed_total": ("reason",),
    "breaker_transitions_total": ("rung", "to"),
    "subprocess_respawns_total": ("reason",),
    "supervisor_restarts_total": ("reason",),
    "program_cache_evictions_total": ("cache",),
    "service_idempotent_replays_total": (),
}

#: Documented load-shed reasons (runbook, "Overload & self-healing").
#: Queue-full is deliberately NOT a shed reason: it keeps its own
#: ``service_requests_total{outcome="rejected"}`` accounting.
#: ``draining`` is the fleet-era admission close: a member told to
#: drain sheds new work with a retryable rejection while finishing
#: its in-flight requests.
SHED_REASONS = ("rss-hard", "rss-soft", "projected-deadline", "draining")

#: Circuit-breaker states as published in the ``breaker_state`` gauge.
BREAKER_STATES = (0, 1, 2)  # closed / open / half-open

#: Breaker transition targets (``breaker_transitions_total{to=…}``).
BREAKER_TARGETS = ("closed", "open", "half-open")

#: Label keys of the device-render / snapshot-residency metric series
#: (ops/render.py, service/residency.py). Series of these names
#: carrying other label sets are schema drift.
RENDER_METRIC_LABELS = {
    "snapshot_residency_hits_total": ("outcome",),
    "snapshot_residency_evictions_total": ("reason",),
}

#: Documented residency lookup outcomes (service/residency.py):
#: a validated hit, a cold miss, and the three invalidation classes
#: (repo GC'd the tree, fleet-failover epoch bump, interner replaced).
RESIDENCY_OUTCOMES = ("hit", "miss", "stale-tree", "stale-epoch",
                      "stale-interner")

#: Documented residency eviction reasons: LRU byte-budget pressure,
#: the daemon's RSS hard watermark, an explicit clear, and lookup-time
#: invalidation of a stale entry.
RESIDENCY_EVICTION_REASONS = ("lru", "rss-hard", "clear", "stale")

#: Required keys of a postmortem bundle (``obs/flight.py`` dump).
POSTMORTEM_REQUIRED = ("schema", "trace_id", "reason", "ts", "spans",
                       "fault", "fault_chain", "breakers", "metrics", "env")

#: Documented postmortem dump reasons (``obs/flight.py`` REASONS).
POSTMORTEM_REASONS = ("fault-escape", "degradation", "breaker-transition",
                      "supervisor-restart", "daemon-drain", "slo-burn",
                      "resolver-fault", "fleet-failover", "anomaly")

#: Required keys of one flight-ring row (``obs/flight.py`` note()).
FLIGHT_ROW_REQUIRED = ("name", "t", "seconds", "layer", "status", "error",
                       "trace_id", "thread", "meta")

#: Required keys of a BENCH JSON record (the driver contract).
BENCH_REQUIRED = ("metric", "value", "unit", "vs_baseline")

#: Additive BENCH fields that must be numbers when present (the
#: host-tail, strict-preset, incremental, roundtrip, and batched-serve
#: extensions).
BENCH_NUMERIC_OPTIONAL = (
    "host_tail_ms", "device_roundtrip_ms", "incremental_ms",
    "full_scan_device_ms", "full_scan_host_ms", "vs_full_scan_device",
    "strict_ms", "nonstrict_ms", "strict_conflicts", "strict_motion_ops",
    "cold_ms", "warm_ms", "warm_speedup", "declcache_hit_rate",
    "daemon_rss_mb",
    "serial_merges_per_sec", "batch_merges_per_sec_c4",
    "batch_merges_per_sec_c16", "batch_speedup_c16",
    "batch_p50_ms", "batch_p99_ms", "mean_batch_size",
    "batch_padding_waste_ratio", "batch_program_cache_hit_rate",
    "overload_shed_rate", "overload_p99_ms", "baseline_p99_ms",
    "breaker_open_latency_ms", "breaker_recovery_s", "steady_rss_mb",
    "trace_overhead_pct", "trace_dark_ms", "trace_on_ms",
    "slo_overhead_pct", "slo_dark_ms", "slo_on_ms",
    "telemetry_overhead_pct", "telemetry_dark_ms", "telemetry_on_ms",
    "telemetry_soak_bytes", "telemetry_soak_budget_bytes",
    "telemetry_soak_protected_pct", "telemetry_triage_fired",
    "resolution_rate", "resolve_on_ms", "resolve_off_ms",
    "gate_recompose_ms", "gate_parity_ms", "gate_typecheck_ms",
    "gate_format_ms",
    "chips", "mesh_merges_per_sec_c16", "merges_per_sec_per_chip",
    "scaling_efficiency", "mesh_p50_ms", "mesh_p99_ms",
    "fleet_merges_per_sec_m1", "fleet_merges_per_sec_m2",
    "fleet_merges_per_sec_m3", "fleet_failover_recovery_s",
    "fleet_rehash_miss_rate", "fleet_hedge_win_rate",
    "fleet_trace_overhead_pct", "fleet_trace_dark_ms",
    "fleet_trace_on_ms",
    "host_tail_cold_ms", "host_tail_resident_ms", "resident_merge_ms",
    "residency_hit_rate", "residency_entries", "d2h_bytes",
)

#: Versions of the structured ``.semmerge-conflicts.json`` object form.
#: The legacy bare array (tier never ran) is implicitly version 1.
CONFLICTS_SCHEMA_VERSIONS = (2,)

#: Required keys of one conflict record (``core/conflict.py``).
CONFLICT_REQUIRED = ("id", "category", "symbolId", "addressIds",
                     "opA", "opB", "minimalSlice", "suggestions")

#: Terminal statuses of one resolution audit record
#: (``resolve/engine.py``).
RESOLUTION_STATUSES = ("accepted", "rejected")

#: Required keys of one resolution audit record.
RESOLUTION_REQUIRED = ("conflict_id", "category", "resolver", "status",
                       "cause", "candidate", "candidates", "scores",
                       "gates")

#: Verify gates of the resolution tier, in documented run order
#: (``resolve/engine.py`` GATES).
RESOLUTION_GATES = ("recompose", "parity", "typecheck", "format")

#: Span names of the fleet router layer (``fleet/router.py``).
#: ``fleet.route`` wraps one successfully dispatched request;
#: ``fleet.failover`` records one member ejection/dispatch transfer;
#: ``fleet.hedge`` records each hedge-race leg's outcome (won/lost);
#: ``fleet.wal_fsync`` the pre-dispatch journal fsync;
#: ``fleet.relay`` one member round-trip leg;
#: ``fleet.hedge_wait`` the p99-derived delay before a hedge launch;
#: ``fleet.join`` one remote member admitted via the join handshake;
#: ``fleet.handoff`` one rehashed repo key prewarmed onto its new
#: owner; ``fleet.heartbeat`` a transport heartbeat edge (recorded on
#: probe failures and on the recovery after them, not every probe).
FLEET_SPANS = ("fleet.route", "fleet.failover", "fleet.hedge",
               "fleet.wal_fsync", "fleet.relay", "fleet.hedge_wait",
               "fleet.join", "fleet.handoff", "fleet.heartbeat")

#: Required meta keys per fleet span name.
FLEET_SPAN_META = {
    "fleet.route": ("verb", "member"),
    "fleet.failover": ("reason", "member"),
    "fleet.hedge": ("member", "won"),
    "fleet.wal_fsync": (),
    "fleet.relay": ("member",),
    "fleet.hedge_wait": (),
    "fleet.join": ("member", "address", "capacity"),
    "fleet.handoff": ("member", "reason", "ok"),
    "fleet.heartbeat": ("member", "outcome"),
}

#: Documented ``fleet.relay`` outcomes: the leg answered first
#: (``ok``), answered after another leg had already won (``late``), or
#: died transport-style (``transport``).
FLEET_RELAY_OUTCOMES = ("ok", "late", "transport")

#: Documented ``fleet_failovers_total`` / ``fleet.failover`` reasons:
#: supervisor reaped the child (``crash``), a dispatch hit a dead
#: socket (``transport``), the heartbeat probe failed repeatedly
#: (``health``), the member was told to drain (``drain``), heartbeats
#: read-timed-out against a half-open connection (``partition``), a
#: remote member deliberately left the fleet (``leave``).
FLEET_FAILOVER_REASONS = ("crash", "transport", "health", "drain",
                          "partition", "leave")

#: Label keys of the fleet metric series (``fleet/router.py``). The
#: ``fleet_members`` gauge is the live ring size (unlabeled, >= 0);
#: everything else is an event counter.
FLEET_METRIC_LABELS = {
    "fleet_failovers_total": ("reason",),
    "fleet_rehash_moves_total": (),
    "fleet_hedges_total": (),
    "fleet_hedge_wins_total": (),
    "fleet_wal_replayed_total": (),
    "fleet_scrape_errors_total": ("member",),
    "fleet_trace_dropped_total": (),
}

#: Label keys of the cross-host transport metric series
#: (``fleet/transport.py`` + the router's membership counters).
TRANSPORT_METRIC_LABELS = {
    "fleet_transport_errors_total": ("op",),
    "fleet_transport_resends_total": (),
    "fleet_heartbeats_total": ("outcome",),
    "fleet_handoffs_total": ("reason",),
    "fleet_affinity_misses_total": (),
    "fleet_joins_total": (),
}

#: Documented ``fleet_transport_errors_total`` op label values
#: (``fleet/transport.py`` OPS).
TRANSPORT_OPS = ("dial", "read", "control", "heartbeat")

#: Documented ``fleet_heartbeats_total`` / ``fleet.heartbeat`` outcome
#: values (``fleet/transport.py`` HEARTBEAT_OUTCOMES).
TRANSPORT_HEARTBEAT_OUTCOMES = ("ok", "connect", "timeout", "error")

#: Documented ``fleet_handoffs_total`` / ``fleet.handoff`` reasons —
#: the ring change that moved the keys being prewarmed.
TRANSPORT_HANDOFF_REASONS = ("join", "leave", "crash", "transport",
                             "health", "partition", "drain")

#: Documented WAL record kinds (``fleet/wal.py``).
FLEET_WAL_KINDS = ("request", "dispatch", "ack")

#: Required keys per WAL record kind.
FLEET_WAL_REQUIRED = {
    "request": ("kind", "key", "verb", "params", "trace_id", "t"),
    "dispatch": ("kind", "key", "member", "t"),
    "ack": ("kind", "key", "t"),
}

#: Label keys of the SLO-engine metric series (``obs/slo.py``). The
#: burn gauge carries exactly (objective, window) with window in
#: SLO_WINDOWS; the trip counter exactly (objective,).
SLO_METRIC_LABELS = {
    "slo_burn_rate": ("objective", "window"),
    "slo_burn_trips_total": ("objective",),
}

#: Documented burn-rate windows (multi-window alerting: fast ~5 min,
#: slow ~1 h).
SLO_WINDOWS = ("fast", "slow")

#: Documented tail-sampling keep reasons (``obs/sampling.py``
#: KEEP_REASONS): outcome keeps (error/degraded/breaker/resolver),
#: latency keep (slow = at-or-over the rolling per-verb p99), the
#: deterministic 1-in-N head sample, and the sampling-disabled
#: keep-everything verdict.
SAMPLING_KEEP_REASONS = ("error", "degraded", "breaker", "resolver",
                         "slow", "head", "always")

#: The single documented drop reason.
SAMPLING_DROP_REASON = "sampled-out"

#: ``trace_sampling_decisions_total{decision=…}`` values.
SAMPLING_DECISIONS = ("keep", "drop")

#: Label keys of the telemetry-pipeline metric series
#: (``obs/sampling.py`` verdict/prune counters, ``obs/flight.py``
#: bounded retention, ``obs/metrics.py`` cardinality budget).
SAMPLING_METRIC_LABELS = {
    "trace_sampling_decisions_total": ("decision", "reason"),
    "trace_store_pruned_total": ("store",),
    "postmortem_pruned_total": ("dir",),
    "metrics_series_dropped_total": ("metric",),
}

#: Rollup windows of the streaming aggregator (``obs/agg.py``).
WINDOW_KEYS = ("1s", "1m")

#: Required keys of one window rollup block.
WINDOW_REQUIRED = ("span_s", "count", "errors", "qps", "error_rate",
                   "p50_ms", "p99_ms", "max_ms", "phases_ms", "verbs")

#: Window gauges published into the registry (labels exactly
#: ``("window",)`` with a documented window value).
WINDOW_GAUGES = ("semmerge_window_qps", "semmerge_window_p50_ms",
                 "semmerge_window_p99_ms", "semmerge_window_error_rate")

#: Required keys of a triage block (``obs/anomaly.py`` _capture) inside
#: an ``anomaly`` postmortem bundle.
TRIAGE_REQUIRED = ("schema", "phase", "suspect_phase", "z",
                   "threshold_z", "sustain", "offender", "baseline",
                   "diff", "ts")

#: Required keys of the triage ``offender`` / non-null ``baseline``.
TRIAGE_SIDE_REQUIRED = ("trace_id", "verb", "seconds", "phases_ms")

#: Required keys of one phase-diff row (``obs/anomaly.py``
#: phase_diff — also the ``semmerge trace diff`` row shape).
TRIAGE_DIFF_ROW_REQUIRED = ("phase", "a_ms", "b_ms", "delta_ms",
                            "ratio")


def _is_num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_span(row: dict, where: str) -> List[str]:
    errors = []
    for key in SPAN_REQUIRED:
        if key not in row:
            errors.append(f"{where}: span missing key {key!r}")
    if not isinstance(row.get("name"), str) or not row.get("name"):
        errors.append(f"{where}: span name must be a non-empty string")
    layer = row.get("layer")
    if layer is not None and not isinstance(layer, str):
        errors.append(f"{where}: span layer must be a string or null")
    for key in ("t_start", "seconds"):
        if key in row and (not _is_num(row[key]) or row[key] < 0):
            errors.append(f"{where}: span {key} must be a number >= 0")
    for key in ("depth", "span_id", "parent_id"):
        if key in row and not isinstance(row[key], int):
            errors.append(f"{where}: span {key} must be an int")
    if row.get("depth", 0) < 0:
        errors.append(f"{where}: span depth must be >= 0")
    if "status" in row and row["status"] not in SPAN_STATUS:
        errors.append(f"{where}: span status {row['status']!r} not in "
                      f"{SPAN_STATUS}")
    if "meta" in row and not isinstance(row["meta"], dict):
        errors.append(f"{where}: span meta must be an object")
    return errors


def validate_metrics(data: Any, where: str = "metrics") -> List[str]:
    errors: List[str] = []
    if not isinstance(data, dict):
        return [f"{where}: must be an object"]
    for kind in ("counters", "gauges"):
        for name, m in data.get(kind, {}).items():
            for i, s in enumerate(m.get("series", [])):
                if not isinstance(s.get("labels"), dict):
                    errors.append(f"{where}.{kind}.{name}[{i}]: labels must "
                                  f"be an object")
                if not _is_num(s.get("value")):
                    errors.append(f"{where}.{kind}.{name}[{i}]: value must "
                                  f"be a number")
    for name, m in data.get("histograms", {}).items():
        buckets = m.get("buckets")
        if (not isinstance(buckets, list) or not buckets
                or sorted(buckets) != buckets):
            errors.append(f"{where}.histograms.{name}: buckets must be a "
                          f"sorted non-empty array")
            continue
        for i, s in enumerate(m.get("series", [])):
            counts = s.get("counts")
            if not isinstance(counts, list) or len(counts) != len(buckets) + 1:
                errors.append(f"{where}.histograms.{name}[{i}]: counts must "
                              f"have len(buckets)+1 entries")
            elif sum(counts) != s.get("count"):
                errors.append(f"{where}.histograms.{name}[{i}]: counts do "
                              f"not sum to count")
            if "exemplar" in s:
                errors.append(f"{where}.histograms.{name}[{i}]: per-series "
                              f"'exemplar' is the pre-OpenMetrics shape; "
                              f"use per-bucket 'exemplars'")
            ex = s.get("exemplars")
            if ex is None:
                continue
            if not isinstance(ex, dict):
                errors.append(f"{where}.histograms.{name}[{i}]: exemplars "
                              f"must be an object keyed by bucket index")
                continue
            for key, e in ex.items():
                w = f"{where}.histograms.{name}[{i}].exemplars[{key!r}]"
                try:
                    idx = int(key)
                except (TypeError, ValueError):
                    errors.append(f"{w}: key must be a stringified "
                                  f"bucket index")
                    continue
                if not 0 <= idx <= len(buckets):
                    errors.append(f"{w}: bucket index out of range "
                                  f"0..{len(buckets)}")
                if not isinstance(e, dict):
                    errors.append(f"{w}: must be an object")
                    continue
                tid = e.get("trace_id")
                if not isinstance(tid, str) or not tid:
                    errors.append(f"{w}: trace_id must be a non-empty "
                                  f"string")
                if not _is_num(e.get("value")):
                    errors.append(f"{w}: value must be a number")
    return errors


def validate_trace(data: Any) -> List[str]:
    errors: List[str] = []
    if not isinstance(data, dict):
        return ["trace: top level must be a JSON object"]
    for key in TRACE_REQUIRED:
        if key not in data:
            errors.append(f"trace: missing key {key!r}")
    if "schema" in data and data["schema"] != 1:
        errors.append(f"trace: unknown schema version {data['schema']!r}")
    phases = data.get("phases", [])
    if not isinstance(phases, list):
        errors.append("trace: phases must be an array")
        phases = []
    for i, p in enumerate(phases):
        if not isinstance(p, dict) or not isinstance(p.get("name"), str):
            errors.append(f"trace: phases[{i}] needs a string name")
            continue
        if not _is_num(p.get("seconds")) or p["seconds"] < 0:
            errors.append(f"trace: phases[{i}] seconds must be a number >= 0")
        if "meta" in p and not isinstance(p["meta"], dict):
            errors.append(f"trace: phases[{i}] meta must be an object")
    if not isinstance(data.get("counters", {}), dict):
        errors.append("trace: counters must be an object")
    if "total_seconds" in data and not _is_num(data["total_seconds"]):
        errors.append("trace: total_seconds must be a number")
    device = data.get("device")
    if device is not None:
        if not isinstance(device, dict):
            errors.append("trace: device must be an object")
        else:
            for key in DEVICE_REQUIRED:
                if key not in device:
                    errors.append(f"trace: device missing key {key!r}")
    for i, row in enumerate(data.get("spans", [])):
        errors.extend(validate_span(row, f"trace.spans[{i}]"))
    if "metrics" in data:
        errors.extend(validate_metrics(data["metrics"]))
    return errors


def validate_degradations(data: Any) -> List[str]:
    """Validate the fault-containment records of a trace artifact:
    every ``degradation`` span carries the full rung-transition meta
    (``from``/``to``/``fault``/``stage``), and the fault-layer metric
    series carry their documented label sets."""
    errors: List[str] = []
    if not isinstance(data, dict):
        return ["trace: top level must be a JSON object"]
    for i, row in enumerate(data.get("spans", [])):
        if not isinstance(row, dict) or row.get("name") != "degradation":
            continue
        meta = row.get("meta")
        if not isinstance(meta, dict):
            errors.append(f"trace.spans[{i}]: degradation span needs meta")
            continue
        for key in DEGRADATION_META:
            if not isinstance(meta.get(key), str) or not meta.get(key):
                errors.append(f"trace.spans[{i}]: degradation meta "
                              f"missing/empty {key!r}")
    metrics = data.get("metrics", data)
    counters = metrics.get("counters", {}) if isinstance(metrics, dict) else {}
    for name, labels in FAULT_METRIC_LABELS.items():
        m = counters.get(name)
        if not isinstance(m, dict):
            continue
        for j, s in enumerate(m.get("series", [])):
            got = tuple(sorted((s.get("labels") or {}).keys()))
            if got != tuple(sorted(labels)):
                errors.append(f"metrics.counters.{name}[{j}]: labels {got} "
                              f"!= documented {tuple(sorted(labels))}")
    return errors


def validate_service(data: Any) -> List[str]:
    """Validate the merge-service records of a trace/events-shaped
    artifact (or a daemon status payload's ``metrics`` block): every
    ``service.*`` span carries its per-request meta (``verb``), the
    service metric series carry their documented label sets, and
    ``service_queue_depth`` — when present — is a plain gauge."""
    errors: List[str] = []
    if not isinstance(data, dict):
        return ["service: top level must be a JSON object"]
    for i, row in enumerate(data.get("spans", [])):
        if not isinstance(row, dict):
            continue
        name = row.get("name")
        if not isinstance(name, str) or not name.startswith("service."):
            continue
        if name not in SERVICE_SPANS:
            errors.append(f"trace.spans[{i}]: unknown service span {name!r}")
        meta = row.get("meta")
        if not isinstance(meta, dict):
            errors.append(f"trace.spans[{i}]: service span needs meta")
            continue
        for key in SERVICE_SPAN_META:
            if not isinstance(meta.get(key), str) or not meta.get(key):
                errors.append(f"trace.spans[{i}]: service span meta "
                              f"missing/empty {key!r}")
    metrics = data.get("metrics", data)
    if not isinstance(metrics, dict):
        return errors
    counters = metrics.get("counters", {})
    for name, labels in SERVICE_METRIC_LABELS.items():
        m = counters.get(name) if isinstance(counters, dict) else None
        if not isinstance(m, dict):
            continue
        for j, s in enumerate(m.get("series", [])):
            got = tuple(sorted((s.get("labels") or {}).keys()))
            if got != tuple(sorted(labels)):
                errors.append(f"metrics.counters.{name}[{j}]: labels {got} "
                              f"!= documented {tuple(sorted(labels))}")
    gauges = metrics.get("gauges", {})
    depth = gauges.get("service_queue_depth") if isinstance(gauges, dict) \
        else None
    if isinstance(depth, dict):
        for j, s in enumerate(depth.get("series", [])):
            if (s.get("labels") or {}) != {}:
                errors.append(f"metrics.gauges.service_queue_depth[{j}]: "
                              f"must carry no labels")
            if not _is_num(s.get("value")) or s.get("value") < 0:
                errors.append(f"metrics.gauges.service_queue_depth[{j}]: "
                              f"value must be a number >= 0")
    return errors


def validate_batch(data: Any) -> List[str]:
    """Validate the continuous-batching records of a trace/events-shaped
    artifact (or a daemon status payload's ``metrics`` block): every
    ``batch.*`` span is a documented one and carries its ``requests``
    meta (mesh spans additionally ``mesh_shape``/``rows_per_chip``),
    ``batch_requests_total``/``batch_mesh_fallbacks_total`` series
    carry exactly their documented label (fallback reasons from the
    documented set), ``batch_size`` is an unlabeled histogram, and
    ``batch_padding_waste_ratio``/``batch_mesh_occupancy_ratio``
    unlabeled gauges in [0, 1]."""
    errors: List[str] = []
    if not isinstance(data, dict):
        return ["batch: top level must be a JSON object"]
    for i, row in enumerate(data.get("spans", [])):
        if not isinstance(row, dict):
            continue
        name = row.get("name")
        if not isinstance(name, str) or not name.startswith("batch."):
            continue
        if name not in BATCH_SPANS:
            errors.append(f"trace.spans[{i}]: unknown batch span {name!r}")
        meta = row.get("meta")
        if not isinstance(meta, dict):
            errors.append(f"trace.spans[{i}]: batch span needs meta")
            continue
        for key in BATCH_SPAN_META:
            v = meta.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errors.append(f"trace.spans[{i}]: batch span meta "
                              f"{key!r} must be an int >= 0")
        # Mesh meta: mandatory on mesh_build, optional-but-typed on
        # dispatch (absent entirely on single-device dispatches).
        check_mesh = name == "batch.mesh_build" or (
            name == "batch.dispatch"
            and any(k in meta for k in MESH_SPAN_META))
        if check_mesh:
            shape = meta.get("mesh_shape")
            if not isinstance(shape, str) or not shape:
                errors.append(f"trace.spans[{i}]: {name} meta "
                              f"'mesh_shape' must be a non-empty string")
            rows = meta.get("rows_per_chip")
            if not isinstance(rows, int) or isinstance(rows, bool) \
                    or rows < 1:
                errors.append(f"trace.spans[{i}]: {name} meta "
                              f"'rows_per_chip' must be an int >= 1")
    metrics = data.get("metrics", data)
    if not isinstance(metrics, dict):
        return errors
    counters = metrics.get("counters", {})
    for name, labels in BATCH_METRIC_LABELS.items():
        m = counters.get(name) if isinstance(counters, dict) else None
        if not isinstance(m, dict):
            continue
        for j, s in enumerate(m.get("series", [])):
            got = tuple(sorted((s.get("labels") or {}).keys()))
            if got != tuple(sorted(labels)):
                errors.append(f"metrics.counters.{name}[{j}]: labels {got} "
                              f"!= documented {tuple(sorted(labels))}")
            if name == "batch_mesh_fallbacks_total" and got == ("reason",):
                reason = (s.get("labels") or {}).get("reason")
                if reason not in BATCH_MESH_FALLBACK_REASONS:
                    errors.append(
                        f"metrics.counters.{name}[{j}]: reason {reason!r} "
                        f"not in documented {BATCH_MESH_FALLBACK_REASONS}")
    hists = metrics.get("histograms", {})
    size = hists.get("batch_size") if isinstance(hists, dict) else None
    if isinstance(size, dict):
        for j, s in enumerate(size.get("series", [])):
            if (s.get("labels") or {}) != {}:
                errors.append(f"metrics.histograms.batch_size[{j}]: "
                              f"must carry no labels")
    gauges = metrics.get("gauges", {})
    for gname in ("batch_padding_waste_ratio",
                  "batch_mesh_occupancy_ratio"):
        g = gauges.get(gname) if isinstance(gauges, dict) else None
        if not isinstance(g, dict):
            continue
        for j, s in enumerate(g.get("series", [])):
            if (s.get("labels") or {}) != {}:
                errors.append(
                    f"metrics.gauges.{gname}[{j}]: must carry no labels")
            v = s.get("value")
            if not _is_num(v) or not (0.0 <= v <= 1.0):
                errors.append(
                    f"metrics.gauges.{gname}[{j}]: "
                    f"value must be a number in [0, 1]")
    return errors


def validate_resilience(data: Any) -> List[str]:
    """Validate the overload/self-healing records of a trace/events-
    shaped artifact (or a daemon status payload's ``metrics`` block):
    the resilience metric series carry their documented label sets,
    ``service_shed_total`` reasons are documented ones, the
    ``breaker_state`` gauge carries exactly a ``rung`` label with a
    value in {0 closed, 1 open, 2 half-open}, ``service_rss_mb`` is an
    unlabeled non-negative gauge, and every ``supervisor.restart`` span
    carries its restart meta (``reason``/``attempt``/``rc``)."""
    errors: List[str] = []
    if not isinstance(data, dict):
        return ["resilience: top level must be a JSON object"]
    for i, row in enumerate(data.get("spans", [])):
        if not isinstance(row, dict) or row.get("name") != "supervisor.restart":
            continue
        meta = row.get("meta")
        if not isinstance(meta, dict):
            errors.append(f"trace.spans[{i}]: supervisor.restart span "
                          f"needs meta")
            continue
        if not isinstance(meta.get("reason"), str) or not meta.get("reason"):
            errors.append(f"trace.spans[{i}]: supervisor.restart meta "
                          f"missing/empty 'reason'")
        attempt = meta.get("attempt")
        if not isinstance(attempt, int) or isinstance(attempt, bool) \
                or attempt < 1:
            errors.append(f"trace.spans[{i}]: supervisor.restart meta "
                          f"'attempt' must be an int >= 1")
        if "rc" not in meta:
            errors.append(f"trace.spans[{i}]: supervisor.restart meta "
                          f"missing 'rc'")
    metrics = data.get("metrics", data)
    if not isinstance(metrics, dict):
        return errors
    counters = metrics.get("counters", {})
    if not isinstance(counters, dict):
        counters = {}
    for name, labels in RESILIENCE_METRIC_LABELS.items():
        m = counters.get(name)
        if not isinstance(m, dict):
            continue
        for j, s in enumerate(m.get("series", [])):
            got = tuple(sorted((s.get("labels") or {}).keys()))
            if got != tuple(sorted(labels)):
                errors.append(f"metrics.counters.{name}[{j}]: labels {got} "
                              f"!= documented {tuple(sorted(labels))}")
    shed = counters.get("service_shed_total")
    if isinstance(shed, dict):
        for j, s in enumerate(shed.get("series", [])):
            reason = (s.get("labels") or {}).get("reason")
            if reason not in SHED_REASONS:
                errors.append(f"metrics.counters.service_shed_total[{j}]: "
                              f"reason {reason!r} not in {SHED_REASONS}")
    trans = counters.get("breaker_transitions_total")
    if isinstance(trans, dict):
        for j, s in enumerate(trans.get("series", [])):
            to = (s.get("labels") or {}).get("to")
            if to not in BREAKER_TARGETS:
                errors.append(
                    f"metrics.counters.breaker_transitions_total[{j}]: "
                    f"to {to!r} not in {BREAKER_TARGETS}")
    gauges = metrics.get("gauges", {})
    if not isinstance(gauges, dict):
        gauges = {}
    state = gauges.get("breaker_state")
    if isinstance(state, dict):
        for j, s in enumerate(state.get("series", [])):
            got = tuple(sorted((s.get("labels") or {}).keys()))
            if got != ("rung",):
                errors.append(f"metrics.gauges.breaker_state[{j}]: labels "
                              f"{got} != ('rung',)")
            if s.get("value") not in BREAKER_STATES:
                errors.append(f"metrics.gauges.breaker_state[{j}]: value "
                              f"{s.get('value')!r} not in {BREAKER_STATES}")
    rss = gauges.get("service_rss_mb")
    if isinstance(rss, dict):
        for j, s in enumerate(rss.get("series", [])):
            if (s.get("labels") or {}) != {}:
                errors.append(f"metrics.gauges.service_rss_mb[{j}]: must "
                              f"carry no labels")
            if not _is_num(s.get("value")) or s.get("value") < 0:
                errors.append(f"metrics.gauges.service_rss_mb[{j}]: value "
                              f"must be a number >= 0")
    return errors


def validate_device_render(data: Any) -> List[str]:
    """Validate the device-render / residency records of a trace or
    events-shaped artifact (or a daemon status payload's ``metrics``
    block): every ``render.d2h`` span carries the ``ops`` layer and
    its transfer meta (``rows``/``width`` ints >= 0), every
    ``residency.hit`` / ``residency.encode_delta`` span carries the
    ``frontend`` layer and a non-empty ``repo`` meta, residency metric
    series carry their documented label sets with documented
    ``outcome``/``reason`` values, and ``snapshot_residency_bytes`` is
    an unlabeled non-negative gauge."""
    errors: List[str] = []
    if not isinstance(data, dict):
        return ["device_render: top level must be a JSON object"]
    for i, row in enumerate(data.get("spans", [])):
        if not isinstance(row, dict):
            continue
        name = row.get("name")
        where = f"trace.spans[{i}]"
        if name == "render.d2h":
            if row.get("layer") != "ops":
                errors.append(f"{where}: render.d2h span layer must be "
                              f"'ops'")
            meta = row.get("meta")
            if not isinstance(meta, dict):
                errors.append(f"{where}: render.d2h span needs meta")
                continue
            for key in ("rows", "width"):
                v = meta.get(key)
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    errors.append(f"{where}: render.d2h meta {key!r} must "
                                  f"be an int >= 0")
        elif name in ("residency.hit", "residency.encode_delta"):
            if row.get("layer") != "frontend":
                errors.append(f"{where}: {name} span layer must be "
                              f"'frontend'")
            meta = row.get("meta")
            if not isinstance(meta, dict) \
                    or not isinstance(meta.get("repo"), str) \
                    or not meta.get("repo"):
                errors.append(f"{where}: {name} span needs a non-empty "
                              f"'repo' meta")
    metrics = data.get("metrics", data)
    if not isinstance(metrics, dict):
        return errors
    counters = metrics.get("counters", {})
    if not isinstance(counters, dict):
        counters = {}
    for name, labels in RENDER_METRIC_LABELS.items():
        m = counters.get(name)
        if not isinstance(m, dict):
            continue
        for j, s in enumerate(m.get("series", [])):
            got = tuple(sorted((s.get("labels") or {}).keys()))
            if got != tuple(sorted(labels)):
                errors.append(f"metrics.counters.{name}[{j}]: labels {got} "
                              f"!= documented {tuple(sorted(labels))}")
    hits = counters.get("snapshot_residency_hits_total")
    if isinstance(hits, dict):
        for j, s in enumerate(hits.get("series", [])):
            outcome = (s.get("labels") or {}).get("outcome")
            if outcome not in RESIDENCY_OUTCOMES:
                errors.append(
                    f"metrics.counters.snapshot_residency_hits_total[{j}]: "
                    f"outcome {outcome!r} not in {RESIDENCY_OUTCOMES}")
    evs = counters.get("snapshot_residency_evictions_total")
    if isinstance(evs, dict):
        for j, s in enumerate(evs.get("series", [])):
            reason = (s.get("labels") or {}).get("reason")
            if reason not in RESIDENCY_EVICTION_REASONS:
                errors.append(
                    f"metrics.counters."
                    f"snapshot_residency_evictions_total[{j}]: reason "
                    f"{reason!r} not in {RESIDENCY_EVICTION_REASONS}")
    gauges = metrics.get("gauges", {})
    if not isinstance(gauges, dict):
        gauges = {}
    res_bytes = gauges.get("snapshot_residency_bytes")
    if isinstance(res_bytes, dict):
        for j, s in enumerate(res_bytes.get("series", [])):
            if (s.get("labels") or {}) != {}:
                errors.append(f"metrics.gauges.snapshot_residency_bytes"
                              f"[{j}]: must carry no labels")
            if not _is_num(s.get("value")) or s.get("value") < 0:
                errors.append(f"metrics.gauges.snapshot_residency_bytes"
                              f"[{j}]: value must be a number >= 0")
    return errors


def validate_slo(data: Any) -> List[str]:
    """Validate the SLO-engine records of a trace/events-shaped artifact
    (or a daemon status payload's ``metrics`` block): ``slo_burn_rate``
    series carry exactly the ``objective``/``window`` labels with a
    documented window and a non-negative value, ``slo_burn_trips_total``
    series exactly the ``objective`` label, and — when a daemon-status
    ``slo`` block is present — its objectives carry non-negative burn
    rates and sample counts."""
    errors: List[str] = []
    if not isinstance(data, dict):
        return ["slo: top level must be a JSON object"]
    metrics = data.get("metrics", data)
    if isinstance(metrics, dict):
        gauges = metrics.get("gauges", {})
        burn = gauges.get("slo_burn_rate") if isinstance(gauges, dict) \
            else None
        if isinstance(burn, dict):
            for j, s in enumerate(burn.get("series", [])):
                labels = s.get("labels") or {}
                got = tuple(sorted(labels.keys()))
                if got != tuple(sorted(SLO_METRIC_LABELS["slo_burn_rate"])):
                    errors.append(f"metrics.gauges.slo_burn_rate[{j}]: "
                                  f"labels {got} != documented "
                                  f"('objective', 'window')")
                elif labels.get("window") not in SLO_WINDOWS:
                    errors.append(f"metrics.gauges.slo_burn_rate[{j}]: "
                                  f"window {labels.get('window')!r} not in "
                                  f"{SLO_WINDOWS}")
                if not _is_num(s.get("value")) or s.get("value") < 0:
                    errors.append(f"metrics.gauges.slo_burn_rate[{j}]: "
                                  f"value must be a number >= 0")
        counters = metrics.get("counters", {})
        trips = counters.get("slo_burn_trips_total") \
            if isinstance(counters, dict) else None
        if isinstance(trips, dict):
            for j, s in enumerate(trips.get("series", [])):
                got = tuple(sorted((s.get("labels") or {}).keys()))
                if got != ("objective",):
                    errors.append(
                        f"metrics.counters.slo_burn_trips_total[{j}]: "
                        f"labels {got} != ('objective',)")
                if not _is_num(s.get("value")) or s.get("value") < 0:
                    errors.append(
                        f"metrics.counters.slo_burn_trips_total[{j}]: "
                        f"value must be a number >= 0")
    slo = data.get("slo")
    if slo is not None:
        if not isinstance(slo, dict):
            errors.append("slo: status block must be an object or null")
            return errors
        if not isinstance(slo.get("healthy"), bool):
            errors.append("slo: healthy must be a boolean")
        objectives = slo.get("objectives", [])
        if not isinstance(objectives, list):
            errors.append("slo: objectives must be an array")
            objectives = []
        for i, row in enumerate(objectives):
            where = f"slo.objectives[{i}]"
            if not isinstance(row, dict):
                errors.append(f"{where}: must be an object")
                continue
            if not isinstance(row.get("objective"), str) \
                    or not row.get("objective"):
                errors.append(f"{where}: objective must be a non-empty "
                              f"string")
            for key in ("burn_fast", "burn_slow"):
                if not _is_num(row.get(key)) or row.get(key) < 0:
                    errors.append(f"{where}: {key} must be a number >= 0")
            for key in ("samples_fast", "samples_slow"):
                v = row.get(key)
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    errors.append(f"{where}: {key} must be an int >= 0")
            if "tripped" in row and not isinstance(row["tripped"], bool):
                errors.append(f"{where}: tripped must be a boolean")
    return errors


def validate_fleet(data: Any) -> List[str]:
    """Validate the fleet-router records of a trace/events-shaped
    artifact (or a router status payload's ``metrics`` block), plus —
    when a ``wal`` array is present — the dispatch-journal records:
    every ``fleet.*`` span is a documented one carrying its meta
    (failover reasons from the documented set, ``fleet.hedge`` a
    boolean ``won``), the fleet metric series carry their documented
    label sets, ``fleet_members`` is an unlabeled non-negative gauge,
    and each WAL record has its kind's required keys."""
    errors: List[str] = []
    if not isinstance(data, dict):
        return ["fleet: top level must be a JSON object"]
    for i, row in enumerate(data.get("spans", [])):
        if not isinstance(row, dict):
            continue
        name = row.get("name")
        if not isinstance(name, str) or not name.startswith("fleet."):
            continue
        if name not in FLEET_SPANS:
            errors.append(f"trace.spans[{i}]: unknown fleet span {name!r}")
            continue
        meta = row.get("meta")
        if not isinstance(meta, dict):
            errors.append(f"trace.spans[{i}]: fleet span needs meta")
            continue
        for key in FLEET_SPAN_META[name]:
            if key not in meta:
                errors.append(f"trace.spans[{i}]: {name} meta missing "
                              f"{key!r}")
        member = meta.get("member")
        if "member" in meta and (not isinstance(member, str)
                                 or not member):
            errors.append(f"trace.spans[{i}]: {name} meta 'member' must "
                          f"be a non-empty string")
        if name == "fleet.failover":
            reason = meta.get("reason")
            if "reason" in meta and reason not in FLEET_FAILOVER_REASONS:
                errors.append(f"trace.spans[{i}]: fleet.failover reason "
                              f"{reason!r} not in "
                              f"{FLEET_FAILOVER_REASONS}")
        if name == "fleet.hedge" and "won" in meta:
            if not isinstance(meta["won"], bool):
                errors.append(f"trace.spans[{i}]: fleet.hedge meta 'won' "
                              f"must be a boolean")
            elif "outcome" in meta and meta["outcome"] != \
                    ("won" if meta["won"] else "lost"):
                errors.append(f"trace.spans[{i}]: fleet.hedge outcome "
                              f"{meta['outcome']!r} contradicts "
                              f"won={meta['won']}")
        if name == "fleet.relay" and "outcome" in meta \
                and meta["outcome"] not in FLEET_RELAY_OUTCOMES:
            errors.append(f"trace.spans[{i}]: fleet.relay outcome "
                          f"{meta['outcome']!r} not in "
                          f"{FLEET_RELAY_OUTCOMES}")
        if name == "fleet.route":
            verb = meta.get("verb")
            if "verb" in meta and (not isinstance(verb, str) or not verb):
                errors.append(f"trace.spans[{i}]: fleet.route meta "
                              f"'verb' must be a non-empty string")
    metrics = data.get("metrics", data)
    if isinstance(metrics, dict):
        counters = metrics.get("counters", {})
        if not isinstance(counters, dict):
            counters = {}
        for name, labels in FLEET_METRIC_LABELS.items():
            m = counters.get(name)
            if not isinstance(m, dict):
                continue
            for j, s in enumerate(m.get("series", [])):
                got = tuple(sorted((s.get("labels") or {}).keys()))
                if got != tuple(sorted(labels)):
                    errors.append(f"metrics.counters.{name}[{j}]: labels "
                                  f"{got} != documented "
                                  f"{tuple(sorted(labels))}")
        fo = counters.get("fleet_failovers_total")
        if isinstance(fo, dict):
            for j, s in enumerate(fo.get("series", [])):
                reason = (s.get("labels") or {}).get("reason")
                if reason not in FLEET_FAILOVER_REASONS:
                    errors.append(
                        f"metrics.counters.fleet_failovers_total[{j}]: "
                        f"reason {reason!r} not in "
                        f"{FLEET_FAILOVER_REASONS}")
        gauges = metrics.get("gauges", {})
        members = gauges.get("fleet_members") \
            if isinstance(gauges, dict) else None
        if isinstance(members, dict):
            for j, s in enumerate(members.get("series", [])):
                if (s.get("labels") or {}) != {}:
                    errors.append(f"metrics.gauges.fleet_members[{j}]: "
                                  f"must carry no labels")
                if not _is_num(s.get("value")) or s.get("value") < 0:
                    errors.append(f"metrics.gauges.fleet_members[{j}]: "
                                  f"value must be a number >= 0")
    wal = data.get("wal")
    if isinstance(wal, list):
        for i, rec in enumerate(wal):
            where = f"wal[{i}]"
            if not isinstance(rec, dict):
                errors.append(f"{where}: must be an object")
                continue
            kind = rec.get("kind")
            if kind not in FLEET_WAL_KINDS:
                errors.append(f"{where}: kind {kind!r} not in "
                              f"{FLEET_WAL_KINDS}")
                continue
            for key in FLEET_WAL_REQUIRED[kind]:
                if key not in rec:
                    errors.append(f"{where}: {kind} record missing "
                                  f"key {key!r}")
            if not isinstance(rec.get("key"), str) or not rec.get("key"):
                errors.append(f"{where}: key must be a non-empty string")
            if "t" in rec and (not _is_num(rec["t"]) or rec["t"] < 0):
                errors.append(f"{where}: t must be a number >= 0")
            if kind == "request" and not isinstance(rec.get("params"),
                                                   dict):
                errors.append(f"{where}: request params must be an object")
            if kind == "dispatch" and (
                    not isinstance(rec.get("member"), str)
                    or not rec.get("member")):
                errors.append(f"{where}: dispatch member must be a "
                              f"non-empty string")
    elif wal is not None:
        errors.append("fleet: wal must be an array of records")
    return errors


def validate_transport(data: Any) -> List[str]:
    """Validate the cross-host transport records of a trace/events or
    metrics-shaped artifact (``fleet/transport.py`` + the router's
    membership plane): the ``fleet.join`` / ``fleet.handoff`` /
    ``fleet.heartbeat`` spans carry their documented meta with values
    from the documented sets, the ``fleet_transport_*`` and membership
    counters carry their documented label sets (ops from
    ``TRANSPORT_OPS``, heartbeat outcomes from
    ``TRANSPORT_HEARTBEAT_OUTCOMES``, handoff reasons from
    ``TRANSPORT_HANDOFF_REASONS``), and ``fleet_member_draining`` is a
    member-labeled 0/1 gauge."""
    errors: List[str] = []
    if not isinstance(data, dict):
        return ["transport: top level must be a JSON object"]
    for i, row in enumerate(data.get("spans", [])):
        if not isinstance(row, dict):
            continue
        name = row.get("name")
        if name not in ("fleet.join", "fleet.handoff", "fleet.heartbeat"):
            continue
        meta = row.get("meta")
        if not isinstance(meta, dict):
            errors.append(f"trace.spans[{i}]: {name} needs meta")
            continue
        for key in FLEET_SPAN_META[name]:
            if key not in meta:
                errors.append(f"trace.spans[{i}]: {name} meta missing "
                              f"{key!r}")
        member = meta.get("member")
        if "member" in meta and (not isinstance(member, str)
                                 or not member):
            errors.append(f"trace.spans[{i}]: {name} meta 'member' must "
                          f"be a non-empty string")
        if name == "fleet.join":
            address = meta.get("address")
            if "address" in meta and (not isinstance(address, str)
                                      or not address):
                errors.append(f"trace.spans[{i}]: fleet.join meta "
                              f"'address' must be a non-empty string")
            capacity = meta.get("capacity")
            if "capacity" in meta and (
                    not isinstance(capacity, int)
                    or isinstance(capacity, bool) or capacity < 1):
                errors.append(f"trace.spans[{i}]: fleet.join meta "
                              f"'capacity' must be an int >= 1")
        if name == "fleet.handoff":
            reason = meta.get("reason")
            if "reason" in meta and reason not in \
                    TRANSPORT_HANDOFF_REASONS:
                errors.append(f"trace.spans[{i}]: fleet.handoff reason "
                              f"{reason!r} not in "
                              f"{TRANSPORT_HANDOFF_REASONS}")
            if "ok" in meta and not isinstance(meta["ok"], bool):
                errors.append(f"trace.spans[{i}]: fleet.handoff meta "
                              f"'ok' must be a boolean")
        if name == "fleet.heartbeat":
            outcome = meta.get("outcome")
            if "outcome" in meta and outcome not in \
                    TRANSPORT_HEARTBEAT_OUTCOMES:
                errors.append(f"trace.spans[{i}]: fleet.heartbeat outcome "
                              f"{outcome!r} not in "
                              f"{TRANSPORT_HEARTBEAT_OUTCOMES}")
    metrics = data.get("metrics", data)
    if isinstance(metrics, dict):
        counters = metrics.get("counters", {})
        if not isinstance(counters, dict):
            counters = {}
        for name, labels in TRANSPORT_METRIC_LABELS.items():
            m = counters.get(name)
            if not isinstance(m, dict):
                continue
            for j, s in enumerate(m.get("series", [])):
                got = tuple(sorted((s.get("labels") or {}).keys()))
                if got != tuple(sorted(labels)):
                    errors.append(f"metrics.counters.{name}[{j}]: labels "
                                  f"{got} != documented "
                                  f"{tuple(sorted(labels))}")
        label_values = (
            ("fleet_transport_errors_total", "op", TRANSPORT_OPS),
            ("fleet_heartbeats_total", "outcome",
             TRANSPORT_HEARTBEAT_OUTCOMES),
            ("fleet_handoffs_total", "reason",
             TRANSPORT_HANDOFF_REASONS),
        )
        for name, label, allowed in label_values:
            m = counters.get(name)
            if not isinstance(m, dict):
                continue
            for j, s in enumerate(m.get("series", [])):
                value = (s.get("labels") or {}).get(label)
                if value not in allowed:
                    errors.append(f"metrics.counters.{name}[{j}]: "
                                  f"{label} {value!r} not in {allowed}")
        gauges = metrics.get("gauges", {})
        draining = gauges.get("fleet_member_draining") \
            if isinstance(gauges, dict) else None
        if isinstance(draining, dict):
            for j, s in enumerate(draining.get("series", [])):
                got = tuple(sorted((s.get("labels") or {}).keys()))
                if got != ("member",):
                    errors.append(
                        f"metrics.gauges.fleet_member_draining[{j}]: "
                        f"labels {got} != ('member',)")
                if s.get("value") not in (0, 0.0, 1, 1.0):
                    errors.append(
                        f"metrics.gauges.fleet_member_draining[{j}]: "
                        f"value must be 0 or 1")
    return errors


def validate_fleet_trace(data: Any) -> List[str]:
    """Validate one *stitched* fleet-trace artifact
    (``SEMMERGE_FLEET_TRACE_DIR/<trace_id>.json``): span rows conform,
    the tree carries at least one router-layer ``fleet.*`` span AND at
    least one grafted member span, and every grafted span (anything not
    on the router's ``fleet`` layer) is stamped with the graft meta —
    ``member`` id and an ``attempt`` int >= 1 — so failover retries and
    hedge legs stay attributable after the graft."""
    errors: List[str] = []
    if not isinstance(data, dict):
        return ["fleet-trace: top level must be a JSON object"]
    if data.get("schema") != 1:
        errors.append(f"fleet-trace: unknown schema version "
                      f"{data.get('schema')!r}")
    tid = data.get("trace_id")
    if not isinstance(tid, str) or not tid:
        errors.append("fleet-trace: trace_id must be a non-empty string")
    spans = data.get("spans")
    if not isinstance(spans, list) or not spans:
        errors.append("fleet-trace: spans must be a non-empty array")
        return errors
    fleet_seen = grafted_seen = False
    for i, row in enumerate(spans):
        where = f"fleet-trace.spans[{i}]"
        if not isinstance(row, dict):
            errors.append(f"{where}: must be an object")
            continue
        errors.extend(validate_span(row, where))
        name = row.get("name")
        meta = row.get("meta") if isinstance(row.get("meta"), dict) else {}
        if isinstance(name, str) and name.startswith("fleet."):
            fleet_seen = True
            continue
        if row.get("layer") == "fleet":
            continue
        grafted_seen = True
        member = meta.get("member")
        if not isinstance(member, str) or not member:
            errors.append(f"{where}: grafted span {name!r} missing "
                          f"graft meta 'member'")
        attempt = meta.get("attempt")
        if not isinstance(attempt, int) or isinstance(attempt, bool) \
                or attempt < 1:
            errors.append(f"{where}: grafted span {name!r} needs graft "
                          f"meta 'attempt' (int >= 1)")
    if not fleet_seen:
        errors.append("fleet-trace: no fleet.* router span in the tree")
    if not grafted_seen:
        errors.append("fleet-trace: no grafted member span in the tree")
    errors.extend(validate_fleet(data))
    return errors


def _hex_id(v: Any, width: int) -> bool:
    return isinstance(v, str) and len(v) == width and \
        all(c in "0123456789abcdef" for c in v)


def _unix_nano(v: Any) -> Any:
    """OTLP JSON encodes uint64 nanos as strings (ints tolerated);
    returns the int value or None when malformed."""
    if isinstance(v, str) and v.isdigit():
        return int(v)
    if isinstance(v, int) and not isinstance(v, bool) and v >= 0:
        return v
    return None


def validate_export(data: Any) -> List[str]:
    """Validate an OTLP JSON export payload (``obs/export.py``): an
    ``ExportTraceServiceRequest`` (``resourceSpans`` → ``scopeSpans`` →
    spans with 32-hex traceId, 16-hex spanId, uint64 nano timestamps
    with end >= start, attribute key/value lists) or an
    ``ExportMetricsServiceRequest`` (``resourceMetrics`` with exactly
    one of sum/gauge/histogram per metric)."""
    errors: List[str] = []
    if not isinstance(data, dict):
        return ["export: top level must be a JSON object"]
    has_spans = "resourceSpans" in data
    has_metrics = "resourceMetrics" in data
    if not has_spans and not has_metrics:
        return ["export: need resourceSpans or resourceMetrics"]
    if has_spans:
        rss = data["resourceSpans"]
        if not isinstance(rss, list) or not rss:
            return ["export: resourceSpans must be a non-empty array"]
        for ri, rs in enumerate(rss):
            where = f"export.resourceSpans[{ri}]"
            if not isinstance(rs, dict):
                errors.append(f"{where}: must be an object")
                continue
            sss = rs.get("scopeSpans")
            if not isinstance(sss, list) or not sss:
                errors.append(f"{where}: scopeSpans must be a non-empty "
                              f"array")
                continue
            for si, ss in enumerate(sss):
                spans = ss.get("spans") if isinstance(ss, dict) else None
                if not isinstance(spans, list):
                    errors.append(f"{where}.scopeSpans[{si}]: spans must "
                                  f"be an array")
                    continue
                for pi, span in enumerate(spans):
                    w = f"{where}.scopeSpans[{si}].spans[{pi}]"
                    if not isinstance(span, dict):
                        errors.append(f"{w}: must be an object")
                        continue
                    if not _hex_id(span.get("traceId"), 32):
                        errors.append(f"{w}: traceId must be 32 lowercase "
                                      f"hex chars")
                    if not _hex_id(span.get("spanId"), 16):
                        errors.append(f"{w}: spanId must be 16 lowercase "
                                      f"hex chars")
                    if "parentSpanId" in span and \
                            not _hex_id(span["parentSpanId"], 16):
                        errors.append(f"{w}: parentSpanId must be 16 "
                                      f"lowercase hex chars")
                    if not isinstance(span.get("name"), str) \
                            or not span.get("name"):
                        errors.append(f"{w}: name must be a non-empty "
                                      f"string")
                    start = _unix_nano(span.get("startTimeUnixNano"))
                    end = _unix_nano(span.get("endTimeUnixNano"))
                    if start is None:
                        errors.append(f"{w}: startTimeUnixNano must be "
                                      f"uint64 nanos (string or int)")
                    if end is None:
                        errors.append(f"{w}: endTimeUnixNano must be "
                                      f"uint64 nanos (string or int)")
                    if start is not None and end is not None \
                            and end < start:
                        errors.append(f"{w}: endTimeUnixNano < "
                                      f"startTimeUnixNano")
                    attrs = span.get("attributes", [])
                    if not isinstance(attrs, list):
                        errors.append(f"{w}: attributes must be an array")
                        attrs = []
                    for ai, attr in enumerate(attrs):
                        if not isinstance(attr, dict) \
                                or not isinstance(attr.get("key"), str) \
                                or not isinstance(attr.get("value"), dict):
                            errors.append(f"{w}.attributes[{ai}]: must be "
                                          f"{{key, value}} objects")
                    status = span.get("status")
                    if status is not None and (
                            not isinstance(status, dict) or
                            not isinstance(status.get("code"), int)):
                        errors.append(f"{w}: status must carry an int code")
    if has_metrics:
        rms = data["resourceMetrics"]
        if not isinstance(rms, list) or not rms:
            return errors + ["export: resourceMetrics must be a non-empty "
                             "array"]
        for ri, rm in enumerate(rms):
            where = f"export.resourceMetrics[{ri}]"
            if not isinstance(rm, dict):
                errors.append(f"{where}: must be an object")
                continue
            sms = rm.get("scopeMetrics")
            if not isinstance(sms, list) or not sms:
                errors.append(f"{where}: scopeMetrics must be a non-empty "
                              f"array")
                continue
            for si, sm in enumerate(sms):
                mlist = sm.get("metrics") if isinstance(sm, dict) else None
                if not isinstance(mlist, list):
                    errors.append(f"{where}.scopeMetrics[{si}]: metrics "
                                  f"must be an array")
                    continue
                for mi, m in enumerate(mlist):
                    w = f"{where}.scopeMetrics[{si}].metrics[{mi}]"
                    if not isinstance(m, dict):
                        errors.append(f"{w}: must be an object")
                        continue
                    if not isinstance(m.get("name"), str) \
                            or not m.get("name"):
                        errors.append(f"{w}: name must be a non-empty "
                                      f"string")
                    kinds = [k for k in ("sum", "gauge", "histogram")
                             if k in m]
                    if len(kinds) != 1:
                        errors.append(f"{w}: need exactly one of "
                                      f"sum/gauge/histogram, got {kinds}")
                        continue
                    points = m[kinds[0]].get("dataPoints") \
                        if isinstance(m[kinds[0]], dict) else None
                    if not isinstance(points, list):
                        errors.append(f"{w}.{kinds[0]}: dataPoints must "
                                      f"be an array")
                        continue
                    for pi, p in enumerate(points):
                        if not isinstance(p, dict):
                            errors.append(f"{w}.{kinds[0]}.dataPoints"
                                          f"[{pi}]: must be an object")
                            continue
                        if _unix_nano(p.get("timeUnixNano")) is None:
                            errors.append(f"{w}.{kinds[0]}.dataPoints"
                                          f"[{pi}]: timeUnixNano must be "
                                          f"uint64 nanos")
                        if kinds[0] == "histogram":
                            bc = p.get("bucketCounts")
                            eb = p.get("explicitBounds")
                            if not isinstance(bc, list) \
                                    or not isinstance(eb, list) \
                                    or len(bc) != len(eb) + 1:
                                errors.append(
                                    f"{w}.histogram.dataPoints[{pi}]: "
                                    f"bucketCounts must have "
                                    f"len(explicitBounds)+1 entries")
    return errors


def validate_phase_coverage(data: Any, required) -> List[str]:
    """Check a trace artifact's span/phase names include ``required`` —
    the drift guard for load-bearing phase names (e.g. the apply-layer
    spans BENCH and the runbook reference by name)."""
    if not isinstance(data, dict):
        return ["trace: top level must be a JSON object"]
    names = {row.get("name") for row in data.get("spans", [])
             if isinstance(row, dict)}
    names.update(p.get("name") for p in data.get("phases", [])
                 if isinstance(p, dict))
    return [f"trace: expected span/phase {r!r} not present"
            for r in required if r not in names]


def validate_request_traces(traces: Any) -> List[str]:
    """Validate a set of per-request trace artifacts for span isolation:
    each is a conforming trace carrying a non-empty ``trace_id``, no two
    share an id, and no span inside one trace is stamped with another
    request's ``trace_id`` — the concurrent-daemon-merges contract."""
    errors: List[str] = []
    if not isinstance(traces, list) or not traces:
        return ["request-traces: need a non-empty array of trace artifacts"]
    seen: dict = {}
    for i, data in enumerate(traces):
        where = f"request-traces[{i}]"
        if not isinstance(data, dict):
            errors.append(f"{where}: must be a JSON object")
            continue
        errors.extend(f"{where}: {e}" for e in validate_trace(data))
        tid = data.get("trace_id")
        if not isinstance(tid, str) or not tid:
            errors.append(f"{where}: trace_id must be a non-empty string")
            continue
        if tid in seen:
            errors.append(f"{where}: trace_id {tid!r} duplicates "
                          f"request-traces[{seen[tid]}] — requests must "
                          f"not share ids")
        else:
            seen[tid] = i
        for j, row in enumerate(data.get("spans", [])):
            if not isinstance(row, dict):
                continue
            meta = row.get("meta")
            row_tid = meta.get("trace_id") if isinstance(meta, dict) else None
            if row_tid is not None and row_tid != tid:
                errors.append(f"{where}.spans[{j}]: span stamped with "
                              f"foreign trace_id {row_tid!r} (own {tid!r}) "
                              f"— request traces interleaved")
    return errors


def validate_postmortem(data: Any) -> List[str]:
    """Validate one postmortem bundle (``.semmerge-postmortem/<id>.json``,
    written by ``obs/flight.py``): required keys, a documented reason, a
    non-empty ``trace_id``, conforming flight-ring rows, a string fault
    chain, breaker states by name, and a conforming metrics block."""
    errors: List[str] = []
    if not isinstance(data, dict):
        return ["postmortem: top level must be a JSON object"]
    for key in POSTMORTEM_REQUIRED:
        if key not in data:
            errors.append(f"postmortem: missing key {key!r}")
    if "schema" in data and data["schema"] != 1:
        errors.append(f"postmortem: unknown schema version "
                      f"{data['schema']!r}")
    tid = data.get("trace_id")
    if not isinstance(tid, str) or not tid:
        errors.append("postmortem: trace_id must be a non-empty string")
    if "reason" in data and data["reason"] not in POSTMORTEM_REASONS:
        errors.append(f"postmortem: reason {data.get('reason')!r} not in "
                      f"{POSTMORTEM_REASONS}")
    if "ts" in data and (not _is_num(data["ts"]) or data["ts"] < 0):
        errors.append("postmortem: ts must be a number >= 0")
    spans = data.get("spans", [])
    if not isinstance(spans, list):
        errors.append("postmortem: spans must be an array")
        spans = []
    for i, row in enumerate(spans):
        where = f"postmortem.spans[{i}]"
        if not isinstance(row, dict):
            errors.append(f"{where}: must be an object")
            continue
        for key in FLIGHT_ROW_REQUIRED:
            if key not in row:
                errors.append(f"{where}: missing key {key!r}")
        if not isinstance(row.get("name"), str) or not row.get("name"):
            errors.append(f"{where}: name must be a non-empty string")
        for key in ("t", "seconds"):
            if key in row and (not _is_num(row[key]) or row[key] < 0):
                errors.append(f"{where}: {key} must be a number >= 0")
        if "status" in row and row["status"] not in SPAN_STATUS:
            errors.append(f"{where}: status {row['status']!r} not in "
                          f"{SPAN_STATUS}")
        for key in ("layer", "error", "trace_id"):
            v = row.get(key)
            if v is not None and not isinstance(v, str):
                errors.append(f"{where}: {key} must be a string or null")
        if row.get("meta") is not None and not isinstance(row["meta"], dict):
            errors.append(f"{where}: meta must be an object or null")
    fault = data.get("fault")
    if fault is not None:
        if not isinstance(fault, dict):
            errors.append("postmortem: fault must be an object or null")
        else:
            for key in ("type", "message", "stage", "exit_code"):
                if key not in fault:
                    errors.append(f"postmortem: fault missing key {key!r}")
    chain = data.get("fault_chain")
    if chain is not None:
        if not isinstance(chain, list) or any(
                not isinstance(c, str) for c in chain):
            errors.append("postmortem: fault_chain must be an array of "
                          "strings")
    brk = data.get("breakers")
    if brk is not None:
        if not isinstance(brk, dict):
            errors.append("postmortem: breakers must be an object or null")
        else:
            for rung, state in brk.items():
                if state not in BREAKER_TARGETS:
                    errors.append(f"postmortem: breakers[{rung!r}] state "
                                  f"{state!r} not in {BREAKER_TARGETS}")
    if "metrics" in data:
        errors.extend(validate_metrics(data["metrics"],
                                       where="postmortem.metrics"))
    env = data.get("env")
    if env is not None:
        if not isinstance(env, dict):
            errors.append("postmortem: env must be an object")
        else:
            if not isinstance(env.get("pid"), int):
                errors.append("postmortem: env.pid must be an int")
            if not isinstance(env.get("env"), dict):
                errors.append("postmortem: env.env must be an object")
    return errors


def _validate_conflict_rows(rows: Any, where: str) -> List[str]:
    errors: List[str] = []
    if not isinstance(rows, list):
        return [f"{where}: must be an array"]
    for i, row in enumerate(rows):
        if not isinstance(row, dict):
            errors.append(f"{where}[{i}]: must be an object")
            continue
        for key in CONFLICT_REQUIRED:
            if key not in row:
                errors.append(f"{where}[{i}]: missing key {key!r}")
        for key in ("id", "category", "symbolId"):
            if key in row and (not isinstance(row[key], str)
                               or not row[key]):
                errors.append(f"{where}[{i}]: {key} must be a non-empty "
                              f"string")
        if "suggestions" in row and not isinstance(row["suggestions"], list):
            errors.append(f"{where}[{i}]: suggestions must be an array")
    return errors


def validate_conflicts(data: Any) -> List[str]:
    """Validate one ``.semmerge-conflicts.json`` artifact. Two shapes
    are legal: the legacy bare array of conflict records (implicitly
    schema version 1 — emitted whenever the resolution tier did not
    run, byte-identical to the reference), and the versioned object
    form ``{"schema_version", "conflicts", "resolutions"}`` the tier
    emits, whose ``resolutions`` audit rows carry a documented status,
    per-candidate scores, and gate rows in documented order."""
    if isinstance(data, list):
        return _validate_conflict_rows(data, "conflicts")
    errors: List[str] = []
    if not isinstance(data, dict):
        return ["conflicts: top level must be an array or object"]
    if data.get("schema_version") not in CONFLICTS_SCHEMA_VERSIONS:
        errors.append(f"conflicts: unknown schema_version "
                      f"{data.get('schema_version')!r}")
    errors.extend(_validate_conflict_rows(data.get("conflicts"),
                                          "conflicts.conflicts"))
    resolutions = data.get("resolutions")
    if not isinstance(resolutions, list):
        errors.append("conflicts: resolutions must be an array")
        resolutions = []
    for i, row in enumerate(resolutions):
        where = f"conflicts.resolutions[{i}]"
        if not isinstance(row, dict):
            errors.append(f"{where}: must be an object")
            continue
        for key in RESOLUTION_REQUIRED:
            if key not in row:
                errors.append(f"{where}: missing key {key!r}")
        status = row.get("status")
        if "status" in row and status not in RESOLUTION_STATUSES:
            errors.append(f"{where}: status {status!r} not in "
                          f"{RESOLUTION_STATUSES}")
        cause = row.get("cause")
        if status == "accepted" and cause is not None:
            errors.append(f"{where}: accepted record must carry a null "
                          f"cause (got {cause!r})")
        if status == "rejected" and (not isinstance(cause, str)
                                     or not cause):
            errors.append(f"{where}: rejected record needs a non-empty "
                          f"string cause")
        n = row.get("candidates")
        if "candidates" in row and (not isinstance(n, int)
                                    or isinstance(n, bool) or n < 0):
            errors.append(f"{where}: candidates must be an int >= 0")
        scores = row.get("scores")
        if "scores" in row:
            if not isinstance(scores, dict):
                errors.append(f"{where}: scores must be an object")
            else:
                for cid, v in scores.items():
                    if not _is_num(v):
                        errors.append(f"{where}: scores[{cid!r}] must be "
                                      f"a number")
        gates = row.get("gates")
        if not isinstance(gates, list):
            errors.append(f"{where}: gates must be an array")
            gates = []
        order = [g.get("gate") for g in gates if isinstance(g, dict)]
        if order != [g for g in RESOLUTION_GATES if g in order]:
            errors.append(f"{where}: gates out of documented order "
                          f"{RESOLUTION_GATES}")
        for j, g in enumerate(gates):
            gw = f"{where}.gates[{j}]"
            if not isinstance(g, dict):
                errors.append(f"{gw}: must be an object")
                continue
            if g.get("gate") not in RESOLUTION_GATES:
                errors.append(f"{gw}: gate {g.get('gate')!r} not in "
                              f"{RESOLUTION_GATES}")
            if not isinstance(g.get("ok"), bool):
                errors.append(f"{gw}: ok must be a boolean")
            if not _is_num(g.get("ms")) or g.get("ms") < 0:
                errors.append(f"{gw}: ms must be a number >= 0")
            if "detail" in g and not isinstance(g["detail"], str):
                errors.append(f"{gw}: detail must be a string")
    return errors


def validate_bench(data: Any) -> List[str]:
    """Validate one BENCH JSON record (``bench.py``'s single output
    line). Required driver fields plus the additive extensions:
    ``phases_ms``/``host_phases_ms`` maps of non-negative numbers,
    boolean ``parity``, the ``overlap`` block, and the numeric
    host-tail/strict/incremental fields."""
    errors: List[str] = []
    if not isinstance(data, dict):
        return ["bench: record must be a JSON object"]
    for key in BENCH_REQUIRED:
        if key not in data:
            errors.append(f"bench: missing key {key!r}")
    for key in ("metric", "unit"):
        if key in data and not isinstance(data[key], str):
            errors.append(f"bench: {key} must be a string")
    for key in ("value", "vs_baseline"):
        if key in data and not _is_num(data[key]):
            errors.append(f"bench: {key} must be a number")
    for key in ("phases_ms", "host_phases_ms", "phases_cold_ms"):
        block = data.get(key)
        if block is None:
            continue
        if not isinstance(block, dict):
            errors.append(f"bench: {key} must be an object")
            continue
        for name, v in block.items():
            if not _is_num(v) or v < 0:
                errors.append(f"bench: {key}.{name} must be a number >= 0")
    if "parity" in data and not isinstance(data["parity"], bool):
        errors.append("bench: parity must be a boolean")
    if "error" in data and not isinstance(data["error"], str):
        errors.append("bench: error must be a string")
    overlap = data.get("overlap")
    if overlap is not None:
        if not isinstance(overlap, dict):
            errors.append("bench: overlap must be an object")
        else:
            for key in ("host_workers", "worker_ms", "hidden_ms"):
                if not _is_num(overlap.get(key)):
                    errors.append(f"bench: overlap.{key} must be a number")
    for key in BENCH_NUMERIC_OPTIONAL:
        if key in data and not _is_num(data[key]):
            errors.append(f"bench: {key} must be a number")
    return errors


def validate_events(lines: List[str]) -> List[str]:
    errors: List[str] = []
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        where = f"events line {i + 1}"
        try:
            row = json.loads(line)
        except json.JSONDecodeError as exc:
            errors.append(f"{where}: not valid JSON ({exc})")
            continue
        if not isinstance(row, dict):
            errors.append(f"{where}: must be a JSON object")
            continue
        kind = row.get("type")
        if kind == "span":
            errors.extend(validate_span(row, where))
        elif kind == "event":
            if not isinstance(row.get("name"), str):
                errors.append(f"{where}: event needs a string name")
            if not _is_num(row.get("t_start")):
                errors.append(f"{where}: event t_start must be a number")
            if not isinstance(row.get("fields", {}), dict):
                errors.append(f"{where}: event fields must be an object")
        else:
            errors.append(f"{where}: type must be 'span' or 'event', "
                          f"got {kind!r}")
    return errors


def validate_sampling(data: Any) -> List[str]:
    """Validate the tail-sampling records of a status payload or a
    kept trace artifact: an embedded ``sampling`` verdict (Decision
    meta — kept artifacts only ever carry ``keep: true`` with a
    documented keep reason and a non-empty ``minted_by``), a policy
    ``sampling`` stats block (documented decision reasons with
    non-negative counts), a ``trace_store`` stats block (non-negative
    count/bytes, bytes within ``budget_bytes`` when one is set), and
    the telemetry-pipeline counters carrying their documented label
    sets (``decision`` from the documented pair, keep reasons vs the
    one drop reason cross-checked)."""
    errors: List[str] = []
    if not isinstance(data, dict):
        return ["sampling: top level must be a JSON object"]
    block = data.get("sampling")
    if isinstance(block, dict) and "keep" in block:
        # Decision meta embedded in a kept artifact.
        if block.get("keep") is not True:
            errors.append("sampling: a persisted artifact must carry "
                          "keep=true (drops are never written)")
        reason = block.get("reason")
        if reason not in SAMPLING_KEEP_REASONS:
            errors.append(f"sampling: kept reason {reason!r} not in "
                          f"{SAMPLING_KEEP_REASONS}")
        minted = block.get("minted_by")
        if not isinstance(minted, str) or not minted:
            errors.append("sampling: minted_by must be a non-empty "
                          "string")
        n = block.get("sample_n")
        if n is not None and (not isinstance(n, int)
                              or isinstance(n, bool) or n < 0):
            errors.append("sampling: sample_n must be an int >= 0 or "
                          "null")
    elif isinstance(block, dict) and "enabled" in block:
        # SamplingPolicy.stats() in a status payload.
        if not isinstance(block.get("enabled"), bool):
            errors.append("sampling: enabled must be a boolean")
        n = block.get("sample_n")
        if n is not None and (not isinstance(n, int)
                              or isinstance(n, bool) or n < 1):
            errors.append("sampling: sample_n must be an int >= 1 or "
                          "null")
        decisions = block.get("decisions")
        if not isinstance(decisions, dict):
            errors.append("sampling: decisions must be an object")
            decisions = {}
        allowed = SAMPLING_KEEP_REASONS + (SAMPLING_DROP_REASON,)
        for reason, count in decisions.items():
            if reason not in allowed:
                errors.append(f"sampling: decision reason {reason!r} "
                              f"not in {allowed}")
            if not isinstance(count, int) or isinstance(count, bool) \
                    or count < 0:
                errors.append(f"sampling: decisions[{reason!r}] must "
                              f"be an int >= 0")
        p99 = block.get("p99_ms")
        if p99 is not None:
            if not isinstance(p99, dict):
                errors.append("sampling: p99_ms must be an object")
            else:
                for verb, v in p99.items():
                    if not _is_num(v) or v < 0:
                        errors.append(f"sampling: p99_ms[{verb!r}] "
                                      f"must be a number >= 0")
    elif block is not None:
        errors.append("sampling: block must be a Decision meta or a "
                      "policy stats object")
    store = data.get("trace_store")
    if store is not None:
        if not isinstance(store, dict):
            errors.append("sampling: trace_store must be an object or "
                          "null")
        else:
            for key in ("count", "bytes"):
                v = store.get(key)
                if not isinstance(v, int) or isinstance(v, bool) \
                        or v < 0:
                    errors.append(f"sampling: trace_store.{key} must "
                                  f"be an int >= 0")
            budget = store.get("budget_bytes")
            if budget is not None and (not _is_num(budget)
                                       or budget <= 0):
                errors.append("sampling: trace_store.budget_bytes "
                              "must be a number > 0 or null")
            if _is_num(budget) and isinstance(store.get("bytes"), int) \
                    and store["bytes"] > budget:
                errors.append(f"sampling: trace_store over budget "
                              f"({store['bytes']} > {budget} bytes)")
            mc = store.get("max_count")
            if mc is not None and (not isinstance(mc, int)
                                   or isinstance(mc, bool) or mc < 1):
                errors.append("sampling: trace_store.max_count must "
                              "be an int >= 1 or null")
    metrics = data.get("metrics", data)
    if not isinstance(metrics, dict):
        return errors
    counters = metrics.get("counters", {})
    if not isinstance(counters, dict):
        counters = {}
    for name, labels in SAMPLING_METRIC_LABELS.items():
        m = counters.get(name)
        if not isinstance(m, dict):
            continue
        for j, s in enumerate(m.get("series", [])):
            got = tuple(sorted((s.get("labels") or {}).keys()))
            if got != tuple(sorted(labels)):
                errors.append(f"metrics.counters.{name}[{j}]: labels "
                              f"{got} != documented "
                              f"{tuple(sorted(labels))}")
    verdicts = counters.get("trace_sampling_decisions_total")
    if isinstance(verdicts, dict):
        for j, s in enumerate(verdicts.get("series", [])):
            labels = s.get("labels") or {}
            decision = labels.get("decision")
            reason = labels.get("reason")
            w = f"metrics.counters.trace_sampling_decisions_total[{j}]"
            if decision not in SAMPLING_DECISIONS:
                errors.append(f"{w}: decision {decision!r} not in "
                              f"{SAMPLING_DECISIONS}")
            elif decision == "keep" and reason not in \
                    SAMPLING_KEEP_REASONS:
                errors.append(f"{w}: keep reason {reason!r} not in "
                              f"{SAMPLING_KEEP_REASONS}")
            elif decision == "drop" and reason != SAMPLING_DROP_REASON:
                errors.append(f"{w}: drop reason {reason!r} != "
                              f"{SAMPLING_DROP_REASON!r}")
    return errors


def _validate_window_block(win: Any, where: str) -> List[str]:
    errors: List[str] = []
    if not isinstance(win, dict):
        return [f"{where}: must be an object"]
    for key in WINDOW_REQUIRED:
        if key not in win:
            errors.append(f"{where}: missing key {key!r}")
    for key in ("span_s", "qps", "error_rate", "p50_ms", "p99_ms",
                "max_ms"):
        if key in win and (not _is_num(win[key]) or win[key] < 0):
            errors.append(f"{where}: {key} must be a number >= 0")
    for key in ("count", "errors"):
        v = win.get(key)
        if key in win and (not isinstance(v, int)
                           or isinstance(v, bool) or v < 0):
            errors.append(f"{where}: {key} must be an int >= 0")
    if isinstance(win.get("errors"), int) \
            and isinstance(win.get("count"), int) \
            and win["errors"] > win["count"]:
        errors.append(f"{where}: errors > count")
    if _is_num(win.get("p50_ms")) and _is_num(win.get("p99_ms")) \
            and win["p50_ms"] > win["p99_ms"]:
        errors.append(f"{where}: p50_ms > p99_ms")
    for key in ("phases_ms", "verbs"):
        block = win.get(key)
        if key not in win or block is None:
            continue
        if not isinstance(block, dict):
            errors.append(f"{where}: {key} must be an object")
            continue
        for name, v in block.items():
            if key == "phases_ms" and (not _is_num(v) or v < 0):
                errors.append(f"{where}: phases_ms[{name!r}] must be "
                              f"a number >= 0")
            if key == "verbs" and (not isinstance(v, int)
                                   or isinstance(v, bool) or v < 0):
                errors.append(f"{where}: verbs[{name!r}] must be an "
                              f"int >= 0")
    return errors


def validate_window(data: Any) -> List[str]:
    """Validate the streaming-aggregation records of a status payload
    (daemon/router ``window`` block: both documented rollup windows,
    each with its full field set, non-negative rates, errors <= count,
    p50 <= p99) and — when a ``metrics`` block is present — the
    window gauges carrying exactly a documented ``window`` label."""
    errors: List[str] = []
    if not isinstance(data, dict):
        return ["window: top level must be a JSON object"]
    window = data.get("window")
    if window is not None:
        if not isinstance(window, dict):
            errors.append("window: block must be an object or null")
        else:
            for key in WINDOW_KEYS:
                if key not in window:
                    errors.append(f"window: missing rollup {key!r}")
                    continue
                errors.extend(_validate_window_block(
                    window[key], f"window[{key!r}]"))
            for key in window:
                if key not in WINDOW_KEYS:
                    errors.append(f"window: unknown rollup {key!r} "
                                  f"not in {WINDOW_KEYS}")
    metrics = data.get("metrics", data)
    if not isinstance(metrics, dict):
        return errors
    gauges = metrics.get("gauges", {})
    if not isinstance(gauges, dict):
        gauges = {}
    for gname in WINDOW_GAUGES:
        g = gauges.get(gname)
        if not isinstance(g, dict):
            continue
        for j, s in enumerate(g.get("series", [])):
            labels = s.get("labels") or {}
            got = tuple(sorted(labels.keys()))
            if got != ("window",):
                errors.append(f"metrics.gauges.{gname}[{j}]: labels "
                              f"{got} != ('window',)")
            elif labels.get("window") not in WINDOW_KEYS:
                errors.append(f"metrics.gauges.{gname}[{j}]: window "
                              f"{labels.get('window')!r} not in "
                              f"{WINDOW_KEYS}")
            if not _is_num(s.get("value")) or s.get("value") < 0:
                errors.append(f"metrics.gauges.{gname}[{j}]: value "
                              f"must be a number >= 0")
    return errors


def _validate_triage_side(side: Any, where: str) -> List[str]:
    errors: List[str] = []
    if not isinstance(side, dict):
        return [f"{where}: must be an object"]
    for key in TRIAGE_SIDE_REQUIRED:
        if key not in side:
            errors.append(f"{where}: missing key {key!r}")
    if "trace_id" in side and (not isinstance(side["trace_id"], str)
                               or not side["trace_id"]):
        errors.append(f"{where}: trace_id must be a non-empty string")
    if "verb" in side and not isinstance(side["verb"], str):
        errors.append(f"{where}: verb must be a string")
    if "seconds" in side and (not _is_num(side["seconds"])
                              or side["seconds"] < 0):
        errors.append(f"{where}: seconds must be a number >= 0")
    phases = side.get("phases_ms")
    if "phases_ms" in side:
        if not isinstance(phases, dict):
            errors.append(f"{where}: phases_ms must be an object")
        else:
            for name, v in phases.items():
                if not _is_num(v) or v < 0:
                    errors.append(f"{where}: phases_ms[{name!r}] must "
                                  f"be a number >= 0")
    return errors


def validate_triage(data: Any) -> List[str]:
    """Validate one auto-captured triage bundle: a conforming
    ``anomaly``-reason postmortem whose ``triage`` block carries the
    breach identity (phase, z >= 0, threshold_z > 0, sustain >= 1),
    a conforming offender (and baseline, when one was in budget), and
    a phase-aligned diff whose rows are sorted by descending delta
    with ``suspect_phase`` naming the top positive contributor (or
    null/the breached phase when nothing regressed)."""
    errors: List[str] = []
    if not isinstance(data, dict):
        return ["triage: top level must be a JSON object"]
    if data.get("reason") != "anomaly":
        errors.append(f"triage: bundle reason {data.get('reason')!r} "
                      f"!= 'anomaly'")
    errors.extend(validate_postmortem(data))
    triage = data.get("triage")
    if not isinstance(triage, dict):
        errors.append("triage: bundle needs a 'triage' object")
        return errors
    for key in TRIAGE_REQUIRED:
        if key not in triage:
            errors.append(f"triage: missing key {key!r}")
    if "schema" in triage and triage["schema"] != 1:
        errors.append(f"triage: unknown schema version "
                      f"{triage['schema']!r}")
    for key in ("phase", "suspect_phase"):
        v = triage.get(key)
        if key in triage and (not isinstance(v, str) or not v):
            errors.append(f"triage: {key} must be a non-empty string")
    if "z" in triage and (not _is_num(triage["z"]) or triage["z"] < 0):
        errors.append("triage: z must be a number >= 0")
    if "threshold_z" in triage and (not _is_num(triage["threshold_z"])
                                    or triage["threshold_z"] <= 0):
        errors.append("triage: threshold_z must be a number > 0")
    sustain = triage.get("sustain")
    if "sustain" in triage and (not isinstance(sustain, int)
                                or isinstance(sustain, bool)
                                or sustain < 1):
        errors.append("triage: sustain must be an int >= 1")
    if "ts" in triage and (not _is_num(triage["ts"])
                           or triage["ts"] < 0):
        errors.append("triage: ts must be a number >= 0")
    if "offender" in triage:
        errors.extend(_validate_triage_side(triage["offender"],
                                            "triage.offender"))
    if triage.get("baseline") is not None:
        errors.extend(_validate_triage_side(triage["baseline"],
                                            "triage.baseline"))
    diff = triage.get("diff")
    if "diff" in triage:
        if not isinstance(diff, list):
            errors.append("triage: diff must be an array")
            diff = []
        prev = None
        for i, row in enumerate(diff):
            where = f"triage.diff[{i}]"
            if not isinstance(row, dict):
                errors.append(f"{where}: must be an object")
                continue
            for key in TRIAGE_DIFF_ROW_REQUIRED:
                if key not in row:
                    errors.append(f"{where}: missing key {key!r}")
            for key in ("a_ms", "b_ms"):
                if key in row and (not _is_num(row[key])
                                   or row[key] < 0):
                    errors.append(f"{where}: {key} must be a number "
                                  f">= 0")
            if "delta_ms" in row and not _is_num(row["delta_ms"]):
                errors.append(f"{where}: delta_ms must be a number")
            ratio = row.get("ratio")
            if "ratio" in row and ratio is not None \
                    and (not _is_num(ratio) or ratio < 0):
                errors.append(f"{where}: ratio must be a number >= 0 "
                              f"or null")
            delta = row.get("delta_ms")
            if _is_num(delta):
                if prev is not None and delta > prev:
                    errors.append(f"{where}: diff rows not sorted by "
                                  f"descending delta_ms")
                prev = delta
        if isinstance(diff, list) and diff \
                and isinstance(diff[0], dict) \
                and _is_num(diff[0].get("delta_ms")) \
                and diff[0]["delta_ms"] > 0 \
                and isinstance(triage.get("suspect_phase"), str) \
                and isinstance(triage.get("baseline"), dict) \
                and triage["suspect_phase"] != diff[0].get("phase"):
            errors.append(f"triage: suspect_phase "
                          f"{triage['suspect_phase']!r} is not the "
                          f"top positive-delta row "
                          f"{diff[0].get('phase')!r}")
    return errors


def _finish(errors: List[str]) -> int:
    for err in errors:
        print(err, file=sys.stderr)
    if not errors:
        print("ok")
    return 1 if errors else 0


def main(argv: List[str]) -> int:
    if argv and argv[0] == "validate_postmortem":
        if len(argv) < 2:
            print("usage: check_trace_schema.py validate_postmortem "
                  "BUNDLE_JSON [...]", file=sys.stderr)
            return 2
        errors: List[str] = []
        for path in argv[1:]:
            try:
                with open(path, encoding="utf-8") as fh:
                    errors.extend(f"{path}: {e}" for e in
                                  validate_postmortem(json.load(fh)))
            except (OSError, json.JSONDecodeError) as exc:
                errors.append(f"{path}: unreadable ({exc})")
        return _finish(errors)
    if argv and argv[0] == "validate_conflicts":
        if len(argv) < 2:
            print("usage: check_trace_schema.py validate_conflicts "
                  "CONFLICTS_JSON [...]", file=sys.stderr)
            return 2
        errors = []
        for path in argv[1:]:
            try:
                with open(path, encoding="utf-8") as fh:
                    errors.extend(f"{path}: {e}" for e in
                                  validate_conflicts(json.load(fh)))
            except (OSError, json.JSONDecodeError) as exc:
                errors.append(f"{path}: unreadable ({exc})")
        return _finish(errors)
    if argv and argv[0] == "validate_slo":
        if len(argv) < 2:
            print("usage: check_trace_schema.py validate_slo "
                  "STATUS_OR_TRACE_JSON [...]", file=sys.stderr)
            return 2
        errors = []
        for path in argv[1:]:
            try:
                with open(path, encoding="utf-8") as fh:
                    errors.extend(f"{path}: {e}" for e in
                                  validate_slo(json.load(fh)))
            except (OSError, json.JSONDecodeError) as exc:
                errors.append(f"{path}: unreadable ({exc})")
        return _finish(errors)
    if argv and argv[0] == "validate_device_render":
        if len(argv) < 2:
            print("usage: check_trace_schema.py validate_device_render "
                  "STATUS_OR_TRACE_JSON [...]", file=sys.stderr)
            return 2
        errors = []
        for path in argv[1:]:
            try:
                with open(path, encoding="utf-8") as fh:
                    errors.extend(f"{path}: {e}" for e in
                                  validate_device_render(json.load(fh)))
            except (OSError, json.JSONDecodeError) as exc:
                errors.append(f"{path}: unreadable ({exc})")
        return _finish(errors)
    if argv and argv[0] == "validate_fleet":
        if len(argv) < 2:
            print("usage: check_trace_schema.py validate_fleet "
                  "STATUS_OR_TRACE_JSON [...]", file=sys.stderr)
            return 2
        errors = []
        for path in argv[1:]:
            try:
                with open(path, encoding="utf-8") as fh:
                    errors.extend(f"{path}: {e}" for e in
                                  validate_fleet(json.load(fh)))
            except (OSError, json.JSONDecodeError) as exc:
                errors.append(f"{path}: unreadable ({exc})")
        return _finish(errors)
    if argv and argv[0] == "validate_transport":
        if len(argv) < 2:
            print("usage: check_trace_schema.py validate_transport "
                  "STATUS_OR_TRACE_JSON [...]", file=sys.stderr)
            return 2
        errors = []
        for path in argv[1:]:
            try:
                with open(path, encoding="utf-8") as fh:
                    errors.extend(f"{path}: {e}" for e in
                                  validate_transport(json.load(fh)))
            except (OSError, json.JSONDecodeError) as exc:
                errors.append(f"{path}: unreadable ({exc})")
        return _finish(errors)
    if argv and argv[0] == "validate_fleet_trace":
        if len(argv) < 2:
            print("usage: check_trace_schema.py validate_fleet_trace "
                  "STITCHED_TRACE_JSON [...]", file=sys.stderr)
            return 2
        errors = []
        for path in argv[1:]:
            try:
                with open(path, encoding="utf-8") as fh:
                    errors.extend(f"{path}: {e}" for e in
                                  validate_fleet_trace(json.load(fh)))
            except (OSError, json.JSONDecodeError) as exc:
                errors.append(f"{path}: unreadable ({exc})")
        return _finish(errors)
    if argv and argv[0] == "validate_export":
        if len(argv) < 2:
            print("usage: check_trace_schema.py validate_export "
                  "OTLP_PAYLOAD_JSON [...]", file=sys.stderr)
            return 2
        errors = []
        for path in argv[1:]:
            try:
                with open(path, encoding="utf-8") as fh:
                    errors.extend(f"{path}: {e}" for e in
                                  validate_export(json.load(fh)))
            except (OSError, json.JSONDecodeError) as exc:
                errors.append(f"{path}: unreadable ({exc})")
        return _finish(errors)
    if argv and argv[0] == "validate_sampling":
        if len(argv) < 2:
            print("usage: check_trace_schema.py validate_sampling "
                  "STATUS_OR_KEPT_TRACE_JSON [...]", file=sys.stderr)
            return 2
        errors = []
        for path in argv[1:]:
            try:
                with open(path, encoding="utf-8") as fh:
                    errors.extend(f"{path}: {e}" for e in
                                  validate_sampling(json.load(fh)))
            except (OSError, json.JSONDecodeError) as exc:
                errors.append(f"{path}: unreadable ({exc})")
        return _finish(errors)
    if argv and argv[0] == "validate_window":
        if len(argv) < 2:
            print("usage: check_trace_schema.py validate_window "
                  "STATUS_JSON [...]", file=sys.stderr)
            return 2
        errors = []
        for path in argv[1:]:
            try:
                with open(path, encoding="utf-8") as fh:
                    errors.extend(f"{path}: {e}" for e in
                                  validate_window(json.load(fh)))
            except (OSError, json.JSONDecodeError) as exc:
                errors.append(f"{path}: unreadable ({exc})")
        return _finish(errors)
    if argv and argv[0] == "validate_triage":
        if len(argv) < 2:
            print("usage: check_trace_schema.py validate_triage "
                  "TRIAGE_BUNDLE_JSON [...]", file=sys.stderr)
            return 2
        errors = []
        for path in argv[1:]:
            try:
                with open(path, encoding="utf-8") as fh:
                    errors.extend(f"{path}: {e}" for e in
                                  validate_triage(json.load(fh)))
            except (OSError, json.JSONDecodeError) as exc:
                errors.append(f"{path}: unreadable ({exc})")
        return _finish(errors)
    if argv and argv[0] == "validate_request_traces":
        if len(argv) < 2:
            print("usage: check_trace_schema.py validate_request_traces "
                  "TRACE_JSON [...]", file=sys.stderr)
            return 2
        traces: List[Any] = []
        errors = []
        for path in argv[1:]:
            try:
                with open(path, encoding="utf-8") as fh:
                    traces.append(json.load(fh))
            except (OSError, json.JSONDecodeError) as exc:
                errors.append(f"{path}: unreadable ({exc})")
        errors.extend(validate_request_traces(traces))
        return _finish(errors)
    bench_path = None
    if "--bench" in argv:
        i = argv.index("--bench")
        try:
            bench_path = argv[i + 1]
        except IndexError:
            print("--bench requires a path", file=sys.stderr)
            return 2
        argv = argv[:i] + argv[i + 2:]
    if not argv or len(argv) > 2:
        print(__doc__.strip().splitlines()[0])
        print("usage: check_trace_schema.py TRACE_JSON [EVENTS_JSONL] "
              "[--bench BENCH_JSON]", file=sys.stderr)
        return 2
    errors: List[str] = []
    try:
        with open(argv[0], encoding="utf-8") as fh:
            trace = json.load(fh)
        errors.extend(validate_trace(trace))
        errors.extend(validate_degradations(trace))
        errors.extend(validate_service(trace))
        errors.extend(validate_batch(trace))
        errors.extend(validate_resilience(trace))
        errors.extend(validate_slo(trace))
        errors.extend(validate_fleet(trace))
    except (OSError, json.JSONDecodeError) as exc:
        errors.append(f"trace: unreadable ({exc})")
    if len(argv) == 2:
        try:
            with open(argv[1], encoding="utf-8") as fh:
                errors.extend(validate_events(fh.read().splitlines()))
        except OSError as exc:
            errors.append(f"events: unreadable ({exc})")
    if bench_path is not None:
        try:
            with open(bench_path, encoding="utf-8") as fh:
                errors.extend(validate_bench(json.load(fh)))
        except (OSError, json.JSONDecodeError) as exc:
            errors.append(f"bench: unreadable ({exc})")
    return _finish(errors)


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
