#!/usr/bin/env python
"""Stage-split timing of the fused merge kernel.

Answers the profiling question VERDICT r4 left open — how much of the
device time is the diff join vs SHA op identity vs the compose sorts/
scans — by jitting cumulative PREFIXES of the fused program and timing
each: the difference between consecutive prefixes is that stage's cost
(each prefix is one jitted program, so XLA still fuses within it; the
split is therefore a faithful attribution, not a hand-scheduled one).

Runs on whatever platform jax selects (real chip when the relay is up;
`JAX_PLATFORMS=cpu` for XLA-on-CPU). Usage::

    python scripts/kernel_split.py [--files 10000] [--decls 4]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from semantic_merge_tpu.utils.jaxenv import enable_compile_cache  # noqa: E402

enable_compile_cache()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--files", type=int, default=10000)
    ap.add_argument("--decls", type=int, default=4)
    ap.add_argument("--repeats", type=int, default=5)
    args = ap.parse_args()

    import bench
    from semantic_merge_tpu.backends.base import get_backend

    base, left, right = bench.synth_repo(args.files, args.decls,
                                         divergent=True)
    bk = get_backend("tpu")
    # Warm scan/encode + device decl columns through the normal path.
    bench.run_merge(bk, base, left, right)
    eng = bk._fused_engine()
    base_t, base_nodes, base_key = bk._scan_encode_keyed(base)
    left_t, left_nodes, left_key = bk._scan_encode_keyed(left)
    right_t, right_nodes, right_key = bk._scan_encode_keyed(right)
    hash_tab = eng.strings.sync()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from functools import partial

    from semantic_merge_tpu.core.ids import op_id_prefix_digest
    from semantic_merge_tpu.ops import fused as F

    dev_b, nb = eng._device_decl(base_t, base_key)
    dev_l, nl = eng._device_decl(left_t, left_key)
    dev_r, nr = eng._device_decl(right_t, right_key)
    C = eng._bucket(max(eng._cap_hint, 8))
    dig_l = np.frombuffer(op_id_prefix_digest("bench/L", "bench"), np.uint8)
    dig_r = np.frombuffer(op_id_prefix_digest("bench/R", "bench"), np.uint8)

    def stage_diff(b, l, r, tab, dl, dr):
        planL = F._diff_plan(b[0], b[1], b[2], l[0], l[1], l[2], nb, nl)
        planR = F._diff_plan(b[0], b[1], b[2], r[0], r[1], r[2], nb, nr)
        return planL["n_ops"], planR["n_ops"]

    def stage_emit(b, l, r, tab, dl, dr):
        planL = F._diff_plan(b[0], b[1], b[2], l[0], l[1], l[2], nb, nl)
        planR = F._diff_plan(b[0], b[1], b[2], r[0], r[1], r[2], nb, nr)
        kL, aL, bL, nL_ = F._emit_slots(planL, C, nb, nl)
        kR, aR, bR, nR_ = F._emit_slots(planR, C, nb, nr)
        return kL, kR, nL_, nR_

    def stage_sha(b, l, r, tab, dl, dr):
        planL = F._diff_plan(b[0], b[1], b[2], l[0], l[1], l[2], nb, nl)
        planR = F._diff_plan(b[0], b[1], b[2], r[0], r[1], r[2], nb, nr)
        kL, aL, bL, _ = F._emit_slots(planL, C, nb, nl)
        kR, aR, bR, _ = F._emit_slots(planR, C, nb, nr)
        wL = F._op_id_words(kL, aL, bL, b, l, tab, dl, C=C)
        wR = F._op_id_words(kR, aR, bR, b, r, tab, dr, C=C)
        return wL, wR

    def stage_full(b, l, r, tab, dl, dr):
        return F._fused_merge_kernel(b, l, r, tab, dl, dr,
                                     nb=nb, nl=nl, nr=nr, C=C)

    stages = [("diff_join", stage_diff), ("emit_slots", stage_emit),
              ("sha_ids", stage_sha), ("full_kernel", stage_full)]
    results = {}
    inputs = (dev_b, dev_l, dev_r, hash_tab, jnp.asarray(dig_l),
              jnp.asarray(dig_r))
    for name, fn in stages:
        jf = jax.jit(fn)
        jax.block_until_ready(jf(*inputs))  # compile
        best = float("inf")
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            jax.block_until_ready(jf(*inputs))
            best = min(best, time.perf_counter() - t0)
        results[name] = best * 1e3

    plat = jax.devices()[0].platform
    print(f"# platform={plat} files={args.files} C={C} nb={nb}")
    prev = 0.0
    for name, _ in stages:
        t = results[name]
        print(f"{name:14s} cumulative {t:8.1f} ms   stage {t - prev:8.1f} ms")
        prev = t
    compose_share = results["full_kernel"] - results["sha_ids"]
    print(f"# compose stages (sorts + candidate join + scans + pack): "
          f"{compose_share:.1f} ms "
          f"({100 * compose_share / results['full_kernel']:.0f}% of kernel)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
