#!/usr/bin/env python3
"""Git merge-driver shim.

Git invokes this once per conflicted file. Configure with the real
pathname placeholder ``%P`` included::

    git config merge.semmerge.driver \
        "python3 scripts/semmerge-driver.py %O %A %B %P"

``%O/%A/%B`` are *temporary* files git materializes (``.merge_file_*``)
— only ``%P`` names the actual conflicted path, which is why the
reference driver (reference ``scripts/semmerge-driver.py:46-49``),
which computes the path by relpath-ing ``%A`` against the repo root,
ends up copying the temp file onto itself and silently publishing
"ours" as the merge result. This driver requires ``%P`` and copies the
engine-resolved working-tree file onto ``%A``.

The engine merges at repo scope, so the first file invocation runs the
full CLI merge ``--inplace`` and records the merge in a latch file
under ``.git/``; later invocations for the *same* merge skip straight
to the copy-back. The reference's lock unlinks itself in a ``finally``
as soon as the first invocation completes, so sequential per-file
driver calls each re-run the full merge; here the latch persists for
the duration of the merge (cleared by age or a different merge head),
so the repo-level merge truly runs once.
"""
from __future__ import annotations

import os
import pathlib
import shutil
import subprocess
import sys
import time

STALE_LOCK_SECONDS = 3600


def run(cmd: list[str], cwd: str | None = None) -> str:
    proc = subprocess.run(cmd, cwd=cwd, stdout=subprocess.PIPE,
                          stderr=subprocess.PIPE, text=True)
    if proc.returncode != 0:
        if proc.stderr:
            sys.stderr.write(proc.stderr)
        sys.exit(proc.returncode)
    return proc.stdout.strip()


def incoming_head(repo_root: pathlib.Path, head: str) -> str | None:
    """The rev being merged in.

    While ``git merge`` is *running* its strategies, ``MERGE_HEAD`` does
    not exist yet (it is written only when the merge stops for conflicts
    or a commit); what git gives merge drivers is a ``GITHEAD_<sha>``
    environment variable per head being merged. So: a single non-HEAD
    ``GITHEAD_*`` sha wins (the normal two-head merge); otherwise fall
    back to the on-disk refs, which cover rebase (``REBASE_HEAD``),
    cherry-pick (``CHERRY_PICK_HEAD``) and ``git merge --continue``
    flows. Octopus merges (several incoming heads) return ``None`` —
    the driver leaves those files conflicted rather than guessing."""
    githeads = [key[len("GITHEAD_"):] for key in os.environ
                if key.startswith("GITHEAD_")]
    others = sorted({sha for sha in githeads if sha != head})
    if len(others) == 1:
        return others[0]
    if len(others) > 1:
        return None
    for ref in ("MERGE_HEAD", "REBASE_HEAD", "CHERRY_PICK_HEAD"):
        proc = subprocess.run(["git", "rev-parse", "--verify", "--quiet", ref],
                              cwd=repo_root, stdout=subprocess.PIPE, text=True)
        if proc.returncode == 0 and proc.stdout.strip():
            return proc.stdout.strip()
    return None


def main() -> None:
    if len(sys.argv) < 5:
        sys.exit(
            "semmerge-driver requires %O %A %B %P arguments — configure "
            "merge.semmerge.driver with the %P placeholder"
        )
    _base_file, ours_file, _theirs_file, pathname = sys.argv[1:5]

    repo_root = pathlib.Path(run(["git", "rev-parse", "--show-toplevel"]))
    head = run(["git", "rev-parse", "HEAD"])
    merge_head = incoming_head(repo_root, head)
    if merge_head is None:
        # No merge in progress that we understand: leave the file
        # conflicted rather than guessing.
        sys.exit(1)
    base_commit = run(["git", "merge-base", "HEAD", merge_head])

    lock = repo_root / ".git" / ".semmerge.lock"
    lock.parent.mkdir(parents=True, exist_ok=True)
    stale = lock.exists() and time.time() - lock.stat().st_mtime > STALE_LOCK_SECONDS
    same_merge = (
        lock.exists() and not stale
        and lock.read_text().strip() == f"{head} {merge_head}"
    )
    if not same_merge:
        lock.write_text(f"{head} {merge_head}")
        # Warm path by default: repeated driver invocations in one
        # rebase/merge train are exactly the workload the service
        # daemon amortizes. auto falls back to one-shot on any
        # connect/spawn failure, so this never costs correctness; an
        # explicit SEMMERGE_DAEMON (off/require) is respected.
        env = dict(os.environ)
        env.setdefault("SEMMERGE_DAEMON", "auto")
        try:
            code = subprocess.run(
                [sys.executable, "-m", "semantic_merge_tpu", "semmerge",
                 base_commit, head, merge_head, "--inplace", "--git"],
                cwd=repo_root, env=env,
            ).returncode
        except BaseException:
            # A crashed run must not latch; the next invocation retries.
            lock.unlink(missing_ok=True)
            raise
        if code != 0:
            # Engine failure: clear the latch so the NEXT driver
            # invocation retries the full merge instead of copying back
            # a stale resolution, and leave %A exactly as git
            # materialized it — git's own conflict markers win. (The
            # CLI's crash-safe --inplace commit guarantees the work
            # tree itself is untouched on every failure exit.)
            lock.unlink(missing_ok=True)
            sys.exit(code)

    resolved = repo_root / pathname
    if resolved.exists():
        shutil.copyfile(resolved, ours_file)
        sys.exit(0)
    # The engine deleted/moved the file away; report conflict so git
    # keeps the user in the loop rather than silently taking "ours".
    sys.exit(1)


if __name__ == "__main__":
    main()
