from .scanner import DeclNode, scan_file, scan_snapshot
from .snapshot import Snapshot, snapshot_tree

__all__ = ["DeclNode", "scan_file", "scan_snapshot", "Snapshot", "snapshot_tree"]
