"""ctypes bindings for the C++ native scanner (``native/semmerge_native.cpp``).

The native library is the TPU framework's equivalent of the reference's
native Node.js worker (reference ``workers/ts/src/sast.ts``): it owns
the host-side hot path — tokenize + declaration indexing — and feeds
the device encoders. The Python scanner
(:mod:`semantic_merge_tpu.frontend.scanner`) remains the semantic
oracle; this module returns identical ``DeclNode`` lists on ASCII
sources and *refuses* non-ASCII snapshots (the Python scanner indexes
by code point, the C++ one by byte — falling back keeps offsets
bit-identical).

Selection is controlled by ``SEMMERGE_NATIVE``:

- ``auto`` (default): use the library if present or buildable.
- ``1``: require it (raise if unavailable).
- ``0``: never use it.

The shared library is built on demand with ``make -C native`` the first
time it is needed; build failures degrade to the Python path (matching
the reference's graceful-degradation posture for optional tooling,
reference ``semmerge/verify.py:28-30``).
"""
from __future__ import annotations

import ctypes
import json
import os
import pathlib
import subprocess
from typing import List, Optional, Sequence

from ..utils.loggingx import logger
from .scanner import DeclNode

_NATIVE_DIR = pathlib.Path(__file__).resolve().parents[2] / "native"
_LIB_PATH = _NATIVE_DIR / "libsemmerge_native.so"
_ABI_VERSION = 4

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _mode() -> str:
    return os.environ.get("SEMMERGE_NATIVE", "auto").strip().lower()


def _build() -> bool:
    src = _NATIVE_DIR / "semmerge_native.cpp"
    if not src.exists():
        return False
    try:
        proc = subprocess.run(
            ["make", "-C", str(_NATIVE_DIR), "libsemmerge_native.so"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            timeout=300,
        )
    except (OSError, subprocess.TimeoutExpired) as exc:
        logger.debug("native build unavailable: %s", exc)
        return False
    if proc.returncode != 0:
        logger.warning("native frontend build failed:\n%s", proc.stdout[-2000:])
        return False
    return _LIB_PATH.exists()


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    if _mode() == "0":
        return None
    src = _NATIVE_DIR / "semmerge_native.cpp"
    stale = (_LIB_PATH.exists() and src.exists()
             and src.stat().st_mtime > _LIB_PATH.stat().st_mtime)
    if (not _LIB_PATH.exists() or stale) and not _build():
        if _mode() == "1":
            raise RuntimeError(
                f"SEMMERGE_NATIVE=1 but {_LIB_PATH} is missing and could not be built")
        return None
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
    except OSError as exc:
        if _mode() == "1":
            raise
        logger.warning("native frontend load failed: %s", exc)
        return None
    lib.smn_abi_version.restype = ctypes.c_int
    if lib.smn_abi_version() != _ABI_VERSION:
        logger.warning("native frontend ABI %d != expected %d; ignoring",
                       lib.smn_abi_version(), _ABI_VERSION)
        return None
    lib.smn_scan_snapshot.restype = ctypes.c_void_p
    lib.smn_scan_snapshot.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_char_p),
        ctypes.c_int,
    ]
    lib.smn_type_names.restype = ctypes.c_void_p
    lib.smn_type_names.argtypes = [ctypes.POINTER(ctypes.c_char_p), ctypes.c_int]
    lib.smn_scan_with_names.restype = ctypes.c_void_p
    lib.smn_scan_with_names.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_char_p),
        ctypes.c_int,
    ]
    lib.smn_oplog_json.restype = ctypes.c_void_p
    lib.smn_oplog_json.argtypes = [
        ctypes.c_int,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_char_p, ctypes.c_void_p,
        ctypes.c_char_p, ctypes.c_void_p,
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
    ]
    lib.smn_free.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def _ascii_arrays(files: Sequence[dict]):
    """Marshal a snapshot into ctypes arrays, or ``None`` when the
    content is not ASCII/NUL-safe (code-point vs byte offsets would
    diverge; ``c_char_p`` is NUL-terminated so C would see a prefix)."""
    paths: List[bytes] = []
    contents: List[bytes] = []
    for f in files:
        content = f["content"]
        if not content.isascii() or not f["path"].isascii():
            return None
        if "\x00" in content or "\x00" in f["path"]:
            return None
        paths.append(f["path"].encode("ascii"))
        contents.append(content.encode("ascii"))
    n = len(files)
    return (ctypes.c_char_p * n)(*paths), (ctypes.c_char_p * n)(*contents), n


def try_type_names(files: Sequence[dict]) -> Optional[List[frozenset]]:
    """Per-file declared type names via the native tokenizer (pass 1 of
    the scan); ``None`` → caller should tokenize in Python."""
    lib = _load()
    if lib is None:
        return None
    arrays = _ascii_arrays(files)
    if arrays is None:
        return None
    _, content_arr, n = arrays
    ptr = lib.smn_type_names(content_arr, n)
    if not ptr:
        return None
    try:
        raw = ctypes.string_at(ptr)
    finally:
        lib.smn_free(ptr)
    return [frozenset(names) for names in json.loads(raw)]


def try_scan_with_names(files: Sequence[dict]):
    """One native pass returning ``(per_file_name_sets, nodes)`` — the
    cold path of the cached scan; ``None`` → Python fallback."""
    lib = _load()
    if lib is None:
        return None
    arrays = _ascii_arrays(files)
    if arrays is None:
        return None
    path_arr, content_arr, n = arrays
    ptr = lib.smn_scan_with_names(path_arr, content_arr, n)
    if not ptr:
        return None
    try:
        raw = ctypes.string_at(ptr)
    finally:
        lib.smn_free(ptr)
    payload = json.loads(raw)
    names = [frozenset(ns) for ns in payload["names"]]
    nodes = [
        DeclNode(
            symbolId=r["symbolId"], addressId=r["addressId"], kind=r["kind"],
            name=r["name"], file=r["file"], pos=r["pos"], end=r["end"],
            signature=r["signature"],
        )
        for r in payload["nodes"]
    ]
    return names, nodes


def try_scan_snapshot(files: Sequence[dict]) -> Optional[List[DeclNode]]:
    """Scan with the native library; ``None`` → caller should use the
    Python path (library unavailable or snapshot not ASCII-safe)."""
    lib = _load()
    if lib is None:
        return None
    arrays = _ascii_arrays(files)
    if arrays is None:
        return None
    path_arr, content_arr, n = arrays
    ptr = lib.smn_scan_snapshot(path_arr, content_arr, n)
    if not ptr:
        return None
    try:
        raw = ctypes.string_at(ptr)
    finally:
        lib.smn_free(ptr)
    records = json.loads(raw)
    return [
        DeclNode(
            symbolId=r["symbolId"], addressId=r["addressId"], kind=r["kind"],
            name=r["name"], file=r["file"], pos=r["pos"], end=r["end"],
            signature=r["signature"],
        )
        for r in records
    ]


def try_oplog_json_bytes(n: int, kind, a_slot, b_slot, words,
                         base_blob: bytes, base_offs,
                         side_blob: bytes, side_offs,
                         prov_json: str) -> Optional[bytes]:
    """Render an op stream's canonical JSON (UTF-8 bytes) from its
    device columns via the native serializer (``smn_oplog_json``);
    ``None`` → caller uses the Python columnar serializer. Arrays must
    be C-contiguous int32 (columns) / int64 (table offsets)."""
    lib = _load()
    if lib is None:
        return None
    out_len = ctypes.c_int64(0)
    ptr = lib.smn_oplog_json(
        n,
        kind.ctypes.data_as(ctypes.c_void_p),
        a_slot.ctypes.data_as(ctypes.c_void_p),
        b_slot.ctypes.data_as(ctypes.c_void_p),
        words.ctypes.data_as(ctypes.c_void_p),
        base_blob, base_offs.ctypes.data_as(ctypes.c_void_p),
        side_blob, side_offs.ctypes.data_as(ctypes.c_void_p),
        prov_json.encode("utf-8"), ctypes.byref(out_len))
    if not ptr:
        return None
    try:
        return ctypes.string_at(ptr, out_len.value)
    finally:
        lib.smn_free(ptr)



_OPFACTORY_PATH = _NATIVE_DIR / "semmerge_opfactory.so"
_opfactory = None
_opfactory_attempted = False


def load_opfactory():
    """The C op-object factory extension (``native/opfactory.c``), or
    ``None`` when unavailable (SEMMERGE_NATIVE=0, no compiler, load
    failure). Built on demand like the scanner library."""
    global _opfactory, _opfactory_attempted
    if _opfactory is not None or _opfactory_attempted:
        return _opfactory
    _opfactory_attempted = True
    if _mode() == "0":
        return None
    src = _NATIVE_DIR / "opfactory.c"
    stale = (_OPFACTORY_PATH.exists() and src.exists()
             and src.stat().st_mtime > _OPFACTORY_PATH.stat().st_mtime)
    if not _OPFACTORY_PATH.exists() or stale:
        if not src.exists():
            if _mode() == "1":
                raise RuntimeError(
                    f"SEMMERGE_NATIVE=1 but {src} is missing")
            return None
        import sysconfig
        try:
            proc = subprocess.run(
                ["make", "-C", str(_NATIVE_DIR),
                 # Build against the RUNNING interpreter's headers, not
                 # whatever python3 is first on make's PATH.
                 f"PY_INC={sysconfig.get_paths()['include']}",
                 "semmerge_opfactory.so"],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                timeout=300)
        except (OSError, subprocess.TimeoutExpired) as exc:
            if _mode() == "1":
                raise RuntimeError(f"SEMMERGE_NATIVE=1 but the opfactory "
                                   f"build could not run: {exc}") from exc
            logger.debug("opfactory build unavailable: %s", exc)
            return None
        if proc.returncode != 0:
            if _mode() == "1":
                raise RuntimeError("SEMMERGE_NATIVE=1 but the opfactory "
                                   "build failed:\n" + proc.stdout[-2000:])
            logger.warning("opfactory build failed:\n%s", proc.stdout[-2000:])
            return None
    try:
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "semmerge_opfactory", str(_OPFACTORY_PATH))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    except Exception as exc:
        if _mode() == "1":
            raise
        logger.warning("opfactory load failed: %s", exc)
        return None
    _opfactory = mod
    return _opfactory
