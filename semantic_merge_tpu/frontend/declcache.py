"""Incremental declaration-index cache.

The reference *designs* a warm-cache story it never implements: parse
caches with memory caps and adaptive eviction (reference
``architecture.md:206-208, 313-314``; ``requirements.md:171``
[NFR-PERF-004]; ``semmerge/config.py:23`` ``memory_cap_mb`` — dead
code there). This module implements it: scan results are cached per
``(path, content-hash, declared-set-hash)`` with LRU eviction bounded
by ``memory_cap_mb``.

Why the declared-set hash is part of the key: the scanner resolves type
annotations against the set of type names declared anywhere in the
snapshot (the stand-in for the reference worker's no-default-lib
``ts.TypeChecker``, reference ``workers/ts/src/sast.ts:19-22``), so an
*unchanged* file's signatures can legitimately change when another file
adds or removes a type declaration. Keying on the global declared-set
hash keeps the cache exact, never heuristic: any snapshot that would
produce different decl nodes misses.

Within a single three-way merge the base/left/right snapshots share
almost every file (a 10k-file repo with 200 changed files re-scans 200
files, not 30k), and repeated merges in one process (watch mode, the
bench harness, the merge driver's repo-level run) hit across calls —
the reference's "warm cache e2e merge ≤ 10 s" budget
(reference ``architecture.md:313``).

The intended *cross-invocation* consumer of that warm state is the
merge service daemon (:mod:`semantic_merge_tpu.service`): a one-shot
CLI process dies with its cache, but ``semmerge serve`` keeps this
process-global cache alive across requests, so the Nth merge of a repo
re-scans only the files that changed since the first. Hit/miss/eviction
counts are visible two ways: per-merge in the trace counters
(``decl_cache_hits``/``decl_cache_misses``), and cumulatively in the
obs registry (``declcache_hits_total`` / ``declcache_misses_total`` /
``declcache_evictions_total``, delta-published by
:func:`publish_metrics` at the end of each merge/request — per-``get``
counter updates would tax the scan hot path for numbers nobody reads
mid-merge).
"""
from __future__ import annotations

import functools
import hashlib
import os
import sys
from collections import OrderedDict
from typing import Any, Hashable, Optional

DEFAULT_CAP_MB = 512


class DeclCache:
    """LRU cache bounded by an approximate byte budget."""

    def __init__(self, cap_mb: int = DEFAULT_CAP_MB) -> None:
        self.cap_bytes = cap_mb * 1024 * 1024
        self._store: "OrderedDict[Hashable, tuple[Any, int]]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key: Hashable) -> Optional[Any]:
        entry = self._store.get(key)
        if entry is None:
            self.misses += 1
            return None
        self._store.move_to_end(key)
        self.hits += 1
        return entry[0]

    def put(self, key: Hashable, value: Any, size: int | None = None) -> None:
        size = size if size is not None else approx_size(value)
        old = self._store.pop(key, None)
        if old is not None:
            self._bytes -= old[1]
        self._store[key] = (value, size)
        self._bytes += size
        while self._bytes > self.cap_bytes and len(self._store) > 1:
            _, (_, evicted_size) = self._store.popitem(last=False)
            self._bytes -= evicted_size
            self.evictions += 1

    def set_cap_mb(self, cap_mb: int) -> None:
        self.cap_bytes = cap_mb * 1024 * 1024

    def clear(self) -> None:
        self._store.clear()
        self._bytes = 0

    @property
    def n_entries(self) -> int:
        return len(self._store)

    @property
    def bytes_used(self) -> int:
        return self._bytes

    def stats(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "evictions": self.evictions, "entries": len(self._store),
                "bytes": self._bytes}


def approx_size(value: Any) -> int:
    """Rough byte estimate for cap accounting — strings dominate.

    Deliberately flat (two levels, no recursion): cap accounting runs on
    every put and must not dominate a cold scan; the cached values are
    string collections and DeclNode lists, both covered exactly by the
    container → (string | attr-dict) shape."""
    if isinstance(value, str):
        return 49 + len(value)
    if isinstance(value, (list, tuple, frozenset, set)):
        total = 64
        for v in value:
            if isinstance(v, str):
                total += 49 + len(v)
                continue
            attrs = _attr_values(v)
            if attrs is not None:
                total += 80
                for a in attrs:
                    total += (49 + len(a)) if isinstance(a, str) else 24
            else:
                total += 24
        return total
    attrs = _attr_values(value)
    if attrs is not None:
        total = 80
        for a in attrs:
            total += (49 + len(a)) if isinstance(a, str) else 24
        return total
    return max(sys.getsizeof(value, 64), 16)


def _attr_values(v):
    """Attribute values of a record object, for size accounting —
    supports both ``__dict__``-backed and ``slots=True`` dataclasses
    (DeclNode is slotted: it is constructed ~90k times per 10k-file
    scan and slots measurably cheapen that)."""
    d = getattr(v, "__dict__", None)
    if d is not None:
        return d.values()
    names = _slot_names(type(v))
    if names:
        return [getattr(v, s, None) for s in names]
    return None


@functools.lru_cache(maxsize=None)
def _slot_names(klass) -> tuple:
    """All slot names of a type, inherited slots included — memoized:
    this runs once per *cached object* during size accounting (~90k
    DeclNodes per 10k-file cold scan)."""
    names: list = []
    for k in klass.__mro__:
        slots = k.__dict__.get("__slots__", ())
        names.extend((slots,) if isinstance(slots, str) else slots)
    return tuple(names)


def content_hash(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8")).hexdigest()[:32]


def declared_hash(declared) -> str:
    return hashlib.sha256("\n".join(sorted(declared)).encode("utf-8")).hexdigest()[:32]


_GLOBAL: Optional[DeclCache] = None


def enabled() -> bool:
    return os.environ.get("SEMMERGE_CACHE", "1").strip().lower() not in ("0", "off")


def global_cache() -> Optional[DeclCache]:
    """The process-wide cache, or ``None`` when disabled
    (``SEMMERGE_CACHE=0``)."""
    global _GLOBAL
    if not enabled():
        return None
    if _GLOBAL is None:
        _GLOBAL = DeclCache()
    return _GLOBAL


def configure(memory_cap_mb: int) -> None:
    """Apply the ``[core] memory_cap_mb`` budget (the CLI calls this
    once config is loaded). Half the budget goes to the decl cache; the
    rest stays headroom for snapshots and device buffers."""
    cache = global_cache()
    if cache is not None:
        cache.set_cap_mb(max(1, memory_cap_mb // 2))


_PUBLISHED = {"hits": 0, "misses": 0, "evictions": 0}
_PUBLISH_METRICS = {"hits": "declcache_hits_total",
                    "misses": "declcache_misses_total",
                    "evictions": "declcache_evictions_total"}
_PUBLISH_HELP = {"hits": "Decl-cache lookups served from cache",
                 "misses": "Decl-cache lookups that re-scanned",
                 "evictions": "Decl-cache entries evicted by the byte cap"}


def publish_metrics() -> None:
    """Delta-sync the cache's internal counters into the obs registry.

    Called at the end of each merge (CLI) and each service request
    (daemon) rather than on every ``get``: one registry update per
    merge instead of one per file lookup. Safe when the cache is
    disabled or was never touched."""
    cache = _GLOBAL
    if cache is None:
        return
    from ..obs import metrics as obs_metrics
    for field, metric in _PUBLISH_METRICS.items():
        current = getattr(cache, field)
        delta = current - _PUBLISHED[field]
        if delta > 0:
            obs_metrics.REGISTRY.counter(metric, _PUBLISH_HELP[field]).inc(delta)
        _PUBLISHED[field] = current
