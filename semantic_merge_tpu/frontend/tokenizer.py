"""A compact TypeScript/JavaScript tokenizer.

The declaration scanner (:mod:`semantic_merge_tpu.frontend.scanner`) only
needs token boundaries, not a full grammar: identifiers/keywords,
numbers, string/template/regex literals, and punctuation, each with
source offsets. Comments and whitespace are skipped but two pieces of
trivia metadata are kept because the indexing semantics depend on them:

- ``prev_end``: the end offset of the previous token. The reference
  addresses declarations by their *full start* — the TS parser's
  ``node.pos``, which equals the end of the preceding token (leading
  trivia belongs to the node; reference ``workers/ts/src/sast.ts:66``
  embeds ``n.pos`` into the addressId). Tracking ``prev_end`` lets the
  scanner reproduce that offset exactly.
- ``nl_before``: whether a line terminator precedes the token, needed
  for the scanner's ASI heuristics when counting members.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List

# Longest-match-first operator table. Only boundaries matter to the
# scanner, but multi-char operators must not be split (``=>`` vs ``=``,
# ``...`` vs ``.``), and ``/`` needs regex disambiguation.
_OPERATORS = [
    ">>>=", "...", "===", "!==", "**=", "<<=", ">>=", ">>>", "&&=", "||=", "??=",
    "=>", "==", "!=", "<=", ">=", "&&", "||", "??", "?.", "++", "--", "+=", "-=",
    "*=", "/=", "%=", "&=", "|=", "^=", "<<", ">>", "**",
    "{", "}", "(", ")", "[", "]", ";", ",", "<", ">", "+", "-", "*", "/", "%",
    "&", "|", "^", "!", "~", "?", ":", "=", ".", "@", "#",
]

IDENT = "ident"
NUMBER = "number"
STRING = "string"
TEMPLATE = "template"
REGEX = "regex"
PUNCT = "punct"

# After these identifier-like tokens a ``/`` begins a regex literal, not
# a division (they end a statement/expression context, not an operand).
_REGEX_ALLOWED_KEYWORDS = {
    "return", "typeof", "instanceof", "in", "of", "new", "delete", "void",
    "throw", "case", "do", "else", "yield", "await",
}

_IDENT_START = set("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ_$")
_IDENT_PART = _IDENT_START | set("0123456789")


@dataclass
class Token:
    type: str
    text: str
    start: int
    end: int
    prev_end: int
    nl_before: bool


class TokenizeError(ValueError):
    pass


def tokenize(text: str) -> List[Token]:
    toks: List[Token] = []
    i = 0
    n = len(text)
    prev_end = 0
    nl_before = False
    while i < n:
        c = text[i]
        if c in " \t\r\f\v":
            i += 1
            continue
        if c == "\n":
            nl_before = True
            i += 1
            continue
        if c == "/" and i + 1 < n:
            if text[i + 1] == "/":
                j = text.find("\n", i)
                i = n if j < 0 else j
                continue
            if text[i + 1] == "*":
                j = text.find("*/", i + 2)
                if j < 0:
                    i = n
                    continue
                if "\n" in text[i:j]:
                    nl_before = True
                i = j + 2
                continue
        start = i
        if c in _IDENT_START:
            while i < n and text[i] in _IDENT_PART:
                i += 1
            tok = Token(IDENT, text[start:i], start, i, prev_end, nl_before)
        elif c.isdigit() or (c == "." and i + 1 < n and text[i + 1].isdigit()):
            while i < n and (text[i].isalnum() or text[i] in "._"):
                i += 1
            tok = Token(NUMBER, text[start:i], start, i, prev_end, nl_before)
        elif c in "'\"":
            i = _scan_string(text, i, c)
            tok = Token(STRING, text[start:i], start, i, prev_end, nl_before)
        elif c == "`":
            i = _scan_template(text, i)
            tok = Token(TEMPLATE, text[start:i], start, i, prev_end, nl_before)
        elif c == "/" and _regex_allowed(toks):
            i = _scan_regex(text, i)
            tok = Token(REGEX, text[start:i], start, i, prev_end, nl_before)
        else:
            op = _match_operator(text, i)
            if op is None:
                # Unknown byte (e.g. stray unicode): skip it rather than fail;
                # the scanner only needs declaration-shaped structure.
                i += 1
                continue
            i += len(op)
            tok = Token(PUNCT, op, start, i, prev_end, nl_before)
        toks.append(tok)
        prev_end = tok.end
        nl_before = False
    return toks


def _match_operator(text: str, i: int) -> str | None:
    for op in _OPERATORS:
        if text.startswith(op, i):
            return op
    return None


def _regex_allowed(toks: List[Token]) -> bool:
    if not toks:
        return True
    prev = toks[-1]
    if prev.type in (NUMBER, STRING, TEMPLATE, REGEX):
        return False
    if prev.type == IDENT:
        return prev.text in _REGEX_ALLOWED_KEYWORDS
    return prev.text not in (")", "]", "}", "++", "--")


def _scan_string(text: str, i: int, quote: str) -> int:
    n = len(text)
    i += 1
    while i < n:
        c = text[i]
        if c == "\\":
            i += 2
            continue
        if c == quote or c == "\n":
            return i + 1
        i += 1
    return n


def _scan_regex(text: str, i: int) -> int:
    n = len(text)
    i += 1
    in_class = False
    while i < n:
        c = text[i]
        if c == "\\":
            i += 2
            continue
        if c == "[":
            in_class = True
        elif c == "]":
            in_class = False
        elif c == "/" and not in_class:
            i += 1
            while i < n and text[i] in _IDENT_PART:
                i += 1
            return i
        elif c == "\n":
            return i
        i += 1
    return n


def _scan_template(text: str, i: int) -> int:
    """Scan a template literal starting at the backtick; returns the end
    offset. Substitutions ``${...}`` may nest strings, templates, and
    braces arbitrarily."""
    n = len(text)
    i += 1
    while i < n:
        c = text[i]
        if c == "\\":
            i += 2
            continue
        if c == "`":
            return i + 1
        if c == "$" and i + 1 < n and text[i + 1] == "{":
            i = _scan_substitution(text, i + 2)
            continue
        i += 1
    return n


def _scan_substitution(text: str, i: int) -> int:
    n = len(text)
    depth = 1
    while i < n:
        c = text[i]
        if c == "\\":
            i += 2
            continue
        if c in "'\"":
            i = _scan_string(text, i, c)
            continue
        if c == "`":
            i = _scan_template(text, i)
            continue
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
            if depth == 0:
                return i + 1
        i += 1
    return n
