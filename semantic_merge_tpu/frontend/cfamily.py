"""C-family (Java / C#) declaration scanner.

The reference ships Java and C# backends only as ``NotImplementedError``
stubs (reference ``semmerge/lang/java/bridge.py:4-8``,
``semmerge/lang/cs/bridge.py:4-8``) with the real designs deferred to
its P1 roadmap (reference ``architecture.md`` §language backends,
``requirements.md`` [LNG-*]). This module implements them for real: a
token-level structural indexer for the two languages, producing the
same :class:`~semantic_merge_tpu.frontend.scanner.DeclNode` records the
TypeScript frontend produces, so the entire downstream pipeline —
diff/lift (:mod:`semantic_merge_tpu.core.difflift`), device kernels,
compose, conflicts, applier — is shared across languages.

Indexing scheme (designed to mirror the TS scheme so cross-language
behavior is uniform):

- Indexed kinds: type declarations (``class`` / ``interface`` /
  ``enum`` / ``record`` / ``struct`` / ``@interface``), methods and
  constructors, fields, and C# properties — at any nesting depth.
- ``addressId = <file>::<name>::<pos>`` with ``pos`` the declaration's
  full start (the end offset of the token preceding its first token,
  annotations/attributes/modifiers included) — the same ``node.pos``
  semantics as the TS frontend (reference ``workers/ts/src/sast.ts:66``).
- ``symbolId`` = first 16 hex of sha256 over a **name-free** structural
  signature: methods → ``fn(<paramTypes>)-><retType>``; constructors →
  ``ctor(<paramTypes>)``; classes → ``class{N}`` (N = direct member
  count); interfaces → ``iface{N}``; enums → ``enum{N}`` (constant
  count); records → ``record{N}`` (component count); structs →
  ``struct{N}``; fields → ``vars{N}`` (declarator count); properties →
  ``prop:<type>``. Same-shape declarations therefore collide exactly as
  they do in the TS frontend (last-wins map semantics downstream) —
  uniform quirks, uniform parity tests.

The tokenizer is shared with the TS frontend — Java/C# token structure
is close enough (strings, comments, operators); constructs the TS
tokenizer over-recognizes (regex/template literals) cannot appear in
valid Java/C# sources in positions that change declaration boundaries.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

from ..core.ids import symbol_id_from_signature
from .scanner import DeclNode, normalize_path
from .tokenizer import IDENT, NUMBER, PUNCT, STRING, Token, tokenize

KIND_TYPE = {
    "class": "ClassDeclaration",
    "interface": "InterfaceDeclaration",
    "enum": "EnumDeclaration",
    "record": "RecordDeclaration",
    "struct": "StructDeclaration",
}
KIND_METHOD = "MethodDeclaration"
KIND_CTOR = "ConstructorDeclaration"
KIND_FIELD = "FieldDeclaration"
KIND_PROPERTY = "PropertyDeclaration"

_SIG_PREFIX = {
    "ClassDeclaration": "class",
    "InterfaceDeclaration": "iface",
    "EnumDeclaration": "enum",
    "RecordDeclaration": "record",
    "StructDeclaration": "struct",
}


@dataclass(frozen=True)
class LanguageSpec:
    name: str
    extensions: frozenset
    type_keywords: frozenset          # keywords that open a type declaration
    modifiers: frozenset              # skipped when finding decl heads
    control_keywords: frozenset       # never method names
    has_properties: bool              # C# `T Name { get; set; }`
    namespace_keywords: frozenset     # bodies to recurse straight into


JAVA = LanguageSpec(
    name="java",
    extensions=frozenset({".java"}),
    type_keywords=frozenset({"class", "interface", "enum", "record"}),
    modifiers=frozenset({
        "public", "protected", "private", "static", "final", "abstract",
        "synchronized", "native", "strictfp", "transient", "volatile",
        "default", "sealed", "non-sealed",
    }),
    control_keywords=frozenset({
        "if", "while", "for", "switch", "catch", "return", "throw", "new",
        "do", "else", "try", "finally", "assert", "synchronized", "super",
        "this", "yield",
    }),
    has_properties=False,
    namespace_keywords=frozenset(),
)

CSHARP = LanguageSpec(
    name="cs",
    extensions=frozenset({".cs"}),
    type_keywords=frozenset({"class", "interface", "enum", "record", "struct"}),
    modifiers=frozenset({
        "public", "protected", "private", "internal", "static", "readonly",
        "sealed", "abstract", "virtual", "override", "async", "partial",
        "extern", "unsafe", "new", "volatile", "const", "required", "ref",
    }),
    control_keywords=frozenset({
        "if", "while", "for", "foreach", "switch", "catch", "return",
        "throw", "do", "else", "try", "finally", "using", "lock", "base",
        "this", "new", "nameof", "typeof", "default", "checked", "unchecked",
    }),
    has_properties=True,
    namespace_keywords=frozenset({"namespace"}),
)


def scan_snapshot_cfamily(files, spec: LanguageSpec) -> List[DeclNode]:
    """Index every file of a snapshot with the given language spec."""
    nodes: List[DeclNode] = []
    for f in files:
        nodes.extend(scan_file_cfamily(f["path"], f["content"], spec))
    return nodes


def scan_file_cfamily(path: str, content: str, spec: LanguageSpec) -> List[DeclNode]:
    toks = tokenize(content)
    nodes: List[DeclNode] = []
    _scan_region(normalize_path(path), toks, 0, len(toks), spec, None, nodes)
    return nodes


# ---------------------------------------------------------------------------
# region / body scanning


def _matching(toks: List[Token], i: int, open_t: str, close_t: str) -> int:
    """Index of the token closing the ``open_t`` at *i* (or last index)."""
    depth = 0
    n = len(toks)
    while i < n:
        if toks[i].text == open_t:
            depth += 1
        elif toks[i].text == close_t:
            depth -= 1
            if depth == 0:
                return i
        i += 1
    return n - 1


def _scan_region(path: str, toks: List[Token], lo: int, hi: int,
                 spec: LanguageSpec, enclosing: Optional[str],
                 nodes: List[DeclNode]) -> int:
    """Scan ``[lo, hi)`` for declarations; returns the member count of
    the region (the N of the enclosing type's signature)."""
    members = 0
    i = lo
    seg_start = lo  # first token of the current member/statement head
    while i < hi:
        t = toks[i]
        text = t.text
        if text in ("}", ")"):
            i += 1
            seg_start = i
            continue
        if t.type == IDENT and text in spec.namespace_keywords:
            # `namespace X { ... }` (or C# file-scoped `namespace X;`):
            # recurse straight into the body; namespaces are not indexed.
            j = i + 1
            while j < hi and toks[j].text not in ("{", ";"):
                j += 1
            if j < hi and toks[j].text == "{":
                close = _matching(toks, j, "{", "}")
                _scan_region(path, toks, j + 1, close, spec, None, nodes)
                i = close + 1
            else:
                i = j + 1
            seg_start = i
            continue
        if t.type == IDENT and text == "non" and i + 2 < hi \
                and toks[i + 1].text == "-" and toks[i + 2].text == "sealed":
            # Java `non-sealed` tokenizes as three tokens.
            i += 3
            continue
        if t.type == IDENT and text in spec.type_keywords and _is_type_decl(toks, i, hi):
            i = _scan_type_decl(path, toks, seg_start, i, hi, spec, nodes)
            members += 1
            seg_start = i
            continue
        if (text == "@" and i + 1 < hi and toks[i + 1].text == "interface"
                and i + 2 < hi and toks[i + 2].type == IDENT):
            # Java annotation type — indexed as an interface.
            i = _scan_type_decl(path, toks, seg_start, i + 1, hi, spec, nodes,
                                kind_override="InterfaceDeclaration")
            members += 1
            seg_start = i
            continue
        if t.type == IDENT and text in spec.modifiers:
            # Walk over decl modifiers token-wise so a following type
            # keyword is still seen; seg_start stays at the decl's first
            # token (full-start semantics).
            i += 1
            continue
        if text == "@" and i + 1 < hi and toks[i + 1].type == IDENT:
            # Annotation before a declaration head: @Foo, @a.b.Foo(...)
            i += 2
            while i + 1 < hi and toks[i].text == ".":
                i += 2
            if i < hi and toks[i].text == "(":
                i = _matching(toks, i, "(", ")") + 1
            continue
        if enclosing is not None:
            member, i = _scan_member(path, toks, seg_start, i, hi, spec, enclosing, nodes)
            if member is not None:
                nodes.append(member)
                members += 1
            seg_start = i
            continue
        # File/namespace scope, not a type decl head: skip the statement
        # (package/import/using directives, attributes, top-level code).
        if text == "{":
            i = _matching(toks, i, "{", "}") + 1
        elif text == "[" and spec.has_properties:
            i = _matching(toks, i, "[", "]") + 1  # C# attribute
        else:
            while i < hi and toks[i].text not in (";", "{"):
                i += 1
            if i < hi and toks[i].text == "{":
                continue  # let the block skip above handle it
            i += 1
        seg_start = i
    return members


def _is_type_decl(toks: List[Token], i: int, hi: int) -> bool:
    """``class``/``enum``/... followed by an identifier — and not used as
    an identifier itself (``record`` is contextual in both languages)."""
    if i + 1 >= hi or toks[i + 1].type != IDENT:
        return False
    if i > 0 and toks[i - 1].text in (".", "::", "?."):
        return False
    return True


def _full_start(toks: List[Token], seg_start: int) -> int:
    return toks[seg_start].prev_end if seg_start < len(toks) else 0


# ---------------------------------------------------------------------------
# type declarations


def _scan_type_decl(path: str, toks: List[Token], seg_start: int, i: int,
                    hi: int, spec: LanguageSpec, nodes: List[DeclNode],
                    kind_override: str | None = None) -> int:
    keyword = toks[i].text
    if keyword == "record" and i + 2 < hi and toks[i + 1].text in ("struct", "class") \
            and toks[i + 2].type == IDENT:
        # C# `record struct P` / `record class P` — name after both keywords.
        i += 1
    kind = kind_override or KIND_TYPE[keyword]
    name = toks[i + 1].text
    pos = _full_start(toks, seg_start)
    j = i + 2
    j = _skip_generics(toks, j, hi)
    record_components = None
    if j < hi and toks[j].text == "(":  # record header (Java / C# record)
        close = _matching(toks, j, "(", ")")
        record_components = _count_top_level_commas(toks, j + 1, close) if close > j + 1 else 0
        j = close + 1
    # extends / implements / permits / where / primary-ctor base — skip to body.
    while j < hi and toks[j].text not in ("{", ";"):
        j += 1
    end = toks[j].end if j < hi else (toks[hi - 1].end if hi else 0)
    body_members = 0
    if j < hi and toks[j].text == "{":
        close = _matching(toks, j, "{", "}")
        end = toks[close].end
        if kind == "EnumDeclaration":
            body_members = _scan_enum_body(path, toks, j, close, spec, name, nodes)
        else:
            body_members = _scan_region(path, toks, j + 1, close, spec, name, nodes)
        j = close + 1
    else:
        j = min(j + 1, hi)

    if kind == "EnumDeclaration":
        n = body_members  # constant count
    elif record_components is not None:
        n = record_components
    else:
        n = body_members
    sig = f"{_SIG_PREFIX[kind]}{{{n}}}"
    nodes.insert(_insert_at(nodes, pos, path), DeclNode(
        symbolId=symbol_id_from_signature(sig),
        addressId=f"{path}::{name}::{pos}",
        kind=kind, name=name, file=path, pos=pos,
        end=end, signature=sig,
    ))
    return j


def _insert_at(nodes: List[DeclNode], pos: int, path: str) -> int:
    """Document-order insertion point: parents list before their members,
    matching the TS frontend's pre-order listing. Members of this file
    scanned before the parent (the parent's record is built after its
    body) slot after it by position."""
    k = len(nodes)
    while k > 0 and nodes[k - 1].file == path and nodes[k - 1].pos > pos:
        k -= 1
    return k


def _scan_enum_body(path: str, toks: List[Token], i_open: int, i_close: int,
                    spec: LanguageSpec, name: str, nodes: List[DeclNode]) -> int:
    """Count the constants; index any members after the ``;``."""
    i = i_open + 1
    constants = 0
    expect_const = True
    while i < i_close:
        t = toks[i]
        if t.text == ";":
            _scan_region(path, toks, i + 1, i_close, spec, name, nodes)
            break
        if t.text == ",":
            expect_const = True
            i += 1
            continue
        if expect_const and t.type == IDENT:
            constants += 1
            expect_const = False
            i += 1
            continue
        if t.text == "(":
            i = _matching(toks, i, "(", ")") + 1
            continue
        if t.text == "{":  # constant body (Java) — skip
            i = _matching(toks, i, "{", "}") + 1
            continue
        if t.text == "=":  # C# explicit value — skip to , or ;
            while i < i_close and toks[i].text not in (",", ";"):
                i += 1
            continue
        i += 1
    return constants


# ---------------------------------------------------------------------------
# members (methods / constructors / fields / properties)


def _scan_member(path: str, toks: List[Token], seg_start: int, i: int, hi: int,
                 spec: LanguageSpec, enclosing: str,
                 nodes: List[DeclNode]) -> Tuple[Optional[DeclNode], int]:
    """Parse one member whose head starts at ``seg_start``; *i* is the
    current cursor (== seg_start on entry for a fresh member)."""
    # Skip leading annotations/attributes and modifiers to the head's
    # type-and-name part.
    j = seg_start
    j = _skip_decorations(toks, j, hi, spec)
    if j >= hi or toks[j].text in ("}", ";"):
        return None, min(j + 1, hi) if j < hi and toks[j].text == ";" else max(j, i + 1)
    if toks[j].text == "{":
        # Initializer block (static { ... } already had its modifier skipped).
        return None, _matching(toks, j, "{", "}") + 1
    # Walk to the decisive token at angle/bracket depth 0.
    head_start = j
    k = j
    angle = 0
    while k < hi:
        text = toks[k].text
        if text == "<":
            angle += 1
        elif text in (">", ">>", ">>>"):
            angle = max(0, angle - text.count(">"))
        elif angle == 0 and text in ("(", "=", ";", "{", "}", "=>"):
            break
        k += 1
    if k >= hi:
        return None, hi
    decisive = toks[k].text
    pos = _full_start(toks, seg_start)

    if decisive == "(":
        name_tok = toks[k - 1] if k - 1 >= head_start else None
        if (name_tok is None or name_tok.type != IDENT
                or name_tok.text in spec.control_keywords):
            # Not a member head (e.g. stray code) — skip the parens.
            return None, _matching(toks, k, "(", ")") + 1
        close = _matching(toks, k, "(", ")")
        params = _render_param_types(toks, k + 1, close, spec)
        ret = _render_type(toks, head_start, k - 1, spec)
        is_ctor = name_tok.text == enclosing and ret == ""
        # Skip throws-clause / where-clause / C# expression body to the
        # body or terminator.
        m = close + 1
        while m < hi and toks[m].text not in ("{", ";", "=>"):
            m += 1
        end = toks[close].end
        if m < hi and toks[m].text == "{":
            body_close = _matching(toks, m, "{", "}")
            end = toks[body_close].end
            m = body_close + 1
        elif m < hi and toks[m].text == "=>":
            while m < hi and toks[m].text != ";":
                m += 1
            end = toks[min(m, hi - 1)].end
            m += 1
        elif m < hi:
            end = toks[m].end
            m += 1
        if is_ctor:
            sig = f"ctor({params})"
            kind = KIND_CTOR
        else:
            sig = f"fn({params})->{ret or 'void'}"
            kind = KIND_METHOD
        return DeclNode(
            symbolId=symbol_id_from_signature(sig),
            addressId=f"{path}::{name_tok.text}::{pos}",
            kind=kind, name=name_tok.text, file=path, pos=pos,
            end=end, signature=sig,
        ), m

    if decisive == "{" and spec.has_properties:
        name_tok = toks[k - 1] if k - 1 > head_start else None
        if name_tok is not None and name_tok.type == IDENT:
            close = _matching(toks, k, "{", "}")
            ptype = _render_type(toks, head_start, k - 1, spec)
            m = close + 1
            # C# property initializer: `{ get; set; } = value;`
            if m < hi and toks[m].text == "=":
                while m < hi and toks[m].text != ";":
                    m += 1
                m += 1
            sig = f"prop:{ptype or 'var'}"
            return DeclNode(
                symbolId=symbol_id_from_signature(sig),
                addressId=f"{path}::{name_tok.text}::{pos}",
                kind=KIND_PROPERTY, name=name_tok.text, file=path, pos=pos,
                end=toks[close].end, signature=sig,
            ), m
        return None, _matching(toks, k, "{", "}") + 1
    if decisive == "{":
        return None, _matching(toks, k, "{", "}") + 1

    if decisive == "=>" and spec.has_properties:
        # C# expression-bodied property: `public int X => expr;`
        name_tok = toks[k - 1] if k - 1 > head_start else None
        if name_tok is not None and name_tok.type == IDENT:
            ptype = _render_type(toks, head_start, k - 1, spec)
            m = k
            while m < hi and toks[m].text != ";":
                if toks[m].text == "{":
                    m = _matching(toks, m, "{", "}")
                m += 1
            sig = f"prop:{ptype or 'var'}"
            return DeclNode(
                symbolId=symbol_id_from_signature(sig),
                addressId=f"{path}::{name_tok.text}::{pos}",
                kind=KIND_PROPERTY, name=name_tok.text, file=path, pos=pos,
                end=toks[min(m, hi - 1)].end, signature=sig,
            ), m + 1

    if decisive in ("=", ";"):
        # Field declaration: `<type> a = ..., b;` — count declarators.
        # Legacy array suffix (`int a[];`) puts brackets between the
        # name and the decisive token — but only *empty* `[]` pairs walk
        # back, so `arr[idx] = val;` stays a bare statement, not a field.
        name_at = k - 1
        while (name_at - 1 >= head_start and toks[name_at].text == "]"
               and toks[name_at - 1].text == "["):
            name_at -= 2
        name_tok = toks[name_at] if name_at >= head_start else None
        if name_tok is None or name_tok.type != IDENT or name_at == head_start:
            # No type+name pair — a bare statement; skip it.
            m = k
            while m < hi and toks[m].text != ";":
                if toks[m].text == "{":
                    m = _matching(toks, m, "{", "}")
                m += 1
            return None, m + 1
        count = 1
        m = k
        last_end = toks[k - 1].end
        while m < hi:
            text = toks[m].text
            if text in ("(", "[", "{"):
                m = _matching(toks, m, text, {"(": ")", "[": "]", "{": "}"}[text])
                last_end = toks[m].end
            elif text == ",":
                # A declarator comma is followed by `name` then
                # `=`/`,`/`;`/`[` — commas inside generic arguments
                # (`Map<String,Integer>`) fail this lookahead.
                if (m + 1 < hi and toks[m + 1].type == IDENT
                        and m + 2 < hi and toks[m + 2].text in ("=", ",", ";", "[")):
                    count += 1
            elif text == ";":
                last_end = toks[m].end
                break
            else:
                last_end = toks[m].end
            m += 1
        sig = f"vars{{{count}}}"
        return DeclNode(
            symbolId=symbol_id_from_signature(sig),
            addressId=f"{path}::{name_tok.text}::{pos}",
            kind=KIND_FIELD, name=name_tok.text, file=path, pos=pos,
            end=last_end, signature=sig,
        ), min(m + 1, hi)

    return None, k + 1


def _skip_decorations(toks: List[Token], j: int, hi: int,
                      spec: LanguageSpec) -> int:
    """Skip annotations (``@Foo``, ``@Foo(...)``), C# attributes
    (``[Foo]``), and modifier keywords before a member head."""
    while j < hi:
        t = toks[j]
        if t.text == "@" and j + 1 < hi and toks[j + 1].type == IDENT:
            j += 2
            while j < hi and toks[j].text == ".":
                j += 2
            if j < hi and toks[j].text == "(":
                j = _matching(toks, j, "(", ")") + 1
            continue
        if t.text == "[" and spec.has_properties:
            j = _matching(toks, j, "[", "]") + 1
            continue
        if t.type == IDENT and t.text in spec.modifiers:
            # `new` is a C# modifier only right before a member head —
            # but also an expression keyword; in head position both skip.
            j += 1
            continue
        if t.type == IDENT and t.text == "non" and j + 2 < hi \
                and toks[j + 1].text == "-" and toks[j + 2].text == "sealed":
            j += 3
            continue
        break
    return j


def _skip_generics(toks: List[Token], j: int, hi: int) -> int:
    if j < hi and toks[j].text == "<":
        depth = 0
        while j < hi:
            text = toks[j].text
            if text == "<":
                depth += 1
            elif text in (">", ">>", ">>>"):
                depth -= text.count(">")
                if depth <= 0:
                    return j + 1
            j += 1
    return j


def _count_top_level_commas(toks: List[Token], lo: int, hi: int) -> int:
    if lo >= hi:
        return 0
    depth = 0
    count = 1
    for m in range(lo, hi):
        text = toks[m].text
        if text in ("(", "[", "<", "{"):
            depth += 1
        elif text in (")", "]", "}", ">"):
            depth -= 1
        elif text == "," and depth == 0:
            count += 1
    return count


# ---------------------------------------------------------------------------
# signature rendering (name-free types)


def _render_type(toks: List[Token], lo: int, hi: int, spec: LanguageSpec) -> str:
    """Render tokens ``[lo, hi)`` as a canonical type string: modifier
    keywords dropped, single spaces only between adjacent word tokens."""
    parts: List[str] = []
    prev_word = False
    for m in range(lo, hi):
        t = toks[m]
        if t.type == IDENT and t.text in spec.modifiers:
            continue
        word = t.type in (IDENT, NUMBER, STRING)
        if word and prev_word:
            parts.append(" ")
        parts.append(t.text)
        prev_word = word
    return "".join(parts)


def _render_param_types(toks: List[Token], lo: int, hi: int,
                        spec: LanguageSpec) -> str:
    """Comma-joined parameter *types* (names stripped): each top-level
    comma segment renders without its final identifier. Varargs dots and
    array brackets stay; parameter annotations/attributes drop."""
    if lo >= hi:
        return ""
    segments: List[Tuple[int, int]] = []
    depth = 0
    start = lo
    for m in range(lo, hi):
        text = toks[m].text
        if text in ("(", "[", "<", "{"):
            depth += 1
        elif text in (")", "]", "}", ">"):
            depth -= 1
        elif text == "," and depth == 0:
            segments.append((start, m))
            start = m + 1
    segments.append((start, hi))

    rendered = []
    for s_lo, s_hi in segments:
        s_lo = _skip_decorations(toks, s_lo, s_hi, spec)
        # Default value `= expr` truncates the segment.
        cut = s_hi
        d = 0
        for m in range(s_lo, s_hi):
            text = toks[m].text
            if text in ("(", "[", "<", "{"):
                d += 1
            elif text in (")", "]", "}", ">"):
                d -= 1
            elif text == "=" and d == 0:
                cut = m
                break
        # The trailing identifier is the parameter name (legacy Java
        # array suffix `a[]` keeps the brackets with the type).
        name_idx = None
        trailing = cut
        while trailing - 1 >= s_lo and toks[trailing - 1].text in ("[", "]"):
            trailing -= 1
        if trailing - 1 >= s_lo and toks[trailing - 1].type == IDENT:
            prev = toks[trailing - 2] if trailing - 2 >= s_lo else None
            if prev is not None and (prev.type == IDENT or prev.text in
                                     (">", "]", "?", "...", "*")):
                name_idx = trailing - 1
        if name_idx is not None:
            body = _render_type(toks, s_lo, name_idx, spec)
            suffix = _render_type(toks, trailing, cut, spec) if trailing < cut else ""
            rendered.append(body + suffix)
        else:
            rendered.append(_render_type(toks, s_lo, cut, spec))
    return ",".join(r for r in rendered if r)
