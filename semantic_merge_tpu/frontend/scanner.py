"""TypeScript/JavaScript declaration scanner (parse + index).

This replaces the reference's Node.js worker parse/index stage
(reference ``workers/ts/src/sast.ts``) with a dependency-free host
implementation. Indexing semantics reproduced:

- The five indexed declaration kinds, found at *any* nesting depth
  (the reference walks every AST child recursively, reference
  ``workers/ts/src/sast.ts:44-60``): ``FunctionDeclaration``,
  ``ClassDeclaration``, ``InterfaceDeclaration``, ``EnumDeclaration``,
  ``VariableStatement``.
- Pre-order listing: declarations appear in document order of their
  first token, parents before nested children.
- ``addressId = <file>::<name|anon>::<pos>`` where ``pos`` is the
  declaration's *full start* — the end offset of the token preceding
  the declaration's first token (modifiers included), matching the TS
  parser's ``node.pos`` (reference ``workers/ts/src/sast.ts:65-67``).
- ``symbolId`` = first 16 hex chars of sha256 over a name-free
  structural signature (reference ``workers/ts/src/sast.ts:73-96``):
  functions → ``fn(<paramTypes>)-><retType>``; classes → ``class{N}``;
  interfaces → ``iface{N}``; enums → ``enum{N}``; variable statements
  → ``vars{N}``.
- Function expressions / class expressions / arrow functions are *not*
  indexed (they are not declaration statements), and ``var/let/const``
  inside ``for (...)`` heads are not VariableStatements.

Type-annotation rendering emulates ``checker.typeToString`` as the
reference configures it: the in-memory compiler host loads **no
default library** (``readFile`` returns ``""`` for anything outside the
snapshot, reference ``workers/ts/src/sast.ts:19-22``), so identifiers
that do not resolve to a type declared *in the snapshot* display as
``any``; annotated primitives display as written; ``T[]`` renders the
element type; unions/intersections are spaced ``A | B`` / ``A & B``.
Missing annotations are ``any`` (reference ``workers/ts/src/sast.ts:78,82``).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Hashable, List, Sequence

from ..core.ids import symbol_id_from_signature
from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans
from .tokenizer import IDENT, PUNCT, Token, tokenize

KIND_FUNCTION = "FunctionDeclaration"
KIND_CLASS = "ClassDeclaration"
KIND_INTERFACE = "InterfaceDeclaration"
KIND_ENUM = "EnumDeclaration"
KIND_VARS = "VariableStatement"

# Tokens after which ``function``/``class`` begin an *expression*, not a
# declaration statement.
_EXPRESSION_PREV = {
    "=", "(", "[", ",", ":", "?", "!", "&", "|", "+", "-", "*", "/", "%",
    "<", ">", "=>", "==", "===", "!=", "!==", "&&", "||", "??", "...",
    "+=", "-=", "*=", "/=", "??=", "&&=", "||=", ".", "?.",
}
_EXPRESSION_PREV_IDENTS = {
    "return", "typeof", "new", "delete", "void", "in", "of", "instanceof",
    "yield", "await", "case", "do", "throw", "extends", "default",
}

_DECL_MODIFIERS = {"export", "default", "declare", "async", "abstract", "public", "private", "protected"}

_PRIMITIVE_TYPES = {
    "string", "number", "boolean", "any", "unknown", "never", "void", "object",
    "undefined", "null", "bigint", "symbol", "this", "true", "false",
}


@dataclass(slots=True)
class DeclNode:
    """One indexed declaration — the unit the differ joins on.

    Mirrors the reference's ``NodeInfo`` record
    (reference ``workers/ts/src/sast.ts:4-10``).
    """

    symbolId: str
    addressId: str
    kind: str
    name: str | None
    file: str
    pos: int
    end: int
    signature: str

    def to_dict(self) -> dict:
        return {
            "symbolId": self.symbolId,
            "addressId": self.addressId,
            "kind": self.kind,
            "name": self.name,
            "range": {"file": self.file, "start": self.pos, "end": self.end},
        }


def normalize_path(p: str) -> str:
    """Path normalization, identical to the reference's
    (reference ``workers/ts/src/sast.ts:98-100``)."""
    p = p.replace("\\", "/")
    if p.startswith("./"):
        p = p[2:]
    if p.startswith("/"):
        p = p[1:]
    return p


def scan_snapshot(files: Sequence[dict]) -> List[DeclNode]:
    """Index every file of a snapshot (``[{path, content}, ...]``).

    Two passes: first collect the type names declared anywhere in the
    snapshot (the scanner's stand-in for the checker's symbol table),
    then scan each file, resolving annotations against that set. Files
    are processed in snapshot order, matching the program's source-file
    iteration in the reference (reference ``workers/ts/src/sast.ts:42``).

    When the C++ native frontend is available (``native/``) and the
    snapshot is ASCII, the scan runs there (same results, ~order of
    magnitude faster host path); this Python implementation is the
    semantic oracle and the fallback.

    Per-file results are memoized in the process-wide decl cache
    (:mod:`semantic_merge_tpu.frontend.declcache`): within one 3-way
    merge the base/left/right snapshots share almost every file, so only
    changed files re-scan.
    """
    return [n for _, nodes in scan_snapshot_keyed(files) for n in nodes]


def scan_snapshot_keyed(files: Sequence[dict]
                        ) -> List[tuple[Hashable | None, List[DeclNode]]]:
    """Like :func:`scan_snapshot` but grouped per file, each group tagged
    with a stable identity key ``(path, content-hash, declared-set-hash)``
    — exactly the decl-cache key, so downstream per-file caches (e.g. the
    device backend's encoded-column cache) can reuse it. ``None`` keys
    mean "no stable identity" (cache disabled)."""
    from ..errors import ParseFault
    from ..utils import faults
    from .declcache import global_cache
    faults.check("scan")
    cache = global_cache()
    hits0 = cache.hits if cache is not None else 0
    with obs_spans.span("scan", layer="frontend", files=len(files)):
        try:
            if cache is not None:
                keyed = _scan_snapshot_cached(files, cache)
            else:
                from . import native  # local import: native binds against this module
                nodes = native.try_scan_snapshot(files)
                if nodes is None:
                    nodes = scan_snapshot_py(files)
                keyed = _group_unkeyed(files, nodes)
        except ParseFault:
            raise
        except Exception as exc:
            # A parse/scan failure (native frontend abort, tokenizer
            # bug) is a contained frontend fault, not a raw traceback.
            raise ParseFault(f"snapshot scan failed: {exc}", stage="scan",
                             cause=type(exc).__name__) from exc
    reg = obs_metrics.REGISTRY
    reg.counter("semmerge_files_scanned_total",
                "Snapshot files handed to the decl scanner").inc(len(files))
    reg.counter("semmerge_decls_indexed_total",
                "Declarations indexed by the scanner").inc(
        sum(len(nodes) for _, nodes in keyed))
    if cache is not None:
        reg.counter("semmerge_decl_cache_hits_total",
                    "Decl-cache hits during snapshot scans").inc(
            cache.hits - hits0)
        reg.gauge("semmerge_decl_cache_entries",
                  "Cumulative decl-cache hit/miss counters of the "
                  "process-wide cache").set(cache.hits, kind="hits")
        reg.gauge("semmerge_decl_cache_entries").set(cache.misses,
                                                     kind="misses")
    return keyed


def _group_unkeyed(files: Sequence[dict], nodes: List[DeclNode]):
    by_file: Dict[str, List[DeclNode]] = {}
    for n in nodes:
        by_file.setdefault(n.file, []).append(n)
    return [(None, by_file.get(normalize_path(f["path"]), [])) for f in files]


# A file path that cannot collide with real snapshot paths carries the
# synthetic type declarations when a cache-miss subset scans natively
# (its nodes are filtered out of the result).
_SYNTH_PATH = "__semmerge_synthetic_decls__.d.ts"


def _scan_snapshot_cached(files: Sequence[dict], cache
                          ) -> List[tuple[Hashable, List[DeclNode]]]:
    from .declcache import content_hash, declared_hash

    # Pass 1 — the global declared-type-name set, from per-file cached
    # name sets (cache key: content hash alone; names don't depend on
    # other files). Misses batch through the native tokenizer when
    # available so a cold scan stays native-speed.
    from . import native
    hashes: List[str] = []
    toks_for: Dict[int, List[Token]] = {}
    name_sets: List[frozenset | None] = []
    type_miss: List[int] = []
    for idx, f in enumerate(files):
        h = content_hash(f["content"])
        hashes.append(h)
        names = cache.get(("types", h))
        if names is None:
            type_miss.append(idx)
        name_sets.append(names)

    if files and len(type_miss) == len(files):
        # Fully cold (nothing cached for any content): one combined
        # native pass yields names + nodes together — no duplicate
        # tokenize, no synthetic-decls file.
        combined = native.try_scan_with_names(files)
        if combined is not None:
            per_file_names, nodes = combined
            declared = set().union(*per_file_names) if per_file_names else set()
            dh = declared_hash(declared)
            by_file: Dict[str, List[DeclNode]] = {}
            for n in nodes:
                by_file.setdefault(n.file, []).append(n)
            keyed = []
            for idx, f in enumerate(files):
                path = normalize_path(f["path"])
                key = ("decls", path, hashes[idx], dh)
                cache.put(("types", hashes[idx]), per_file_names[idx])
                cache.put(key, by_file.get(path, []))
                keyed.append((key, by_file.get(path, [])))
            return keyed

    if type_miss:
        native_names = native.try_type_names([files[i] for i in type_miss])
        for j, idx in enumerate(type_miss):
            if native_names is not None:
                names = native_names[j]
            else:
                toks = tokenize(files[idx]["content"])
                toks_for[idx] = toks
                names = frozenset(_collect_type_names(toks))
            cache.put(("types", hashes[idx]), names)
            name_sets[idx] = names
    declared: set[str] = set().union(*name_sets) if name_sets else set()
    dh = declared_hash(declared)

    # Pass 2 — per-file decl nodes keyed by (path, content, declared
    # set). Keys are built exactly once (this loop runs 30k×/snapshot on
    # the 10k-file rung; redundant tuple/path work showed in profiles).
    get = cache.get
    keys = [("decls", normalize_path(f["path"]), h, dh)
            for f, h in zip(files, hashes)]
    out_slots: List[List[DeclNode] | None] = [get(k) for k in keys]
    miss_idx = [i for i, v in enumerate(out_slots) if v is None]

    if miss_idx:
        scanned = _scan_subset([files[i] for i in miss_idx], declared,
                               [toks_for.get(i) for i in miss_idx])
        for slot, nodes in zip(miss_idx, scanned):
            out_slots[slot] = nodes
            cache.put(keys[slot], nodes)

    return [(k, v or []) for k, v in zip(keys, out_slots)]


def _scan_subset(files: Sequence[dict], declared: set[str],
                 toks: Sequence[List[Token] | None]) -> List[List[DeclNode]]:
    """Scan a subset of a snapshot against a *global* declared set;
    returns per-file node lists in input order."""
    from . import native

    # Native path: prepend a synthetic file declaring every global type
    # name, so the library's internally-computed declared set equals the
    # full snapshot's; its nodes are dropped from the result.
    if not any(normalize_path(f["path"]) == _SYNTH_PATH for f in files):
        synth_names = sorted(n for n in declared if n.isascii())
        if len(synth_names) == len(declared):
            synth = {"path": _SYNTH_PATH,
                     "content": "".join(f"interface {n} {{}}\n" for n in synth_names)}
            nodes = native.try_scan_snapshot([synth, *files])
            if nodes is not None:
                by_file: Dict[str, List[DeclNode]] = {}
                for n in nodes:
                    if n.file != _SYNTH_PATH:
                        by_file.setdefault(n.file, []).append(n)
                return [by_file.get(normalize_path(f["path"]), []) for f in files]

    out: List[List[DeclNode]] = []
    for f, t in zip(files, toks):
        if t is None:
            t = tokenize(f["content"])
        out.append(_scan_tokens(normalize_path(f["path"]), t, declared))
    return out


def scan_snapshot_py(files: Sequence[dict]) -> List[DeclNode]:
    """The pure-Python snapshot scan (oracle path)."""
    declared = set()
    tokens_by_file: List[tuple[str, List[Token]]] = []
    for f in files:
        path = normalize_path(f["path"])
        toks = tokenize(f["content"])
        tokens_by_file.append((path, toks))
        declared |= _collect_type_names(toks)
    nodes: List[DeclNode] = []
    for path, toks in tokens_by_file:
        nodes.extend(_scan_tokens(path, toks, declared))
    return nodes


def scan_file(path: str, content: str) -> List[DeclNode]:
    """Index a single file in isolation (type names resolve only
    against declarations in this file)."""
    toks = tokenize(content)
    return _scan_tokens(normalize_path(path), toks, _collect_type_names(toks))


# ---------------------------------------------------------------------------
# Pass 1: declared type names


def _collect_type_names(toks: List[Token]) -> set[str]:
    """Names introduced by class / interface / enum / type-alias
    declarations — the names a type annotation can resolve to."""
    names = set()
    for i, t in enumerate(toks):
        if t.type != IDENT or i + 1 >= len(toks):
            continue
        nxt = toks[i + 1]
        if t.text in ("class", "interface", "enum", "type") and nxt.type == IDENT:
            if t.text == "type" and (i + 2 >= len(toks) or toks[i + 2].text not in ("=", "<")):
                continue
            if _is_expression_position(toks, i) and t.text in ("class",):
                continue
            names.add(nxt.text)
    return names


# ---------------------------------------------------------------------------
# Pass 2: declaration scan


def _scan_tokens(path: str, toks: List[Token], declared: set[str]) -> List[DeclNode]:
    nodes: List[DeclNode] = []
    n = len(toks)
    for i in range(n):
        t = toks[i]
        if t.type != IDENT:
            continue
        word = t.text
        if word == "function":
            node = _scan_function(path, toks, i, declared)
        elif word == "class":
            node = _scan_braced_decl(path, toks, i, KIND_CLASS)
        elif word == "interface":
            node = _scan_braced_decl(path, toks, i, KIND_INTERFACE)
        elif word == "enum":
            node = _scan_braced_decl(path, toks, i, KIND_ENUM)
        elif word in ("var", "let", "const"):
            node = _scan_var_statement(path, toks, i)
        else:
            node = None
        if node is not None:
            nodes.append(node)
    return nodes


def _is_expression_position(toks: List[Token], i: int) -> bool:
    """True when the construct whose head keyword is at index *i* sits in
    expression position (→ function/class *expression*, not indexed)."""
    j = i - 1
    # Walk back over the construct's own modifiers; they are part of the
    # declaration node, so the expression/statement test applies before them.
    while j >= 0 and toks[j].type == IDENT and toks[j].text in _DECL_MODIFIERS:
        # ``export default function`` is a declaration, but ``x = default`` is
        # not valid — treating default/export as transparent is safe.
        j -= 1
    if j < 0:
        return False
    prev = toks[j]
    if prev.type == PUNCT:
        return prev.text in _EXPRESSION_PREV
    if prev.type == IDENT:
        return prev.text in _EXPRESSION_PREV_IDENTS
    return True  # literal directly before => malformed/expression-ish; skip


def _full_start(toks: List[Token], i: int) -> int:
    """The declaration's ``pos``: walk back over modifier tokens — and
    decorators, which TS parses as part of the declaration node (a
    ``@dec class C`` node's span starts at the decorator) — to the
    first token of the declaration node, then take the preceding
    token's end offset (0 at file start) — TS ``node.pos`` semantics."""
    j = i
    while j - 1 >= 0:
        prev = toks[j - 1]
        if prev.type == IDENT and prev.text in _DECL_MODIFIERS:
            j -= 1
            continue
        # Decorator: ``@ Name``, ``@ ns.Name``, or either with a call
        # ``(...)`` — immediately before the declaration head / its
        # modifiers.
        if prev.text == ")":
            k = j - 1
            depth = 0
            while k >= 0:
                if toks[k].text == ")":
                    depth += 1
                elif toks[k].text == "(":
                    depth -= 1
                    if depth == 0:
                        break
                k -= 1
            start = _decorator_start(toks, k)
            if start is not None:
                j = start
                continue
        if prev.type == IDENT:
            start = _decorator_start(toks, j)
            if start is not None:
                j = start
                continue
        break
    return toks[j].prev_end


def _decorator_start(toks: List[Token], j: int) -> int | None:
    """Index of the ``@`` starting a (possibly dotted) decorator name
    that ends just before *j* — ``@Name`` / ``@ns.sub.Name`` — or
    ``None`` if tokens before *j* are not a decorator name."""
    t = j - 1
    if t < 0 or toks[t].type != IDENT:
        return None
    while t - 2 >= 0 and toks[t - 1].text == "." and toks[t - 2].type == IDENT:
        t -= 2
    if t - 1 >= 0 and toks[t - 1].text == "@":
        return t - 1
    return None


def _skip_type_params(toks: List[Token], i: int) -> int:
    """Skip ``<...>`` starting at *i* (if present); returns index after."""
    return _type_param_names(toks, i)[1]


def _type_param_names(toks: List[Token], i: int) -> tuple:
    """``(names, index_after)`` for a ``<T, U extends X = Y>`` list at
    *i* (empty names if absent). Type parameters resolve *lexically* —
    the reference checker renders a type-parameter reference by its
    name regardless of the missing default lib
    (``checker.typeToString`` of a TypeParameter prints the parameter
    name; reference ``workers/ts/src/sast.ts:78-83``) — so the
    signature renderers must treat these names as in-scope types."""
    names: list = []
    if i < len(toks) and toks[i].text == "<":
        depth = 0
        expecting = False
        while i < len(toks):
            t = toks[i].text
            if t == "<":
                depth += 1
                if depth == 1:
                    expecting = True
            elif t in (">", ">>", ">>>"):
                depth -= t.count(">")
                if depth <= 0:
                    return names, i + 1
            elif depth == 1 and t == ",":
                expecting = True
            elif (expecting and depth == 1 and toks[i].type == IDENT
                    and t not in ("const", "in", "out")):
                names.append(t)
                expecting = False
            i += 1
    return names, i


def _matching_brace(toks: List[Token], i: int) -> int:
    """Index of the ``}`` matching the ``{`` at *i* (or last token)."""
    depth = 0
    n = len(toks)
    while i < n:
        if toks[i].text == "{":
            depth += 1
        elif toks[i].text == "}":
            depth -= 1
            if depth == 0:
                return i
        i += 1
    return n - 1


def _scan_function(path: str, toks: List[Token], i: int, declared: set[str]) -> DeclNode | None:
    if _is_expression_position(toks, i):
        return None
    n = len(toks)
    j = i + 1
    if j < n and toks[j].text == "*":  # generator
        j += 1
    name = None
    if j < n and toks[j].type == IDENT:
        name = toks[j].text
        j += 1
    tp_names, j = _type_param_names(toks, j)
    if j >= n or toks[j].text != "(":
        return None
    if name is None and not _has_default_modifier(toks, i):
        # A nameless ``function (`` in statement position is not a valid
        # declaration unless it is ``export default function``.
        return None
    # The decl's own type parameters are lexically in scope for its
    # param/return annotations and render by name (checker semantics).
    local = declared | set(tp_names) if tp_names else declared
    params_start = j
    params_end = _matching_paren(toks, params_start)
    param_types = _parse_param_types(toks[params_start + 1 : params_end], local)
    # Return type: ``: T`` after the parameter list, up to ``{`` or ``;``.
    k = params_end + 1
    ret_type = "any"
    if k < n and toks[k].text == ":":
        type_toks, k = _collect_type_tokens(toks, k + 1, stop={"{", ";"})
        ret_type = _render_type(type_toks, local)
    # Body or overload signature end.
    if k < n and toks[k].text == "{":
        end_idx = _matching_brace(toks, k)
    elif k < n and toks[k].text == ";":
        end_idx = k
    else:
        end_idx = params_end
    sig = f"fn({','.join(param_types)})->{ret_type}"
    return _mk_node(path, toks, i, end_idx, KIND_FUNCTION, name, sig)


def _has_default_modifier(toks: List[Token], i: int) -> bool:
    j = i - 1
    while j >= 0 and toks[j].type == IDENT and toks[j].text in _DECL_MODIFIERS:
        if toks[j].text == "default":
            return True
        j -= 1
    return False


def _matching_paren(toks: List[Token], i: int) -> int:
    depth = 0
    n = len(toks)
    while i < n:
        if toks[i].text == "(":
            depth += 1
        elif toks[i].text == ")":
            depth -= 1
            if depth == 0:
                return i
        i += 1
    return n - 1


def _parse_param_types(param_toks: List[Token], declared: set[str]) -> List[str]:
    """Each parameter's displayed type: the annotation after ``:`` at the
    parameter's top level (before any ``=`` default), else ``any``."""
    if not param_toks:
        return []
    params: List[List[Token]] = [[]]
    depth = 0
    for t in param_toks:
        if t.text in ("(", "[", "{", "<"):
            depth += 1
        elif t.text in (")", "]", "}", ">"):
            depth -= 1
        if t.text == "," and depth == 0:
            params.append([])
        else:
            params[-1].append(t)
    types = []
    for ptoks in params:
        if not ptoks:
            continue
        ann = _annotation_of(ptoks)
        types.append(_render_type(ann, declared) if ann else "any")
    return types


def _annotation_of(ptoks: List[Token]) -> List[Token]:
    """Tokens of the ``: type`` annotation within one parameter."""
    depth = 0
    start = None
    for idx, t in enumerate(ptoks):
        if t.text in ("(", "[", "{", "<"):
            depth += 1
        elif t.text in (")", "]", "}", ">"):
            depth -= 1
        elif depth == 0 and t.text == ":" and start is None:
            start = idx + 1
        elif depth == 0 and t.text == "=" and start is not None:
            return ptoks[start:idx]
        elif depth == 0 and t.text == "=" and start is None:
            return []
    return ptoks[start:] if start is not None else []


#: A depth-0 ``{`` after one of these tokens continues the type (an
#: object-literal type is expected there); after anything else it opens
#: the declaration body.
_TYPE_EXPECTED_AFTER = {":", "|", "&", "(", ",", "<", "=>", "extends", "keyof",
                        "readonly", "?"}


def _collect_type_tokens(toks: List[Token], i: int, stop: set[str]) -> tuple[List[Token], int]:
    """Collect annotation tokens from *i* until a depth-0 stop token.

    ``{`` is positional: directly after ``:`` / ``|`` / ``&`` / ``(`` /
    ``,`` / ``<`` it begins an object-literal *type* (``): { ok:
    boolean } {``); after a completed type atom it is the declaration
    body and stops collection — the distinction ``tsc``'s parser makes
    grammatically."""
    out: List[Token] = []
    depth = 0
    n = len(toks)
    expecting = True  # start of annotation: a type is expected
    while i < n:
        t = toks[i]
        if depth == 0 and t.text in stop and not (t.text == "{" and expecting):
            break
        if t.text in ("(", "[", "<", "{"):
            depth += 1
        elif t.text in (")", "]", ">", "}"):
            if depth == 0:
                break
            depth -= 1
        expecting = t.text in _TYPE_EXPECTED_AFTER
        out.append(t)
        i += 1
    return out, i


# --- type display (typeToString emulation) ---------------------------------


def _render_type(type_toks: List[Token], declared: set[str]) -> str:
    if not type_toks:
        return "any"
    return _render_type_text([t.text for t in type_toks], declared)


def _render_type_text(parts: List[str], declared: set[str]) -> str:
    """Render a type annotation the way the reference's checker displays
    it with no default library loaded: in-snapshot type references keep
    their name, unresolved references collapse to ``any``, primitives as
    written, ``T[]`` arrays, `` | `` / `` & `` spacing."""
    if not parts:  # e.g. a trailing comma's empty tuple element
        return "any"
    # Union / intersection at top level.
    for op in ("|", "&"):
        pieces = _split_top(parts, op)
        if len(pieces) > 1:
            rendered = [_render_type_text(p, declared) for p in pieces]
            return f" {op} ".join(rendered)
    # Trailing [] — array type.
    if len(parts) >= 2 and parts[-1] == "]" and parts[-2] == "[":
        elem = _render_type_text(parts[:-2], declared)
        if " | " in elem or " & " in elem:
            return f"({elem})[]"
        return f"{elem}[]"
    # Parenthesized.
    if parts and parts[0] == "(" and _split_top(parts, "|") == [parts]:
        if parts[-1] == ")":
            return _render_type_text(parts[1:-1], declared)
    if len(parts) == 1:
        name = parts[0]
        if name in _PRIMITIVE_TYPES or name.lstrip("-").isdigit() or name[:1] in "'\"`":
            return name
        return name if name in declared else "any"
    # Generic reference ``Name<...>`` — unresolved without a default lib
    # (including Array/Promise), so it displays as ``any`` unless declared.
    if parts[0] not in _PRIMITIVE_TYPES and len(parts) >= 2 and parts[1] == "<":
        return parts[0] if parts[0] in declared else "any"
    # Qualified name ``Ns.Thing``: namespaces are not indexed decl kinds,
    # so the reference's no-default-lib checker cannot resolve the root
    # — it displays ``any`` (e.g. ``JSX.Element`` in a bare snapshot).
    if (len(parts) >= 3 and len(parts) % 2 == 1
            and all(p == "." for p in parts[1::2])
            and all(p.isidentifier() for p in parts[::2])):
        return "any"
    # Tuple type ``[A, B]``: render element-wise like the checker
    # (a trailing comma's empty element drops, as tsc displays it).
    if parts[0] == "[" and parts[-1] == "]":
        inner = parts[1:-1]
        if inner:
            elems = [_render_type_text(p, declared)
                     for p in _split_top(inner, ",") if p]
            return f"[{', '.join(elems)}]"
    # Literal object type, function type, …: not reproduced
    # structurally; display as written with checker-style punctuation
    # spacing (no space before ``:,;.)]>``, none after ``([<.``).
    out: List[str] = []
    for p in parts:
        if out and (p in (",", ";", ":", ")", "]", ">", ".")
                    or out[-1][-1] in "([<."):
            out[-1] += p
        else:
            out.append(p)
    return " ".join(out)


def _split_top(parts: List[str], sep: str) -> List[List[str]]:
    out: List[List[str]] = [[]]
    depth = 0
    for p in parts:
        if p in ("(", "[", "{", "<"):
            depth += 1
        elif p in (")", "]", "}", ">"):
            depth -= 1
        if p == sep and depth == 0:
            out.append([])
        else:
            out[-1].append(p)
    return out


# --- braced declarations (class / interface / enum) -------------------------


def _scan_braced_decl(path: str, toks: List[Token], i: int, kind: str) -> DeclNode | None:
    if _is_expression_position(toks, i):
        return None
    n = len(toks)
    j = i + 1
    name = None
    if j < n and toks[j].type == IDENT and toks[j].text not in ("extends", "implements"):
        name = toks[j].text
        j += 1
    if name is None and kind in (KIND_INTERFACE, KIND_ENUM):
        return None  # interface/enum require a name; bare word was an identifier
    j = _skip_type_params(toks, j)
    # Heritage clauses up to the body brace.
    while j < n and toks[j].text != "{":
        if toks[j].text in (";", ")"):
            return None
        j += 1
    if j >= n:
        return None
    body_start = j
    body_end = _matching_brace(toks, body_start)
    if kind == KIND_CLASS:
        count = _count_class_members(toks, body_start, body_end)
        sig = f"class{{{count}}}"
    elif kind == KIND_INTERFACE:
        count = _count_interface_members(toks, body_start, body_end)
        sig = f"iface{{{count}}}"
    else:
        count = _count_enum_members(toks, body_start, body_end)
        sig = f"enum{{{count}}}"
    start_i = i
    # ``const enum``: the const modifier is part of the declaration.
    if kind == KIND_ENUM and i - 1 >= 0 and toks[i - 1].text == "const":
        start_i = i - 1
    return _mk_node(path, toks, start_i, body_end, kind, name, sig)


def _count_class_members(toks: List[Token], body_start: int, body_end: int) -> int:
    """Count class members the way ``ClassDeclaration.members.length``
    does: methods/accessors/constructors (body or overload signature),
    properties, index signatures, static blocks, and bare ``;`` members
    (SemicolonClassElement)."""
    count = 0
    i = body_start + 1
    while i < body_end:
        t = toks[i]
        if t.text == ";":
            count += 1  # SemicolonClassElement
            i += 1
            continue
        # One member: scan to its end.
        count += 1
        i = _member_end(toks, i, body_end, allow_method_body=True)
    return count


def _count_interface_members(toks: List[Token], body_start: int, body_end: int) -> int:
    count = 0
    i = body_start + 1
    while i < body_end:
        if toks[i].text in (";", ","):
            i += 1
            continue
        count += 1
        i = _member_end(toks, i, body_end, allow_method_body=False)
    return count


def _member_end(toks: List[Token], i: int, body_end: int, allow_method_body: bool) -> int:
    """Scan one class/interface member starting at *i*; return the index
    just past it."""
    depth = 0
    seen_eq = False
    n = body_end
    start = i  # the ASI check must not fire on the member's own first token
    while i < n:
        t = toks[i]
        if t.text in ("(", "["):
            depth += 1
        elif t.text in (")", "]"):
            depth -= 1
        elif t.text == "{":
            if depth == 0 and not seen_eq and allow_method_body:
                return _matching_brace(toks, i) + 1  # method/accessor/static body
            depth += 1
        elif t.text == "}":
            depth -= 1
        elif depth == 0:
            if t.text == "=":
                seen_eq = True
            elif t.text in (";", ","):
                return i + 1
            elif t.nl_before and i > start and _asi_break(toks[i - 1], t):
                return i
        i += 1
    return n


def _asi_break(prev: Token, cur: Token) -> bool:
    """Heuristic ASI boundary between two members on separate lines."""
    if prev.type == PUNCT and prev.text not in (")", "]", "}"):
        return False
    if cur.type == PUNCT and cur.text not in ("[", "@", "#"):
        return False
    if prev.type == IDENT and prev.text in ("get", "set", "static", "readonly", "public",
                                            "private", "protected", "abstract", "async", "new"):
        return False
    return True


def _count_enum_members(toks: List[Token], body_start: int, body_end: int) -> int:
    count = 0
    depth = 0
    has_content = False
    for i in range(body_start + 1, body_end):
        t = toks[i]
        if t.text in ("(", "[", "{"):
            depth += 1
        elif t.text in (")", "]", "}"):
            depth -= 1
        elif t.text == "," and depth == 0:
            if has_content:
                count += 1
            has_content = False
            continue
        if depth == 0 and t.text != ",":
            has_content = True
    if has_content:
        count += 1
    return count


# --- variable statements -----------------------------------------------------


def _scan_var_statement(path: str, toks: List[Token], i: int) -> DeclNode | None:
    n = len(toks)
    t = toks[i]
    # ``const enum`` is an EnumDeclaration (handled by the enum scan).
    if i + 1 < n and toks[i + 1].text == "enum":
        return None
    # Must be followed by a binding (identifier or destructuring pattern).
    if i + 1 >= n or not (toks[i + 1].type == IDENT or toks[i + 1].text in ("[", "{")):
        return None
    if toks[i + 1].type == IDENT and toks[i + 1].text in ("in", "of", "instanceof"):
        return None
    # Inside a ``for (...)`` head → VariableDeclarationList, not a statement.
    j = i - 1
    if j >= 0 and toks[j].text == "(" and j - 1 >= 0 and toks[j - 1].type == IDENT \
            and toks[j - 1].text in ("for", "await"):
        return None
    if _is_expression_position(toks, i):
        return None
    # Scan declarators until ``;`` / block close / ASI at depth 0.
    depth = 0
    declarators = 1
    k = i + 1
    end_idx = i
    while k < n:
        t2 = toks[k]
        if t2.text in ("(", "[", "{"):
            depth += 1
        elif t2.text in (")", "]"):
            depth -= 1
            if depth < 0:
                break
        elif t2.text == "}":
            depth -= 1
            if depth < 0:
                break
        elif depth == 0:
            if t2.text == ";":
                end_idx = k
                break
            if t2.text == ",":
                declarators += 1
            elif t2.nl_before and _var_asi_break(toks[k - 1], t2):
                break
            # ``for`` heads already excluded; ``of``/``in`` end the list
            elif t2.type == IDENT and t2.text in ("of", "in") and toks[k - 1].type == IDENT:
                return None
        end_idx = k
        k += 1
    sig = f"vars{{{declarators}}}"
    # VariableStatement nodes have no ``.name`` → addressId uses "anon"
    # (reference ``workers/ts/src/sast.ts:52,66``).
    return _mk_node(path, toks, i, end_idx, KIND_VARS, None, sig)


def _var_asi_break(prev: Token, cur: Token) -> bool:
    if prev.type == PUNCT and prev.text not in (")", "]", "}"):
        return False
    if cur.type == PUNCT and cur.text in ("+", "-", "*", "/", ".", "?.", "=", "(", "[", "`"):
        return False
    if cur.type == IDENT and cur.text in ("instanceof", "in", "of", "as"):
        return False
    return True


# ---------------------------------------------------------------------------


def _mk_node(path: str, toks: List[Token], start_i: int, end_i: int,
             kind: str, name: str | None, sig: str) -> DeclNode:
    pos = _full_start(toks, start_i)
    end = toks[min(end_i, len(toks) - 1)].end
    address = f"{path}::{name if name is not None else 'anon'}::{pos}"
    return DeclNode(
        symbolId=symbol_id_from_signature(sig),
        addressId=address,
        kind=kind,
        name=name,
        file=path,
        pos=pos,
        end=end,
        signature=sig,
    )
