"""Snapshot construction: a checked-out tree → in-memory file list.

Mirrors the reference bridge's snapshot semantics (reference
``semmerge/lang/ts/bridge.py:66-78``): every ``.ts/.tsx/.js/.jsx`` file
under the tree, path as POSIX-relative, full contents in memory. File
order is sorted for determinism (the reference relies on ``rglob``
order, which is OS-dependent — a determinism bug this framework fixes;
reference ``requirements.md:163`` [NFR-DET-001]).
"""
from __future__ import annotations

import pathlib
from dataclasses import dataclass, field
from typing import Dict, List

TS_EXTENSIONS = {".ts", ".tsx", ".js", ".jsx"}
# Everything any registered language backend can index. Snapshots carry
# the union; each backend filters to its own extensions (the TS backends
# keep reference-parity by seeing exactly the TS/JS set).
SOURCE_EXTENSIONS = TS_EXTENSIONS | {".java", ".cs"}


@dataclass
class Snapshot:
    files: List[Dict[str, str]] = field(default_factory=list)
    project: str | None = None

    def to_dict(self) -> dict:
        return {"files": self.files, "project": self.project}

    def restrict(self, paths) -> "Snapshot":
        """The sub-snapshot of files whose path is in ``paths`` —
        the incremental-merge scope (reference ``architecture.md:202-204``
        prunes to changed files the same way). File order is preserved,
        so per-file scan keys, decl emission order, and therefore op
        ids are identical to the full snapshot's for every op the
        restricted merge can produce."""
        keep = set(paths)
        return Snapshot(files=[f for f in self.files if f["path"] in keep],
                        project=self.project)


def annotate_residency(snap: Snapshot, repo_root: str, tree_oid: str,
                       scope=None) -> Snapshot:
    """Mark a snapshot as addressable in the warm residency cache
    (``service/residency.py``) under ``(repo_root, tree_oid, scope)``.
    A backend seeing the annotation may serve the encoded form from
    residency — skipping scan+encode+h2d entirely — instead of
    re-encoding; byte-identical either way. Returns ``snap`` for
    chaining. ``repo_root`` may be ``""`` for synthetic snapshots."""
    from ..service import residency
    residency.annotate(snap, repo_root, tree_oid, scope=scope)
    return snap


def filter_files(snap: Snapshot, extensions) -> List[Dict[str, str]]:
    """The subset of a snapshot's files a backend can index.

    ``str.endswith`` takes the whole suffix tuple in C — this runs per
    file per scan (30k×/snapshot at the 10k-file bench rung), where a
    Python-level ``any(...)`` generator showed up in profiles. Suffix
    *match* semantics (not exact-extension): ``foo.d.ts`` matches
    ``.ts``, as in the reference bridge's filter."""
    suffixes = tuple(extensions)
    return [f for f in snap.files if f["path"].endswith(suffixes)]


def snapshot_tree(root: pathlib.Path) -> Snapshot:
    from ..obs import spans as obs_spans
    root = pathlib.Path(root)
    files = []
    with obs_spans.span("snapshot_tree", layer="frontend"):
        for path in sorted(root.rglob("*")):
            if path.is_file() and path.suffix in SOURCE_EXTENSIONS:
                files.append({
                    "path": path.relative_to(root).as_posix(),
                    "content": path.read_text(encoding="utf-8"),
                })
    return Snapshot(files=files)
