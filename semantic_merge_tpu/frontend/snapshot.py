"""Snapshot construction: a checked-out tree → in-memory file list.

Mirrors the reference bridge's snapshot semantics (reference
``semmerge/lang/ts/bridge.py:66-78``): every ``.ts/.tsx/.js/.jsx`` file
under the tree, path as POSIX-relative, full contents in memory. File
order is sorted for determinism (the reference relies on ``rglob``
order, which is OS-dependent — a determinism bug this framework fixes;
reference ``requirements.md:163`` [NFR-DET-001]).
"""
from __future__ import annotations

import pathlib
from dataclasses import dataclass, field
from typing import Dict, List

TS_EXTENSIONS = {".ts", ".tsx", ".js", ".jsx"}


@dataclass
class Snapshot:
    files: List[Dict[str, str]] = field(default_factory=list)
    project: str | None = None

    def to_dict(self) -> dict:
        return {"files": self.files, "project": self.project}


def snapshot_tree(root: pathlib.Path) -> Snapshot:
    root = pathlib.Path(root)
    files = []
    for path in sorted(root.rglob("*")):
        if path.is_file() and path.suffix in TS_EXTENSIONS:
            files.append({
                "path": path.relative_to(root).as_posix(),
                "content": path.read_text(encoding="utf-8"),
            })
    return Snapshot(files=files)
