"""Per-request working directory — the daemon's cwd seam.

Every one-shot entry point resolves repo-relative work (git plumbing,
``.semmerge.toml`` discovery, conflict/trace artifacts, the in-place
commit root) against the process cwd. That is correct for a CLI that
``cd``s into the repo, but the merge service daemon
(:mod:`semantic_merge_tpu.service`) executes requests for *arbitrary*
repos from one process — and ``os.chdir`` is process-global, so two
concurrent requests cannot each own the process cwd.

This module is the seam: a :class:`contextvars.ContextVar` holding the
request's repo root. Call sites that used to default to
``pathlib.Path.cwd()`` default to :func:`root` instead, which returns
the active request root when one is set and the process cwd otherwise —
byte-identical behavior for every one-shot path (the var is never set
there), explicit roots for daemon worker threads. ContextVars are
per-thread by construction, so each executor thread scoping a request
with :func:`scoped` sees only its own root.
"""
from __future__ import annotations

import contextlib
import pathlib
from contextvars import ContextVar
from typing import Iterator, Optional

_ROOT: "ContextVar[Optional[str]]" = ContextVar("semmerge_workdir", default=None)


def current() -> Optional[pathlib.Path]:
    """The scoped request root, or ``None`` outside any request scope
    (callers that pass ``cwd=None`` to subprocesses want exactly that)."""
    value = _ROOT.get()
    return pathlib.Path(value) if value is not None else None


def root() -> pathlib.Path:
    """The directory repo-relative work resolves against: the scoped
    request root when inside one, the process cwd otherwise."""
    return current() or pathlib.Path.cwd()


def path(rel: str) -> pathlib.Path:
    """A repo-relative artifact path (``.semmerge-conflicts.json`` and
    friends) under :func:`root`."""
    return root() / rel


@contextlib.contextmanager
def scoped(new_root: pathlib.Path | str) -> Iterator[pathlib.Path]:
    """Scope the working directory for the current thread/context."""
    resolved = pathlib.Path(new_root).resolve()
    token = _ROOT.set(str(resolved))
    try:
        yield resolved
    finally:
        _ROOT.reset(token)
