"""Fault-injection harness — ``SEMMERGE_FAULT=stage:kind[:nth]``.

Deterministic fault injection for exercising the degradation ladder and
the crash-safe in-place commit without contriving real failures. The
env var names ONE injection spec::

    SEMMERGE_FAULT=scan:raise        # RuntimeError on every scan hit
    SEMMERGE_FAULT=worker:fault      # typed WorkerFault on every hit
    SEMMERGE_FAULT=apply:fault:2     # ApplyFault on the 2nd hit only
    SEMMERGE_FAULT=worker-serve:hang=30   # worker wedges for 30 s
    SEMMERGE_FAULT=commit:kill       # SIGKILL self mid-commit

Stages with injection points wired in this tree:

=============  ========================================================
stage          call site
=============  ========================================================
scan           ``frontend.scanner.scan_snapshot_keyed`` (host + tpu)
worker         ``backends.subproc.SubprocessBackend._call`` (client)
worker-serve   ``runtime.worker.serve`` request loop (worker process)
kernel         ``ops.fused.FusedMergeEngine.merge`` dispatch
chain          ``ops.fused.TailPlan._timed_decode`` (chain decode)
apply          ``runtime.applier.apply_ops``
emit           ``runtime.emitter.emit_files``
commit         ``runtime.inplace.commit_tree_inplace`` (post-journal)
=============  ========================================================

Service-daemon stages (``semantic_merge_tpu/service/daemon.py``) — the
stage name itself contains a colon, so the parser treats a leading
``service`` segment as part of the stage, not the kind::

    SEMMERGE_FAULT=service:accept:fault     # typed fault at admission
    SEMMERGE_FAULT=service:dispatch:fault   # typed fault at dequeue
    SEMMERGE_FAULT=service:execute:hang=2   # wedge the executor 2 s

===================  ==================================================
stage                call site
===================  ==================================================
service:accept       connection handler, post-parse / pre-enqueue
service:dispatch     executor thread, post-dequeue / pre-repo-lock
service:execute      executor thread, inside the execute span
===================  ==================================================

Continuous-batching stages (``semantic_merge_tpu/batch/``) parse the
same way (``SEMMERGE_FAULT=batch:pack:fault`` …). All four fire on the
*request's* thread, where its env overlay is in scope — so a batch
fault lands the affected request alone on the inline unbatched path
(posture ``auto``) or its documented exit code (``require`` + strict),
while co-batched requests complete normally:

===================  ==================================================
stage                call site
===================  ==================================================
batch:pack           ``batch.dispatcher.submit_request`` (pre-enqueue)
batch:mesh           ``batch.dispatcher.collect_request`` (mesh seam;
                     also counts a ``batch_mesh_fallbacks_total``
                     ``reason="fault"`` increment)
batch:dispatch       ``batch.dispatcher.collect_request`` (await row)
batch:scatter        ``batch.dispatcher.collect_request`` (row fetch)
===================  ==================================================

Conflict-resolution stages (``semantic_merge_tpu/resolve/engine.py``)
parse the same way. Both land on conflict-as-result under posture
``auto`` and on exit 17 under ``require``:

===================  ==================================================
stage                call site
===================  ==================================================
resolver:propose     ``resolve.engine`` inside the propose span
resolver:verify      ``resolve.engine`` before the gate ladder
===================  ==================================================

Network stages (``semantic_merge_tpu/fleet/transport.py``) fire at the
transport seam every cross-host (and unix-socket) member call goes
through, and parse the same compound way. All four classify as
:class:`~semantic_merge_tpu.errors.TransportFault` (exit 21 under
``SEMMERGE_FLEET=require``; ladder fallthrough under ``auto``):

===================  ==================================================
stage                call site
===================  ==================================================
net:connect          ``transport.dial`` — before the socket connect
net:read             ``transport.Conn.request`` — before the reply read
net:partition        both dial and read (half-open: the connect
                     succeeds upstream but every read deadline expires)
net:slow             dial — injects ``SEMMERGE_FAULT_NET_SLOW_S``
                     (default 0.2 s) latency per call, then proceeds
===================  ==================================================

Inside the daemon the injection spec and the per-stage hit counters are
read through the request overlay (:mod:`semantic_merge_tpu.utils.
reqenv`): each request carries its client's ``SEMMERGE_FAULT`` and gets
fresh counters, exactly like the one-shot process it replaces.

Kinds:

- ``raise`` — a plain ``RuntimeError`` (exercises the CLI's stage
  classification boundaries);
- ``fault`` — the stage's typed :class:`~semantic_merge_tpu.errors.
  MergeFault` subclass, ``cause="injected"``;
- ``hang[=secs]`` — sleep (default 3600 s; deadline tests);
- ``exit[=code]`` — ``os._exit`` (default 70; worker-death tests);
- ``kill`` — SIGKILL the current process (crash-safe-commit tests);
- any other token is returned to the call site verbatim for
  site-specific handling (the worker loop implements ``garbage``).

``nth`` is 1-based and counts hits of that stage within one process;
omitted means *every* hit (so a retried/degraded rung re-faults and the
ladder genuinely lands on textual merge). Counters are process-local:
a respawned worker starts fresh.
"""
from __future__ import annotations

import os
import signal
import time
from typing import Dict, Optional

from ..errors import fault_for_stage
from . import reqenv

ENV_VAR = "SEMMERGE_FAULT"

#: Stage-name prefixes that contain a colon themselves (the service
#: daemon's and batching subsystem's stages) — the parser joins the
#: first two segments for these.
COMPOUND_STAGE_PREFIXES = ("service", "batch", "resolver", "net")

_counters: Dict[str, int] = {}


def reset() -> None:
    """Forget hit counters (test isolation)."""
    _counters.clear()


def _parse(env: str):
    """``(stage, kind, nth)`` or ``None`` for an unparseable spec."""
    parts = env.strip().split(":")
    if not parts or not parts[0]:
        return None
    if parts[0] in COMPOUND_STAGE_PREFIXES and len(parts) > 1 and parts[1]:
        # service:<substage>[:kind[:nth]] (likewise batch:<substage>) —
        # the stage IS two segments.
        parts = [f"{parts[0]}:{parts[1]}"] + parts[2:]
    stage = parts[0]
    kind = parts[1] if len(parts) > 1 and parts[1] else "raise"
    nth = None
    if len(parts) > 2 and parts[2]:
        try:
            nth = int(parts[2])
        except ValueError:
            return None
    return stage, kind, nth


def _arg(kind: str, default: float) -> float:
    if "=" in kind:
        try:
            return float(kind.split("=", 1)[1])
        except ValueError:
            pass
    return default


def check(stage: str) -> Optional[str]:
    """Injection point: fire the configured fault when ``stage``
    matches. Returns ``None`` (no spec / not this stage / not this
    hit), or the kind token for site-specific kinds."""
    env = reqenv.get(ENV_VAR)
    if not env:
        return None
    spec = _parse(env)
    if spec is None or spec[0] != stage:
        return None
    _, kind, nth = spec
    ov = reqenv.active()
    counters = (_counters if ov is None
                else ov.setdefault("__fault_counters__", {}))
    count = counters[stage] = counters.get(stage, 0) + 1
    if nth is not None and count != nth:
        return None
    if kind == "raise":
        raise RuntimeError(f"SEMMERGE_FAULT injected failure at {stage}")
    if kind == "fault":
        raise fault_for_stage(stage)(
            f"SEMMERGE_FAULT injected fault at {stage}",
            stage=stage, cause="injected")
    if kind.startswith("hang"):
        time.sleep(_arg(kind, 3600.0))
        return None
    if kind.startswith("exit"):
        os._exit(int(_arg(kind, 70)))
    if kind == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
    return kind
