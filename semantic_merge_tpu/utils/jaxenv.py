"""JAX environment hardening shared by every process entry point.

The deployment image registers an accelerator *relay* plugin (``axon``)
via a sitecustomize hook: importing anything that touches jax makes
backend discovery dial a TPU tunnel that may be absent, slow, or down.
Round 1 lost its entire scoreboard to this — ``bench.py`` crashed on
``jax.devices()`` (UNAVAILABLE) and ``dryrun_multichip`` hung >560 s in
backend discovery — while the test suite survived because
``tests/conftest.py`` carried the fix. This module is that fix, made
reusable: call :func:`force_cpu` before any jax device work to guarantee
host-CPU execution, or :func:`accelerator_available` to probe the real
chip safely (in a throwaway subprocess, so a hang cannot take down the
caller).
"""
from __future__ import annotations

import os
import subprocess
import sys


def force_cpu(n_devices: int | None = None) -> None:
    """Pin this process to the host-CPU XLA backend, no matter what
    plugins a sitecustomize hook registered.

    Safe to call whether or not jax is already imported (a hook importing
    the plugin pulls jax in before user code runs, so env vars alone are
    read too late — the live config is updated too). Must run before the
    first backend *initialisation* (`jax.devices()` etc.).

    ``n_devices`` requests a virtual CPU device count (for mesh tests /
    multi-chip dry runs on one host).
    """
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n_devices}"
            ).strip()
    # CPU runs must NOT use the persistent compilation cache: XLA:CPU's
    # AOT reload of multi-replica (collective) executables aborts the
    # process on a cache hit (observed round 5: fatal rendezvous
    # deadlock / PThread abort re-loading a shard_map train step). The
    # cache exists for real-TPU cold starts, where reload works. The
    # marker env var makes the prohibition stick in CHILD processes
    # whose own entry point calls enable_compile_cache (bench --cold).
    os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
    os.environ["SEMMERGE_NO_COMPILE_CACHE"] = "1"
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    # chex (via optax) imports jax.experimental.checkify, whose
    # import-time MLIR lowering registration inspects the live platform
    # registry — import it BEFORE the factory surgery or it raises on a
    # half-removed plugin platform. Same for pallas, which registers a
    # 'tpu' lowering at import time (the kernels run in interpret mode
    # on CPU). Failures must not skip the surgery.
    try:
        import optax  # noqa: F401
    except Exception:
        pass
    try:
        import jax.experimental.pallas  # noqa: F401
        import jax.experimental.pallas.tpu  # noqa: F401
    except Exception:
        pass

    import jax._src.xla_bridge as _xb

    jax.config.update("jax_platforms", "cpu")
    try:  # live-config twin of the env-var pop above
        jax.config.update("jax_compilation_cache_dir", None)
    except Exception:
        pass
    if n_devices is not None:
        try:
            jax.config.update("jax_num_cpu_devices", n_devices)
        except Exception:
            pass  # backend already initialised; XLA_FLAGS took care of it
    # Drop every non-CPU backend factory so discovery can never dial the
    # accelerator relay.
    for _name in list(getattr(_xb, "_backend_factories", {})):
        if _name not in ("cpu", "interpreter"):
            _xb._backend_factories.pop(_name, None)


def shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma=False):
    """``jax.shard_map`` across jax versions.

    The public ``jax.shard_map`` (with its ``check_vma`` kwarg) only
    exists on newer jax; earlier releases ship the same transform as
    ``jax.experimental.shard_map.shard_map`` with the kwarg named
    ``check_rep``. Every shard_map call site in this package goes
    through here so one jax upgrade/downgrade cannot strand the mesh
    kernels (this image's jax 0.4.37 has only the experimental form)."""
    import jax
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as sm_exp
    return sm_exp(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma)


def accelerator_available(timeout: float = 120.0, retries: int = 1) -> str | None:
    """Probe whether a real accelerator backend initialises, without
    risking this process.

    Runs ``jax.devices()`` in a subprocess with a hard timeout (backend
    discovery through a relay plugin can hang indefinitely — a signal
    alarm does not interrupt the blocked C++ call, a subprocess kill
    does). Returns the platform string (e.g. ``"tpu"``) on success, or
    ``None`` if every attempt fails or times out.
    """
    code = (
        "import jax; ds = jax.devices(); "
        "print('PLATFORM=' + ds[0].platform)"
    )
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    for _ in range(retries + 1):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code], capture_output=True,
                text=True, timeout=timeout, env=env)
        except subprocess.TimeoutExpired:
            continue
        if proc.returncode == 0:
            for line in proc.stdout.splitlines():
                if line.startswith("PLATFORM="):
                    plat = line.split("=", 1)[1].strip()
                    if plat and plat != "cpu":
                        return plat
            return None  # initialised but CPU-only: no accelerator
    return None


def compile_cache_dir() -> str:
    """Machine-fingerprinted persistent-compile-cache path.

    jaxlib's XLA:CPU AOT entries embed the *compile* machine's CPU
    features; loading them on a host with fewer features is undefined
    ("could lead to execution errors such as SIGILL", cpu_aot_loader) —
    observed in round 5 as a fatal collective-rendezvous deadlock when
    a cache written on an avx512vp2intersect machine was reused on a
    lesser host. Keying the directory by a CPU-feature fingerprint
    makes a machine change start a fresh cache instead of loading
    poison."""
    import hashlib
    try:
        with open("/proc/cpuinfo", "r", encoding="utf-8") as fh:
            for line in fh:
                if line.startswith("flags"):
                    fp = hashlib.sha256(line.encode()).hexdigest()[:12]
                    break
            else:
                fp = "noflags"
    except OSError:
        import platform
        fp = hashlib.sha256(platform.processor().encode()).hexdigest()[:12]
    return f"/tmp/semmerge_jax_cache_{fp}"


def enable_compile_cache() -> None:
    """Default the persistent compilation cache to the per-machine path
    (no-op if the caller already set JAX_COMPILATION_CACHE_DIR, or if a
    CPU-pinned ancestor prohibited the cache via
    SEMMERGE_NO_COMPILE_CACHE — see :func:`force_cpu`)."""
    if os.environ.get("SEMMERGE_NO_COMPILE_CACHE") == "1":
        return
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", compile_cache_dir())
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
