"""Deadline-supervised subprocess execution.

``subprocess.run(timeout=...)`` kills only the direct child on expiry;
``npx``-style launchers leave grandchildren holding the pipe, so the
follow-up ``communicate()`` wedges exactly when the deadline mattered.
:func:`run_with_deadline` runs the child in its own session and
SIGKILLs the whole process group on timeout, then raises a
:class:`~semantic_merge_tpu.errors.DeadlineFault` carrying the stage.
Used by ``runtime/verify.py`` (tsc) and ``runtime/emitter.py``
(prettier); the worker seam has its own reader-thread deadline in
``backends/subproc.py`` because its child is long-lived.
"""
from __future__ import annotations

import os
import signal
import subprocess
from typing import Optional, Sequence

from ..errors import DeadlineFault


def env_seconds(name: str, default: float) -> float:
    """A non-negative float from the environment; 0 disables the
    deadline; unparseable values fall back to ``default``."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        value = float(raw)
    except ValueError:
        return default
    return max(value, 0.0)


def kill_process_group(proc: subprocess.Popen) -> None:
    """SIGKILL ``proc``'s whole process group (falling back to the
    process itself when it leads no group we can signal)."""
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            proc.kill()
        except OSError:
            pass


def run_with_deadline(cmd: Sequence[str], *, timeout: Optional[float],
                      stage: str, **kwargs) -> subprocess.CompletedProcess:
    """``subprocess.run`` with process-group deadline semantics.

    ``timeout`` of ``None``/``0`` runs unbounded. On expiry the group is
    SIGKILLed and a :class:`DeadlineFault` (stage + cause="deadline")
    raised. ``FileNotFoundError`` (missing tool) propagates unchanged so
    callers keep their vacuous-pass contracts.
    """
    cmd = list(cmd)
    if not timeout or timeout <= 0:
        return subprocess.run(cmd, **kwargs)
    kwargs.setdefault("start_new_session", True)
    check = kwargs.pop("check", False)
    proc = subprocess.Popen(cmd, **kwargs)
    try:
        out, err = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        kill_process_group(proc)
        try:
            proc.communicate(timeout=5)
        except Exception:
            pass
        raise DeadlineFault(
            f"{cmd[0]} exceeded its {timeout:g}s deadline",
            stage=stage, cause="deadline") from None
    completed = subprocess.CompletedProcess(cmd, proc.returncode, out, err)
    if check and proc.returncode != 0:
        raise subprocess.CalledProcessError(
            proc.returncode, cmd, output=out, stderr=err)
    return completed
