"""Per-request environment overlay — how the daemon honors a client's
``SEMMERGE_*`` variables without mutating ``os.environ``.

A one-shot CLI reads behavior toggles (``SEMMERGE_FAULT``,
``SEMMERGE_STRICT``, the ``SEMMERGE_BATCH`` batching posture) straight
from its process environment. The merge
service daemon executes many clients' requests from one process, so a
request's environment must scope to the request: mutating
``os.environ`` would race concurrent requests and forcing every
override-carrying request to run exclusively would serialize exactly
the workloads the daemon exists to overlap.

The overlay is a :class:`contextvars.ContextVar` dict the daemon sets
around each request (:func:`overlay`); :func:`get` consults it first
and falls back to ``os.environ`` — so the overlay-aware read sites
behave identically in one-shot processes (the var is never set there).
The overlay dict also hosts request-scoped mutable state keyed by
dunder names (the fault-injection hit counters live at
``__fault_counters__``), giving each daemon request the fresh
process-local counters a one-shot run would have had.
"""
from __future__ import annotations

import contextlib
import os
from contextvars import ContextVar
from typing import Dict, Iterator, Optional

_OVERLAY: "ContextVar[Optional[dict]]" = ContextVar("semmerge_reqenv",
                                                    default=None)


def get(name: str, default: Optional[str] = None) -> Optional[str]:
    """``os.environ.get`` with the request overlay consulted first."""
    ov = _OVERLAY.get()
    if ov is not None and name in ov:
        return ov[name]
    return os.environ.get(name, default)


def posture(name: str, default: str = "off",
            choices: tuple = ("off", "auto", "require")) -> str:
    """Parse a three-state ``off|auto|require`` posture variable.

    The service postures (``SEMMERGE_DAEMON``, ``SEMMERGE_MESH``,
    ``SEMMERGE_FLEET``) share one vocabulary; this is the one
    overlay-aware parser for it. Unknown or empty values normalize to
    ``default`` — a misspelled posture must degrade to the safe
    default, never crash a merge. Common boolean spellings map onto
    the vocabulary (``1/on/yes/true`` → ``auto``, ``0/no/false`` →
    ``off``) so operators who treat the knob as a switch get the
    conservative reading.
    """
    raw = (get(name) or "").strip().lower()
    if raw in choices:
        return raw
    if raw in ("1", "on", "yes", "true"):
        return "auto"
    if raw in ("0", "no", "false"):
        return "off"
    return default


def active() -> Optional[dict]:
    """The current overlay dict (request-scoped mutable state lives
    here), or ``None`` outside any request scope."""
    return _OVERLAY.get()


@contextlib.contextmanager
def overlay(env: Dict[str, str]) -> Iterator[dict]:
    """Scope ``env`` over ``os.environ`` for the current thread/context.
    The yielded dict is the live overlay — request-scoped state may be
    stashed in it under dunder keys."""
    ov = dict(env)
    token = _OVERLAY.set(ov)
    try:
        yield ov
    finally:
        _OVERLAY.reset(token)
