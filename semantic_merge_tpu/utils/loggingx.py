"""Shared logger for the framework.

Behavioral parity with the reference logger (reference
``semmerge/loggingx.py:7-13``): a single package logger with a plain
``LEVEL message`` stream format whose level is taken from the
``SEMMERGE_LOG`` environment variable (default ``INFO``).
"""
from __future__ import annotations

import logging
import os

logger = logging.getLogger("semantic_merge_tpu")

if not logger.handlers:
    _handler = logging.StreamHandler()
    _handler.setFormatter(logging.Formatter("%(levelname)s %(message)s"))
    logger.addHandler(_handler)
logger.setLevel(os.environ.get("SEMMERGE_LOG", "INFO"))
