"""Shared logger for the framework.

Behavioral parity with the reference logger (reference
``semmerge/loggingx.py:7-13``): a single package logger with a plain
``LEVEL message`` stream format whose level is taken from the
``SEMMERGE_LOG`` environment variable (default ``INFO``).
"""
from __future__ import annotations

import logging
import os

logger = logging.getLogger("semantic_merge_tpu")

if not logger.handlers:
    _handler = logging.StreamHandler()
    _handler.setFormatter(logging.Formatter("%(levelname)s %(message)s"))
    logger.addHandler(_handler)

_raw_level = os.environ.get("SEMMERGE_LOG", "INFO")
try:
    # Accept names case-insensitively and numeric levels ("10").
    logger.setLevel(int(_raw_level) if _raw_level.isdigit()
                    else _raw_level.upper())
except (ValueError, TypeError):
    # An invalid value must degrade, not raise at import time and kill
    # every entry point (SEMMERGE_LOG=verbose used to do exactly that).
    logger.setLevel(logging.INFO)
    logger.warning("invalid SEMMERGE_LOG=%r; falling back to INFO",
                   _raw_level)
