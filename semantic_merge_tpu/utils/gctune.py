"""GC tuning for merge-sized batch work.

A 10k-file merge materializes ~10^5 short-lived-looking but actually
retained record objects (DeclNodes, Ops, dicts); CPython's default
gen-0 threshold (700 allocations) makes the collector re-scan the
growing object graph dozens of times during one merge — measured ~40%
of warm wall time at the 5k-file bench rung (331 → 202 ms with the
tuning below). For a batch CLI process that performs one merge and
exits, freezing startup objects out of the young generations and
raising the thresholds is the standard production posture.

Called explicitly by entry points (CLI, bench) — never on library
import: a host application embedding the library owns its own GC
policy.
"""
from __future__ import annotations

import gc
import os


def tune_for_merge() -> None:
    """Freeze everything allocated so far into the permanent generation
    and raise collection thresholds. Idempotent; cheap to call again.

    ``SEMMERGE_GC_TUNE=0`` disables the tuning: long-running processes
    (the merge service daemon sets it for itself) must keep normal
    collection cadence — freezing per-request garbage into the
    permanent generation would leak it for the process lifetime."""
    if os.environ.get("SEMMERGE_GC_TUNE", "").strip() == "0":
        return
    gc.collect()
    gc.freeze()
    gc.set_threshold(100_000, 50, 50)
