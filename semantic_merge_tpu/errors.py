"""Structured fault taxonomy — the pipeline-wide error contract.

Any failure the merge pipeline can contain is represented by a
:class:`MergeFault` subclass carrying the *stage* it arose in and the
underlying *cause*. The CLI's degradation ladder (``cli.py``) catches
``MergeFault`` at each rung boundary and either degrades to the next
rung (fused-TPU → host → whole-tree textual merge) or — under
``SEMMERGE_STRICT=1`` / ``--no-degrade`` — exits with the fault's
documented exit code. LastMerge (arXiv:2507.19687) and DeepMerge
(arXiv:2105.07569) both treat "never worse than the textual baseline"
as the floor a structured merger must guarantee; this taxonomy is how
every layer of this pipeline reports into that guarantee instead of
escaping as a raw traceback.

Documented exit codes (also in ``runbook.md`` "Failure modes"):

====  =============================================================
code  meaning
====  =============================================================
0     merged cleanly
1     conflicts (written to ``.semmerge-conflicts.json``)
2     type errors (diagnostics on stderr)
3     git/subprocess plumbing failure (bad revision, missing git)
10    ``ParseFault`` — frontend scan/parse failure
11    ``KernelFault`` — device kernel / engine failure
12    ``WorkerFault`` — out-of-process worker died/wedged/spoke garbage
13    ``ApplyFault`` — tree materialization or in-place commit failure
14    ``FormatFault`` — formatter failure escalated by fault injection
15    ``DeadlineFault`` — a per-request deadline expired
16    ``BatchFault`` — batched dispatch failed or posture unsatisfiable
17    ``ResolveFault`` — conflict-resolution tier failed under
      ``--resolve require``
18    ``MeshFault`` — a device mesh could not be built/used under
      ``SEMMERGE_MESH=require``
19    ``FleetFault`` — the daemon fleet router could not route/serve a
      request under ``SEMMERGE_FLEET=require``
20    ``RenderFault`` — device-side op-log rendering failed under
      ``SEMMERGE_DEVICE_RENDER=require``
21    ``TransportFault`` — a cross-host fleet transport call failed
      (dial refused, read deadline, half-open partition) under
      ``SEMMERGE_FLEET=require``
====  =============================================================

Codes 10-17 are only ever *exit* codes in strict mode (or, for
``ResolveFault``, under the ``require`` resolution posture) or when
the textual rung itself fails; in the default posture they name the
fault that triggered a ladder rung (the ``fault`` label of the
``merge_degradations_total`` metric and ``degradation`` span).
"""
from __future__ import annotations

from typing import Optional


class MergeFault(Exception):
    """Base class for contained pipeline failures.

    ``stage`` names the pipeline stage the fault arose in (``scan``,
    ``merge``, ``apply``, …); ``cause`` is a short machine-readable
    reason (``"deadline"``, ``"injected"``, an exception class name).
    """

    exit_code = 70
    default_stage = "merge"

    def __init__(self, message: str = "", *, stage: Optional[str] = None,
                 cause: Optional[str] = None) -> None:
        super().__init__(message)
        self.stage = stage or self.default_stage
        self.cause = cause

    def describe(self) -> str:
        parts = [f"{type(self).__name__} at {self.stage}"]
        msg = str(self)
        if msg:
            parts.append(msg)
        if self.cause:
            parts.append(f"cause={self.cause}")
        return ": ".join(parts)


class ParseFault(MergeFault):
    """Frontend scan/parse failure (``frontend/``)."""

    exit_code = 10
    default_stage = "scan"


class KernelFault(MergeFault):
    """Device kernel dispatch / merge-engine failure (``ops/fused.py``,
    backend merge paths)."""

    exit_code = 11
    default_stage = "kernel"


class WorkerFault(MergeFault):
    """Out-of-process worker died, wedged past its deadline, or spoke
    a broken protocol (``backends/subproc.py``)."""

    exit_code = 12
    default_stage = "worker"


class ApplyFault(MergeFault):
    """Tree materialization / in-place commit failure (``runtime/
    applier.py``, ``runtime/inplace.py``)."""

    exit_code = 13
    default_stage = "apply"


class FormatFault(MergeFault):
    """Formatter/emitter failure escalated past the best-effort
    posture (``runtime/emitter.py``)."""

    exit_code = 14
    default_stage = "format"


class DeadlineFault(MergeFault):
    """A per-request deadline expired (worker call, typecheck,
    formatter)."""

    exit_code = 15
    default_stage = "deadline"


class BatchFault(MergeFault):
    """Batched fused dispatch failed, or a ``SEMMERGE_BATCH=require``
    posture could not be satisfied (``batch/``). In the default
    posture the affected request degrades to the inline unbatched
    dispatch — co-batched requests are never touched."""

    exit_code = 16
    default_stage = "batch"


class ResolveFault(MergeFault):
    """Conflict-resolution tier failure (``resolve/``). Under posture
    ``auto`` the CLI contains it — conflict-as-result, byte-identical
    to the tier being off — so this only ever *exits* under
    ``--resolve require``, where tier availability is the contract."""

    exit_code = 17
    default_stage = "resolve"


class MeshFault(MergeFault):
    """A device mesh the ``SEMMERGE_MESH=require`` posture demands
    could not be built or used (single-chip host, mesh construction
    failure, or a mesh-sharded dispatch failure). Under the default
    ``auto`` posture the mesh layers fall back to the single-device
    programs instead — byte-identical output, never worse than a
    1-chip run — so this fault only surfaces under ``require``."""

    exit_code = 18
    default_stage = "mesh"


class FleetFault(MergeFault):
    """The daemon fleet tier (``fleet/``) could not route or serve a
    request. Under the default ``auto`` posture the client falls back
    to the single-daemon path (and from there to in-process execution)
    — never worse than a fleet-less run — so this fault only surfaces
    as an exit under ``SEMMERGE_FLEET=require``, where router
    availability is the contract. Inside the router it also classifies
    unexpected routing/WAL/dispatch errors."""

    exit_code = 19
    default_stage = "fleet"


class RenderFault(MergeFault):
    """Device-side op-log rendering (``ops/render.py``) failed — the
    render program could not be built, the rendered bytes failed the
    eligibility contract, or the posture could not be satisfied. Under
    the default ``auto`` posture every render failure falls back to the
    PR-2 host tail pipeline — byte-identical output — so this fault
    only surfaces as an exit under ``SEMMERGE_DEVICE_RENDER=require``,
    where device rendering is the contract."""

    exit_code = 20
    default_stage = "render"


class TransportFault(MergeFault):
    """A cross-host fleet transport call (``fleet/transport.py``)
    failed: the dial was refused or timed out, a read deadline expired,
    or an application-level heartbeat declared the connection half-open
    (partition). Under the default ``auto`` posture the caller degrades
    through the existing ladder — the router health-ejects the member
    and replays its WAL entries onto survivors; the client falls back
    to the single-daemon / in-process path — so this fault only
    surfaces as an exit under ``SEMMERGE_FLEET=require``, where the
    transport is the contract."""

    exit_code = 21
    default_stage = "transport"


#: Fault class each pipeline stage wraps *unexpected* exceptions into.
STAGE_FAULTS = {
    "snapshot": ParseFault,
    "scan": ParseFault,
    "merge": KernelFault,
    "kernel": KernelFault,
    "chain": KernelFault,
    "worker": WorkerFault,
    "worker-serve": WorkerFault,
    # The merge service daemon (service/daemon.py) is an out-of-process
    # worker from the client's point of view, so its stages classify as
    # WorkerFault (exit 12) — except deadline expiry, which the daemon
    # raises as DeadlineFault explicitly.
    "service:accept": WorkerFault,
    "service:dispatch": WorkerFault,
    "service:execute": WorkerFault,
    # Continuous-batching subsystem (batch/): pack/dispatch/scatter all
    # classify as BatchFault so the request seam can degrade the one
    # affected request to the inline unbatched dispatch.
    "batch": BatchFault,
    "batch:pack": BatchFault,
    "batch:dispatch": BatchFault,
    "batch:scatter": BatchFault,
    # The mesh-sharded batched program: a request-side batch:mesh fault
    # degrades that one request to the inline dispatch like any other
    # batch stage; the leader-side mesh build itself raises MeshFault
    # (under SEMMERGE_MESH=require) with its own stage "mesh".
    "batch:mesh": BatchFault,
    "mesh": MeshFault,
    # Fleet router tier (fleet/): routing, WAL, and failover stages all
    # classify as FleetFault; member-side execution faults keep their
    # own typed class from the member daemon's wire error.
    "fleet": FleetFault,
    "fleet:route": FleetFault,
    "fleet:dispatch": FleetFault,
    "fleet:failover": FleetFault,
    "fleet:replay": FleetFault,
    # Cross-host member transport (fleet/transport.py): dial, read,
    # heartbeat, and injected net:* stages all classify as
    # TransportFault so the posture seam (auto → ladder fallthrough,
    # require → exit 21) sees one fault type for the network.
    "transport": TransportFault,
    "net:connect": TransportFault,
    "net:read": TransportFault,
    "net:partition": TransportFault,
    "net:slow": TransportFault,
    # Conflict-resolution tier (resolve/): propose/verify classify as
    # ResolveFault so the CLI's containment (auto → conflict-as-result,
    # require → exit 17) sees one fault type for the whole tier.
    # Device-side op-log rendering (ops/render.py): build/dispatch/d2h
    # failures classify as RenderFault so the posture seam (auto →
    # host-tail fallback, require → exit 20) sees one fault type.
    "render": RenderFault,
    "resolve": ResolveFault,
    "resolver:propose": ResolveFault,
    "resolver:verify": ResolveFault,
    "materialize": ApplyFault,
    "apply": ApplyFault,
    "commit": ApplyFault,
    "format": FormatFault,
    "emit": FormatFault,
    "verify": DeadlineFault,
}

#: The documented fault exit codes, by class name (runbook table).
EXIT_CODES = {cls.__name__: cls.exit_code for cls in
              (ParseFault, KernelFault, WorkerFault, ApplyFault,
               FormatFault, DeadlineFault, BatchFault, ResolveFault,
               MeshFault, FleetFault, RenderFault, TransportFault)}


def fault_for_stage(stage: str) -> type:
    """The fault class a stage's unexpected exceptions classify into."""
    return STAGE_FAULTS.get(stage, MergeFault)


class fault_boundary:
    """Context manager classifying a stage's unexpected exceptions.

    A :class:`MergeFault` (raised by a deeper, better-informed layer)
    passes through unchanged. ``subprocess.CalledProcessError`` passes
    through too — git plumbing failures (bad revision, missing git) are
    usage errors the ladder cannot fix, and keep their documented
    exit 3 via the CLI's top-level handler. Everything else derived
    from ``Exception`` is wrapped into the stage's fault class with the
    original exception chained as ``__cause__``.
    """

    def __init__(self, stage: str) -> None:
        self.stage = stage

    def __enter__(self) -> "fault_boundary":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is None or not isinstance(exc, Exception):
            return False
        import subprocess
        if isinstance(exc, (MergeFault, subprocess.CalledProcessError)):
            return False
        fault = fault_for_stage(self.stage)(
            str(exc), stage=self.stage, cause=type(exc).__name__)
        raise fault from exc
