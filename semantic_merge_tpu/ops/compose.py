"""Op-log composition on device.

Lifts the reference's sequential two-pointer composer (reference
``semmerge/compose.py:51-112``) into a JAX program with three stages:

1. **Canonical order** — each encoded log sorts by ``(precedence,
   timestamp rank, id rank)``; the merged order is one stable lexsort
   of the concatenation by ``(precedence, timestamp, side, id rank)``
   — cross-stream order compares ``(precedence, timestamp)`` only with
   A before B on ties, matching the host composer's two-pointer pick
   (see the rationale in :mod:`semantic_merge_tpu.core.compose`).
2. **Conflict detection** — DivergentRename pairs. A fully parallel
   sorted self-join finds whether any *candidate* exists (same symbol
   renamed to different names on both sides). If none — the common
   case — the sequential phase is skipped entirely. Otherwise a
   bounded ``lax.while_loop`` replays the reference's head-vs-head
   cursor walk exactly, including its quirks: conflicts are only seen
   when both cursors surface the two renames simultaneously, both ops
   drop without updating chains, and interleaved unrelated ops can
   mask detection.
3. **Chain propagation** — rename/move chains are per-symbol
   last-valid-wins prefix state, i.e. a segmented inclusive scan. Rows
   sort by ``(symbol, merged position)`` and three masked last-value
   scans (``newAddress``, ``newFile``/``file``, rename ``newName``)
   run via ``jax.lax.associative_scan`` in O(log n) depth, then
   unsort. This is the stage that lets 10k-file op streams compose in
   logarithmic depth instead of the reference's O(n) Python loop.

The decoded result is bit-identical to
:func:`semantic_merge_tpu.core.compose.compose_oplogs` (property-tested
in ``tests/test_device_parity.py``).
"""
from __future__ import annotations

from functools import partial
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.conflict import Conflict, divergent_rename_conflict
from ..core.encode import (NULL_ID, PAD_ID, Interner, OpTensor,
                           build_rank_tables, encode_oplog, pad_to,
                           shard_bucket)
from ..core.ops import Op, Target
from .oplog_view import ComposedOpView

_PAD_PREC = np.int32(2**30)  # sorts after every real precedence


def _pad_op_tensor(t: OpTensor, size: int) -> Dict[str, np.ndarray]:
    cols = {}
    for name in ("prec", "ts_rank", "id_rank", "is_rename", "is_move", "sym",
                 "new_name", "chain_name", "new_addr", "chain_file", "op_index"):
        arr = getattr(t, name)
        fill = _PAD_PREC if name == "prec" else (PAD_ID if name == "sym" else NULL_ID)
        cols[name] = pad_to(arr, size, np.int32(fill))
    return cols


def _sort_perm(*keys):
    """Stable sort permutation by lexicographic ``keys`` (primary
    first): one multi-key XLA sort with an iota payload. Returns
    ``(order, iota)`` — ``keys[i][order]`` is sorted, ties keep
    original index order; ``iota`` is returned for callers that
    scatter-invert the permutation."""
    iota = jnp.arange(keys[0].shape[0], dtype=jnp.int32)
    *_, order = jax.lax.sort((*keys, iota), num_keys=len(keys))
    return order, iota


def _key_leq(pa, ta, pb, tb):
    """Cross-stream (prec, ts) <= comparison — A wins ties; the op id
    never decides cross-stream order (see module docstring)."""
    return (pa < pb) | ((pa == pb) & (ta <= tb))


#: Column order of the encoded op stream (OpTensor fields).
_STREAM_COLS = ("prec", "ts_rank", "id_rank", "is_rename", "is_move", "sym",
                "new_name", "chain_name", "new_addr", "chain_file", "op_index")


def _sort_stream(cols):
    """Stage 1: canonical per-stream sort by (prec, ts rank, id rank).

    One stable multi-key XLA sort with every other column carried as
    payload — a k-key ``jnp.lexsort`` lowers to k *sequential* sorts
    plus k gathers, and sorts dominate the fused kernel's device time
    (rung-5 TPU phase split)."""
    out = jax.lax.sort(tuple(cols[k] for k in _STREAM_COLS),
                       num_keys=3, is_stable=True)
    return dict(zip(_STREAM_COLS, out))


def _rename_pairs(cols, n_real, n_pad):
    """(symbol, newName) pairs of a stream's rename rows (PAD elsewhere)."""
    idx = jnp.arange(n_pad)
    is_r = (cols["is_rename"] == 1) & (idx < n_real)
    sym = jnp.where(is_r, cols["sym"], PAD_ID)
    return sym, cols["new_name"]


def _rename_candidate_tables(a, n_a, na):
    """Sorted lookup tables over A's rename pairs for the DivergentRename
    candidate join; replicated across shards in the mesh kernel (the
    symbol-table all-gather of the north star)."""
    a_rsym, a_rname = _rename_pairs(a, n_a, na)
    # Sorting by (sym, name) lets a query read the run's min/max name —
    # scanning the ≤2 boundary slots is not enough when one symbol has
    # several renames with mixed names. The sym column of this one sort
    # is already the sym-sorted table the membership probe needs.
    nm_sym, nm_name = jax.lax.sort((a_rsym, a_rname), num_keys=2)
    return nm_sym, nm_sym, nm_name


def _rename_candidate_query(tables, na, b_rsym, b_rname):
    """For each B rename (query side — the shardable axis): does any A
    rename share the symbol with a different name?"""
    srt_sym, nm_sym, nm_name = tables
    left = jnp.clip(jnp.searchsorted(srt_sym, b_rsym, side="left"), 0, na - 1)
    seg_has = srt_sym[left] == b_rsym
    lo = jnp.clip(jnp.searchsorted(nm_sym, b_rsym, side="left"), 0, na - 1)
    hi = jnp.clip(jnp.searchsorted(nm_sym, b_rsym, side="right") - 1, 0, na - 1)
    run_min = nm_name[lo]
    run_max = nm_name[hi]
    return (seg_has & (b_rsym != PAD_ID)
            & ((run_min != b_rname) | (run_max != b_rname)))


@partial(jax.jit, static_argnames=("na", "nb"))
def _compose_kernel(a_cols, b_cols, n_a, n_b, na: int, nb: int):
    # ---- stage 1: canonical per-stream sort + merged order -----------------
    a = _sort_stream({k: jnp.asarray(v) for k, v in a_cols.items()})
    b = _sort_stream({k: jnp.asarray(v) for k, v in b_cols.items()})

    # ---- stage 2: DivergentRename candidates (parallel precheck) ----------
    tables = _rename_candidate_tables(a, n_a, na)
    b_rsym, b_rname = _rename_pairs(b, n_b, nb)
    differing = _rename_candidate_query(tables, na, b_rsym, b_rname)
    has_candidates = jnp.any(differing)

    drop_a, drop_b, conf_a, conf_b, n_conf = _conflict_cursor_walk(
        a, b, n_a, n_b, na, nb, has_candidates)

    # ---- stage 3: merged order + segmented chain scans --------------------
    return _merge_and_scan(a, b, n_a, n_b, na, nb,
                           drop_a, drop_b, conf_a, conf_b, n_conf,
                           seg_scan_impl=_local_seg_scan)


def _conflict_cursor_walk(a, b, n_a, n_b, na: int, nb: int, has_candidates):
    """Stage 2b: exact head-vs-head cursor walk, entered only when the
    candidate join found a possible DivergentRename. Inherently
    sequential (reference ``semmerge/compose.py:51-112``); in the mesh
    kernel it runs replicated on the gathered streams — identical on
    every shard."""
    max_conf = min(na, nb)

    def cursor_walk(_):
        def cond(st):
            ia, ib = st[0], st[1]
            return (ia < n_a) | (ib < n_b)

        def body(st):
            ia, ib, drop_a, drop_b, conf_a, conf_b, n_conf = st
            ia_c = jnp.clip(ia, 0, na - 1)
            ib_c = jnp.clip(ib, 0, nb - 1)
            a_ok = ia < n_a
            b_ok = ib < n_b
            take_a = a_ok & (~b_ok | _key_leq(a["prec"][ia_c], a["ts_rank"][ia_c],
                                              b["prec"][ib_c], b["ts_rank"][ib_c]))
            conflict = (
                a_ok & b_ok
                & (a["is_rename"][ia_c] == 1) & (b["is_rename"][ib_c] == 1)
                & (a["sym"][ia_c] == b["sym"][ib_c])
                & (a["new_name"][ia_c] != b["new_name"][ib_c])
            )
            drop_a = drop_a.at[ia_c].set(jnp.where(conflict, True, drop_a[ia_c]))
            drop_b = drop_b.at[ib_c].set(jnp.where(conflict, True, drop_b[ib_c]))
            conf_a = conf_a.at[n_conf].set(jnp.where(conflict, ia_c, conf_a[n_conf]), mode="drop")
            conf_b = conf_b.at[n_conf].set(jnp.where(conflict, ib_c, conf_b[n_conf]), mode="drop")
            n_conf = n_conf + jnp.where(conflict, 1, 0)
            ia = ia + jnp.where(conflict | take_a, 1, 0)
            ib = ib + jnp.where(conflict | ~take_a, 1, 0)
            return ia, ib, drop_a, drop_b, conf_a, conf_b, n_conf

        init = (jnp.int32(0), jnp.int32(0),
                jnp.zeros((na,), bool), jnp.zeros((nb,), bool),
                jnp.full((max_conf,), NULL_ID, jnp.int32),
                jnp.full((max_conf,), NULL_ID, jnp.int32),
                jnp.int32(0))
        out = jax.lax.while_loop(cond, body, init)
        return out[2], out[3], out[4], out[5], out[6]

    def no_walk(_):
        return (jnp.zeros((na,), bool), jnp.zeros((nb,), bool),
                jnp.full((max_conf,), NULL_ID, jnp.int32),
                jnp.full((max_conf,), NULL_ID, jnp.int32),
                jnp.int32(0))

    return jax.lax.cond(has_candidates, cursor_walk, no_walk, operand=None)


def _local_seg_scan(seg_sym, seg_order, vals):
    """Single-device segmented inclusive last-valid scan: rows are in
    (sym, merged position) order; returns per-row chain value, unsorted
    back to row order. ``NULL_ID`` where no valid value precedes."""
    v = vals[seg_order]
    m = v != NULL_ID
    _, sv, sm = jax.lax.associative_scan(_seg_combine, (seg_sym, v, m))
    out = jnp.full_like(vals, NULL_ID)
    return out.at[seg_order].set(jnp.where(sm, sv, NULL_ID))


def _seg_combine(x, y):
    """Associative 'last valid value within the symbol segment' combine.
    Elements are (sym, value, valid); invariant: value == NULL_ID
    whenever valid is False."""
    xs, xv, xm = x
    ys, yv, ym = y
    same = ys == xs
    val = jnp.where(ym, yv, jnp.where(same, xv, NULL_ID))
    msk = ym | (same & xm)
    return ys, val, msk


def _merge_and_scan(a, b, n_a, n_b, na: int, nb: int,
                    drop_a, drop_b, conf_a, conf_b, n_conf,
                    *, seg_scan_impl):
    """Stage 3: merged order + segmented chain scans + output assembly.

    ``seg_scan_impl(seg_sym, seg_order, vals)`` performs the segmented
    last-valid scan — injected so the mesh kernel can substitute the
    distributed scan (local scans + carry exchange over the ``dp`` axis)
    while every other instruction stays bit-identical to the
    single-device path.
    """
    def cat(name):
        return jnp.concatenate([a[name], b[name]])

    side = jnp.concatenate([jnp.zeros((na,), jnp.int32), jnp.ones((nb,), jnp.int32)])
    within = jnp.concatenate([jnp.arange(na, dtype=jnp.int32), jnp.arange(nb, dtype=jnp.int32)])
    valid = jnp.concatenate([jnp.arange(na) < n_a, jnp.arange(nb) < n_b])
    dropped = jnp.concatenate([drop_a, drop_b])
    live = valid & ~dropped

    prec, ts, idr = cat("prec"), cat("ts_rank"), cat("id_rank")
    # (prec, ts, side, id): id orders rows only *within* a stream, side
    # breaks cross-stream ties — the merged order of the two-pointer walk.
    merged_order, iota = _sort_perm(prec, ts, side, idr)
    # Inverse of a permutation is a scatter, not another sort.
    merged_pos = jnp.zeros_like(iota).at[merged_order].set(iota)

    sym = cat("sym")
    is_rename = cat("is_rename") == 1
    is_move = cat("is_move") == 1
    new_name = cat("chain_name")
    new_addr = cat("new_addr")
    file_contrib = cat("chain_file")

    # Chain contributions (dropped/padded rows contribute nothing).
    move_live = is_move & live
    c_addr_val = jnp.where(move_live & (new_addr != NULL_ID), new_addr, NULL_ID)
    c_file_val = jnp.where(move_live & (file_contrib != NULL_ID), file_contrib, NULL_ID)
    c_name_val = jnp.where(is_rename & live, new_name, NULL_ID)

    # Segmented inclusive last-valid scan over (sym, merged_pos) order.
    seg_order, _ = _sort_perm(sym, merged_pos)
    seg_sym = sym[seg_order]

    chain_addr = seg_scan_impl(seg_sym, seg_order, c_addr_val)
    chain_file = seg_scan_impl(seg_sym, seg_order, c_file_val)
    chain_name = seg_scan_impl(seg_sym, seg_order, c_name_val)

    # ---- output assembly ---------------------------------------------------
    live_m = live[merged_order]
    out_pos_m = jnp.cumsum(live_m.astype(jnp.int32)) - 1
    n_out = jnp.sum(live_m.astype(jnp.int32))
    total = na + nb
    out_side = jnp.full((total,), NULL_ID, jnp.int32)
    out_row = jnp.full((total,), NULL_ID, jnp.int32)
    out_chain_addr = jnp.full((total,), NULL_ID, jnp.int32)
    out_chain_file = jnp.full((total,), NULL_ID, jnp.int32)
    out_chain_name = jnp.full((total,), NULL_ID, jnp.int32)
    pos = jnp.where(live_m, out_pos_m, total)
    out_side = out_side.at[pos].set(side[merged_order], mode="drop")
    out_row = out_row.at[pos].set(within[merged_order], mode="drop")
    out_chain_addr = out_chain_addr.at[pos].set(chain_addr[merged_order], mode="drop")
    out_chain_file = out_chain_file.at[pos].set(chain_file[merged_order], mode="drop")
    out_chain_name = out_chain_name.at[pos].set(chain_name[merged_order], mode="drop")

    # Stack everything into one int32 matrix so the host fetches the
    # result in a single device→host transfer (per-fetch latency on a
    # remote tunnel dwarfs per-byte cost). Short rows pad with NULL_ID;
    # scalars broadcast across their row.
    a_op_index = a["op_index"]
    b_op_index = b["op_index"]

    def row(arr):
        return jnp.pad(arr.astype(jnp.int32), (0, total - arr.shape[0]),
                       constant_values=NULL_ID)

    return jnp.stack([
        out_side, out_row, out_chain_addr, out_chain_file, out_chain_name,
        jnp.full((total,), n_out, jnp.int32),
        row(conf_a), row(conf_b),
        jnp.full((total,), n_conf, jnp.int32),
        row(a_op_index), row(b_op_index),
    ])


def encode_compose_inputs(delta_a: List[Op], delta_b: List[Op],
                          shard_multiple: int = 1):
    """Host-side encoding shared by the single-device and mesh compose
    paths: intern both logs, pad to buckets divisible by
    ``shard_multiple`` (the mesh ``dp`` size) so the sharded kernel's
    row axis splits evenly across any device count."""
    interner = Interner()
    ts_table, id_table = build_rank_tables(delta_a, delta_b)
    ta = encode_oplog(delta_a, interner, ts_table, id_table)
    tb = encode_oplog(delta_b, interner, ts_table, id_table)
    na = shard_bucket(ta.n, shard_multiple)
    nb = shard_bucket(tb.n, shard_multiple)
    return interner, ta, tb, na, nb


def recompose_resolved(delta_a: List[Op], delta_b: List[Op],
                       ) -> Tuple[List[Op], List[Conflict]]:
    """Re-compose entry for the conflict-resolution tier
    (:mod:`semantic_merge_tpu.resolve.engine`): compose the two
    *rewritten* op streams after a resolution dropped/replaced the
    conflicting ops. Delegates to the host oracle — the streams at this
    point are plain object lists (the resolver works on materialized
    ops), re-encoding them for one small device pass would cost more
    than the compose, and the host composer is the semantics the verify
    gates pin against."""
    from ..core.compose import compose_oplogs
    from ..obs import spans as obs_spans
    with obs_spans.span("recompose_resolved", layer="ops",
                        n_a=len(delta_a), n_b=len(delta_b)):
        return compose_oplogs(list(delta_a), list(delta_b))


def compose_oplogs_device(delta_a: List[Op], delta_b: List[Op]) -> Tuple[List[Op], List[Conflict]]:
    """Device-composed twin of :func:`core.compose.compose_oplogs`."""
    from ..obs import spans as obs_spans
    if not delta_a and not delta_b:
        return [], []
    with obs_spans.span("compose_device", layer="ops",
                        n_a=len(delta_a), n_b=len(delta_b)):
        interner, ta, tb, na, nb = encode_compose_inputs(delta_a, delta_b)
        out = np.asarray(_compose_kernel(
            _pad_op_tensor(ta, na), _pad_op_tensor(tb, nb),
            np.int32(ta.n), np.int32(tb.n), na, nb))
        return decode_compose_output(out, delta_a, delta_b, interner, na, nb)


def decode_compose_output(out: np.ndarray, delta_a: List[Op], delta_b: List[Op],
                          interner: Interner, na: int, nb: int
                          ) -> Tuple[List[Op], List[Conflict]]:
    """Decode the kernel's stacked int32 result matrix into the composed
    stream + conflict list (shared by the single-device and mesh compose
    paths).

    The composed stream comes back as a lazy
    :class:`~semantic_merge_tpu.ops.oplog_view.ComposedOpView` over the
    two sorted *object* streams — the view is handed through instead of
    a materialized list, so consumers that never need full ``Op`` rows
    (``len``, the applier's object loop deferred to apply time) skip the
    override clones, and every composed result reaches the apply layer
    as one shape. Materializing the view is bit-identical to the eager
    decode this replaces: no-override rows pass the stream op through
    unchanged (``_materialize_decoded``'s identity case), override rows
    pay the per-op clone."""
    (out_side, out_row, chain_addr, chain_file, chain_name,
     n_out_row, conf_a, conf_b, n_conf_row, a_op_index, b_op_index) = out
    n_out, n_conf = int(n_out_row[0]), int(n_conf_row[0])

    sorted_a = [delta_a[i] for i in a_op_index[:na].tolist() if i != NULL_ID]
    sorted_b = [delta_b[i] for i in b_op_index[:nb].tolist() if i != NULL_ID]

    conflicts: List[Conflict] = []
    for k in range(n_conf):
        conflicts.append(divergent_rename_conflict(
            sorted_a[int(conf_a[k])], sorted_b[int(conf_b[k])]))
    if n_out == 0:
        return [], conflicts

    # Columnar decode: one object-array gather resolves every interned
    # chain id to its string (NULL_ID = -1 wraps to the trailing None),
    # and `.tolist()` turns the int32 rows into plain ints once — the
    # per-op numpy-scalar indexing this replaces was the hot loop at the
    # 1k-file rung (VERDICT round 1, Weak #3). Only override rows get
    # string columns; everything else stays None (= no override).
    sides = out_side[:n_out].tolist()
    rows = out_row[:n_out].tolist()
    ca, cf, cn = chain_addr[:n_out], chain_file[:n_out], chain_name[:n_out]
    addr_s: List = [None] * n_out
    file_s: List = [None] * n_out
    name_s: List = [None] * n_out
    override_rows = np.nonzero(
        (ca != NULL_ID) | (cf != NULL_ID) | (cn != NULL_ID))[0]
    if len(override_rows):
        strings = interner.object_table()
        a_vals = strings[ca[override_rows]].tolist()
        f_vals = strings[cf[override_rows]].tolist()
        n_vals = strings[cn[override_rows]].tolist()
        for k, i in enumerate(override_rows.tolist()):
            addr_s[i] = a_vals[k]
            file_s[i] = f_vals[k]
            name_s[i] = n_vals[k]
    composed = ComposedOpView(sides, rows, addr_s, file_s, name_s,
                              sorted_a, sorted_b)
    return composed, conflicts
