"""One-round-trip fused merge program.

The round-2/3 device path ran diff and compose as *separate* device
programs with a Python hop in between: fetch diff rows, build ``Op``
objects, hash their ids one ``hashlib`` call at a time, re-intern, and
ship the encoding back (``backends/ts_tpu.py`` round 2). On a
locally-attached accelerator that is merely wasteful; through the
remote TPU tunnel this session measured (~65 ms per host↔device round
trip, ~25 MB/s) it is fatal — BENCH_r03 showed the device path at
0.277× the pure-Python baseline.

This module collapses everything between scan and final decode into
ONE jitted program and ONE compact fetch:

1. **diff** both sides against base — the parallel join plan from
   :mod:`semantic_merge_tpu.ops.diff`, emitting ``(kind, base-slot,
   side-slot)`` rows (slots index the scanned decl lists, so the host
   can materialize ops without any interned-string round trip);
2. **op identity on device** — each op's deterministic id payload (a
   fixed 51-byte block: (seed, rev) prefix digest ‖ index ‖ type code
   ‖ three 80-bit string value digests, see
   :mod:`semantic_merge_tpu.core.ids`) is assembled from a
   device-resident string-hash table and hashed in ONE compression by
   the batched SHA-256 of :mod:`semantic_merge_tpu.ops.sha256`;
3. **id tiebreaks from raw digest words** — the composition sort key
   ranks id *strings* (reference ``semmerge/compose.py:16-18``);
   UUID-formatted hex ids with dashes at fixed positions order exactly
   like their leading 128 digest bits, so the canonical and merged
   sorts simply take the four uint32 digest words as trailing keys —
   no separate rank sort exists;
4. **compose** — the canonical sorts, DivergentRename candidate join,
   and segmented chain scans of :mod:`semantic_merge_tpu.ops.compose`,
   run directly on columns derived from the diff output (no re-intern:
   scan-interner ids are the compose equality ids);
5. one **compact fetch**: op rows + digest words + canonical-order
   permutations + composed stream references + chain columns, packed
   into a single int32 vector sized by a learned capacity hint.

Conflicts are handled *speculatively*: the device program runs the
parallel candidate join only. In the overwhelmingly common case (no
candidates) the fetched result is final. When candidates exist, the
host replays the reference's sequential head-vs-head cursor walk
(:func:`semantic_merge_tpu.core.compose.cursor_walk_conflicts`) over
the already-materialized sorted streams and patches the few affected
symbols — exact oracle semantics at a cost proportional to the
conflict count, not the merge size.

Replaces the hot path of reference ``workers/ts/src/diff.ts:5-31``,
``workers/ts/src/lift.ts:11-66`` and ``semmerge/compose.py:51-112``.
"""
from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from functools import lru_cache, partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.conflict import Conflict, divergent_rename_conflict
from ..core.encode import (NULL_ID, PAD_ID, DeclTensor, Interner,
                           bucket_size, pad_to, shard_ranges)
from ..core.ops import Op, dumps_canonical
from ..obs import device as obs_device
from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans
from .compose import (_PAD_PREC, _local_seg_scan,
                      _rename_candidate_query, _rename_candidate_tables,
                      _rename_pairs, _sort_perm)
from .diff import KIND_ADD, KIND_DELETE, KIND_MOVE, KIND_RENAME, _diff_plan
from .oplog_view import (ComposedOpView, OpStreamView,
                         cursor_walk_conflicts_renames_only)
from .sha256 import sha256_device

#: OP_PRECEDENCE of each KIND_* code (core/ops.py).
_PREC_BY_KIND = np.asarray([11, 10, 30, 31], dtype=np.int32)

#: Byte length of the fixed op-id payload (core.ids.deterministic_op_id):
#: prefix digest 16 + idx 4 + type code 1 + 3×10-byte string digests.
_ID_PAYLOAD_LEN = 51


# --------------------------------------------------------------------------
# Host-tail pipeline: chunked decode → materialize → serialize workers
# --------------------------------------------------------------------------

def resolve_host_workers(configured: Optional[int] = None) -> int:
    """Worker count for the host-tail pipeline. Resolution order:
    ``SEMMERGE_HOST_WORKERS`` env var, then the ``[engine]
    host_workers`` config value (``configured``), then the default
    ``min(8, cpu_count)``. Always ≥ 1 (1 = serial execution through
    the same shard plan — byte-identical output)."""
    env = os.environ.get("SEMMERGE_HOST_WORKERS", "").strip()
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            from ..utils.loggingx import logger
            logger.warning("invalid SEMMERGE_HOST_WORKERS=%r ignored", env)
    if configured:
        return max(1, int(configured))
    return min(8, os.cpu_count() or 1)


_pool_lock = threading.Lock()
_pool: Optional[ThreadPoolExecutor] = None
_pool_size = 0


def _host_pool(workers: int) -> ThreadPoolExecutor:
    """Process-shared tail worker pool, resized on demand (merges are
    sequential per process; the pool outlives engines so warm merges
    skip thread startup)."""
    global _pool, _pool_size
    with _pool_lock:
        if _pool is None or _pool_size != workers:
            if _pool is not None:
                _pool.shutdown(wait=False)
            _pool = ThreadPoolExecutor(max_workers=workers,
                                       thread_name_prefix="semmerge-tail")
            _pool_size = workers
        return _pool


class _Immediate:
    """Future-shaped wrapper that runs its thunk at ``result()`` — the
    inline (non-pooled) execution mode of :class:`TailPlan` shards."""

    __slots__ = ("_fn", "_val", "_done")

    def __init__(self, fn) -> None:
        self._fn = fn
        self._val = None
        self._done = False

    def result(self):
        if not self._done:
            self._val = self._fn()
            self._done = True
            self._fn = None
        return self._val


class _OnceCell:
    """Thread-safe memoized thunk — shards share one chains fetch and
    one interner table snapshot without racing the producers."""

    __slots__ = ("_fn", "_lock", "_val", "_done")

    def __init__(self, fn) -> None:
        self._fn = fn
        self._lock = threading.Lock()
        self._val = None
        self._done = False

    def get(self):
        if self._done:
            return self._val
        with self._lock:
            if not self._done:
                self._val = self._fn()
                self._done = True
                self._fn = None
        return self._val


class TailPipeline:
    """Worker pool + shard geometry for the post-kernel host tail.

    One instance per engine; attached to the op-stream/composed views
    so chain decode, op materialization, and op-log serialization all
    run as row-range shards over the same pool. ``shard_rows`` (env
    ``SEMMERGE_TAIL_SHARD_ROWS``, default 8192) bounds shard size; the
    per-shard results merge in deterministic shard order, so output is
    byte-identical for every worker count."""

    __slots__ = ("workers", "shard_rows", "eager_overlap")

    def __init__(self, workers: Optional[int] = None,
                 shard_rows: Optional[int] = None) -> None:
        self.workers = workers if workers else resolve_host_workers()
        if shard_rows is None:
            env = os.environ.get("SEMMERGE_TAIL_SHARD_ROWS", "").strip()
            shard_rows = int(env) if env.isdigit() and int(env) > 0 else 8192
        self.shard_rows = shard_rows
        # Whether to pre-submit shard decodes at merge return and to
        # fan serialization out across the pool (overlapping the
        # caller's work). Requires BOTH more than one worker and more
        # than one core: with a single worker there is nothing to
        # overlap with, and on a single-core host pooled jobs only
        # time-slice against the very phases they would hide behind —
        # shards run lazily in submission order instead (same plan,
        # same deterministic output). A plain attribute so tests can
        # force the concurrent schedule on any host.
        self.eager_overlap = self.workers > 1 and (os.cpu_count() or 1) > 1

    def submit(self, fn, *args):
        return _host_pool(self.workers).submit(fn, *args)


class TailPlan:
    """Shard plan for ONE merge's composed stream: the ranges, the
    chain-decode function, and memoized per-shard decode results.

    ``decode_fn(lo, hi)`` returns the shard's decoded chain-override
    columns ``(addr, file, name)`` (local indexing). The plan may be
    driven eagerly (:meth:`prefetch` — the producer/consumer overlap:
    decodes run in workers while the caller serializes op-log payloads,
    and on a real accelerator link while later shards' chain bytes are
    still in flight) or lazily (first materialize/chain access). A
    queued-but-unstarted decode future is cancelled and computed inline
    by its consumer, so shard consumers never deadlock behind their own
    pool (workers=1 included). Worker execution is recorded under the
    ``materialize_overlap`` phase."""

    def __init__(self, pipeline: TailPipeline, n: int, decode_fn) -> None:
        self.pipeline = pipeline
        self.ranges = shard_ranges(n, pipeline.shard_rows)
        self._decode_fn = decode_fn
        self._lock = threading.Lock()
        self._decoded: Dict[Tuple[int, int], object] = {}

    def prefetch(self) -> None:
        """Submit every shard's chain decode to the pool now."""
        with self._lock:
            for r in self.ranges:
                if r not in self._decoded:
                    self._decoded[r] = self.pipeline.submit(
                        self._timed_decode, *r)

    def _timed_decode(self, lo: int, hi: int):
        from ..utils import faults
        faults.check("chain")
        t0 = time.perf_counter()
        out = self._decode_fn(lo, hi)
        obs_spans.record("materialize_overlap", time.perf_counter() - t0,
                         layer="ops", t_start=t0, stage="decode",
                         rows=hi - lo)
        return out

    def shard_overrides(self, lo: int, hi: int):
        """One shard's decoded chain-override columns ``(addr, file,
        name)`` — cached, claimed from an in-flight pool future, or
        computed inline. Public: the columnar applier walks the plan's
        shard ranges through :meth:`ComposedOpView.override_rows`, so
        apply work on early shards overlaps later shards' decodes (and,
        split-fetch, the chain transfer itself)."""
        key = (lo, hi)
        with self._lock:
            ent = self._decoded.get(key)
        if isinstance(ent, tuple):
            return ent
        if ent is not None:
            if ent.cancel():  # queued but unstarted: compute inline
                out = self._timed_decode(lo, hi)
            else:
                out = ent.result()
        else:
            out = self._timed_decode(lo, hi)
        with self._lock:
            self._decoded[key] = out
        return out

    def submit_materialize(self, lo: int, hi: int, build_fn):
        """Submit one shard's materialization (``build_fn(lo, hi,
        overrides) -> list``); the job resolves its own shard's decode
        first (cached, cancelled-inline, or computed). Without
        ``eager_overlap`` the shard runs inline in the consumer thread
        instead — on a single core the pool's GIL hand-offs between
        blocked workers only add cost, and the shard plan (hence the
        output) is identical either way."""
        def run():
            overrides = self.shard_overrides(lo, hi)
            t0 = time.perf_counter()
            ops = build_fn(lo, hi, overrides)
            obs_spans.record("materialize_overlap",
                             time.perf_counter() - t0, layer="ops",
                             t_start=t0, stage="materialize", rows=hi - lo)
            return ops
        if not self.pipeline.eager_overlap:
            return _Immediate(run)
        return self.pipeline.submit(run)

    def decode_all(self) -> Tuple[list, list, list]:
        """All shards' override columns concatenated in shard order —
        the full-column view for single-op access paths."""
        addr: list = []
        file: list = []
        name: list = []
        for lo, hi in self.ranges:
            a, f, nm = self.shard_overrides(lo, hi)
            addr.extend(a)
            file.extend(f)
            name.extend(nm)
        return addr, file, name


class DeviceStrings:
    """Device-resident 80-bit value-hash table for an
    :class:`Interner`'s strings.

    One 10-byte ``core.ids.value_digest10`` row per interned string.
    Append-only (interner ids are stable), so warm merges ship only the
    *new* strings' digests — on the tunnel-attached TPU the h2d cost of
    a repeated merge is a few hundred bytes. Fixed row width means no
    growth-on-long-string geometry changes and no ineligible strings —
    the fused path never falls back on string content.
    """

    def __init__(self, interner: Interner, sharding=None) -> None:
        self.interner = interner
        self.sharding = sharding  # replicated mesh sharding, or None
        self.cap = 1024
        self._host = np.zeros((self.cap, 10), dtype=np.uint8)
        self._n_hashed = 0
        self._dev = None
        self._n_dev = 0  # rows synced to device

    def _put(self, arr):
        return (jax.device_put(arr, self.sharding) if self.sharding is not None
                else jax.device_put(arr))

    def sync(self):
        """Bring the device hash table up to date with the interner;
        returns the device array (rows beyond the interned count are
        zeros, never gathered by valid ids)."""
        from ..core.ids import value_digest10
        strings = self.interner.strings
        n = len(strings)
        cap = self.cap
        while n > cap:
            cap *= 2
        if cap != self.cap:
            grown = np.zeros((cap, 10), dtype=np.uint8)
            grown[:self._n_hashed] = self._host[:self._n_hashed]
            self._host, self.cap = grown, cap
            self._dev = None  # geometry change: full reship
        if n > self._n_hashed:
            view = self._host
            for i in range(self._n_hashed, n):
                view[i] = np.frombuffer(value_digest10(strings[i]), np.uint8)
            self._n_hashed = n
        if self._dev is None:
            self._dev = self._put(self._host)
            obs_device.record_transfer("h2d", self._host.nbytes)
            self._n_dev = n
        elif n > self._n_dev:
            # Ship only the delta, padded to a bucket-ladder row count
            # so the update-slice kernel compiles O(log) variants.
            rows = bucket_size(n - self._n_dev, minimum=8)
            if self._n_dev + rows > self.cap:
                self._dev = self._put(self._host)
                obs_device.record_transfer("h2d", self._host.nbytes)
            else:
                upd = self._host[self._n_dev:self._n_dev + rows]
                self._dev = _dev_update2(self._dev, upd,
                                         np.int32(self._n_dev))
                obs_device.record_transfer("h2d", upd.nbytes)
            self._n_dev = n
        return self._dev


@jax.jit
def _dev_update2(buf, upd, start):
    return jax.lax.dynamic_update_slice(buf, upd, (start, jnp.int32(0)))


# --------------------------------------------------------------------------
# Device program
# --------------------------------------------------------------------------

def _emit_slots(plan, C: int, nb: int, ns: int):
    """Scatter the diff plan into compact ``(kind, a_slot, b_slot)``
    rows of capacity ``C`` (rows beyond C drop; the overflow flag tells
    the host to retry with a larger capacity)."""
    neg = jnp.int32(NULL_ID)
    kind = jnp.full((C,), neg)
    a_slot = jnp.full((C,), neg)
    b_slot = jnp.full((C,), neg)
    idx_s = jnp.arange(ns, dtype=jnp.int32)
    bl, s_repr = plan["bl"], plan["s_repr"]

    def scat(cols, posn, mask, vals):
        posn = jnp.where(mask, posn, C)
        return [c.at[posn].set(v, mode="drop") for c, v in zip(cols, vals)]

    cols = [kind, a_slot, b_slot]
    nbneg = jnp.full((nb,), neg)
    nsneg = jnp.full((ns,), neg)
    cols = scat(cols, plan["base_off"], plan["is_delete"],
                [jnp.full((nb,), KIND_DELETE, jnp.int32), bl, nbneg])
    cols = scat(cols, plan["base_off"], plan["is_move"],
                [jnp.full((nb,), KIND_MOVE, jnp.int32), bl, s_repr])
    cols = scat(cols, plan["base_off"] + plan["is_move"].astype(jnp.int32),
                plan["is_rename"],
                [jnp.full((nb,), KIND_RENAME, jnp.int32), bl, s_repr])
    cols = scat(cols, plan["add_off"], plan["is_add"],
                [jnp.full((ns,), KIND_ADD, jnp.int32), nsneg, idx_s])
    return cols[0], cols[1], cols[2], plan["n_ops"]


def _op_id_words(kind, a_slot, b_slot, b_cols, s_cols, hash_tab,
                 pre_digest, *, C: int, idx0=0):
    """Assemble each op's fixed-width id payload and hash it: uint32 [C, 4].

    Payload layout (must match ``core.ids.deterministic_op_id``): the
    16-byte (seed, rev) prefix digest ‖ op index be32 ‖ type code ‖
    three 10-byte string value digests gathered from ``hash_tab``
    (zeros for absent values — ``value_digest10("")``). 51 bytes always,
    so the SHA runs exactly ONE compression per row with a fixed
    concatenate instead of variable-length byte compaction (the v1
    ASCII payload was ~2/3 of the fused kernel's device time). Device
    kind codes 0-3 equal the ``OP_TYPES`` type codes by construction.
    ``idx0`` offsets the op index — the sharded kernel hashes row
    blocks, so block ``j`` passes ``idx0 = j * rows_per_shard``.
    """
    b_sym, b_addr = b_cols[0], b_cols[1]
    s_sym, s_addr = s_cols[0], s_cols[1]
    a_sl = jnp.clip(a_slot, 0, b_sym.shape[0] - 1)
    b_sl = jnp.clip(b_slot, 0, s_sym.shape[0] - 1)
    is_add = kind == KIND_ADD
    valid = kind >= 0
    sym_id = jnp.where(is_add, s_sym[b_sl], b_sym[a_sl])
    a_id = jnp.where(valid & ~is_add, b_addr[a_sl], NULL_ID)
    b_id = jnp.where((kind == KIND_MOVE) | (kind == KIND_RENAME) | is_add,
                     s_addr[b_sl], NULL_ID)

    cap = hash_tab.shape[0]

    def hrows(sid):
        row = hash_tab[jnp.clip(sid, 0, cap - 1)]
        return jnp.where((sid >= 0)[:, None], row, jnp.uint8(0))

    idx = idx0 + jnp.arange(C, dtype=jnp.int32)
    idx_be = jnp.stack([idx >> 24, idx >> 16, idx >> 8, idx],
                       axis=1).astype(jnp.uint8)
    kc = jnp.clip(kind, 0, 3).astype(jnp.uint8)[:, None]
    msg = jnp.concatenate([
        jnp.broadcast_to(pre_digest[None, :], (C, 16)),
        idx_be,
        kc,
        hrows(sym_id), hrows(a_id), hrows(b_id),
        jnp.zeros((C, 64 - _ID_PAYLOAD_LEN), jnp.uint8),
    ], axis=1)
    return sha256_device(msg, jnp.full((C,), _ID_PAYLOAD_LEN, jnp.int32),
                         n_words=4)


def _compose_cols(kind, a_slot, b_slot, words, b_cols, s_cols, C: int):
    """Derive the composer's encoded columns directly from diff rows —
    the scan interner's ids ARE the compose equality ids (names, files
    and addresses only ever get compared or decoded, never re-tagged;
    see ``core.encode.encode_oplog`` for the host's equivalent).
    ``words`` are the [C, 4] uint32 op-id digest words; invalid rows
    mask to the max key (their _PAD_PREC already sorts them last)."""
    b_file = b_cols[3]
    s_name, s_file = s_cols[2], s_cols[3]
    s_addr = s_cols[1]
    b_sym, s_sym = b_cols[0], s_cols[0]
    a_sl = jnp.clip(a_slot, 0, b_sym.shape[0] - 1)
    b_sl = jnp.clip(b_slot, 0, s_sym.shape[0] - 1)
    valid = kind >= 0
    is_add = kind == KIND_ADD
    is_ren = kind == KIND_RENAME
    is_mv = kind == KIND_MOVE
    kc = jnp.clip(kind, 0, 3)
    sym_id = jnp.where(is_add, s_sym[b_sl], b_sym[a_sl])
    # new_name doubles as the rename chain value on the fused path
    # (host encode distinguishes equality-keyed vs chain forms; here
    # both are the interned side name).
    nn = jnp.where(is_ren, s_name[b_sl], NULL_ID)
    inval = jnp.uint32(0xFFFFFFFF)
    vmask = valid[:, None]
    wmask = jnp.where(vmask, words, inval)
    return {
        "prec": jnp.where(valid, jnp.asarray(_PREC_BY_KIND)[kc], _PAD_PREC),
        "ts_rank": jnp.where(valid, 0, NULL_ID),  # single shared timestamp
        "idw0": wmask[:, 0], "idw1": wmask[:, 1],
        "idw2": wmask[:, 2], "idw3": wmask[:, 3],
        "is_rename": (is_ren & valid).astype(jnp.int32),
        "is_move": (is_mv & valid).astype(jnp.int32),
        "sym": jnp.where(valid, sym_id, PAD_ID),
        "new_name": nn,
        "new_addr": jnp.where(is_mv, s_addr[b_sl], NULL_ID),
        "chain_file": jnp.where(valid,
                                jnp.where(kind == KIND_DELETE,
                                          b_file[a_sl], s_file[b_sl]),
                                NULL_ID),
        "op_index": jnp.where(valid, jnp.arange(C, dtype=jnp.int32), NULL_ID),
    }


def _merge_scan_spec(m, side_m, C: int):
    """Segmented chain scans + compact ``side<<30|op_index`` references
    over rows ALREADY in merged (composed) order — the same stage-3
    instructions as ``ops.compose._merge_and_scan``. The caller's one
    canonical sort produced the merged layout, so the only sort here is
    the 1-key stable symbol grouping for the scans (stability preserves
    merged order within each symbol segment)."""
    total = 2 * C
    opidx = m["op_index"]
    live = opidx != NULL_ID
    sym = m["sym"]
    is_rename = m["is_rename"] == 1
    is_move = m["is_move"] == 1
    new_name = m["new_name"]
    new_addr = m["new_addr"]
    file_contrib = m["chain_file"]

    move_live = is_move & live
    c_addr_val = jnp.where(move_live & (new_addr != NULL_ID), new_addr, NULL_ID)
    c_file_val = jnp.where(move_live & (file_contrib != NULL_ID), file_contrib, NULL_ID)
    c_name_val = jnp.where(is_rename & live, new_name, NULL_ID)

    seg_order, _ = _sort_perm(sym)
    seg_sym = sym[seg_order]
    chain_addr = _local_seg_scan(seg_sym, seg_order, c_addr_val)
    chain_file = _local_seg_scan(seg_sym, seg_order, c_file_val)
    chain_name = _local_seg_scan(seg_sym, seg_order, c_name_val)

    out_pos = jnp.cumsum(live.astype(jnp.int32)) - 1
    n_out = jnp.sum(live.astype(jnp.int32))
    pos = jnp.where(live, out_pos, total)
    packed = (side_m << 30) | jnp.where(opidx >= 0, opidx, 0)

    def place(vals):
        buf = jnp.full((total,), NULL_ID, jnp.int32)
        return buf.at[pos].set(vals, mode="drop")

    return (n_out, place(packed), place(chain_addr), place(chain_file),
            place(chain_name))


@partial(jax.jit, static_argnames=("nb", "nl", "nr", "C", "split"))
def _fused_merge_kernel(b_cols, l_cols, r_cols, hash_tab, dig_l, dig_r,
                        nb: int, nl: int, nr: int, C: int,
                        split: bool = False):
    planL = _diff_plan(b_cols[0], b_cols[1], b_cols[2],
                       l_cols[0], l_cols[1], l_cols[2], nb, nl)
    planR = _diff_plan(b_cols[0], b_cols[1], b_cols[2],
                       r_cols[0], r_cols[1], r_cols[2], nb, nr)
    kL, aL, bL, nopsL = _emit_slots(planL, C, nb, nl)
    kR, aR, bR, nopsR = _emit_slots(planR, C, nb, nr)

    wL = _op_id_words(kL, aL, bL, b_cols, l_cols, hash_tab, dig_l, C=C)
    wR = _op_id_words(kR, aR, bR, b_cols, r_cols, hash_tab, dig_r, C=C)
    return _compose_and_pack(kL, aL, bL, wL, nopsL, kR, aR, bR, wR, nopsR,
                             b_cols, l_cols, r_cols, C, split=split)


def _compose_and_pack(kL, aL, bL, wL, nopsL, kR, aR, bR, wR, nopsR,
                      b_cols, l_cols, r_cols, C: int, split: bool = False):
    """Stages shared by the single-device and dp-sharded fused kernels:
    compose columns (digest words as id tiebreak keys), canonical
    sorts, candidate join, speculative merge+scan, and the compact
    flat packing. Inputs here are full (replicated on every shard in
    the mesh case).

    ``split=True`` returns ``(head, mid, chains)`` instead of one
    vector — byte-identical content, but the host can start async
    copies for all three and materialize the op streams (head) while
    the compose columns (mid) and chain overrides (chains) are still
    in flight through the device tunnel; the chains are not awaited
    until the composed view is actually read."""
    overflow = ((nopsL > C) | (nopsR > C)).astype(jnp.int32)
    colsL = _compose_cols(kL, aL, bL, wL, b_cols, l_cols, C)
    colsR = _compose_cols(kR, aR, bR, wR, b_cols, r_cols, C)

    # ONE canonical sort serves everything: sorting the concatenation
    # by (prec, ts, side, id words) yields the merged (composed) order
    # directly, AND its restriction to one side IS that side's
    # canonical order — so the per-stream sorts of the v1/v2 kernels
    # collapse into a cheap stable partition of the merged rows
    # (cumsum + one bijective scatter per needed column).
    def cat(name):
        return jnp.concatenate([colsL[name], colsR[name]])

    side = jnp.concatenate([jnp.zeros((C,), jnp.int32),
                            jnp.ones((C,), jnp.int32)])
    merged_order, _ = _sort_perm(cat("prec"), cat("ts_rank"), side,
                                 cat("idw0"), cat("idw1"),
                                 cat("idw2"), cat("idw3"))
    m = {k: cat(k)[merged_order]
         for k in ("sym", "is_rename", "is_move", "new_name",
                   "new_addr", "chain_file", "op_index")}
    side_m = side[merged_order]

    is_a = side_m == 0
    pos_a = jnp.cumsum(is_a.astype(jnp.int32)) - 1
    pos_b = jnp.cumsum((~is_a).astype(jnp.int32)) - 1
    ppos = jnp.where(is_a, pos_a, C + pos_b)

    def part(v):  # merged rows -> [A canonical | B canonical]
        return jnp.zeros((2 * C,), v.dtype).at[ppos].set(v)

    a = {}
    b = {}
    for k in ("sym", "is_rename", "new_name", "op_index"):
        pv = part(m[k])
        a[k], b[k] = pv[:C], pv[C:]

    tables = _rename_candidate_tables(a, nopsL, C)
    b_rsym, b_rname = _rename_pairs(b, nopsR, C)
    has_cand = jnp.any(_rename_candidate_query(tables, C, b_rsym, b_rname))

    n_out, ref, c_addr, c_file, c_name = _merge_scan_spec(m, side_m, C)

    scalars = jnp.stack([nopsL, nopsR, n_out, has_cand.astype(jnp.int32),
                         overflow, jnp.int32(0), jnp.int32(0), jnp.int32(0)])
    as_i32 = partial(jax.lax.bitcast_convert_type, new_dtype=jnp.int32)
    head = jnp.concatenate([
        scalars,
        kL, aL, bL, as_i32(wL[:, 0]), as_i32(wL[:, 1]),
        as_i32(wL[:, 2]), as_i32(wL[:, 3]),
        kR, aR, bR, as_i32(wR[:, 0]), as_i32(wR[:, 1]),
        as_i32(wR[:, 2]), as_i32(wR[:, 3]),
    ])
    if split:
        # Three buffers, three independent device→host streams: the
        # host needs `head` to materialize the op streams, `mid` for
        # the composed order + (only when the candidate join fired)
        # the conflict walk, and `chains` not until the composed view
        # is actually read — so `chains` (6C of the 24C transfer) can
        # stream through the tunnel while the host serializes op-log
        # payloads off `head` (the PP seam of SURVEY §2.3, applied to
        # the fetch).
        mid = jnp.concatenate([a["op_index"], b["op_index"], ref])
        chains = jnp.concatenate([c_addr, c_file, c_name])
        return head, mid, chains
    return jnp.concatenate([head, a["op_index"], b["op_index"],
                            ref, c_addr, c_file, c_name])


def _fused_merge_sharded_core(b_st, l_st, r_st, hash_tab, dig_l, dig_r,
                              *, nb: int, nl: int, nr: int, C: int,
                              k: int, split: bool = False):
    """Per-shard body of the dp-sharded fused merge.

    The decl axis shards over ``dp``: the diff join runs as the
    distributed sort-join with the symbol-table all-gather
    (:func:`semantic_merge_tpu.ops.sharded._sharded_diff_slots`), and
    SHA-256 — the dominant vector compute — hashes each shard's block
    of op rows, all-gathering only the 16-byte digests. The compact
    compose stages run replicated (their row count is the op capacity,
    orders of magnitude below the decl axis), so the packed output is
    identical to the single-device kernel's and one host decode serves
    both.
    """
    from jax import lax

    from .sharded import AXIS, _sharded_diff_slots

    b_cols = tuple(b_st[i] for i in range(4))
    l_cols = tuple(l_st[i] for i in range(4))
    r_cols = tuple(r_st[i] for i in range(4))
    kL, aL, bL, nopsL = _sharded_diff_slots(
        b_cols[0], b_cols[1], b_cols[2], l_cols[0], l_cols[1], l_cols[2],
        nb, nl, k, C)
    kR, aR, bR, nopsR = _sharded_diff_slots(
        b_cols[0], b_cols[1], b_cols[2], r_cols[0], r_cols[1], r_cols[2],
        nb, nr, k, C)

    # Full decl columns for slot->id gathers (id assembly, compose cols).
    b_full = tuple(lax.all_gather(c, AXIS, tiled=True) for c in b_cols)
    l_full = tuple(lax.all_gather(c, AXIS, tiled=True) for c in l_cols)
    r_full = tuple(lax.all_gather(c, AXIS, tiled=True) for c in r_cols)

    j = lax.axis_index(AXIS)
    Tc = C // k

    def words_for(kind, a_slot, b_slot, s_full, dig):
        sl = lambda x: lax.dynamic_slice(x, (j * Tc,), (Tc,))  # noqa: E731
        w_my = _op_id_words(sl(kind), sl(a_slot), sl(b_slot), b_full, s_full,
                            hash_tab, dig, C=Tc, idx0=j * Tc)
        return lax.all_gather(w_my, AXIS, tiled=True)

    wL = words_for(kL, aL, bL, l_full, dig_l)
    wR = words_for(kR, aR, bR, r_full, dig_r)
    return _compose_and_pack(kL, aL, bL, wL, nopsL, kR, aR, bR, wR, nopsR,
                             b_full, l_full, r_full, C, split=split)


@partial(jax.jit, static_argnames=("nb", "ns", "C"))
def _fused_diff_kernel(b_cols, s_cols, hash_tab, dig,
                       nb: int, ns: int, C: int):
    """Two-way variant (the ``semdiff`` path): diff join + device op
    identity in one program/one fetch; no compose stages."""
    plan = _diff_plan(b_cols[0], b_cols[1], b_cols[2],
                      s_cols[0], s_cols[1], s_cols[2], nb, ns)
    k_, a_, b_, n_ops = _emit_slots(plan, C, nb, ns)
    w = _op_id_words(k_, a_, b_, b_cols, s_cols, hash_tab, dig, C=C)
    overflow = (n_ops > C).astype(jnp.int32)
    scalars = jnp.stack([n_ops, overflow] + [jnp.int32(0)] * 6)
    as_i32 = partial(jax.lax.bitcast_convert_type, new_dtype=jnp.int32)
    return jnp.concatenate([
        scalars, k_, a_, b_,
        as_i32(w[:, 0]), as_i32(w[:, 1]), as_i32(w[:, 2]), as_i32(w[:, 3]),
    ])


#: Bound on each jitted-program cache (``SEMMERGE_PROG_CACHE``). The
#: bucket ladders keep the key space O(log) so a warm daemon never
#: nears it; the cap is the OOM backstop for adversarial shape mixes.
_PROG_CACHE_CAP = max(4, int(os.environ.get("SEMMERGE_PROG_CACHE", "")
                             or 32))

_EVICTIONS_HELP = "Jitted-program cache evictions, by cache"


@lru_cache(maxsize=_PROG_CACHE_CAP)
def _sharded_fn(mesh, nb: int, nl: int, nr: int,
                C: int, k: int, split: bool = False):
    from jax.sharding import PartitionSpec as P

    from ..utils.jaxenv import shard_map_compat
    from .sharded import AXIS
    decl = P(None, AXIS)
    return jax.jit(shard_map_compat(
        partial(_fused_merge_sharded_core, nb=nb, nl=nl, nr=nr,
                C=C, k=k, split=split),
        mesh=mesh, in_specs=(decl, decl, decl, P(), P(), P()),
        out_specs=P(), check_vma=False))


# --------------------------------------------------------------------------
# Batched entry point (batch/ continuous-batching subsystem)
# --------------------------------------------------------------------------
# The batched program is the single-merge kernel body vmapped over a
# new leading merge axis: every lane is independent, so lane i of the
# batched output is bit-identical to an unbatched dispatch of request i
# (padding lanes are inert — their rows are never scattered back).
# Programs are cached per bucket-shape key; both the decl-column bucket
# ladder and the merge-axis power-of-two ladder keep the key space
# O(log), so a warm daemon compiles a handful of variants ever.

_batch_prog_lock = threading.Lock()
_batch_progs: "OrderedDict[Tuple, object]" = OrderedDict()
_batch_prog_hits = 0
_batch_prog_misses = 0
_batch_prog_evictions = 0


def batched_fused_program(B: int, nb: int, nl: int, nr: int, C: int,
                          mesh=None):
    """The jitted batched fused-merge program for one bucket shape:
    maps ``(b[B,4,nb], l[B,4,nl], r[B,4,nr], hash_tab[B,cap,10],
    dig_l[B,16], dig_r[B,16])`` to the ``[B, 8 + 24C]`` stack of
    one-buffer packed rows (``split=False`` layout). The cache is an
    LRU bounded at ``SEMMERGE_PROG_CACHE`` entries with evictions
    counted (``program_cache_evictions_total{cache="batched"}``).

    With ``mesh`` (the 1-axis dispatch mesh of
    :func:`semantic_merge_tpu.parallel.mesh.build_batch_mesh`) the
    vmapped body runs under ``shard_map`` partitioning the leading
    merge axis across the mesh — ``B`` must be a multiple of the axis
    size (the packer's ``batch_bucket(n, shards)`` ladder guarantees
    it). Lanes are independent and no collective crosses the axis, so
    every row is bit-identical to the single-device program's. The
    cache key includes the mesh, so single-device and per-mesh-shape
    variants coexist under the same LRU bound."""
    global _batch_prog_hits, _batch_prog_misses, _batch_prog_evictions
    key = (B, nb, nl, nr, C, mesh)
    with _batch_prog_lock:
        prog = _batch_progs.get(key)
        if prog is not None:
            _batch_prog_hits += 1
            _batch_progs.move_to_end(key)
            return prog
        _batch_prog_misses += 1

    def one(b_cols, l_cols, r_cols, hash_tab, dig_l, dig_r):
        return _fused_merge_kernel(b_cols, l_cols, r_cols, hash_tab,
                                   dig_l, dig_r, nb=nb, nl=nl, nr=nr,
                                   C=C, split=False)

    vmapped = jax.vmap(one)
    if mesh is None:
        prog = jax.jit(vmapped)
    else:
        from jax.sharding import PartitionSpec as P

        from ..parallel.mesh import BATCH_AXIS
        from ..utils.jaxenv import shard_map_compat
        row = P(BATCH_AXIS)
        prog = jax.jit(shard_map_compat(
            vmapped, mesh=mesh, in_specs=(row,) * 6, out_specs=row,
            check_vma=False))
    evicted = 0
    with _batch_prog_lock:
        prog = _batch_progs.setdefault(key, prog)
        _batch_progs.move_to_end(key)
        while len(_batch_progs) > _PROG_CACHE_CAP:
            _batch_progs.popitem(last=False)
            _batch_prog_evictions += 1
            evicted += 1
    if evicted:
        obs_metrics.REGISTRY.counter(
            "program_cache_evictions_total", _EVICTIONS_HELP).inc(
                evicted, cache="batched")
    return prog


def batched_program_cache_stats() -> Dict[str, object]:
    """Status/stats block for the batched-program cache."""
    with _batch_prog_lock:
        programs = len(_batch_progs)
        hits, misses = _batch_prog_hits, _batch_prog_misses
        evictions = _batch_prog_evictions
    total = hits + misses
    return {"programs": programs, "cap": _PROG_CACHE_CAP, "hits": hits,
            "misses": misses, "evictions": evictions,
            "hit_rate": (hits / total) if total else 0.0}


# --------------------------------------------------------------------------
# Host side: decode, lazy views, conflict patch
# --------------------------------------------------------------------------
# Op-object materialization lives in ops/oplog_view.py now: the fused
# path returns columnar OpStreamView / ComposedOpView sequences whose
# JSON serialization never allocates Op objects (VERDICT r4 #2 — the
# eager loops here were the largest host phase of the rung-5 merge).


class FusedMergeEngine:
    """Owns the device-resident state of the fused path: the string
    byte table, per-snapshot decl-column device arrays (keyed by scan
    identity — warm merges ship zero input bytes), and the learned op
    capacity hint that sizes the compact output."""

    def __init__(self, interner: Interner, mesh=None,
                 host_workers: Optional[int] = None) -> None:
        self.interner = interner
        self.mesh = mesh
        #: Config-level worker request (None = auto); the resolved
        #: pipeline lives in _tail. Kept so backends can detect a
        #: config change and rebuild the engine.
        self.host_workers_cfg = host_workers
        self._tail = TailPipeline(resolve_host_workers(host_workers))
        self._dp = 1
        self._decl_sharding = None
        self._repl_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from .sharded import AXIS, _dp_size
            self._dp = _dp_size(mesh)
            self._decl_sharding = NamedSharding(mesh, P(None, AXIS))
            self._repl_sharding = NamedSharding(mesh, P())
        self.strings = DeviceStrings(interner, sharding=self._repl_sharding)
        #: Device op-log renderer (ops/render.py), built lazily on the
        #: first eligible merge — single-device only (the rendered
        #: byte pool gathers are not mesh-sharded).
        self._renderer = None
        self._decl_cache: "OrderedDict" = OrderedDict()
        # Per-snapshot node string tables for the native op-log
        # serializer, keyed by the same scan identity as _decl_cache.
        self._tbl_cache: "OrderedDict" = OrderedDict()
        self._cap_hint = 256

    def _bucket(self, n: int) -> int:
        from ..core.encode import shard_bucket
        return (shard_bucket(n, self._dp) if self._dp > 1
                else bucket_size(max(n, 1)))

    def _device_decl(self, t: DeclTensor, identity) -> tuple:
        bucket = self._bucket(t.n)
        if identity is not None:
            hit = self._decl_cache.get(identity)
            if hit is not None and hit[1] == bucket:
                self._decl_cache.move_to_end(identity)
                return hit
        null = np.int32(NULL_ID)
        stacked = np.stack([pad_to(t.sym, bucket, PAD_ID),
                            pad_to(t.addr, bucket, null),
                            pad_to(t.name, bucket, null),
                            pad_to(t.file, bucket, null)])
        if self._decl_sharding is not None:
            entry = (jax.device_put(stacked, self._decl_sharding), bucket)
        else:
            entry = (jax.device_put(stacked), bucket)
        obs_device.record_transfer("h2d", stacked.nbytes)
        if identity is not None:
            self._decl_cache[identity] = entry
            while len(self._decl_cache) > 12:
                self._decl_cache.popitem(last=False)
        return entry

    def diff(self, base_t: DeclTensor, base_key, base_nodes,
             side_t: DeclTensor, side_key, side_nodes,
             *, seed: str, base_rev: str, timestamp: str
             ) -> Optional[List[Op]]:
        """Two-way fused diff (the ``semdiff`` path): one dispatch, one
        compact fetch, ops materialized with device-hashed ids.
        ``None`` when ineligible (caller falls back). Single-device
        only — semdiff latency is dominated by the round trip, which is
        exactly what this removes."""
        if self.mesh is not None:
            return None
        from ..core.ids import op_id_prefix_digest
        hash_tab = self.strings.sync()
        dig = np.frombuffer(op_id_prefix_digest(seed + "/R", base_rev),
                            np.uint8)
        dev_b, nb = self._device_decl(base_t, base_key)
        dev_s, ns = self._device_decl(side_t, side_key)
        for _attempt in range(4):
            C = self._bucket(max(self._cap_hint, 8))
            flat = np.asarray(_fused_diff_kernel(
                dev_b, dev_s, hash_tab, dig, nb=nb, ns=ns, C=C))
            obs_device.record_transfer("d2h", flat.nbytes)
            n_ops = int(flat[0])
            if not flat[1]:
                break
            self._cap_hint = n_ops
        else:
            return None
        off = 8
        cols = []
        for _ in range(7):
            cols.append(flat[off:off + C])
            off += C
        kinds, a_sl, b_sl = cols[0][:n_ops], cols[1][:n_ops], cols[2][:n_ops]
        words = np.stack([c[:n_ops] for c in cols[3:7]], axis=1)
        return OpStreamView(kinds, a_sl, b_sl, words,
                            base_nodes, side_nodes,
                            {"rev": base_rev, "timestamp": timestamp},
                            base_tbl_ref=(self._tbl_cache, base_key),
                            side_tbl_ref=(self._tbl_cache, side_key),
                            pipeline=self._tail)

    def merge(self, base_t: DeclTensor, base_key, base_nodes,
              left_t: DeclTensor, left_key, left_nodes,
              right_t: DeclTensor, right_key, right_nodes,
              *, seed: str, base_rev: str, timestamp: str,
              overlap_work=None
              ) -> Optional[Tuple[List[Op], List[Op], List[Op], List[Conflict]]]:
        """Run the one-round-trip merge; ``None`` only when the op
        capacity retries exhaust — the caller falls back to the
        two-program path. (The v1 byte-table scheme could also be
        ineligible on oversized strings; the fixed-width hash-table ids
        removed that class of fallback.)

        ``overlap_work`` (a no-arg callable) runs on the host between
        the async kernel dispatch and the blocking fetch — the
        pipeline-staging seam (SURVEY §2.3 PP): the caller's
        independent host work (e.g. symbolMaps construction) overlaps
        device compute instead of serializing after it.

        The post-kernel HOST TAIL is pipelined: the composed stream is
        split into row-range shards (a :class:`TailPlan` over the
        engine's :class:`TailPipeline` worker pool, ``[engine]
        host_workers`` / ``SEMMERGE_HOST_WORKERS``), and each shard's
        chain decode → op materialization runs as an independent pool
        job — pre-submitted at merge return when more than one worker
        is available, so shard decodes overlap the caller's op-log
        serialization (itself sharded over the same pool) and, on a
        real accelerator link, the still-in-flight chain-column
        transfer. Shard results merge in deterministic shard order:
        output is byte-identical for every worker count. Worker-side
        execution is recorded under the ``materialize_overlap`` phase.

        Detailed phase splits (h2d/kernel/fetch/materialize/
        compose_decode) are recorded through
        :mod:`semantic_merge_tpu.obs` only while a span recorder is
        active (``--trace`` / bench instrumented runs): the kernel
        split needs a ``block_until_ready`` fence that would otherwise
        serialize the dispatch/fetch overlap this path exists for.
        """
        from ..core.ids import op_id_prefix_digest
        from ..utils import faults
        faults.check("kernel")
        detailed = obs_spans.detailed_active()
        t0 = time.perf_counter()
        hash_tab = self.strings.sync()
        dig_l = np.frombuffer(op_id_prefix_digest(seed + "/L", base_rev),
                              np.uint8)
        dig_r = np.frombuffer(op_id_prefix_digest(seed + "/R", base_rev),
                              np.uint8)
        dev_b, nb = self._device_decl(base_t, base_key)
        dev_l, nl = self._device_decl(left_t, left_key)
        dev_r, nr = self._device_decl(right_t, right_key)
        if detailed:
            obs_spans.record("h2d", time.perf_counter() - t0, layer="ops",
                             t_start=t0)

        # Split-fetch mode: the kernel returns (head, mid, chains) so
        # the host can materialize the op streams from head — and
        # serialize payloads off them — while the compose columns are
        # still streaming through the device tunnel; the chain columns
        # (6C of the 24C transfer) are not even awaited until the
        # composed view is actually read. Default-on: measured faster
        # even on zero-latency XLA-on-CPU transport (528 vs 571 ms at
        # the 10k rung, BENCHLOG round 5) and strictly more overlap on
        # a real link; SEMMERGE_SPLIT_FETCH=0 restores the one-buffer
        # packed fetch.
        split = os.environ.get("SEMMERGE_SPLIT_FETCH", "1") == "1"
        # Continuous-batching seam: under an active scheduler (service
        # mode) this merge's dispatch joins a shape-bucketed batched
        # program instead of owning the device alone. Batched rows use
        # the one-buffer packed layout, so split-fetch (a transport
        # optimization; decoded values are identical) is disabled for
        # the request. Any batching fault degrades THIS request to the
        # inline dispatch below (posture permitting) — co-batched
        # requests are unaffected.
        from .. import batch as batch_mod
        batcher = batch_mod.plan_for_request(eligible=self.mesh is None)
        if batcher is not None:
            split = False
        flat = mid_dev = chains_dev = None
        warm_caches = True
        for _attempt in range(4):
            C = self._bucket(max(self._cap_hint, 8 * self._dp))
            t0 = time.perf_counter()
            batch_fut = None
            if batcher is not None:
                from ..errors import MergeFault
                try:
                    batch_fut = batch_mod.submit_request(
                        batcher, dev_b, dev_l, dev_r, hash_tab,
                        dig_l, dig_r, nb=nb, nl=nl, nr=nr, C=C)
                except MergeFault as fault:
                    batch_mod.degrade_or_raise(fault)
                    batcher = None
            if batch_fut is None:
                if self.mesh is not None:
                    fn = _sharded_fn(self.mesh, nb, nl, nr, C, self._dp,
                                     split)
                    out_dev = fn(dev_b, dev_l, dev_r, hash_tab, dig_l,
                                 dig_r)
                else:
                    out_dev = _fused_merge_kernel(
                        dev_b, dev_l, dev_r, hash_tab, dig_l, dig_r,
                        nb=nb, nl=nl, nr=nr, C=C, split=split)
                head_dev, mid_dev, chains_dev = (out_dev if split
                                                 else (out_dev, None, None))
            else:
                head_dev = mid_dev = chains_dev = None
            if overlap_work is not None:
                # Dispatch is async: host-side work here rides along
                # with the device execution.
                overlap_work()
                overlap_work = None  # once per merge, not per retry
            if warm_caches:
                # Serializer-cache prefetch, same overlap seam: the
                # node tables (native op-log renderer) and field lists
                # (C op factory) every tail consumer will need are
                # built while the kernel is still in flight, so the
                # first to_json/materialize after merge() returns pays
                # a cache hit instead of three 40k-node table builds.
                from .oplog_view import _get_fields, _get_table
                for nodes, key in ((base_nodes, base_key),
                                   (left_nodes, left_key),
                                   (right_nodes, right_key)):
                    if key is not None:
                        _get_table((self._tbl_cache, key), nodes)
                        _get_fields((self._tbl_cache, key), nodes)
                warm_caches = False
            if batch_fut is not None:
                from ..errors import MergeFault
                try:
                    flat = batch_mod.collect_request(batch_fut)
                except MergeFault as fault:
                    batch_mod.degrade_or_raise(fault)
                    batcher = None
                    continue  # retry this capacity on the inline path
                if detailed:
                    obs_spans.record("kernel", time.perf_counter() - t0,
                                     layer="ops", t_start=t0)
            else:
                if detailed:
                    head_dev.block_until_ready()
                    obs_spans.record("kernel", time.perf_counter() - t0,
                                     layer="ops", t_start=t0)
                    t0 = time.perf_counter()
                if split:
                    for d in (head_dev, mid_dev, chains_dev):
                        try:
                            d.copy_to_host_async()
                        except AttributeError:
                            pass
                flat = np.asarray(head_dev)
                obs_device.record_transfer("d2h", flat.nbytes)
                if detailed:
                    obs_spans.record("fetch", time.perf_counter() - t0,
                                     layer="ops", t_start=t0)
            n_l, n_r = int(flat[0]), int(flat[1])
            if not flat[4]:  # no overflow
                break
            self._cap_hint = max(n_l, n_r)
        else:
            return None
        n_out, has_cand = int(flat[2]), bool(flat[3])

        t0 = time.perf_counter()
        off = 8

        def take(k):
            nonlocal off
            v = flat[off:off + k]
            off += k
            return v

        kL, aL, bL = take(C), take(C), take(C)
        wL = np.stack([take(C) for _ in range(4)], axis=1)
        kR, aR, bR = take(C), take(C), take(C)
        wR = np.stack([take(C) for _ in range(4)], axis=1)

        prov = {"rev": base_rev, "timestamp": timestamp}
        base_ref = (self._tbl_cache, base_key)
        ops_l = OpStreamView(kL[:n_l], aL[:n_l], bL[:n_l], wL[:n_l],
                             base_nodes, left_nodes, prov,
                             base_tbl_ref=base_ref,
                             side_tbl_ref=(self._tbl_cache, left_key),
                             pipeline=self._tail)
        ops_r = OpStreamView(kR[:n_r], aR[:n_r], bR[:n_r], wR[:n_r],
                             base_nodes, right_nodes, prov,
                             base_tbl_ref=base_ref,
                             side_tbl_ref=(self._tbl_cache, right_key),
                             pipeline=self._tail)
        if detailed:
            obs_spans.record("materialize", time.perf_counter() - t0,
                             layer="ops", t_start=t0)
            t0 = time.perf_counter()

        # Device-side op-log rendering (ops/render.py): launch the
        # render programs for both streams now — they gather over the
        # decl tables already resident from _device_decl — so the
        # caller's to_json_bytes costs one d2h copy + mask-concat
        # instead of a host serialization pass. Async like the kernel
        # dispatch; the detailed-mode fence keeps the phase split
        # honest (otherwise render time would hide inside whatever
        # phase first touches the payload).
        from .render import render_posture
        posture = render_posture()
        if posture != "off":
            if self.mesh is not None:
                if posture == "require":
                    from ..errors import RenderFault
                    raise RenderFault(
                        "device render is single-device only (mesh "
                        "sharding active)", stage="render", cause="mesh")
            else:
                renderer = self._renderer
                if renderer is None:
                    from .render import DeviceRenderer
                    renderer = self._renderer = DeviceRenderer(
                        self.interner)
                if renderer.eligible(max(n_l, n_r), posture=posture):
                    t_r = time.perf_counter()
                    require = posture == "require"
                    prov_json = dumps_canonical(prov)
                    ops_l.render = renderer.dispatch(
                        kL[:n_l], aL[:n_l], bL[:n_l], wL[:n_l],
                        dev_b, dev_l, base_t, left_t, prov_json,
                        require=require)
                    ops_r.render = renderer.dispatch(
                        kR[:n_r], aR[:n_r], bR[:n_r], wR[:n_r],
                        dev_b, dev_r, base_t, right_t, prov_json,
                        require=require)
                    if detailed:
                        for h in (ops_l.render, ops_r.render):
                            if h is not None:
                                h.block_until_ready()
                        obs_spans.record("render",
                                         time.perf_counter() - t_r,
                                         layer="ops", t_start=t_r)

        if split:
            # The mid buffer's device→host copy overlapped the head
            # decode; the chain buffer is not awaited here at all — its
            # fetch+decode defer into the composed view (``chain_decode``
            # phase), overlapping whatever the caller does first
            # (typically serializing the op-log payloads off ``head``).
            fm = np.asarray(mid_dev)
            obs_device.record_transfer("d2h", fm.nbytes)
            if detailed:
                obs_spans.record("fetch", time.perf_counter() - t0,
                                 layer="ops", t_start=t0)
                t0 = time.perf_counter()
            permL, permR = fm[:C], fm[C:2 * C]
            ref = fm[2 * C:]
            chain_cols = None
        else:
            permL, permR = take(C), take(C)
            ref = take(2 * C)
            chain_cols = (take(2 * C), take(2 * C), take(2 * C))

        refs = ref[:n_out]
        sides_np = (refs >> 30).astype(np.int32, copy=False)
        idxs_np = (refs & ((1 << 30) - 1)).astype(np.int32, copy=False)

        conflicts: List[Conflict] = []
        ctx_rows: List[int] = []
        ctx_vals: List[object] = []
        keep = None
        if has_cand:
            # Columnar cursor walk: the reference's head-vs-head
            # DivergentRename walk reads only (precedence, is-rename,
            # symbolId, newName), all derivable as int columns — the
            # interner makes int equality string equality, and every op
            # of one fused merge shares a single timestamp, so the
            # (prec, ts) keys collapse to precedence ints. The walk runs
            # on each side's RENAME substream only (equivalent for
            # canonically-sorted 4-kind streams — see
            # cursor_walk_conflicts_renames_only), so its cost scales
            # with the rename count, not the op count. No Op objects
            # materialize unless a conflict actually fires.
            pL, pR = permL[:n_l], permR[:n_r]
            kLr, kRr = kL[:n_l], kR[:n_r]

            def raw_cols(k_raw, a_raw, b_raw, side_t):
                a_cl = np.maximum(a_raw, 0)
                b_cl = np.maximum(b_raw, 0)
                sym = np.where(k_raw == KIND_ADD,
                               side_t.sym[b_cl], base_t.sym[a_cl])
                name = np.where(k_raw == KIND_RENAME,
                                side_t.name[b_cl], NULL_ID)
                return sym, name

            symL_raw, nameL_raw = raw_cols(kLr, aL[:n_l], bL[:n_l], left_t)
            symR_raw, nameR_raw = raw_cols(kRr, aR[:n_r], bR[:n_r], right_t)
            symL_s, nameL_s = symL_raw[pL], nameL_raw[pL]
            symR_s, nameR_s = symR_raw[pR], nameR_raw[pR]
            renL = np.nonzero(kLr[pL] == KIND_RENAME)[0]
            renR = np.nonzero(kRr[pR] == KIND_RENAME)[0]
            pairs, da, db = cursor_walk_conflicts_renames_only(
                renL, symL_s[renL], nameL_s[renL],
                renR, symR_s[renR], nameR_s[renR],
                prec_rename=int(_PREC_BY_KIND[KIND_RENAME]))
            conflicts = [divergent_rename_conflict(ops_l[int(pL[ia])],
                                                   ops_r[int(pR[ib])])
                         for ia, ib in pairs]
            if pairs:
                # Patch the speculative composition columnar-ly:
                # dropped renames leave the stream, and the rename
                # chains of *affected symbols only* are replayed in
                # composed order (drops are always renames, so the
                # addr/file chains from the device scan remain exact).
                # Only the rename-context values touch the chain
                # columns, and those are recorded as (final row, value)
                # writes so the chain decode can stay deferred — and
                # shard-local (each pipeline shard applies only the
                # writes falling in its row range).
                droppedL = np.asarray(sorted(int(pL[i]) for i in da))
                droppedR = np.asarray(sorted(int(pR[j]) for j in db))
                drop_mask = (((sides_np == 0)
                              & np.isin(idxs_np, droppedL))
                             | ((sides_np == 1)
                                & np.isin(idxs_np, droppedR)))
                il = np.minimum(idxs_np, max(n_l - 1, 0))
                ir = np.minimum(idxs_np, max(n_r - 1, 0))
                sym_row = np.where(sides_np == 0,
                                   symL_raw[il], symR_raw[ir])
                aff = np.asarray(sorted({int(symL_raw[i])
                                         for i in droppedL.tolist()}
                                        | {int(symR_raw[j])
                                           for j in droppedR.tolist()}))
                aff_mask = np.isin(sym_row, aff) & ~drop_mask
                kind_row = np.where(sides_np == 0, kLr[il], kRr[ir])
                newname_row = np.where(sides_np == 0,
                                       nameL_raw[il], nameR_raw[ir])
                table = self.interner.object_table()
                ctx: Dict[int, object] = {}
                for i in np.nonzero(aff_mask)[0].tolist():
                    sym = int(sym_row[i])
                    if kind_row[i] == KIND_RENAME:
                        ctx[sym] = table[newname_row[i]]
                    ctx_rows.append(i)
                    ctx_vals.append(ctx.get(sym))
                keep = np.nonzero(~drop_mask)[0]
                sides_np, idxs_np = sides_np[keep], idxs_np[keep]
                # Affected rows are all kept, so their final positions
                # are their ranks within `keep`.
                ctx_rows = np.searchsorted(
                    keep, np.asarray(ctx_rows, np.int64)).tolist()

        n_pre = n_out  # pre-keep row count for the deferred gathers
        # Bind just the interner: closing over `self` would pin the
        # whole engine (device decl/byte-table caches) for the lifetime
        # of any unread split-fetch composed view.
        interner = self.interner
        keep_idx = keep
        ctx_row_arr = np.asarray(ctx_rows, np.int64)

        def fetch_chains():
            """Fetch (split mode) and slice the chain-override columns,
            plus one interner-table snapshot — shared by every decode
            shard through a _OnceCell (shards may race; the cell
            serializes the producers). On the split path the chain
            bytes have been streaming host-ward since dispatch;
            ``object_table()`` is re-fetched here because gathers must
            not be separated from the live view (the interner may have
            grown since ``merge`` returned; indices are append-only
            stable)."""
            t1 = time.perf_counter()
            if chain_cols is not None:
                c_addr, c_file, c_name = chain_cols
            else:
                fc = np.asarray(chains_dev)
                obs_device.record_transfer("d2h", fc.nbytes)
                c_addr, c_file, c_name = (fc[:2 * C], fc[2 * C:4 * C],
                                          fc[4 * C:])
            tbl = interner.object_table()
            if detailed and split:
                # On the one-buffer path this work already sits inside
                # the compose_decode window; a separate key would
                # double-count it.
                obs_spans.record("chain_decode", time.perf_counter() - t1,
                                 layer="ops", t_start=t1)
            return (c_addr[:n_pre], c_file[:n_pre], c_name[:n_pre], tbl)

        chains_cell = _OnceCell(fetch_chains)

        def decode_rows(lo, hi):
            """One shard's chain-override decode: object-array gathers
            over the shard's pre-keep rows (NULL_ID wraps to the
            mirror's trailing None) plus the shard-local rename-context
            writes."""
            c_addr, c_file, c_name, tbl = chains_cell.get()
            rows = slice(lo, hi) if keep_idx is None else keep_idx[lo:hi]
            addr_o = tbl[c_addr[rows]].tolist()
            file_o = tbl[c_file[rows]].tolist()
            name_o = tbl[c_name[rows]].tolist()
            if len(ctx_row_arr):
                j0, j1 = np.searchsorted(ctx_row_arr, (lo, hi))
                for j in range(int(j0), int(j1)):
                    name_o[int(ctx_row_arr[j]) - lo] = ctx_vals[j]
            return addr_o, file_o, name_o

        plan = TailPlan(self._tail, int(len(sides_np)), decode_rows)
        composed = ComposedOpView.pipelined(sides_np, idxs_np, plan,
                                            ops_l, ops_r)
        if self._tail.eager_overlap:
            # Producer/consumer kick-off: every shard's chain decode is
            # in the pool before merge returns, overlapping the
            # caller's serialization (and the chain transfer itself on
            # a real device link).
            plan.prefetch()
        if detailed:
            obs_spans.record("compose_decode", time.perf_counter() - t0,
                             layer="ops", t_start=t0)
            obs_device.update_live_buffer_hwm()
        reg = obs_metrics.REGISTRY
        reg.counter("semmerge_composed_ops_total",
                    "Composed ops emitted by the fused merge path").inc(
            len(sides_np))
        if conflicts:
            reg.counter("semmerge_fused_conflicts_total",
                        "DivergentRename conflicts from the fused path"
                        ).inc(len(conflicts))
        return ops_l, ops_r, composed, conflicts
