"""Batched RGA materialization on device.

The host RGA (:mod:`semantic_merge_tpu.core.crdt`) resolves one list's
order by O(n) insert scans. A converged RGA's materialized order is a
pure function of its elements: stable sort by the key tuple
``(anchor, t, author, opid)`` with insertion sequence as tiebreaker,
tombstones masked. That makes whole *batches* of lists — every
import-block and parameter-list reorder in a 10k-file merge — one
vmapped segmented sort on device.

String key components are order-rank interned
(:func:`semantic_merge_tpu.core.encode.rank_intern`) so integer sorts
reproduce lexicographic string comparison exactly.
"""
from __future__ import annotations

from functools import partial
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.crdt import RGA
from ..core.encode import bucket_size, rank_intern

#: Padding rank — sorts after every real element.
_PAD = np.int32(2**31 - 1)


@partial(jax.jit, static_argnames=("n",))
def _materialize_kernel(anchor, t, author, opid, seq, tombstone, n: int):
    order = jnp.lexsort((seq, opid, author, t, anchor))
    keep = ~tombstone[order]
    # Compact: positions of kept elements in output order.
    out_pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
    perm = jnp.full((n,), n, dtype=jnp.int32)  # n = "dropped"
    perm = perm.at[jnp.where(keep, out_pos, n)].set(order.astype(jnp.int32), mode="drop")
    count = jnp.sum(keep.astype(jnp.int32))
    return perm, count


_batched_kernel = jax.jit(
    jax.vmap(lambda a, t, u, o, s, tb, n: _materialize_kernel(a, t, u, o, s, tb, n=n),
             in_axes=(0, 0, 0, 0, 0, 0, None)),
    static_argnames=("n",),
)


def materialize_batch(rgas: Sequence[RGA]) -> List[List[str]]:
    """Materialize many RGA lists in one device program.

    Output is identical to calling ``rga.materialize()`` on each list
    (property-tested against the host implementation).
    """
    if not rgas:
        return []
    all_elems = [r.elems for r in rgas]
    n = bucket_size(max((len(e) for e in all_elems), default=1))
    b = len(all_elems)

    anchors = rank_intern([e.key.anchor for elems in all_elems for e in elems])[0]
    authors = rank_intern([e.key.author for elems in all_elems for e in elems])[0]
    opids = rank_intern([e.key.opid for elems in all_elems for e in elems])[0]

    a = np.full((b, n), _PAD, np.int32)
    t = np.full((b, n), _PAD, np.int32)
    u = np.full((b, n), _PAD, np.int32)
    o = np.full((b, n), _PAD, np.int32)
    s = np.full((b, n), _PAD, np.int32)
    tb = np.ones((b, n), bool)  # padding is tombstoned
    flat = 0
    for i, elems in enumerate(all_elems):
        for j, e in enumerate(elems):
            a[i, j] = anchors[flat]
            u[i, j] = authors[flat]
            o[i, j] = opids[flat]
            t[i, j] = e.key.t
            s[i, j] = j  # elems list order = converged insert order
            tb[i, j] = e.tombstone
            flat += 1

    perm, count = _batched_kernel(a, t, u, o, s, tb, n)
    perm = np.asarray(perm)
    count = np.asarray(count)
    out: List[List[str]] = []
    for i, elems in enumerate(all_elems):
        out.append([elems[perm[i, k]].value for k in range(int(count[i]))])
    return out
