"""Batched SHA-256 on device.

The engine's deterministic op identity is SHA-256 over a fixed
51-byte payload — (seed, rev) prefix digest ‖ op index ‖ type code ‖
three 80-bit string value digests (see
:func:`semantic_merge_tpu.core.ids.deterministic_op_id`, replacing the
reference's ``crypto.randomUUID()`` at reference
``workers/ts/src/lift.ts:5-9``) — and the composition sort key *ranks
those ids* (reference ``semmerge/compose.py:16-18``). So a merge
pipeline that wants to stay on device between the diff join and the
composition scans must produce the hashes on device: this module is
what makes the one-round-trip fused merge program possible on a
remote-attached TPU, where every host↔device hop costs ~65 ms.

SHA-256 is pure 32-bit integer arithmetic — rotations, xors, modular
adds — which vectorizes perfectly across message lanes: one lane per
op, every round executed SIMD across the whole op batch on the VPU.
The message schedule is unrolled (48 static steps); the 64 rounds run
as a ``lax.fori_loop`` so the program stays compact for XLA.

Messages are fixed-capacity rows (``B`` 64-byte blocks, static) with a
dynamic byte length per row; standard SHA padding (0x80, zeros, 64-bit
big-endian bit length) is applied on device. Callers guarantee
``msg_len <= B*64 - 9`` so padding never truncates.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

# Round constants (FIPS 180-4).
_K = [
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
]

_H0 = [0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
       0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19]


def _rotr(x, n: int):
    return (x >> jnp.uint32(n)) | (x << jnp.uint32(32 - n))


def _pad_and_pack(msg: jnp.ndarray, msg_len: jnp.ndarray) -> jnp.ndarray:
    """Apply SHA padding and pack bytes into big-endian uint32 words.

    ``msg``: uint8 ``[n, B*64]`` (bytes past ``msg_len`` are ignored);
    ``msg_len``: int32 ``[n]``. Returns uint32 ``[n, B*16]``.

    Rows are padded to their *own* final block — 0x80 after the
    message, the 64-bit big-endian bit length in the last 8 bytes of
    block ``ceil((len+9)/64)`` — not to the buffer capacity; the
    compression loop in :func:`sha256_device` stops per-row at that
    block, so a fixed-capacity batch hashes identically to
    :mod:`hashlib` on each row.
    """
    n, cap = msg.shape
    pos = jnp.arange(cap, dtype=jnp.int32)[None, :]
    length = msg_len[:, None]
    endpos = ((msg_len + 9 + 63) // 64)[:, None] * 64  # per-row padded end
    b = jnp.where(pos < length, msg, jnp.uint8(0))
    b = jnp.where(pos == length, jnp.uint8(0x80), b).astype(jnp.uint32)
    # Messages here are far below 2**29 bytes, so the high length word
    # is always zero and 32-bit shifts suffice.
    bitlen = (msg_len.astype(jnp.uint32) * 8)[:, None]
    shift = 8 * (endpos - 1 - pos)  # negative past the row's end
    in_zone = (pos >= endpos - 8) & (pos < endpos)
    sh = jnp.clip(shift, 0, 31).astype(jnp.uint32)
    len_byte = jnp.where(in_zone & (shift < 32), (bitlen >> sh) & 0xFF, 0)
    b = jnp.where(in_zone, b | len_byte, b)
    w = b.reshape(n, cap // 4, 4)
    return (w[:, :, 0] << 24) | (w[:, :, 1] << 16) | (w[:, :, 2] << 8) | w[:, :, 3]


def _compress_block(state, block):
    """One SHA-256 compression over a ``[n, 16]`` uint32 block; the 64
    rounds run as a fori_loop with the message schedule precomputed."""
    w = [block[:, t] for t in range(16)]
    for t in range(16, 64):
        s0 = _rotr(w[t - 15], 7) ^ _rotr(w[t - 15], 18) ^ (w[t - 15] >> jnp.uint32(3))
        s1 = _rotr(w[t - 2], 17) ^ _rotr(w[t - 2], 19) ^ (w[t - 2] >> jnp.uint32(10))
        w.append(w[t - 16] + s0 + w[t - 7] + s1)
    w_all = jnp.stack(w)                       # [64, n]
    k_all = jnp.asarray(_K, dtype=jnp.uint32)  # [64]

    def round_body(t, vs):
        a, b, c, d, e, f, g, h = vs
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + k_all[t] + w_all[t]
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        return (t1 + t2, a, b, c, d + t1, e, f, g)

    out = jax.lax.fori_loop(0, 64, round_body, tuple(state))
    return tuple(s + o for s, o in zip(state, out))


def sha256_device(msg: jnp.ndarray, msg_len: jnp.ndarray,
                  n_words: int = 8) -> jnp.ndarray:
    """Batched SHA-256: uint8 ``[n, B*64]`` + int32 ``[n]`` lengths →
    uint32 ``[n, n_words]`` big-endian digest words (``n_words=4`` gives
    the 128 bits an op id uses). Traceable; call inside jit."""
    n, cap = msg.shape
    assert cap % 64 == 0, "message capacity must be whole SHA blocks"
    words = _pad_and_pack(msg, msg_len)
    n_blocks = (msg_len + 9 + 63) // 64  # per-row block count
    init = tuple(jnp.full((n,), h, dtype=jnp.uint32) for h in _H0)

    def block_body(blk, state):
        block = jax.lax.dynamic_slice(words, (0, blk * 16), (n, 16))
        nxt = _compress_block(state, block)
        keep = blk < n_blocks  # [n] — rows already finished stay frozen
        return tuple(jnp.where(keep, nw, old) for nw, old in zip(nxt, state))

    state = jax.lax.fori_loop(0, cap // 64, block_body, init)
    return jnp.stack(state[:n_words], axis=1)


@partial(jax.jit, static_argnames=("n_words",))
def _sha256_jit(msg, msg_len, n_words: int = 8):
    return sha256_device(msg, msg_len, n_words)


def sha256_host_check(data: bytes, capacity_blocks: int) -> str:
    """Test helper: run the device implementation on one message and
    return the hex digest (compare against :mod:`hashlib`)."""
    import numpy as np
    cap = capacity_blocks * 64
    assert len(data) <= cap - 9
    row = np.zeros((1, cap), dtype=np.uint8)
    row[0, :len(data)] = np.frombuffer(data, dtype=np.uint8)
    out = np.asarray(_sha256_jit(row, np.asarray([len(data)], np.int32)))
    return "".join(f"{int(w):08x}" for w in out[0])
