"""Columnar op-log views — op logs without Op objects.

Round-4 profiling showed the fused merge's two largest host costs were
``materialize`` (building ~90k Python :class:`Op`/:class:`Target`
objects straight off the device fetch) and ``compose_decode`` (cloning
them again for the composed stream) — 455 ms + 359 ms of a 1,474 ms
rung-5 merge, more than the device kernel itself. The CLI then
immediately re-serializes those objects to the notes op-log JSON
(``cli.py`` → ``runtime/notes.py``), so the object layer existed only
to be flattened back out.

These views keep the fetched int32/digest columns as the source of
truth and materialize on three paths, lazily:

- ``to_json()`` — the notes/op-log payload, synthesized directly from
  the columns. Since the host-tail pipelining round this is SHARDED:
  the stream splits into row ranges, each range serializes
  independently (the native C renderer per shard, or the vectorized
  Python row synthesizer), and the shards byte-join in deterministic
  shard order — so worker threads can serialize shards concurrently
  (the C renderer runs GIL-free through ctypes) and the result is
  byte-identical to the single-pass serialization. Byte parity with
  ``OpLog([...]).to_json()`` over the materialized ops is fuzz-tested
  in ``tests/test_oplog_view.py``; the JSON shape is the reference
  parity surface (reference ``semmerge/ops.py:106-121``).
- ``view[i]`` — one op, built on demand and cached: the conflict
  constructors and spot inspections touch a handful of ops, not 90k.
- ``iter(view)`` — bulk materialization via the C op factory
  (``native/opfactory.c``), which since v2 borrows every field string
  from per-snapshot FIELD LISTS (one Python list per node column,
  cached by the engine) instead of UTF-8-decoding them out of a byte
  blob per op — materializing a 46k-op stream allocates ~46k id +
  summary strings instead of ~230k field strings.

The DivergentRename cursor walk gets a columnar twin here too: the
reference's head-vs-head walk (reference ``semmerge/compose.py:51-112``)
only ever reads ``(precedence, is-rename, symbolId, newName)`` and the
interner makes string equality equal int equality, so the walk runs on
int rows and materializes nothing.
"""
from __future__ import annotations

import re
from bisect import bisect_left, bisect_right
from json.encoder import encode_basestring
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.encode import shard_ranges
from ..core.ops import Op, Target, dumps_canonical

#: Device diff kinds (ops/diff.py) — re-declared to avoid a JAX import
#: in this pure-host module; pinned by tests against the real values.
KIND_RENAME, KIND_MOVE, KIND_ADD, KIND_DELETE = 0, 1, 2, 3

_OP_TYPE_BY_KIND = ("renameSymbol", "moveDecl", "addDecl", "deleteDecl")

#: Characters canonical JSON must escape (json.encoder.ESCAPE), given
#: ensure_ascii=False: quote, backslash, C0 controls.
_ESC_RE = re.compile(r'["\\\x00-\x1f]')


def _esc(s: str) -> str:
    """The exact string token ``json.dumps(s, ensure_ascii=False)``
    emits, quotes included — fast path for clean strings."""
    if _ESC_RE.search(s) is None:
        return f'"{s}"'
    return encode_basestring(s)


def _esc_body(s: str) -> str:
    """The escaped *body* of a JSON string token (no quotes). Escaping
    is per-character, so concatenating bodies with literal ASCII equals
    the body of the concatenation — summaries assemble from cached
    bodies without ever running the escape regex on the joined text."""
    if _ESC_RE.search(s) is None:
        return s
    return encode_basestring(s)[1:-1]


def format_ids(words: np.ndarray) -> List[str]:
    """int32-bitcast digest words [n, 4] → uuid-shaped id strings: one
    bulk hex conversion, then the dashes placed by vectorized byte
    scatter — the per-id work is a single 36-char slice (2× the
    f-string assembly this replaces; ~16 ms for 46k ids)."""
    hx = np.ascontiguousarray(words).view(np.uint32).astype(">u4").tobytes().hex()
    b = np.frombuffer(hx.encode(), np.uint8).reshape(-1, 32)
    out = np.empty((b.shape[0], 36), np.uint8)
    out[:, [8, 13, 18, 23]] = ord("-")
    out[:, 0:8] = b[:, 0:8]
    out[:, 9:13] = b[:, 8:12]
    out[:, 14:18] = b[:, 12:16]
    out[:, 19:23] = b[:, 16:20]
    out[:, 24:36] = b[:, 20:32]
    flat = out.tobytes().decode("ascii")
    return [flat[36 * i:36 * i + 36] for i in range(b.shape[0])]


def _node_table(nodes) -> Tuple[bytes, np.ndarray]:
    """Marshal a node list for the native serializer: one UTF-8 blob of
    the 4 per-node fields (symbolId, addressId, name, file) plus int64
    byte offsets (``4*m+1`` entries). NUL-safe: fields are byte ranges,
    never C strings."""
    fields = [x for nd in nodes
              for x in (nd.symbolId, nd.addressId, nd.name or "", nd.file)]
    joined = "".join(fields)
    if joined.isascii():
        lens = np.fromiter(map(len, fields), np.int64, count=len(fields))
        blob = joined.encode("ascii")
    else:  # rare: per-field encode so offsets stay byte-accurate
        enc = [f.encode("utf-8") for f in fields]
        lens = np.fromiter(map(len, enc), np.int64, count=len(enc))
        blob = b"".join(enc)
    offs = np.zeros(len(fields) + 1, np.int64)
    np.cumsum(lens, out=offs[1:])
    return blob, offs


def _node_fields(nodes) -> Tuple[list, list, list, list]:
    """Per-node field COLUMNS as four Python string lists (symbolId,
    addressId, name, file) — the C op factory borrows every field
    string from these instead of decoding bytes per op, and the
    vectorized Python serializer gathers from them by slot index
    (list indexing, no per-row attribute access)."""
    return ([nd.symbolId for nd in nodes], [nd.addressId for nd in nodes],
            [nd.name for nd in nodes], [nd.file for nd in nodes])


def _get_table(ref, nodes) -> Tuple[bytes, np.ndarray]:
    """Node table via the engine's per-snapshot cache when a stable
    identity exists (``ref = (cache, key)``), else built fresh."""
    cache = key = None
    if ref is not None:
        cache, key = ref
        if key is not None:
            hit = cache.get(key)
            if hit is not None and hit[2] == len(nodes):
                cache.move_to_end(key)
                return hit[0], hit[1]
    tbl = _node_table(nodes)
    if cache is not None and key is not None:
        cache[key] = (tbl[0], tbl[1], len(nodes))
        while len(cache) > 16:
            cache.popitem(last=False)
    return tbl


def _get_fields(ref, nodes) -> Tuple[list, list, list, list]:
    """Field columns via the same per-snapshot cache as
    :func:`_get_table` (entries keyed ``("fields", key)`` so tables and
    field lists coexist in one OrderedDict); built fresh when no stable
    identity exists."""
    cache = key = None
    if ref is not None:
        cache, raw_key = ref
        if raw_key is not None:
            key = ("fields", raw_key)
            hit = cache.get(key)
            if hit is not None and hit[1] == len(nodes):
                cache.move_to_end(key)
                return hit[0]
    fields = _node_fields(nodes)
    if cache is not None and key is not None:
        cache[key] = (fields, len(nodes))
        while len(cache) > 16:
            cache.popitem(last=False)
    return fields


#: Row templates for the vectorized Python serializer, one per kind.
#: ``%s`` slots receive already-escaped string BODIES (ids are hex and
#: never need escaping); the provenance literal is spliced in by
#: :func:`_kind_templates` with its ``%`` doubled.
_TMPL_RENAME = (
    '{"id":"%s","schemaVersion":1,"type":"renameSymbol","target":'
    '{"symbolId":"%s","addressId":"%s"},"params":{"oldName":"%s",'
    '"newName":"%s","file":"%s"},"guards":{"exists":true,'
    '"addressMatch":"%s"},"effects":{"summary":"rename %s→%s"},'
    '"provenance":')
_TMPL_MOVE = (
    '{"id":"%s","schemaVersion":1,"type":"moveDecl","target":'
    '{"symbolId":"%s","addressId":"%s"},"params":{"oldAddress":"%s",'
    '"newAddress":"%s","oldFile":"%s","newFile":"%s"},"guards":'
    '{"exists":true,"addressMatch":"%s"},"effects":{"summary":'
    '"move %s→%s"},"provenance":')
_TMPL_ADD = (
    '{"id":"%s","schemaVersion":1,"type":"addDecl","target":'
    '{"symbolId":"%s","addressId":"%s"},"params":{"file":"%s"},'
    '"guards":{},"effects":{"summary":"add decl"},"provenance":')
_TMPL_DELETE = (
    '{"id":"%s","schemaVersion":1,"type":"deleteDecl","target":'
    '{"symbolId":"%s","addressId":"%s"},"params":{"file":"%s"},'
    '"guards":{},"effects":{"summary":"delete decl"},"provenance":')


def _kind_templates(prov_json: str) -> Tuple[str, str, str, str]:
    suffix = prov_json.replace("%", "%%") + "}"
    return (_TMPL_RENAME + suffix, _TMPL_MOVE + suffix,
            _TMPL_ADD + suffix, _TMPL_DELETE + suffix)


class OpStreamView(Sequence):
    """One side's op log as fetched columns; a lazy ``Sequence[Op]``.

    Rows are ``(kind, a_slot, b_slot, digest_words)`` where the slots
    index the scanned decl node lists. Construction does no per-row
    work at all. ``pipeline`` (optional) is the engine's host-tail
    worker pool (:class:`semantic_merge_tpu.ops.fused.TailPipeline`);
    when set, bulk serialization shards across it."""

    __slots__ = ("kind", "a_slot", "b_slot", "words",
                 "base_nodes", "side_nodes", "prov",
                 "base_tbl_ref", "side_tbl_ref", "pipeline",
                 "render", "_ids", "_ops", "_all_done")

    def __init__(self, kind: np.ndarray, a_slot: np.ndarray,
                 b_slot: np.ndarray, words: np.ndarray,
                 base_nodes, side_nodes, prov: Dict,
                 base_tbl_ref=None, side_tbl_ref=None,
                 pipeline=None) -> None:
        self.kind = kind
        self.a_slot = a_slot
        self.b_slot = b_slot
        self.words = words
        self.base_nodes = base_nodes
        self.side_nodes = side_nodes
        self.prov = prov
        # Optional (cache, identity) pairs for the native serializer's
        # node tables / field lists — the fused engine shares them
        # across merges.
        self.base_tbl_ref = base_tbl_ref
        self.side_tbl_ref = side_tbl_ref
        self.pipeline = pipeline
        # Optional ops.render.RenderedStream handle attached by the
        # fused engine when the device rendered this stream's JSON.
        self.render = None
        self._ids: Optional[List[str]] = None
        self._ops: Optional[List[Optional[Op]]] = None
        self._all_done = False

    # -- sequence protocol -------------------------------------------------
    def __len__(self) -> int:
        return int(self.kind.shape[0])

    # -- columnar accessors ------------------------------------------------
    def base_fields(self) -> Tuple[list, list, list, list]:
        """The base snapshot's per-node field columns ``(symbolId,
        addressId, name, file)`` as plain string lists, via the engine's
        per-snapshot cache — the columnar applier reads op params
        through these instead of materializing ``Op`` objects."""
        return _get_fields(self.base_tbl_ref, self.base_nodes)

    def side_fields(self) -> Tuple[list, list, list, list]:
        """The side snapshot's field columns; see :meth:`base_fields`."""
        return _get_fields(self.side_tbl_ref, self.side_nodes)

    def ids(self) -> List[str]:
        if self._ids is None:
            self._ids = format_ids(self.words)
        return self._ids

    def _build_one(self, i: int) -> Op:
        k = int(self.kind[i])
        op_id = self.ids()[i]
        prov = self.prov
        if k == KIND_RENAME:
            a = self.base_nodes[int(self.a_slot[i])]
            b = self.side_nodes[int(self.b_slot[i])]
            return Op(op_id, 1, "renameSymbol",
                      Target(a.symbolId, a.addressId),
                      {"oldName": a.name, "newName": b.name, "file": b.file},
                      {"exists": True, "addressMatch": a.addressId},
                      {"summary": f"rename {a.name}→{b.name}"}, prov)
        if k == KIND_MOVE:
            a = self.base_nodes[int(self.a_slot[i])]
            b = self.side_nodes[int(self.b_slot[i])]
            return Op(op_id, 1, "moveDecl",
                      Target(a.symbolId, a.addressId),
                      {"oldAddress": a.addressId, "newAddress": b.addressId,
                       "oldFile": a.file, "newFile": b.file},
                      {"exists": True, "addressMatch": a.addressId},
                      {"summary": f"move {a.addressId}→{b.addressId}"}, prov)
        if k == KIND_ADD:
            b = self.side_nodes[int(self.b_slot[i])]
            return Op(op_id, 1, "addDecl", Target(b.symbolId, b.addressId),
                      {"file": b.file}, {}, {"summary": "add decl"}, prov)
        a = self.base_nodes[int(self.a_slot[i])]
        return Op(op_id, 1, "deleteDecl", Target(a.symbolId, a.addressId),
                  {"file": a.file}, {}, {"summary": "delete decl"}, prov)

    def __getitem__(self, i: int) -> Op:
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        n = len(self)
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(i)
        if self._ops is None:
            self._ops = [None] * n
        op = self._ops[i]
        if op is None:
            op = self._ops[i] = self._build_one(i)
        return op

    def _c_stream_args(self):
        """(columns..., field lists...) tuple prefix shared by the C
        factory entry points: 4 contiguous int32 arrays + the 8 cached
        per-node field lists (base then side). Empty streams are the
        CALLER'S guard (len > 0 checks) — this always returns the
        tuple."""
        bf = _get_fields(self.base_tbl_ref, self.base_nodes)
        sf = _get_fields(self.side_tbl_ref, self.side_nodes)
        return (np.ascontiguousarray(self.kind, np.int32),
                np.ascontiguousarray(self.a_slot, np.int32),
                np.ascontiguousarray(self.b_slot, np.int32),
                np.ascontiguousarray(self.words, np.int32),
                *bf, *sf)

    def materialize(self) -> List[Op]:
        """Every op as an object — via the C factory
        (``native/opfactory.c``) when available, else per-kind Python
        loops. Paid only when a consumer actually iterates."""
        if self._all_done:
            return self._ops  # type: ignore[return-value]
        if self._ops is None and len(self) > 0:
            from ..frontend.native import load_opfactory
            fac = load_opfactory()
            if fac is not None:
                ops = fac.stream_ops(*self._c_stream_args(), self.prov,
                                     Op, Target)
                self._ops = ops
                self._all_done = True
                return ops
        ids = self.ids()
        n = len(self)
        ops: List[Optional[Op]] = self._ops if self._ops is not None else [None] * n
        prov = self.prov
        base_nodes, side_nodes = self.base_nodes, self.side_nodes
        kinds = self.kind
        for k in (KIND_RENAME, KIND_MOVE, KIND_ADD, KIND_DELETE):
            idxs = np.nonzero(kinds == k)[0]
            if not len(idxs):
                continue
            ai = self.a_slot[idxs].tolist()
            bi = self.b_slot[idxs].tolist()
            where = idxs.tolist()
            if k == KIND_RENAME:
                for i, x, y in zip(where, ai, bi):
                    if ops[i] is not None:
                        continue
                    a, b = base_nodes[x], side_nodes[y]
                    ops[i] = Op(ids[i], 1, "renameSymbol",
                                Target(a.symbolId, a.addressId),
                                {"oldName": a.name, "newName": b.name,
                                 "file": b.file},
                                {"exists": True, "addressMatch": a.addressId},
                                {"summary": f"rename {a.name}→{b.name}"}, prov)
            elif k == KIND_MOVE:
                for i, x, y in zip(where, ai, bi):
                    if ops[i] is not None:
                        continue
                    a, b = base_nodes[x], side_nodes[y]
                    ops[i] = Op(ids[i], 1, "moveDecl",
                                Target(a.symbolId, a.addressId),
                                {"oldAddress": a.addressId,
                                 "newAddress": b.addressId,
                                 "oldFile": a.file, "newFile": b.file},
                                {"exists": True, "addressMatch": a.addressId},
                                {"summary":
                                 f"move {a.addressId}→{b.addressId}"}, prov)
            elif k == KIND_ADD:
                for i, y in zip(where, bi):
                    if ops[i] is not None:
                        continue
                    b = side_nodes[y]
                    ops[i] = Op(ids[i], 1, "addDecl",
                                Target(b.symbolId, b.addressId),
                                {"file": b.file}, {},
                                {"summary": "add decl"}, prov)
            else:
                for i, x in zip(where, ai):
                    if ops[i] is not None:
                        continue
                    a = base_nodes[x]
                    ops[i] = Op(ids[i], 1, "deleteDecl",
                                Target(a.symbolId, a.addressId),
                                {"file": a.file}, {},
                                {"summary": "delete decl"}, prov)
        self._ops = ops
        self._all_done = True
        return ops  # type: ignore[return-value]

    def __iter__(self):
        return iter(self.materialize())

    # -- columnar serialization --------------------------------------------
    def to_json(self) -> str:
        """The canonical op-log JSON, straight from the columns — no
        ``Op`` allocation. Byte-identical to
        ``dumps_canonical([op.to_dict() for op in self])``.

        Prefers the native C renderer (``smn_oplog_json``): node string
        tables + int32 columns in, JSON bytes out (~20× the Python
        row loop); falls back to the vectorized Python serializer when
        the native library is unavailable."""
        return self.to_json_bytes().decode("utf-8")

    def to_json_bytes(self) -> bytes:
        """UTF-8 bytes of :meth:`to_json` — the native path hands the C
        buffer through without the 20 MB-scale decode/encode round trip
        (the notes writer consumes bytes directly).

        With a :attr:`pipeline` attached and enough rows, the stream
        serializes in row-range SHARDS submitted to the worker pool
        (the native renderer releases the GIL through ctypes, so shards
        genuinely overlap on multi-core hosts) and the shard bodies
        byte-join in deterministic shard order — output identical to
        the single-pass serialization for every worker count."""
        n = len(self)
        if n == 0:
            return b"[]"
        rh = self.render
        if rh is not None:
            # Device-rendered payload: one d2h copy + mask-concat
            # (ops/render.py). A None return is the degradable-posture
            # containment — fall through to the host serializers.
            raw = rh.json_bytes()
            if raw is not None:
                return raw
            self.render = None
        pipe = self.pipeline
        # Sharded serialization only buys time when shards can actually
        # run concurrently (multi-worker AND multi-core — the pipeline's
        # eager_overlap condition); otherwise the per-shard call
        # overhead is pure cost and the single native pass wins.
        if pipe is not None and pipe.eager_overlap and n > pipe.shard_rows:
            parts = self._shard_json_bodies(pipe)
            if parts is not None:
                return b"[" + b",".join(parts) + b"]"
        raw = self._to_json_native_bytes()
        if raw is not None:
            return raw
        return self._to_json_py().encode("utf-8")

    def _shard_json_bodies(self, pipe) -> Optional[List[bytes]]:
        """Serialize in shards over the pipeline pool; returns the
        bracket-stripped shard bodies in shard order, or ``None`` when
        the native renderer is unavailable (caller falls back to one
        Python pass — the vectorized serializer already batches
        internally, so sharding it buys nothing without the GIL-free
        native path)."""
        from ..frontend.native import available
        if not available():
            return None
        # Prebuild shared state in THIS thread: the table/field caches
        # and the id list are plain dict/list mutations, not safe to
        # race from pool workers.
        self._native_args_prefix()
        ranges = shard_ranges(len(self), pipe.shard_rows)
        futs = [pipe.submit(self._native_shard_body, lo, hi)
                for lo, hi in ranges]
        parts = [f.result() for f in futs]
        if any(p is None for p in parts):
            return None
        return parts  # type: ignore[return-value]

    def _native_args_prefix(self):
        base_tbl = _get_table(self.base_tbl_ref, self.base_nodes)
        side_tbl = _get_table(self.side_tbl_ref, self.side_nodes)
        return (np.ascontiguousarray(self.kind, np.int32),
                np.ascontiguousarray(self.a_slot, np.int32),
                np.ascontiguousarray(self.b_slot, np.int32),
                np.ascontiguousarray(self.words, np.int32),
                base_tbl[0], base_tbl[1], side_tbl[0], side_tbl[1])

    def _native_shard_body(self, lo: int, hi: int) -> Optional[bytes]:
        """One shard's rows as a bracket-stripped JSON body (the native
        renderer emits ``[rows]``; shard bodies re-join with commas)."""
        from ..frontend.native import try_oplog_json_bytes
        kind, a_slot, b_slot, words, bb, bo, sb, so = \
            self._native_args_prefix()
        raw = try_oplog_json_bytes(
            hi - lo, kind[lo:hi], a_slot[lo:hi], b_slot[lo:hi],
            words[lo:hi], bb, bo, sb, so, dumps_canonical(self.prov))
        if raw is None:
            return None
        return raw[1:-1]

    def _native_args(self):
        return (len(self), *self._native_args_prefix(),
                dumps_canonical(self.prov))

    def _to_json_native_bytes(self) -> Optional[bytes]:
        from ..frontend.native import try_oplog_json_bytes
        return try_oplog_json_bytes(*self._native_args())

    def _json_rows(self, lo: int, hi: int) -> List[str]:
        """Rows ``lo:hi`` as JSON object strings — the vectorized
        Python serializer. All column prep is batched: numpy row
        selection per kind, field gathers from the cached per-node
        string LISTS (no attribute access), escape-once-per-unique
        string via a shared body cache, and one C-level ``%`` format
        per row; rows land in stream order via object-array scatter."""
        ids = self.ids()
        kinds = self.kind[lo:hi]
        n = hi - lo
        rows = np.empty(n, dtype=object)
        bsym, baddr, bname, bfile = _get_fields(self.base_tbl_ref,
                                                self.base_nodes)
        ssym, saddr, sname, sfile = _get_fields(self.side_tbl_ref,
                                                self.side_nodes)
        tmpl = _kind_templates(dumps_canonical(self.prov))
        # Escaped-body cache: every string is escape-checked at most
        # once per call (files repeat per decl, addressIds per row) and
        # summaries concatenate cached bodies — zero regex on the
        # composed text.
        bc: Dict[str, str] = {}
        bc_get = bc.get

        def body(s: str) -> str:
            r = bc_get(s)
            if r is None:
                r = bc[s] = _esc_body(s)
            return r

        for k in (KIND_RENAME, KIND_MOVE, KIND_ADD, KIND_DELETE):
            where = np.nonzero(kinds == k)[0]
            if not len(where):
                continue
            ai = self.a_slot[lo:hi][where].tolist()
            bi = self.b_slot[lo:hi][where].tolist()
            widx = where.tolist()
            rid = [ids[lo + i] for i in widx]
            if k == KIND_RENAME:
                sym = [body(bsym[x]) for x in ai]
                ea = [body(baddr[x]) for x in ai]
                an = [body(bname[x]) for x in ai]
                bn = [body(sname[y]) for y in bi]
                fl = [body(sfile[y]) for y in bi]
                rows[where] = list(map(tmpl[0].__mod__, zip(
                    rid, sym, ea, an, bn, fl, ea, an, bn)))
            elif k == KIND_MOVE:
                sym = [body(bsym[x]) for x in ai]
                ea = [body(baddr[x]) for x in ai]
                eb = [body(saddr[y]) for y in bi]
                af = [body(bfile[x]) for x in ai]
                bf = [body(sfile[y]) for y in bi]
                rows[where] = list(map(tmpl[1].__mod__, zip(
                    rid, sym, ea, ea, eb, af, bf, ea, ea, eb)))
            elif k == KIND_ADD:
                sym = [body(ssym[y]) for y in bi]
                eb = [body(saddr[y]) for y in bi]
                fl = [body(sfile[y]) for y in bi]
                rows[where] = list(map(tmpl[2].__mod__, zip(
                    rid, sym, eb, fl)))
            else:
                sym = [body(bsym[x]) for x in ai]
                ea = [body(baddr[x]) for x in ai]
                fl = [body(bfile[x]) for x in ai]
                rows[where] = list(map(tmpl[3].__mod__, zip(
                    rid, sym, ea, fl)))
        return rows.tolist()

    def _to_json_py(self) -> str:
        return "[" + ",".join(self._json_rows(0, len(self))) + "]"


class ComposedOpView(Sequence):
    """The composed stream as references into the two side views plus
    per-row chain overrides — a lazy ``Sequence[Op]``.

    ``sides``/``idxs`` index raw (unsorted) stream positions (plain
    lists or int32 numpy arrays); ``addr_s``/``file_s``/``name_s``
    carry the decoded chain-override strings (``None`` = no override),
    exactly the arguments the eager path fed
    :func:`_materialize_decoded`.

    ``left``/``right`` are usually :class:`OpStreamView` columns (the
    fused path), but any indexable ``Sequence[Op]`` works — the device
    composer hands its sorted *object* streams through the same class,
    so every composed result reaches the applier as one shape. Column
    consumers gate on :attr:`supports_columns`."""

    __slots__ = ("sides", "idxs", "addr_s", "file_s", "name_s",
                 "left", "right", "_all", "_chains_thunk", "_plan")

    def __init__(self, sides, idxs,
                 addr_s: Optional[List[Optional[str]]],
                 file_s: Optional[List[Optional[str]]],
                 name_s: Optional[List[Optional[str]]],
                 left: OpStreamView, right: OpStreamView) -> None:
        self.sides = sides
        self.idxs = idxs
        self.addr_s = addr_s
        self.file_s = file_s
        self.name_s = name_s
        self.left = left
        self.right = right
        self._all: Optional[List[Op]] = None
        self._chains_thunk = None
        self._plan = None

    @classmethod
    def deferred(cls, sides, idxs, chains_thunk,
                 left: OpStreamView, right: OpStreamView
                 ) -> "ComposedOpView":
        """A view whose chain-override columns are produced by
        ``chains_thunk() -> (addr_s, file_s, name_s)`` at first op
        access. The split-fetch fused merge uses this to leave the
        chain columns streaming device→host while the caller works off
        the op streams (e.g. serializing payloads); ``len()`` and the
        row structure stay available without forcing the fetch."""
        view = cls(sides, idxs, None, None, None, left, right)
        view._chains_thunk = chains_thunk
        return view

    @classmethod
    def pipelined(cls, sides, idxs, plan,
                  left: OpStreamView, right: OpStreamView
                  ) -> "ComposedOpView":
        """A view whose chain decode AND op materialization run as
        row-range shards over the host-tail worker pool (``plan`` is a
        :class:`semantic_merge_tpu.ops.fused.TailPlan`). Shard results
        concatenate in deterministic shard order, so the materialized
        sequence is identical to the serial path for every worker
        count."""
        view = cls(sides, idxs, None, None, None, left, right)
        view._plan = plan
        return view

    def _force_chains(self) -> None:
        if self.addr_s is not None:
            return
        if self._plan is not None:
            self.addr_s, self.file_s, self.name_s = self._plan.decode_all()
        else:
            self.addr_s, self.file_s, self.name_s = self._chains_thunk()
            self._chains_thunk = None

    def __len__(self) -> int:
        return len(self.sides)

    @property
    def supports_columns(self) -> bool:
        """Whether both sources are columnar :class:`OpStreamView`
        streams — the gate for column consumers (the columnar applier,
        the C composed-op factory). Object-backed views (the device
        composer's sorted op lists) answer False and materialize rows
        instead."""
        return (isinstance(self.left, OpStreamView)
                and isinstance(self.right, OpStreamView))

    def apply_shard_ranges(self) -> List[Tuple[int, int]]:
        """Contiguous ascending ``(lo, hi)`` row ranges a shard-wise
        consumer should walk — the PR-2 tail plan's shard boundaries
        when this view is pipelined (so per-shard chain decodes already
        submitted to the worker pool are consumed as they land, and on
        a split-fetch merge the first shards apply while later chain
        bytes are still streaming device→host), else one full range."""
        if self._plan is not None:
            return list(self._plan.ranges)
        n = len(self)
        return [(0, n)] if n else []

    def override_rows(self, lo: int, hi: int
                      ) -> Tuple[list, list, list]:
        """The decoded chain-override columns ``(addr, file, name)``
        for rows ``lo:hi`` (local indexing, ``None`` = no override).
        ``(lo, hi)`` must be one of :meth:`apply_shard_ranges` when the
        view is pipelined — those are the granularity the tail plan
        memoizes (and may already have decoded in a worker)."""
        if self.addr_s is not None:
            return (self.addr_s[lo:hi], self.file_s[lo:hi],
                    self.name_s[lo:hi])
        if self._plan is not None:
            return self._plan.shard_overrides(lo, hi)
        self._force_chains()
        return self.addr_s[lo:hi], self.file_s[lo:hi], self.name_s[lo:hi]

    def row_slices(self, lo: int, hi: int) -> Tuple[object, object]:
        """Zero-copy ``(sides, idxs)`` row slices for ``lo:hi`` —
        numpy views when the backing columns are arrays (the fused
        path), list slices otherwise."""
        return self.sides[lo:hi], self.idxs[lo:hi]

    def materialize_row(self, i: int) -> Op:
        """Escape hatch: ONE row as a full :class:`Op` — for the rare
        consumers that genuinely need structured params (conflict
        constructors, spot inspection, unknown-kind fallbacks) while
        the bulk path stays on the columns."""
        return self[i]

    def __getitem__(self, i: int) -> Op:
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        n = len(self)
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(i)
        if self._all is not None:
            return self._all[i]
        self._force_chains()
        src = self.left if self.sides[i] == 0 else self.right
        return _materialize_decoded(src[int(self.idxs[i])], self.addr_s[i],
                                    self.file_s[i], self.name_s[i])

    def _shard_ops(self, lo: int, hi: int,
                   overrides: Tuple[list, list, list]) -> List[Op]:
        """Materialize composed rows ``lo:hi`` (one pipeline shard).
        ``overrides`` are the shard's decoded chain columns (local
        indexing: row ``lo + j`` uses ``overrides[*][j]``)."""
        addr_s, file_s, name_s = overrides
        sides = np.ascontiguousarray(np.asarray(self.sides[lo:hi]), np.int32)
        idxs = np.ascontiguousarray(np.asarray(self.idxs[lo:hi]), np.int32)
        if hi > lo:
            from ..frontend.native import load_opfactory
            fac = load_opfactory()
            if fac is not None:
                return fac.composed_ops(
                    *self.left._c_stream_args(),
                    *self.right._c_stream_args(),
                    sides, idxs, addr_s, file_s, name_s,
                    self.left.prov, self.right.prov, Op, Target)
        left_ops = self.left
        right_ops = self.right
        return [
            _materialize_decoded(
                (left_ops if side == 0 else right_ops)[int(i)], na, nf, nn)
            for side, i, na, nf, nn in zip(sides.tolist(), idxs.tolist(),
                                           addr_s, file_s, name_s)]

    def materialize(self) -> List[Op]:
        if self._all is not None:
            return self._all
        plan = self._plan
        if plan is not None:
            # Shard fan-out over the host-tail pool: each shard decodes
            # its chain-override rows and builds its ops; results
            # concatenate in shard order (deterministic merge). With
            # one worker this degrades to the serial loop over the
            # same shard boundaries — byte/value-identical output.
            futs = [plan.submit_materialize(
                        lo, hi, lambda l, h, ov: self._shard_ops(l, h, ov))
                    for lo, hi in plan.ranges]
            out: List[Op] = []
            for f in futs:
                out.extend(f.result())
            self._all = out
            return out
        self._force_chains()
        if len(self) > 0 and self.supports_columns:
            from ..frontend.native import load_opfactory
            fac = load_opfactory()
            if fac is not None:
                # One C pass builds every final composed op straight
                # from the two streams' columns + per-row overrides;
                # the intermediate stream objects never materialize.
                # (Ops are value-identical to the Python path but
                # always fresh — no sharing with the stream views.)
                self._all = fac.composed_ops(
                    *self.left._c_stream_args(),
                    *self.right._c_stream_args(),
                    np.ascontiguousarray(np.asarray(self.sides), np.int32),
                    np.ascontiguousarray(np.asarray(self.idxs), np.int32),
                    self.addr_s, self.file_s, self.name_s,
                    self.left.prov, self.right.prov, Op, Target)
                return self._all
        ops_l = (self.left.materialize()
                 if isinstance(self.left, OpStreamView) else self.left)
        ops_r = (self.right.materialize()
                 if isinstance(self.right, OpStreamView) else self.right)
        self._all = [
            _materialize_decoded(
                (ops_l if side == 0 else ops_r)[int(i)], na, nf, nn)
            for side, i, na, nf, nn in zip(self.sides, self.idxs,
                                           self.addr_s, self.file_s,
                                           self.name_s)]
        return self._all

    def __iter__(self):
        return iter(self.materialize())

    # -- columnar serialization --------------------------------------------
    def to_json(self) -> str:
        return self.to_json_bytes().decode("utf-8")

    def to_json_bytes(self) -> bytes:
        """The composed op-log as canonical JSON bytes — identical to
        ``dumps_canonical([op.to_dict() for op in self])``.

        Device-rendered variant: when both source streams carry a
        :class:`~semantic_merge_tpu.ops.render.RenderedStream` handle,
        the composed payload splices the device-rendered row bytes in
        composed ``(side, idx)`` order; only rows with chain overrides
        (a changed address/file or an appended renameContext — the
        :func:`_materialize_decoded` cases) materialize an ``Op`` and
        re-serialize on the host. Everything else falls back to the
        object path."""
        if len(self) == 0:
            return b"[]"
        if self.supports_columns:
            raw = self._rendered_bytes()
            if raw is not None:
                return raw
        return dumps_canonical(
            [op.to_dict() for op in self.materialize()]).encode("utf-8")

    def _rendered_bytes(self) -> Optional[bytes]:
        lh = getattr(self.left, "render", None)
        rh = getattr(self.right, "render", None)
        if lh is None or rh is None:
            return None
        lrows = lh.row_bytes()
        rrows = rh.row_bytes()
        if lrows is None or rrows is None:
            return None
        self._force_chains()
        addr_s, file_s, name_s = self.addr_s, self.file_s, self.name_s
        lkind, rkind = self.left.kind, self.right.kind
        parts: List[bytes] = []
        for i, (side, idx) in enumerate(zip(self.sides, self.idxs)):
            na, nf, nn = addr_s[i], file_s[i], name_s[i]
            left = side == 0
            if na is None and nf is None and (
                    nn is None
                    or int((lkind if left else rkind)[idx]) == KIND_RENAME):
                parts.append((lrows if left else rrows)[int(idx)])
            else:
                op = _materialize_decoded(
                    (self.left if left else self.right)[int(idx)],
                    na, nf, nn)
                parts.append(dumps_canonical(op.to_dict()).encode("utf-8"))
        return b"[" + b",".join(parts) + b"]"


def _materialize_decoded(op: Op, new_addr: Optional[str],
                         new_file: Optional[str],
                         rename_ctx: Optional[str]) -> Op:
    """Apply a row's decoded chain overrides to its stream op (shared
    with the eager two-program decode; observable output identical to
    the host composer's deep clone — see ``core.compose._materialize``)."""
    if new_addr is None and new_file is None and (
            rename_ctx is None or op.type == "renameSymbol"):
        return op
    cloned = Op(id=op.id, schemaVersion=op.schemaVersion, type=op.type,
                target=op.target, params=dict(op.params),
                guards=op.guards, effects=op.effects,
                provenance=op.provenance)
    if new_addr is not None or new_file is not None:
        if cloned.type == "moveDecl":
            if new_addr is not None:
                cloned.params["newAddress"] = new_addr
            if new_file is not None:
                cloned.params["newFile"] = new_file
        if new_addr is not None:
            cloned.target = Target(symbolId=cloned.target.symbolId,
                                   addressId=new_addr)
        if cloned.type == "renameSymbol" and new_file is not None:
            cloned.params["newFile"] = new_file
            cloned.params["file"] = new_file
    if rename_ctx is not None and cloned.type != "renameSymbol":
        cloned.params["renameContext"] = rename_ctx
    return cloned


def cursor_walk_conflicts_columnar(
        prec_a: List[int], ren_a: List[bool], sym_a: List[int],
        name_a: List[int],
        prec_b: List[int], ren_b: List[bool], sym_b: List[int],
        name_b: List[int]) -> Tuple[List[Tuple[int, int]], set, set]:
    """The reference's head-vs-head DivergentRename walk on int rows.

    Same algorithm (including the bisect bulk-advance) as
    :func:`semantic_merge_tpu.core.compose.cursor_walk_conflicts`, but
    the per-op reads — type, symbolId, newName — come from int columns:
    the interner is injective, so int equality IS string equality.
    Returns ``(pairs, dropped_a, dropped_b)`` where ``pairs`` are
    ``(ia, ib)`` sorted-stream positions of each conflict, in the
    walk's emission order. Parity with the Op-object walk is
    property-tested in ``tests/test_oplog_view.py``."""
    pairs: List[Tuple[int, int]] = []
    dropped_a: set = set()
    dropped_b: set = set()
    na, nb = len(prec_a), len(prec_b)
    ia = ib = 0
    while ia < na or ib < nb:
        if ib >= nb or not ren_b[ib]:
            if ia >= na:
                ib = nb
            elif ib >= nb:
                ia = na
            else:
                nxt = bisect_right(prec_a, prec_b[ib], ia, na)
                if nxt == ia:
                    ib += 1
                else:
                    ia = nxt
            continue
        if ia >= na or not ren_a[ia]:
            if ia >= na:
                ib = nb
            else:
                nxt = bisect_left(prec_b, prec_a[ia], ib, nb)
                if nxt == ib:
                    ia += 1
                else:
                    ib = nxt
            continue
        take_a = prec_a[ia] <= prec_b[ib]
        if sym_a[ia] == sym_b[ib] and name_a[ia] != name_b[ib]:
            pairs.append((ia, ib))
            dropped_a.add(ia)
            dropped_b.add(ib)
            ia += 1
            ib += 1
            continue
        if take_a:
            ia += 1
        else:
            ib += 1
    return pairs, dropped_a, dropped_b


def cursor_walk_conflicts_renames_only(
        ren_pos_a: np.ndarray, sym_a: np.ndarray, name_a: np.ndarray,
        ren_pos_b: np.ndarray, sym_b: np.ndarray, name_b: np.ndarray,
        prec_rename: int = 11
        ) -> Tuple[List[Tuple[int, int]], set, set]:
    """The cursor walk restricted to each stream's RENAME substream.

    For canonically-sorted streams over the fused path's op vocabulary
    (move=10 < rename=11 < add=30 < delete=31, one shared timestamp)
    the full walk can only emit conflicts at rename-vs-rename head
    pairs, and its bisect bulk-advances never let a non-rename reorder
    which rename pairs meet — so walking the two rename substreams
    yields exactly the full walk's pairs at a cost proportional to the
    RENAME count, not the op count (the rung-5 workload walks ~5k rows
    instead of ~47k). Equivalence is property-tested against the full
    walk in ``tests/test_oplog_view.py``.

    ``ren_pos_*`` are the rename rows' positions in the sorted streams;
    returned pairs/drop sets are mapped back to full-stream positions.
    """
    k_a, k_b = len(ren_pos_a), len(ren_pos_b)
    sub_pairs, sub_da, sub_db = cursor_walk_conflicts_columnar(
        [prec_rename] * k_a, [True] * k_a,
        sym_a.tolist(), name_a.tolist(),
        [prec_rename] * k_b, [True] * k_b,
        sym_b.tolist(), name_b.tolist())
    pairs = [(int(ren_pos_a[x]), int(ren_pos_b[y])) for x, y in sub_pairs]
    da = {int(ren_pos_a[x]) for x in sub_da}
    db = {int(ren_pos_b[y]) for y in sub_db}
    return pairs, da, db
