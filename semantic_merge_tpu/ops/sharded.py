"""Mesh-sharded merge kernels — the decl/op axis distributed over ``dp``.

This is the scale path of the north star (BASELINE.json: "op-log
sorting, chaining, CRDT reconciliation run as data-parallel segmented
scans across thousands of files … sharded symbol-ID join … across a
v4-8"). ``dp`` is the merge kernels' ONLY parallel axis by design:
their work is integer sort/join/scan over decl and op rows — there is
no weight matrix whose features could shard over ``tp`` and no layer
stack for ``pp``; the row axis IS the parallelism, and slicing it over
more devices is exactly what tp/pp would otherwise buy. (``tp``/
``pp``/``sp``/``ep`` carry the model half — the matcher encoder.) The
single-device kernels (:mod:`semantic_merge_tpu.ops.diff`,
:mod:`semantic_merge_tpu.ops.compose`) stay the fast path for one chip;
these twins run the same logic under :func:`jax.shard_map` over the
``dp`` axis of the framework mesh
(:mod:`semantic_merge_tpu.parallel.mesh`), with XLA collectives riding
ICI:

- **Diff sort-join** (reference ``workers/ts/src/diff.ts:5-31`` hash
  join): decl slots shard contiguously over ``dp``; each shard sorts
  its slice locally (the distributed sort), then **all-gathers the
  per-shard sorted symbol tables** — the symbol-table exchange of the
  north star — and answers its own slots' join queries against all
  ``k`` runs (first/last occurrence = min/max over shards, presence =
  any). Emission offsets are global prefix sums (local cumsum + an
  all-gather of shard totals); each shard scatters its ops into the
  full output and an elementwise ``pmax`` merges the shards (every
  position is written by exactly one shard; the fill ``NULL_ID`` is
  the identity).
- **Compose** (reference ``semmerge/compose.py:51-112``): op rows
  shard over ``dp``. The streams' key columns are all-gathered (11
  int32 columns — megabytes at 10k files, nothing against ICI), the
  canonical sorts and the sequential conflict cursor walk run
  replicated, the **DivergentRename candidate join** shards its query
  axis, and the **segmented chain scans** — the O(n) state propagation
  that dominates at scale — run as local
  ``lax.associative_scan`` slices with a carry exchange across shards
  (rows are sorted by symbol, so exactly one segment spans each shard
  boundary; the carries combine with the same associative operator).

Bit-parity with the single-device kernels and the host composer is
property-tested on the virtual 8-device CPU mesh
(``tests/test_sharded_merge.py``) and executed by the driver through
``__graft_entry__.dryrun_multichip``.
"""
from __future__ import annotations

from functools import lru_cache, partial
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..core.conflict import Conflict
from ..core.encode import NULL_ID, PAD_ID, DeclTensor, shard_bucket
from ..core.ops import Op
from ..utils.jaxenv import shard_map_compat
from .compose import (_conflict_cursor_walk, _merge_and_scan, _pad_op_tensor,
                      _rename_candidate_query, _rename_candidate_tables,
                      _rename_pairs, _seg_combine, _sort_stream,
                      decode_compose_output, encode_compose_inputs)
from .diff import (KIND_ADD, KIND_DELETE, KIND_MOVE, KIND_RENAME,
                   DiffOpsTensor, _decode_stacked, _padded_cols)

AXIS = "dp"

_INT_MAX = jnp.int32(2**31 - 1)


# --------------------------------------------------------------------------
# sharded diff sort-join
# --------------------------------------------------------------------------

def _local_sorted_run(sym):
    """Stable local sort of this shard's slice by symbol — one run of the
    distributed sort. Returns (sorted syms, sorted-position → local slot)."""
    order = jnp.argsort(sym, stable=True).astype(jnp.int32)
    return sym[order], order


def _run_query(tables, orders, offs, S: int, queries):
    """Join ``queries`` against all ``k`` gathered sorted runs.

    Returns per-query (present-anywhere, global first slot, global last
    slot). Stable sorting makes each run's boundary elements the
    smallest/largest local slot index of the symbol, so min/max over
    shards reconstruct the exact global occurrence bounds the
    single-device kernel reads off its one sorted array.
    """
    lo = jax.vmap(lambda t: jnp.searchsorted(t, queries, side="left"))(tables)
    hi = jax.vmap(lambda t: jnp.searchsorted(t, queries, side="right"))(tables) - 1
    lo_c = jnp.clip(lo, 0, S - 1)
    hi_c = jnp.clip(hi, 0, S - 1)
    present = jnp.take_along_axis(tables, lo_c, axis=1) == queries[None, :]
    first = jnp.take_along_axis(orders, lo_c, axis=1) + offs[:, None]
    last = jnp.take_along_axis(orders, hi_c, axis=1) + offs[:, None]
    g_first = jnp.min(jnp.where(present, first, _INT_MAX), axis=0)
    g_last = jnp.max(jnp.where(present, last, jnp.int32(-1)), axis=0)
    return jnp.any(present, axis=0), g_first, g_last


def _sharded_diff_core(b_sym, b_addr, b_name, b_file,
                       s_sym, s_addr, s_name, s_file,
                       nb: int, ns: int, k: int):
    """Per-shard body: local blocks of the base/side decl columns in,
    full (replicated) stacked op-stream matrix out."""
    j = lax.axis_index(AXIS)
    Sb, Ss = nb // k, ns // k
    my_b_idx = j * Sb + jnp.arange(Sb, dtype=jnp.int32)  # global base slots
    b_valid = b_sym != PAD_ID
    s_valid = s_sym != PAD_ID

    # Distributed sort: local runs, then the symbol-table all-gather.
    b_srt_l, b_ord_l = _local_sorted_run(b_sym)
    s_srt_l, s_ord_l = _local_sorted_run(s_sym)
    b_tab = lax.all_gather(b_srt_l, AXIS)          # (k, Sb)
    b_tord = lax.all_gather(b_ord_l, AXIS)
    s_tab = lax.all_gather(s_srt_l, AXIS)
    s_tord = lax.all_gather(s_ord_l, AXIS)
    off_b = jnp.arange(k, dtype=jnp.int32) * Sb
    off_s = jnp.arange(k, dtype=jnp.int32) * Ss
    # Raw columns, gathered for the cross-shard data lookups (the node
    # payload behind a matched symbol lives on whichever shard owns it).
    b_addr_g = lax.all_gather(b_addr, AXIS, tiled=True)
    b_name_g = lax.all_gather(b_name, AXIS, tiled=True)
    b_file_g = lax.all_gather(b_file, AXIS, tiled=True)
    s_addr_g = lax.all_gather(s_addr, AXIS, tiled=True)
    s_name_g = lax.all_gather(s_name, AXIS, tiled=True)
    s_file_g = lax.all_gather(s_file, AXIS, tiled=True)

    # Occurrence bounds of my base slots' symbols (JS Map semantics:
    # first occurrence emits, last occurrence's data wins).
    _, bg_first, bg_last = _run_query(b_tab, b_tord, off_b, Sb, b_sym)
    emits = b_valid & (bg_first == my_b_idx)
    bl = jnp.clip(bg_last, 0, nb - 1)
    b_addr_l = b_addr_g[bl]
    b_name_l = b_name_g[bl]
    b_file_l = b_file_g[bl]

    # Side representative (Map last-wins) for my base symbols.
    s_found, _, sg_last = _run_query(s_tab, s_tord, off_s, Ss, b_sym)
    found = s_found & b_valid
    sr = jnp.clip(sg_last, 0, ns - 1)
    s_addr_r = s_addr_g[sr]
    s_name_r = s_name_g[sr]
    s_file_r = s_file_g[sr]

    is_delete = emits & ~found
    is_move = emits & found & (b_addr_l != s_addr_r)
    is_rename = (emits & found & (b_name_l != NULL_ID) & (s_name_r != NULL_ID)
                 & (b_name_l != s_name_r))

    # Adds: my side slots whose symbol is absent from the whole base.
    in_base, _, _ = _run_query(b_tab, b_tord, off_b, Sb, s_sym)
    is_add = s_valid & ~in_base

    # Global emission offsets: local cumsum + prefix of shard totals.
    def global_offsets(count, prior_total):
        """(global emission position per slot, running global total)."""
        cum = jnp.cumsum(count)
        totals = lax.all_gather(cum[-1], AXIS)  # (k,)
        prev = jnp.sum(jnp.where(jnp.arange(k) < j, totals, 0))
        return prior_total + prev + cum - count, prior_total + jnp.sum(totals)

    base_count = jnp.where(is_delete, 1,
                           is_move.astype(jnp.int32) + is_rename.astype(jnp.int32))
    base_off, total_base = global_offsets(base_count, 0)
    add_off, total_all = global_offsets(is_add.astype(jnp.int32), total_base)
    n_ops = total_all

    m = 2 * nb + ns
    neg = jnp.int32(NULL_ID)

    def init():
        return jnp.full((m,), neg, dtype=jnp.int32)

    cols = [init() for _ in range(8)]

    def scatter(cols, posn, mask, values):
        posn = jnp.where(mask, posn, m)  # out-of-range rows drop
        return [arr.at[posn].set(val, mode="drop")
                for arr, val in zip(cols, values)]

    full_b = lambda v: jnp.full((Sb,), v, jnp.int32)  # noqa: E731
    full_s = lambda v: jnp.full((Ss,), v, jnp.int32)  # noqa: E731

    cols = scatter(cols, base_off, is_delete,
                   [full_b(KIND_DELETE), b_sym, b_addr_l, b_name_l, b_file_l,
                    full_b(NULL_ID), full_b(NULL_ID), full_b(NULL_ID)])
    cols = scatter(cols, base_off, is_move,
                   [full_b(KIND_MOVE), b_sym, b_addr_l, b_name_l, b_file_l,
                    s_addr_r, s_name_r, s_file_r])
    ren_pos = base_off + is_move.astype(jnp.int32)
    cols = scatter(cols, ren_pos, is_rename,
                   [full_b(KIND_RENAME), b_sym, b_addr_l, b_name_l, b_file_l,
                    s_addr_r, s_name_r, s_file_r])
    cols = scatter(cols, add_off, is_add,
                   [full_s(KIND_ADD), s_sym, full_s(NULL_ID), full_s(NULL_ID),
                    full_s(NULL_ID), s_addr, s_name, s_file])

    out = jnp.concatenate(
        [jnp.stack(cols), jnp.full((1, m), n_ops, jnp.int32)], axis=0)
    # Each emission position was written by exactly one shard (slots are
    # partitioned); everywhere else holds the fill NULL_ID — elementwise
    # max across the axis is the exact union.
    return lax.pmax(out, AXIS)


def _sharded_diff_slots(b_sym, b_addr, b_name, s_sym, s_addr, s_name,
                        nb: int, ns: int, k: int, C: int):
    """Per-shard diff join emitting compact ``(kind, base-slot,
    side-slot)`` rows — the sharded twin of the fused merge program's
    emitter (:func:`semantic_merge_tpu.ops.fused._emit_slots`). Slots
    are GLOBAL decl indices; outputs are replicated via ``pmax`` like
    the stacked-column variant above. Rows beyond capacity ``C`` drop
    (the caller checks ``n_ops > C`` and retries bigger)."""
    j = lax.axis_index(AXIS)
    Sb, Ss = nb // k, ns // k
    my_b_idx = j * Sb + jnp.arange(Sb, dtype=jnp.int32)
    my_s_idx = j * Ss + jnp.arange(Ss, dtype=jnp.int32)
    b_valid = b_sym != PAD_ID
    s_valid = s_sym != PAD_ID

    b_srt_l, b_ord_l = _local_sorted_run(b_sym)
    s_srt_l, s_ord_l = _local_sorted_run(s_sym)
    b_tab = lax.all_gather(b_srt_l, AXIS)
    b_tord = lax.all_gather(b_ord_l, AXIS)
    s_tab = lax.all_gather(s_srt_l, AXIS)
    s_tord = lax.all_gather(s_ord_l, AXIS)
    off_b = jnp.arange(k, dtype=jnp.int32) * Sb
    off_s = jnp.arange(k, dtype=jnp.int32) * Ss
    b_addr_g = lax.all_gather(b_addr, AXIS, tiled=True)
    b_name_g = lax.all_gather(b_name, AXIS, tiled=True)
    s_addr_g = lax.all_gather(s_addr, AXIS, tiled=True)
    s_name_g = lax.all_gather(s_name, AXIS, tiled=True)

    _, bg_first, bg_last = _run_query(b_tab, b_tord, off_b, Sb, b_sym)
    emits = b_valid & (bg_first == my_b_idx)
    bl = jnp.clip(bg_last, 0, nb - 1)
    b_addr_l = b_addr_g[bl]
    b_name_l = b_name_g[bl]

    s_found, _, sg_last = _run_query(s_tab, s_tord, off_s, Ss, b_sym)
    found = s_found & b_valid
    sr = jnp.clip(sg_last, 0, ns - 1)
    s_addr_r = s_addr_g[sr]
    s_name_r = s_name_g[sr]

    is_delete = emits & ~found
    is_move = emits & found & (b_addr_l != s_addr_r)
    is_rename = (emits & found & (b_name_l != NULL_ID) & (s_name_r != NULL_ID)
                 & (b_name_l != s_name_r))
    in_base, _, _ = _run_query(b_tab, b_tord, off_b, Sb, s_sym)
    is_add = s_valid & ~in_base

    def global_offsets(count, prior_total):
        cum = jnp.cumsum(count)
        totals = lax.all_gather(cum[-1], AXIS)
        prev = jnp.sum(jnp.where(jnp.arange(k) < j, totals, 0))
        return prior_total + prev + cum - count, prior_total + jnp.sum(totals)

    base_count = jnp.where(is_delete, 1,
                           is_move.astype(jnp.int32) + is_rename.astype(jnp.int32))
    base_off, total_base = global_offsets(base_count, 0)
    add_off, n_ops = global_offsets(is_add.astype(jnp.int32), total_base)

    neg = jnp.int32(NULL_ID)
    cols = [jnp.full((C,), neg) for _ in range(3)]

    def scatter(cols, posn, mask, values):
        posn = jnp.where(mask, posn, C)
        return [arr.at[posn].set(val, mode="drop")
                for arr, val in zip(cols, values)]

    full_b = lambda v: jnp.full((Sb,), v, jnp.int32)  # noqa: E731
    full_s = lambda v: jnp.full((Ss,), v, jnp.int32)  # noqa: E731
    cols = scatter(cols, base_off, is_delete,
                   [full_b(KIND_DELETE), bl, full_b(NULL_ID)])
    cols = scatter(cols, base_off, is_move, [full_b(KIND_MOVE), bl, sr])
    cols = scatter(cols, base_off + is_move.astype(jnp.int32), is_rename,
                   [full_b(KIND_RENAME), bl, sr])
    cols = scatter(cols, add_off, is_add,
                   [full_s(KIND_ADD), full_s(NULL_ID), my_s_idx])
    merged = [lax.pmax(c, AXIS) for c in cols]
    return merged[0], merged[1], merged[2], n_ops


@lru_cache(maxsize=None)
def _sharded_diff_fn(mesh: Mesh, nb: int, ns: int, k: int):
    spec = P(AXIS)
    return jax.jit(shard_map_compat(
        partial(_sharded_diff_core, nb=nb, ns=ns, k=k),
        mesh=mesh, in_specs=(spec,) * 8, out_specs=P(),
        check_vma=False))


@lru_cache(maxsize=None)
def _sharded_diff_pair_fn(mesh: Mesh, nb: int, nl: int, nr: int, k: int):
    spec = P(AXIS)

    def pair(b_sym, b_addr, b_name, b_file,
             l_sym, l_addr, l_name, l_file,
             r_sym, r_addr, r_name, r_file):
        out_l = _sharded_diff_core(b_sym, b_addr, b_name, b_file,
                                   l_sym, l_addr, l_name, l_file,
                                   nb=nb, ns=nl, k=k)
        out_r = _sharded_diff_core(b_sym, b_addr, b_name, b_file,
                                   r_sym, r_addr, r_name, r_file,
                                   nb=nb, ns=nr, k=k)
        m = max(out_l.shape[1], out_r.shape[1])

        def pad(a):
            return jnp.pad(a, ((0, 0), (0, m - a.shape[1])),
                           constant_values=NULL_ID)

        return jnp.stack([pad(out_l), pad(out_r)])

    return jax.jit(shard_map_compat(
        pair, mesh=mesh, in_specs=(spec,) * 12, out_specs=P(),
        check_vma=False))


def _dp_size(mesh: Mesh) -> int:
    return dict(zip(mesh.axis_names, mesh.devices.shape))[AXIS]


def _bucket(n: int, k: int) -> int:
    return shard_bucket(n, k)


def diff_lift_device_sharded(base: DeclTensor, side: DeclTensor,
                             mesh: Mesh) -> DiffOpsTensor:
    """Mesh twin of :func:`semantic_merge_tpu.ops.diff.diff_lift_device`."""
    from ..obs import spans as obs_spans
    k = _dp_size(mesh)
    nb, ns = _bucket(base.n, k), _bucket(side.n, k)
    with obs_spans.span("diff_sharded", layer="ops", shards=k):
        fn = _sharded_diff_fn(mesh, nb, ns, k)
        out = np.asarray(fn(*_padded_cols(base, nb), *_padded_cols(side, ns)))
        return _decode_stacked(out)


def diff_lift_device_pair_sharded(base: DeclTensor, left: DeclTensor,
                                  right: DeclTensor, mesh: Mesh
                                  ) -> tuple[DiffOpsTensor, DiffOpsTensor]:
    """Mesh twin of :func:`semantic_merge_tpu.ops.diff.diff_lift_device_pair`."""
    from ..obs import spans as obs_spans
    k = _dp_size(mesh)
    nb = _bucket(base.n, k)
    nl = _bucket(left.n, k)
    nr = _bucket(right.n, k)
    with obs_spans.span("diff_pair_sharded", layer="ops", shards=k):
        fn = _sharded_diff_pair_fn(mesh, nb, nl, nr, k)
        out = np.asarray(fn(*_padded_cols(base, nb), *_padded_cols(left, nl),
                            *_padded_cols(right, nr)))
        return _decode_stacked(out[0]), _decode_stacked(out[1])


# --------------------------------------------------------------------------
# sharded compose
# --------------------------------------------------------------------------

def _dist_seg_scan(k: int, seg_sym, seg_order, vals):
    """Distributed segmented last-valid scan over the ``dp`` axis.

    Rows are in (symbol, merged position) order, so each shard's slice
    is a contiguous range of at most one boundary-spanning segment per
    edge. Each shard scans its slice locally; the per-shard carries
    (last row's symbol/value/validity) are all-gathered and prefix-
    combined with the same associative operator; the incoming carry is
    applied elementwise. Bit-identical to the single-device scan —
    integer ops under an exactly associative combine.
    """
    j = lax.axis_index(AXIS)
    total = seg_sym.shape[0]
    T = total // k
    v_sorted = vals[seg_order]
    m_sorted = v_sorted != NULL_ID

    start = (j * T,)
    my_sym = lax.dynamic_slice(seg_sym, start, (T,))
    my_v = lax.dynamic_slice(v_sorted, start, (T,))
    my_m = lax.dynamic_slice(m_sorted, start, (T,))

    _, sv, sm = lax.associative_scan(_seg_combine, (my_sym, my_v, my_m))

    # Carry exchange: combine shards' summaries in axis order.
    cs = lax.all_gather(my_sym[-1], AXIS)   # (k,)
    cv = lax.all_gather(sv[-1], AXIS)
    cm = lax.all_gather(sm[-1], AXIS)
    _, cv_s, cm_s = lax.associative_scan(_seg_combine, (cs, cv, cm))
    prev = jnp.clip(j - 1, 0, k - 1)
    inc_sym = cs[prev]
    inc_v = jnp.where(j > 0, cv_s[prev], NULL_ID)
    inc_m = (j > 0) & cm_s[prev]

    same = my_sym == inc_sym
    out_v = jnp.where(sm, sv, jnp.where(same & inc_m, inc_v, NULL_ID))
    out_m = sm | (same & inc_m)

    sv_full = lax.all_gather(out_v, AXIS, tiled=True)
    sm_full = lax.all_gather(out_m, AXIS, tiled=True)
    out = jnp.full_like(vals, NULL_ID)
    return out.at[seg_order].set(jnp.where(sm_full, sv_full, NULL_ID))


def _sharded_compose_core(a_loc, b_loc, n_a, n_b, na: int, nb: int, k: int):
    """Per-shard body: local row-blocks of both encoded op streams in,
    full (replicated) compose result matrix out."""
    j = lax.axis_index(AXIS)
    # Op-table exchange: gather both streams' key columns (11 × int32).
    a_full = {name: lax.all_gather(v, AXIS, tiled=True)
              for name, v in a_loc.items()}
    b_full = {name: lax.all_gather(v, AXIS, tiled=True)
              for name, v in b_loc.items()}

    a = _sort_stream(a_full)
    b = _sort_stream(b_full)

    # Sharded DivergentRename candidate join: A's rename table is
    # replicated (gathered), B's query axis shards over ``dp``.
    tables = _rename_candidate_tables(a, n_a, na)
    b_rsym, b_rname = _rename_pairs(b, n_b, nb)
    Tb = nb // k
    my_rsym = lax.dynamic_slice(b_rsym, (j * Tb,), (Tb,))
    my_rname = lax.dynamic_slice(b_rname, (j * Tb,), (Tb,))
    differing = _rename_candidate_query(tables, na, my_rsym, my_rname)
    has_candidates = lax.pmax(jnp.any(differing).astype(jnp.int32), AXIS) > 0

    # Sequential cursor walk: replicated (identical on every shard).
    drop_a, drop_b, conf_a, conf_b, n_conf = _conflict_cursor_walk(
        a, b, n_a, n_b, na, nb, has_candidates)

    return _merge_and_scan(a, b, n_a, n_b, na, nb,
                           drop_a, drop_b, conf_a, conf_b, n_conf,
                           seg_scan_impl=partial(_dist_seg_scan, k))


@lru_cache(maxsize=None)
def _sharded_compose_fn(mesh: Mesh, na: int, nb: int, k: int):
    spec = P(AXIS)
    col_specs = {name: spec for name in
                 ("prec", "ts_rank", "id_rank", "is_rename", "is_move", "sym",
                  "new_name", "chain_name", "new_addr", "chain_file",
                  "op_index")}
    return jax.jit(shard_map_compat(
        partial(_sharded_compose_core, na=na, nb=nb, k=k),
        mesh=mesh, in_specs=(col_specs, col_specs, P(), P()),
        out_specs=P(), check_vma=False))


def compose_oplogs_device_sharded(delta_a: List[Op], delta_b: List[Op],
                                  mesh: Mesh
                                  ) -> Tuple[List[Op], List[Conflict]]:
    """Mesh twin of
    :func:`semantic_merge_tpu.ops.compose.compose_oplogs_device`."""
    from ..obs import spans as obs_spans
    if not delta_a and not delta_b:
        return [], []
    k = _dp_size(mesh)
    with obs_spans.span("compose_device_sharded", layer="ops", shards=k,
                        n_a=len(delta_a), n_b=len(delta_b)):
        interner, ta, tb, na, nb = encode_compose_inputs(
            delta_a, delta_b, shard_multiple=k)
        fn = _sharded_compose_fn(mesh, na, nb, k)
        out = np.asarray(fn(_pad_op_tensor(ta, na), _pad_op_tensor(tb, nb),
                            np.int32(ta.n), np.int32(tb.n)))
        return decode_compose_output(out, delta_a, delta_b, interner, na, nb)
