"""Batched declaration diff + lift on device.

Replaces the reference worker's ``diffNodes`` hash-map join and ``lift``
loop (reference ``workers/ts/src/diff.ts:5-31``,
``workers/ts/src/lift.ts:11-66``) with a sort-join over interned int32
ids, executed as one fused XLA program. The whole computation is
data-parallel over decl slots — no Python loops, static shapes, ready
to shard the slot axis across a mesh.

JS ``Map`` semantics are reproduced exactly on device:

- iteration order = first-occurrence order (a slot "emits" only if it
  is the first slot with its symbol id);
- duplicate keys keep the *last* value (per-slot data is gathered from
  the last occurrence via a right-searchsorted into the stable
  sort-by-symbol order);
- the side list's ``add`` loop walks raw slots, so duplicate unseen
  symbols emit repeatedly (reference ``workers/ts/src/diff.ts:24-28``).

Emission layout parity (one op stream, same enumeration as the
reference): per base symbol in map order — ``delete`` *or* (``move``
then ``rename``) — followed by per-side-slot ``add`` ops.
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..core.encode import NULL_ID, PAD_ID, DeclTensor, bucket_size, pad_to

KIND_RENAME = 0
KIND_MOVE = 1
KIND_ADD = 2
KIND_DELETE = 3


@dataclass
class DiffOpsTensor:
    """Device-lifted op stream (struct of arrays, padded).

    ``kind`` is ``-1`` on padding rows. ``a_*`` columns describe the
    base-side node, ``b_*`` the side node; ``NULL_ID`` where absent.
    Row order is exactly the reference's diff enumeration, so row index
    == the deterministic-id sequence number.
    """

    kind: np.ndarray
    sym: np.ndarray
    a_addr: np.ndarray
    a_name: np.ndarray
    a_file: np.ndarray
    b_addr: np.ndarray
    b_name: np.ndarray
    b_file: np.ndarray
    n_ops: int


def _occurrence_bounds(sym, order, sorted_sym, n_pad):
    """For each slot: the first and last slot index holding its symbol."""
    left = jnp.searchsorted(sorted_sym, sym, side="left")
    right = jnp.searchsorted(sorted_sym, sym, side="right") - 1
    left = jnp.clip(left, 0, n_pad - 1)
    right = jnp.clip(right, 0, n_pad - 1)
    first_idx = order[left]
    last_idx = order[right]
    return first_idx, last_idx


def _diff_plan(b_sym, b_addr, b_name, s_sym, s_addr, s_name,
               nb: int, ns: int):
    """The parallel join itself: which slots emit which diff kinds, at
    which positions of the op stream, with node data taken from which
    slots. Shared by the interned-column op emitter below and the fused
    merge program's slot emitter (:mod:`semantic_merge_tpu.ops.fused`)."""
    idx_b = jnp.arange(nb, dtype=jnp.int32)
    b_valid = b_sym != PAD_ID
    s_valid = s_sym != PAD_ID

    # Stable sort by symbol: ties keep slot order, so right-1 = last occurrence.
    b_order = jnp.argsort(b_sym, stable=True).astype(jnp.int32)
    s_order = jnp.argsort(s_sym, stable=True).astype(jnp.int32)
    b_sorted = b_sym[b_order]
    s_sorted = s_sym[s_order]

    b_first, b_last = _occurrence_bounds(b_sym, b_order, b_sorted, nb)

    # Side representative (Map last-wins) for each base symbol.
    pos = jnp.searchsorted(s_sorted, b_sym, side="right") - 1
    pos_c = jnp.clip(pos, 0, ns - 1)
    found = (pos >= 0) & (s_sorted[pos_c] == b_sym) & b_valid
    s_repr = s_order[pos_c]

    # Base-map emission: only the first occurrence emits; data from last.
    emits = b_valid & (idx_b == b_first)
    bl = b_last  # node data index (last occurrence)
    b_addr_l = b_addr[bl]
    b_name_l = b_name[bl]
    s_addr_r = s_addr[s_repr]
    s_name_r = s_name[s_repr]

    is_delete = emits & ~found
    is_move = emits & found & (b_addr_l != s_addr_r)
    is_rename = (emits & found & (b_name_l != NULL_ID) & (s_name_r != NULL_ID)
                 & (b_name_l != s_name_r))

    # Adds: every raw side slot whose symbol is absent from base.
    in_base = jnp.searchsorted(b_sorted, s_sym, side="left")
    in_base_c = jnp.clip(in_base, 0, nb - 1)
    present = b_sorted[in_base_c] == s_sym
    is_add = s_valid & ~present

    # Emission positions: per base slot `delete ? 1 : move+rename`,
    # move before rename within a slot, adds after all base emissions.
    base_count = jnp.where(is_delete, 1, is_move.astype(jnp.int32) + is_rename.astype(jnp.int32))
    base_off = jnp.cumsum(base_count) - base_count
    total_base = jnp.sum(base_count)
    add_count = is_add.astype(jnp.int32)
    add_off = total_base + jnp.cumsum(add_count) - add_count
    n_ops = total_base + jnp.sum(add_count)
    return {
        "is_delete": is_delete, "is_move": is_move, "is_rename": is_rename,
        "is_add": is_add, "base_off": base_off, "add_off": add_off,
        "n_ops": n_ops, "bl": bl, "s_repr": s_repr,
    }


def _diff_lift_core(b_sym, b_addr, b_name, b_file,
                    s_sym, s_addr, s_name, s_file,
                    nb: int, ns: int):
    plan = _diff_plan(b_sym, b_addr, b_name, s_sym, s_addr, s_name, nb, ns)
    is_delete, is_move, is_rename, is_add = (
        plan["is_delete"], plan["is_move"], plan["is_rename"], plan["is_add"])
    base_off, add_off, n_ops = plan["base_off"], plan["add_off"], plan["n_ops"]
    bl, s_repr = plan["bl"], plan["s_repr"]
    b_addr_l = b_addr[bl]
    b_name_l = b_name[bl]
    b_file_l = b_file[bl]
    s_addr_r = s_addr[s_repr]
    s_name_r = s_name[s_repr]
    s_file_r = s_file[s_repr]

    m = 2 * nb + ns  # static output capacity
    neg = jnp.int32(NULL_ID)

    def init(fill=neg):
        return jnp.full((m,), fill, dtype=jnp.int32)

    kind = init()
    o_sym = init(); o_a_addr = init(); o_a_name = init(); o_a_file = init()
    o_b_addr = init(); o_b_name = init(); o_b_file = init()

    def scatter(arrs, posn, mask, values):
        posn = jnp.where(mask, posn, m)  # out-of-range rows drop
        out = []
        for arr, val in zip(arrs, values):
            out.append(arr.at[posn].set(val, mode="drop"))
        return out

    cols = [kind, o_sym, o_a_addr, o_a_name, o_a_file, o_b_addr, o_b_name, o_b_file]

    # deletes (1 op at base_off)
    cols = scatter(cols, base_off, is_delete,
                   [jnp.full((nb,), KIND_DELETE, jnp.int32), b_sym, b_addr_l,
                    b_name_l, b_file_l, jnp.full((nb,), neg), jnp.full((nb,), neg),
                    jnp.full((nb,), neg)])
    # moves (first in slot)
    cols = scatter(cols, base_off, is_move,
                   [jnp.full((nb,), KIND_MOVE, jnp.int32), b_sym, b_addr_l,
                    b_name_l, b_file_l, s_addr_r, s_name_r, s_file_r])
    # renames (after the move when both emit)
    ren_pos = base_off + is_move.astype(jnp.int32)
    cols = scatter(cols, ren_pos, is_rename,
                   [jnp.full((nb,), KIND_RENAME, jnp.int32), b_sym, b_addr_l,
                    b_name_l, b_file_l, s_addr_r, s_name_r, s_file_r])
    # adds
    cols = scatter(cols, add_off, is_add,
                   [jnp.full((ns,), KIND_ADD, jnp.int32), s_sym,
                    jnp.full((ns,), neg), jnp.full((ns,), neg), jnp.full((ns,), neg),
                    s_addr, s_name, s_file])

    # One stacked int32 matrix so the host retrieves the whole op stream
    # in a single device→host transfer (remote-tunnel latency is per
    # fetch, not per byte): rows 0-7 = columns, row 8 = n_ops broadcast.
    return jnp.concatenate(
        [jnp.stack(cols), jnp.full((1, m), n_ops, jnp.int32)], axis=0)


@partial(jax.jit, static_argnames=("nb", "ns"))
def _diff_lift_kernel(b_sym, b_addr, b_name, b_file,
                      s_sym, s_addr, s_name, s_file,
                      nb: int, ns: int):
    return _diff_lift_core(b_sym, b_addr, b_name, b_file,
                           s_sym, s_addr, s_name, s_file, nb, ns)


@partial(jax.jit, static_argnames=("nb", "nl", "nr"))
def _diff_lift_pair_kernel(b_sym, b_addr, b_name, b_file,
                           l_sym, l_addr, l_name, l_file,
                           r_sym, r_addr, r_name, r_file,
                           nb: int, nl: int, nr: int):
    """Both sides of a 3-way merge in one program → one output fetch."""
    out_l = _diff_lift_core(b_sym, b_addr, b_name, b_file,
                            l_sym, l_addr, l_name, l_file, nb, nl)
    out_r = _diff_lift_core(b_sym, b_addr, b_name, b_file,
                            r_sym, r_addr, r_name, r_file, nb, nr)
    m = max(out_l.shape[1], out_r.shape[1])

    def pad(a):
        return jnp.pad(a, ((0, 0), (0, m - a.shape[1])),
                       constant_values=NULL_ID)

    return jnp.stack([pad(out_l), pad(out_r)])


def _decode_stacked(out: np.ndarray) -> DiffOpsTensor:
    (kind, sym, a_addr, a_name, a_file, b_addr, b_name, b_file) = out[:8]
    return DiffOpsTensor(
        kind=kind, sym=sym, a_addr=a_addr, a_name=a_name, a_file=a_file,
        b_addr=b_addr, b_name=b_name, b_file=b_file, n_ops=int(out[8, 0]),
    )


def _padded_cols(t: DeclTensor, size: int):
    return [pad_to(t.sym, size, PAD_ID), pad_to(t.addr, size, NULL_ID),
            pad_to(t.name, size, NULL_ID), pad_to(t.file, size, NULL_ID)]


def diff_lift_device(base: DeclTensor, side: DeclTensor) -> DiffOpsTensor:
    """Run the fused diff+lift program for one (base, side) pair."""
    nb = bucket_size(max(base.n, 1))
    ns = bucket_size(max(side.n, 1))
    out = _diff_lift_kernel(*_padded_cols(base, nb), *_padded_cols(side, ns),
                            nb=nb, ns=ns)
    return _decode_stacked(np.asarray(out))


def diff_lift_device_pair(base: DeclTensor, left: DeclTensor,
                          right: DeclTensor) -> tuple[DiffOpsTensor, DiffOpsTensor]:
    """Diff both sides against base in one device call (one fetch)."""
    nb = bucket_size(max(base.n, 1))
    nl = bucket_size(max(left.n, 1))
    nr = bucket_size(max(right.n, 1))
    out = np.asarray(_diff_lift_pair_kernel(
        *_padded_cols(base, nb), *_padded_cols(left, nl),
        *_padded_cols(right, nr), nb=nb, nl=nl, nr=nr))
    return _decode_stacked(out[0]), _decode_stacked(out[1])
