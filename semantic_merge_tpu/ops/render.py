"""Device-side op-log rendering — the serialize phase as a gather.

Rung-5 profiling (PR-17's ``BENCH_tpu_r5_rung5.json``) left the fused
merge with a 102 ms kernel wrapped in a ~931 ms host tail, ~305 ms of
which is op-log JSON serialization: even the vectorized row serializer
(``oplog_view._json_rows``) and the native C renderer fundamentally
walk ~46k rows on the host, formatting strings one row at a time.

But an op-log row is not *text* the host has to compute — it is a
fixed **segment program** over data the device already holds:

- the row template literals (per kind, known at merge time once the
  provenance JSON is fixed),
- the snapshot field strings (symbolId/addressId/name/file), already
  resident device-side as interner-id columns (the engine's decl
  cache ships ``[4, bucket]`` int32 tables per snapshot),
- the op id, a hex rendering of digest words the device *computed*.

So this module renders the whole payload on device: every interned
string's **escaped JSON body** lives in an append-only device blob
(:class:`EscapedStrings`, the delta-shipped twin of
``fused.DeviceStrings``); a jitted program expands each row's segment
spec — literal / field / uuid — into per-byte source offsets over a
byte pool ``tmpl ‖ escaped-bodies ‖ uuid36(words)`` and gathers them
into a fixed-width ``uint8 [n, W]`` buffer. The host then does ONE
d2h copy plus a mask-concat instead of ~46k Python row formats; byte
parity with ``OpStreamView.to_json_bytes()`` (and therefore with
``dumps_canonical([op.to_dict() ...])``, the reference surface) is
fuzz-tested in ``tests/test_device_render.py``.

Posture (``SEMMERGE_DEVICE_RENDER``, consistent with mesh/batch/
fleet): ``off`` — never render; ``auto`` (default) — render eligible
streams, fall back to the PR-2 host tail pipeline on any failure;
``require`` — a render failure raises :class:`~semantic_merge_tpu.
errors.RenderFault` (exit 20 strict) instead of degrading.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.encode import bucket_size
from ..errors import RenderFault
from ..obs import device as obs_device
from ..obs import spans as obs_spans
from .oplog_view import (_TMPL_ADD, _TMPL_DELETE, _TMPL_MOVE, _TMPL_RENAME,
                         _esc_body)

ENV_POSTURE = "SEMMERGE_DEVICE_RENDER"
ENV_MIN_ROWS = "SEMMERGE_RENDER_MIN_ROWS"
ENV_MAX_WIDTH = "SEMMERGE_RENDER_MAX_WIDTH"

#: Below this row count the dispatch overhead outweighs the host
#: serializer (auto posture only; ``require`` renders any n > 0).
DEFAULT_MIN_ROWS = 4096
#: Rows wider than this (one giant file path blows up W for the whole
#: buffer) make the fixed-width buffer a memory hazard — fall back.
DEFAULT_MAX_WIDTH = 4096

#: Segment selector codes (static per-kind spec tables).
_SEL_PAD, _SEL_LIT, _SEL_UUID = 0, 1, 2
#: Field codes 3.. index the stacked per-row field-id gather:
#: base sym/addr/name, side sym/addr/name, side file, base file.
(_F_BSYM, _F_BADDR, _F_BNAME,
 _F_SSYM, _F_SADDR, _F_SNAME, _F_SFILE, _F_BFILE) = range(3, 11)

#: Per-kind field sequences, in template ``%s``-slot order (matching
#: ``oplog_view._json_rows`` zip orders; the leading uuid slot is
#: implicit). KIND_RENAME=0, MOVE=1, ADD=2, DELETE=3 — pinned by
#: tests against ``ops/diff.py``.
_KIND_FIELDS = (
    (_F_BSYM, _F_BADDR, _F_BNAME, _F_SNAME, _F_SFILE,
     _F_BADDR, _F_BNAME, _F_SNAME),                          # rename
    (_F_BSYM, _F_BADDR, _F_BADDR, _F_SADDR, _F_BFILE, _F_SFILE,
     _F_BADDR, _F_BADDR, _F_SADDR),                          # move
    (_F_SSYM, _F_SADDR, _F_SFILE),                           # add
    (_F_BSYM, _F_BADDR, _F_BFILE),                           # delete
)
_KIND_TMPLS = (_TMPL_RENAME, _TMPL_MOVE, _TMPL_ADD, _TMPL_DELETE)

#: Max segments per row: ``len(fields)+2`` literals interleaved with
#: the uuid segment and ``len(fields)`` field segments. Move: 21.
_S = max(2 * len(f) + 3 for f in _KIND_FIELDS)

#: Rows render in fixed chunks under ``lax.map`` so the [chunk, W]
#: int32 offset intermediates stay ~16 MB instead of O(n*W).
_CHUNK = 4096

#: uuid36 byte positions of the 32 hex chars (dashes at 8/13/18/23).
_HEXPOS = np.asarray([i for i in range(36) if i not in (8, 13, 18, 23)],
                     np.int32)


def render_posture() -> str:
    """``off`` / ``auto`` / ``require`` from ``SEMMERGE_DEVICE_RENDER``
    (unknown values → ``auto``, the degradable default — consistent
    with the mesh/batch/fleet posture knobs)."""
    raw = os.environ.get(ENV_POSTURE, "auto").strip().lower()
    if raw in ("off", "0", "no", "false"):
        return "off"
    if raw in ("require", "required"):
        return "require"
    return "auto"


def _min_rows() -> int:
    try:
        return int(os.environ.get(ENV_MIN_ROWS, DEFAULT_MIN_ROWS))
    except ValueError:
        return DEFAULT_MIN_ROWS


def _max_width() -> int:
    try:
        return int(os.environ.get(ENV_MAX_WIDTH, DEFAULT_MAX_WIDTH))
    except ValueError:
        return DEFAULT_MAX_WIDTH


class EscapedStrings:
    """Device-resident escaped-JSON-body table for an interner.

    One variable-length UTF-8 body per interned string — exactly the
    bytes ``oplog_view._esc_body`` emits, so device-gathered field
    segments concatenate into the same payload the host serializer
    builds. Append-only like ``fused.DeviceStrings``: interner ids are
    stable, so warm merges ship only the new strings' bodies (blob
    delta) and offset/length rows; a capacity growth reships the full
    table once at the new geometry.
    """

    def __init__(self, interner, sharding=None) -> None:
        self.interner = interner
        self.sharding = sharding
        self.blob_cap = 4096
        self.id_cap = 1024
        self._blob = np.zeros(self.blob_cap, np.uint8)
        self._offs = np.zeros(self.id_cap, np.int32)
        self._lens = np.zeros(self.id_cap, np.int32)
        self._n = 0          # ids escaped into the host arrays
        self._blob_n = 0     # blob bytes used
        self._dev = None     # (blob, offs, lens) device triple
        self._n_dev = 0
        self._blob_dev_n = 0

    def _put(self, arr):
        import jax
        return (jax.device_put(arr, self.sharding)
                if self.sharding is not None else jax.device_put(arr))

    def lens_host(self) -> np.ndarray:
        return self._lens

    def _append_host(self, n: int) -> None:
        strings = self.interner.strings
        if n > self.id_cap:
            cap = self.id_cap
            while n > cap:
                cap *= 2
            offs = np.zeros(cap, np.int32)
            lens = np.zeros(cap, np.int32)
            offs[:self._n] = self._offs[:self._n]
            lens[:self._n] = self._lens[:self._n]
            self._offs, self._lens, self.id_cap = offs, lens, cap
            self._dev = None
        for i in range(self._n, n):
            s = strings[i]
            body = _esc_body(s).encode("utf-8") if isinstance(s, str) else b""
            end = self._blob_n + len(body)
            if end > self.blob_cap:
                cap = self.blob_cap
                while end > cap:
                    cap *= 2
                blob = np.zeros(cap, np.uint8)
                blob[:self._blob_n] = self._blob[:self._blob_n]
                self._blob, self.blob_cap = blob, cap
                self._dev = None
            if body:
                self._blob[self._blob_n:end] = np.frombuffer(body, np.uint8)
            self._offs[i] = self._blob_n
            self._lens[i] = len(body)
            self._blob_n = end
        self._n = n

    def sync(self):
        """Bring the device triple up to date with the interner;
        returns ``(blob, offs, lens)`` device arrays (rows beyond the
        interned count are zeros, never gathered by valid ids)."""
        import jax
        import jax.numpy as jnp

        n = len(self.interner.strings)
        if n > self._n:
            self._append_host(n)
        if self._dev is None:
            triple = (self._put(self._blob), self._put(self._offs),
                      self._put(self._lens))
            obs_device.record_transfer(
                "h2d", self._blob.nbytes + self._offs.nbytes
                + self._lens.nbytes)
            self._dev, self._n_dev = triple, n
            self._blob_dev_n = self._blob_n
            return triple
        if n > self._n_dev:
            blob, offs, lens = self._dev
            db = bucket_size(self._blob_n - self._blob_dev_n, minimum=64)
            dn = bucket_size(n - self._n_dev, minimum=8)
            if (self._blob_dev_n + db > self.blob_cap
                    or self._n_dev + dn > self.id_cap):
                return self._reship(n)
            upd_b = self._blob[self._blob_dev_n:self._blob_dev_n + db]
            upd_o = self._offs[self._n_dev:self._n_dev + dn]
            upd_l = self._lens[self._n_dev:self._n_dev + dn]
            blob = _dev_update1(blob, upd_b, np.int32(self._blob_dev_n))
            offs = _dev_update1(offs, upd_o, np.int32(self._n_dev))
            lens = _dev_update1(lens, upd_l, np.int32(self._n_dev))
            obs_device.record_transfer(
                "h2d", upd_b.nbytes + upd_o.nbytes + upd_l.nbytes)
            self._dev = (blob, offs, lens)
            self._n_dev, self._blob_dev_n = n, self._blob_n
        return self._dev

    def _reship(self, n: int):
        triple = (self._put(self._blob), self._put(self._offs),
                  self._put(self._lens))
        obs_device.record_transfer(
            "h2d", self._blob.nbytes + self._offs.nbytes + self._lens.nbytes)
        self._dev, self._n_dev = triple, n
        self._blob_dev_n = self._blob_n
        return triple


_dev_update1_jit = None


def _dev_update1(buf, upd, start):
    global _dev_update1_jit
    if _dev_update1_jit is None:
        import jax
        _dev_update1_jit = jax.jit(
            lambda b, u, s: jax.lax.dynamic_update_slice(b, u, (s,)))
    return _dev_update1_jit(buf, upd, start)


class _KindSpec:
    """Per-provenance static render spec: the template blob plus the
    ``[4, S]`` selector / literal-offset / literal-length tables the
    device program gathers by kind."""

    __slots__ = ("blob", "sel", "lit", "litlen", "lit_total")

    def __init__(self, prov_json: str) -> None:
        blob = bytearray()
        sel = np.zeros((4, _S), np.int32)
        lit = np.zeros((4, _S), np.int32)
        litlen = np.zeros((4, _S), np.int32)
        self.lit_total = np.zeros(4, np.int64)
        for k, (tmpl, fields) in enumerate(zip(_KIND_TMPLS, _KIND_FIELDS)):
            lits = tmpl.split("%s")
            # slot 0 is the uuid; the remaining slots are the field
            # sequence. The closing literal carries the provenance
            # object, the row's closing brace, and the row separator.
            lits[-1] = lits[-1] + prov_json + "}" + ","
            segs: List[Tuple[int, int, int]] = []
            for si, text in enumerate(lits):
                enc = text.encode("utf-8")
                segs.append((_SEL_LIT, len(blob), len(enc)))
                blob.extend(enc)
                self.lit_total[k] += len(enc)
                if si == 0:
                    segs.append((_SEL_UUID, 0, 36))
                elif si <= len(fields):
                    segs.append((fields[si - 1], 0, 0))
            for si, (s, o, ln) in enumerate(segs):
                sel[k, si], lit[k, si], litlen[k, si] = s, o, ln
        # Bucket the blob so the jit signature (tmpl_cap feeds the
        # pool base offsets) is stable across provenance values.
        cap = int(bucket_size(max(len(blob), 1), minimum=256))
        padded = np.zeros(cap, np.uint8)
        padded[:len(blob)] = np.frombuffer(bytes(blob), np.uint8)
        self.blob = padded
        self.sel, self.lit, self.litlen = sel, lit, litlen


def _uuid36_dev(words):
    """Digest words int32 [n, 4] → uuid-shaped ASCII uint8 [n, 36]:
    the device twin of ``oplog_view.format_ids`` (big-endian hex per
    uint32 word, dashes at byte positions 8/13/18/23)."""
    import jax.numpy as jnp
    from jax import lax

    u = lax.bitcast_convert_type(words, jnp.uint32)
    shifts = jnp.asarray([24, 16, 8, 0], jnp.uint32)
    byts = (u[:, :, None] >> shifts[None, None, :]) & jnp.uint32(0xFF)
    byts = byts.reshape(words.shape[0], 16)
    nib = jnp.stack([byts >> 4, byts & jnp.uint32(0xF)],
                    axis=-1).reshape(words.shape[0], 32)
    ascii_ = (nib + 48 + jnp.where(nib > 9, 39, 0)).astype(jnp.uint8)
    out = jnp.full((words.shape[0], 36), np.uint8(ord("-")), jnp.uint8)
    return out.at[:, jnp.asarray(_HEXPOS)].set(ascii_)


def _render_program(kind, a_slot, b_slot, words, bcols, scols,
                    sel_tab, lit_tab, litlen_tab,
                    esc_blob, esc_offs, esc_lens, tmpl_blob, *, W: int):
    """The jitted render body: expand each row's segment spec into
    per-byte pool offsets and gather. Pool layout: template literals ‖
    escaped string bodies ‖ uuid36 bytes (36 per row)."""
    import jax
    import jax.numpy as jnp

    n = kind.shape[0]
    tmpl_cap = tmpl_blob.shape[0]
    esc_cap = esc_blob.shape[0]
    uuid_base = tmpl_cap + esc_cap

    uuid = _uuid36_dev(words)
    pool = jnp.concatenate([tmpl_blob, esc_blob, uuid.reshape(-1)])
    pool_max = pool.shape[0] - 1

    kind_c = jnp.clip(kind, 0, 3)
    a = jnp.clip(a_slot, 0, bcols.shape[1] - 1)
    b = jnp.clip(b_slot, 0, scols.shape[1] - 1)
    max_id = esc_offs.shape[0] - 1
    # Stacked per-row field ids, in _F_* code order (codes 3..10).
    field_ids = jnp.stack(
        [bcols[0][a], bcols[1][a], bcols[2][a],
         scols[0][b], scols[1][b], scols[2][b],
         scols[3][b], bcols[3][a]], axis=1)
    field_ids = jnp.clip(field_ids, 0, max_id)

    sel = sel_tab[kind_c]          # [n, S]
    lit = lit_tab[kind_c]
    litlen = litlen_tab[kind_c]
    fid = jnp.take_along_axis(field_ids, jnp.clip(sel - 3, 0, 7), axis=1)
    f_off = esc_offs[fid] + jnp.int32(tmpl_cap)
    f_len = esc_lens[fid]
    row36 = (jnp.arange(n, dtype=jnp.int32) * 36 + jnp.int32(uuid_base))
    seg_off = jnp.where(sel == _SEL_LIT, lit,
                        jnp.where(sel == _SEL_UUID, row36[:, None], f_off))
    seg_len = jnp.where(sel == _SEL_LIT, litlen,
                        jnp.where(sel == _SEL_UUID, 36,
                                  jnp.where(sel >= 3, f_len, 0)))

    def chunk_body(args):
        c_off, c_len = args
        ends = jnp.cumsum(c_len, axis=1)
        starts = ends - c_len
        total = ends[:, -1]
        j = jnp.arange(W, dtype=jnp.int32)
        k = jax.vmap(lambda e: jnp.searchsorted(e, j, side="right"))(ends)
        k = jnp.clip(k, 0, _S - 1)
        src = (jnp.take_along_axis(c_off, k, axis=1)
               + (j[None, :] - jnp.take_along_axis(starts, k, axis=1)))
        valid = j[None, :] < total[:, None]
        return jnp.where(valid, pool[jnp.clip(src, 0, pool_max)],
                         jnp.uint8(0))

    if n <= _CHUNK:
        return chunk_body((seg_off, seg_len))
    nc = n // _CHUNK  # callers pad n to a _CHUNK multiple past _CHUNK
    buf = jax.lax.map(chunk_body,
                      (seg_off.reshape(nc, _CHUNK, _S),
                       seg_len.reshape(nc, _CHUNK, _S)))
    return buf.reshape(n, W)


class RenderedStream:
    """Handle to one stream's in-flight device render: the device
    buffer plus the host-side row lengths. ``json_bytes()`` performs
    the ONE d2h copy (recorded as the ``render.d2h`` span) and the
    mask-concat; per-row byte access backs the composed view's
    device-rendered serialization."""

    __slots__ = ("_buf_dev", "lens", "n", "W", "require", "_buf", "_rows")

    def __init__(self, buf_dev, lens: np.ndarray, n: int, W: int,
                 require: bool) -> None:
        self._buf_dev = buf_dev
        self.lens = lens
        self.n = n
        self.W = W
        self.require = require
        self._buf: Optional[np.ndarray] = None
        self._rows: Optional[List[bytes]] = None

    def block_until_ready(self) -> None:
        self._buf_dev.block_until_ready()

    def _fetch(self) -> np.ndarray:
        if self._buf is None:
            with obs_spans.span("render.d2h", layer="ops",
                                rows=self.n, width=self.W):
                buf = np.asarray(self._buf_dev)
                obs_device.record_transfer("d2h", buf.nbytes)
            self._buf_dev = None
            self._buf = buf
        return self._buf

    def json_bytes(self) -> Optional[bytes]:
        """The full ``[...]`` payload, or ``None`` when the fetch
        fails under the degradable posture (``require`` re-raises as
        :class:`RenderFault`)."""
        try:
            buf = self._fetch()
            mask = np.arange(self.W) < self.lens[:, None]
            flat = buf[:self.n][mask].tobytes()
            # Every row's closing literal carries the separator comma;
            # drop the trailing one and bracket.
            return b"[" + flat[:-1] + b"]"
        except RenderFault:
            raise
        except Exception as exc:  # noqa: BLE001 — posture seam
            if self.require:
                raise RenderFault(str(exc), stage="render",
                                  cause=type(exc).__name__) from exc
            return None

    def row_bytes(self) -> Optional[List[bytes]]:
        """Per-row JSON bytes *without* the trailing separator comma —
        the composed view splices these by ``(side, idx)``. Same
        containment contract as :meth:`json_bytes`."""
        if self._rows is not None:
            return self._rows
        try:
            buf = self._fetch()
            lens = self.lens
            self._rows = [buf[i, :lens[i] - 1].tobytes()
                          for i in range(self.n)]
            return self._rows
        except RenderFault:
            raise
        except Exception as exc:  # noqa: BLE001
            if self.require:
                raise RenderFault(str(exc), stage="render",
                                  cause=type(exc).__name__) from exc
            return None


class DeviceRenderer:
    """Per-engine render dispatcher: owns the :class:`EscapedStrings`
    table, the per-provenance :class:`_KindSpec` cache, and the jitted
    render program's bucket ladder."""

    def __init__(self, interner, sharding=None) -> None:
        self.interner = interner
        self.esc = EscapedStrings(interner, sharding)
        self._spec_cache: Dict[str, _KindSpec] = {}
        self._jit = None

    def eligible(self, n: int, *, posture: Optional[str] = None) -> bool:
        posture = posture or render_posture()
        if posture == "off" or n <= 0:
            return False
        if posture == "require":
            return True
        return n >= _min_rows()

    def _spec(self, prov_json: str) -> _KindSpec:
        spec = self._spec_cache.get(prov_json)
        if spec is None:
            spec = self._spec_cache[prov_json] = _KindSpec(prov_json)
            if len(self._spec_cache) > 8:
                self._spec_cache.pop(next(iter(self._spec_cache)))
        return spec

    def _program(self):
        if self._jit is None:
            import jax
            self._jit = jax.jit(_render_program,
                                static_argnames=("W",))
        return self._jit

    def _row_lens(self, spec: _KindSpec, kind, a_slot, b_slot,
                  bcols_host, scols_host) -> np.ndarray:
        """Host-side per-row byte lengths (independent of the device
        program, which recomputes them from the same inputs): literal
        total + 36 (uuid) + the kind's field-body lengths."""
        lens_tab = self.esc.lens_host()
        kc = np.clip(kind, 0, 3).astype(np.int64)
        a = np.clip(a_slot, 0, len(bcols_host[0]) - 1)
        b = np.clip(b_slot, 0, len(scols_host[0]) - 1)
        max_id = len(lens_tab) - 1

        def flen(cols, col, slot):
            ids = np.clip(np.asarray(cols[col])[slot], 0, max_id)
            return lens_tab[ids].astype(np.int64)

        bsym = flen(bcols_host, 0, a)
        baddr = flen(bcols_host, 1, a)
        bname = flen(bcols_host, 2, a)
        bfile = flen(bcols_host, 3, a)
        ssym = flen(scols_host, 0, b)
        saddr = flen(scols_host, 1, b)
        sname = flen(scols_host, 2, b)
        sfile = flen(scols_host, 3, b)
        per_kind = np.stack([
            bsym + 2 * baddr + 2 * bname + 2 * sname + sfile,   # rename
            bsym + 4 * baddr + 2 * saddr + bfile + sfile,       # move
            ssym + saddr + sfile,                               # add
            bsym + baddr + bfile,                               # delete
        ])
        rows = np.arange(len(kind))
        return (spec.lit_total[kc] + 36 + per_kind[kc, rows]).astype(np.int64)

    def dispatch(self, kind: np.ndarray, a_slot: np.ndarray,
                 b_slot: np.ndarray, words: np.ndarray,
                 bcols_dev, scols_dev, base_t, side_t,
                 prov_json: str, *, require: bool
                 ) -> Optional[RenderedStream]:
        """Launch one stream's render (async). ``bcols_dev``/
        ``scols_dev`` are the engine's cached ``[4, bucket]`` device
        decl tables; ``base_t``/``side_t`` the matching host
        :class:`DeclTensor`\\ s (the length pass reads their columns).
        Returns ``None`` when ineligible/contained (auto posture);
        raises :class:`RenderFault` under ``require``."""
        try:
            return self._dispatch(kind, a_slot, b_slot, words, bcols_dev,
                                  scols_dev, base_t, side_t, prov_json,
                                  require=require)
        except RenderFault:
            raise
        except Exception as exc:  # noqa: BLE001 — posture seam
            if require:
                raise RenderFault(str(exc), stage="render",
                                  cause=type(exc).__name__) from exc
            return None

    def _dispatch(self, kind, a_slot, b_slot, words, bcols_dev, scols_dev,
                  base_t, side_t, prov_json, *, require: bool
                  ) -> Optional[RenderedStream]:
        import jax
        import jax.numpy as jnp

        n = int(kind.shape[0])
        if n == 0:
            return None
        esc_blob, esc_offs, esc_lens = self.esc.sync()
        spec = self._spec(prov_json)
        bcols_host = (base_t.sym, base_t.addr, base_t.name, base_t.file)
        scols_host = (side_t.sym, side_t.addr, side_t.name, side_t.file)
        lens = self._row_lens(spec, kind, a_slot, b_slot,
                              bcols_host, scols_host)
        W = int(bucket_size(int(lens.max()), minimum=64))
        if W > _max_width():
            if require:
                raise RenderFault(
                    f"row width {W} exceeds {ENV_MAX_WIDTH}"
                    f"={_max_width()}", stage="render", cause="width")
            return None
        n_pad = int(bucket_size(n, minimum=64))
        if n_pad > _CHUNK:
            # lax.map chunking needs a _CHUNK multiple; the ladder's
            # 3·2^(k-1) half-steps aren't all multiples, so round up
            # (still O(log n) compiled shapes).
            n_pad = ((n_pad + _CHUNK - 1) // _CHUNK) * _CHUNK
        null = np.int32(-1)

        def pad(col, fill):
            out = np.full(n_pad, fill, np.int32)
            out[:n] = col
            return out

        kind_p = pad(kind, 3)  # pad rows render as (masked) deletes
        a_p = pad(a_slot, null)
        b_p = pad(b_slot, null)
        w_p = np.zeros((n_pad, 4), np.int32)
        w_p[:n] = words
        obs_device.record_transfer(
            "h2d", kind_p.nbytes + a_p.nbytes + b_p.nbytes + w_p.nbytes
            + spec.blob.nbytes + 3 * spec.sel.nbytes)
        buf = self._program()(
            jnp.asarray(kind_p), jnp.asarray(a_p), jnp.asarray(b_p),
            jnp.asarray(w_p), bcols_dev, scols_dev,
            jnp.asarray(spec.sel), jnp.asarray(spec.lit),
            jnp.asarray(spec.litlen),
            esc_blob, esc_offs, esc_lens, jnp.asarray(spec.blob), W=W)
        try:
            buf.copy_to_host_async()
        except AttributeError:
            pass
        return RenderedStream(buf, lens[:n], n, W, require)
