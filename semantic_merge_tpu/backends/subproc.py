"""Subprocess language backend — the client half of the worker seam.

Speaks the newline JSON-RPC protocol of
:mod:`semantic_merge_tpu.runtime.worker` to a child process (reference
``semmerge/lang/ts/bridge.py:21-47`` spawns its Node worker the same
way). Crash isolation is the point: a dying worker raises a clean
:class:`WorkerError` here, which the CLI's degradation ladder turns
into a host-engine retry instead of a corrupted merge.

Supervision (the fault-containment layer):

- every request carries a **deadline** (``SEMMERGE_WORKER_TIMEOUT``
  seconds, default 120; constructor override for tests). The response
  read happens on a reader thread; on expiry the worker's whole
  process group is SIGKILLed — a wedged worker can never hang the
  merge, and killing the group unblocks the reader;
- **bounded respawn-and-resend**: idempotent methods (every protocol
  method is a pure function of its params) retry once by default
  (``SEMMERGE_WORKER_RETRIES``) against a freshly spawned worker, with
  exponential backoff. Retries land in the
  ``subprocess_retries_total{method}`` counter;
- the worker runs in its own session (``start_new_session``) so the
  group kill cannot take the CLI down with it.

The worker command is configurable (``[engine] worker_cmd`` in
``.semmerge.toml``), so ANY external implementation of the protocol can
serve a language. Default: this package's own worker over the host
engine.
"""
from __future__ import annotations

import json
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from ..core.conflict import Conflict
from ..core.ops import Op
from ..errors import WorkerFault
from ..frontend.snapshot import TS_EXTENSIONS, Snapshot
from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans
from ..utils import faults
from ..utils.loggingx import logger
from ..utils.procs import env_seconds, kill_process_group
from .base import BuildAndDiffResult, register_backend


class WorkerError(WorkerFault):
    """The worker died, wedged past its deadline, or answered with a
    protocol error. Subclasses :class:`~semantic_merge_tpu.errors.
    WorkerFault`, so the CLI's degradation ladder catches it natively."""


#: Protocol methods that are pure functions of their params — safe to
#: resend against a respawned worker.
IDEMPOTENT_METHODS = frozenset({"buildAndDiff", "diff", "compose", "ping"})


# --- keep-alive worker sharing (daemon warm state) -------------------------
#
# The CLI builds a fresh backend per merge rung and closes it at rung
# end, so a one-shot process pays one worker spawn per merge. The merge
# service daemon (service/daemon.py) sets SEMMERGE_WORKER_KEEPALIVE=1 in
# its own environment: backend instances then check a process-global
# worker out of this registry (keyed by the worker command line) instead
# of spawning, and close() leaves it running — the supervised child
# stays warm across requests. Requests sharing a worker serialize their
# write+read round-trips on the registry lock entry; supervision
# (deadline group-kill, respawn-and-resend) is unchanged and a killed
# shared worker is dropped from the registry so the next request
# respawns it.

_SHARED_LOCK = threading.Lock()
_SHARED: Dict[tuple, "tuple[subprocess.Popen, threading.Lock]"] = {}


def _keepalive_enabled() -> bool:
    import os
    return os.environ.get("SEMMERGE_WORKER_KEEPALIVE", "").strip() == "1"


def shutdown_shared() -> None:
    """Close every keep-alive worker (daemon shutdown path)."""
    with _SHARED_LOCK:
        procs = [proc for proc, _ in _SHARED.values()]
        _SHARED.clear()
    for proc in procs:
        if proc.poll() is None:
            try:
                proc.stdin.close()
                proc.wait(timeout=5)
            except Exception:
                kill_process_group(proc)


class SubprocessBackend:
    name = "subprocess"
    extensions = frozenset(TS_EXTENSIONS)

    def __init__(self, worker_cmd: Optional[List[str]] = None, *,
                 deadline: Optional[float] = None,
                 max_retries: Optional[int] = None,
                 retry_backoff: float = 0.05,
                 retry_backoff_cap: Optional[float] = None) -> None:
        self._cmd = worker_cmd or [
            sys.executable, "-m", "semantic_merge_tpu.runtime.worker",
            "--backend", "host"]
        self._proc: Optional[subprocess.Popen] = None
        self._io_lock = threading.Lock()
        self._next_id = 0
        self._deadline = (deadline if deadline is not None
                          else env_seconds("SEMMERGE_WORKER_TIMEOUT", 120.0))
        self._max_retries = (max_retries if max_retries is not None
                             else int(env_seconds("SEMMERGE_WORKER_RETRIES", 1)))
        self._retry_backoff = retry_backoff
        self._retry_backoff_cap = (
            retry_backoff_cap if retry_backoff_cap is not None
            else env_seconds("SEMMERGE_WORKER_BACKOFF_CAP", 2.0))
        #: Why the last worker went down — labels the respawn counter.
        self._down_reason: Optional[str] = None

    def configure(self, config) -> None:
        cmd = getattr(config.engine, "worker_cmd", None)
        if cmd:
            self._cmd = list(cmd)
            self._shutdown()

    # --- protocol plumbing -------------------------------------------------

    def _spawn(self) -> subprocess.Popen:
        # The default worker imports this package; make that work
        # from any cwd (the CLI usually runs inside a user repo).
        import os
        import pathlib
        env = dict(os.environ)
        pkg_root = str(pathlib.Path(__file__).resolve().parents[2])
        parts = [pkg_root, env.get("PYTHONPATH", "")]
        env["PYTHONPATH"] = os.pathsep.join(p for p in parts if p)
        # Own session: deadline expiry kills the worker's whole
        # process group without touching the CLI's.
        return subprocess.Popen(
            self._cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True, bufsize=1, env=env, start_new_session=True)

    def _note_respawn(self, reason: str) -> None:
        obs_metrics.REGISTRY.counter(
            "subprocess_respawns_total",
            "Workers respawned after a previous one went down, by reason",
        ).inc(1, reason=reason)

    def _ensure_proc(self) -> subprocess.Popen:
        if self._proc is None or self._proc.poll() is not None:
            # A recorded teardown reason, or the worker died under us
            # without one (crash between requests). First-ever spawns
            # carry neither and are not respawns.
            reason = self._down_reason
            if reason is None and self._proc is not None:
                reason = "worker-exit"
            if _keepalive_enabled():
                key = tuple(self._cmd)
                with _SHARED_LOCK:
                    entry = _SHARED.get(key)
                    if entry is None or entry[0].poll() is not None:
                        if reason is None and entry is not None:
                            reason = "worker-exit"
                        entry = (self._spawn(), threading.Lock())
                        _SHARED[key] = entry
                        if reason:
                            self._note_respawn(reason)
                self._proc, self._io_lock = entry
            else:
                self._proc = self._spawn()
                if reason:
                    self._note_respawn(reason)
            self._down_reason = None
        return self._proc

    def _call(self, method: str, params: Dict) -> Dict:
        faults.check("worker")
        attempts = 1
        if method in IDEMPOTENT_METHODS and self._max_retries > 0:
            attempts += self._max_retries
        for attempt in range(attempts):
            try:
                return self._call_once(method, params)
            except WorkerError as exc:
                if attempt + 1 >= attempts:
                    raise
                obs_metrics.REGISTRY.counter(
                    "subprocess_retries_total",
                    "Worker requests resent after respawn, by method",
                ).inc(1, method=method)
                obs_spans.event("worker_retry", method=method,
                                attempt=attempt + 1, error=str(exc))
                logger.warning("worker %s failed (%s); respawning and "
                               "resending (attempt %d/%d)", method, exc,
                               attempt + 2, attempts)
                # Exponential with a cap: repeated deaths back off hard
                # enough to stop thrashing spawn/die loops, but a
                # bounded retry never sleeps unboundedly long.
                time.sleep(min(self._retry_backoff * (2 ** attempt),
                               self._retry_backoff_cap))
        raise AssertionError("unreachable")

    def _call_once(self, method: str, params: Dict) -> Dict:
        proc = self._ensure_proc()
        # One request/response round-trip at a time per worker process:
        # a keep-alive worker is shared by concurrent daemon requests,
        # and interleaved writes on one pipe would corrupt the framing.
        with self._io_lock:
            return self._roundtrip(proc, method, params)

    def _roundtrip(self, proc: subprocess.Popen, method: str,
                   params: Dict) -> Dict:
        self._next_id += 1
        request = {"id": self._next_id, "method": method, "params": params}
        tid = obs_spans.trace_id()
        if tid:
            request["trace_id"] = tid
        t_sent = time.perf_counter()
        try:
            proc.stdin.write(json.dumps(request) + "\n")
            proc.stdin.flush()
        except (BrokenPipeError, OSError) as exc:
            self._shutdown(reason="pipe-broken")
            raise WorkerError(f"worker pipe broke during {method}: {exc}",
                              cause=type(exc).__name__) from exc
        line = self._read_response_line(proc, method)
        if not line:
            code = proc.poll()
            self._shutdown(reason="worker-exit")
            raise WorkerError(
                f"worker exited (rc={code}) without answering {method}",
                cause="worker-exit")
        try:
            response = json.loads(line)
        except json.JSONDecodeError as exc:
            self._shutdown(reason="protocol")
            raise WorkerError(f"worker spoke non-JSON: {line[:200]!r}",
                              cause="protocol") from exc
        if response.get("id") != request["id"]:
            self._shutdown(reason="protocol")
            raise WorkerError(
                f"worker answered id {response.get('id')} to {request['id']}",
                cause="protocol")
        if "error" in response:
            # The worker survived — only this request failed.
            raise WorkerError(str(response["error"].get("message", "unknown")),
                              cause="request-error")
        result = response.get("result", {})
        self._graft_worker_spans(result, method, t_sent)
        return result

    @staticmethod
    def _graft_worker_spans(result: Dict, method: str,
                            t_sent: float) -> None:
        """Pull the worker-side ``_worker`` timing block out of the
        result and record it as ``worker.*`` spans in the caller's
        trace — the only window the client has into time spent on the
        far side of the pipe. Start times are approximated by the
        client-side send instant (wire latency shifts them slightly
        but preserves ordering)."""
        block = result.pop("_worker", None)
        if not isinstance(block, dict):
            return
        seconds = block.get("seconds")
        if isinstance(seconds, (int, float)):
            obs_spans.record(f"worker.{method}", float(seconds),
                             layer="worker", t_start=t_sent)
        phases = block.get("phases")
        if isinstance(phases, dict):
            for name, secs in phases.items():
                if isinstance(secs, (int, float)):
                    obs_spans.record(f"worker.{name}", float(secs),
                                     layer="worker", t_start=t_sent)

    def _read_response_line(self, proc: subprocess.Popen, method: str) -> str:
        """One response line, bounded by the per-request deadline.

        ``readline`` blocks forever on a wedged worker, so it runs on a
        daemon reader thread; on expiry the worker's process group is
        killed (which also unblocks the reader via EOF) and a deadline
        WorkerError raised."""
        if not self._deadline or self._deadline <= 0:
            return proc.stdout.readline()
        box: list = []
        done = threading.Event()

        def read() -> None:
            try:
                box.append(proc.stdout.readline())
            except Exception as exc:  # pipe torn down under the reader
                box.append(exc)
            finally:
                done.set()

        reader = threading.Thread(target=read, daemon=True,
                                  name="semmerge-worker-read")
        reader.start()
        if not done.wait(self._deadline):
            kill_process_group(proc)
            done.wait(5.0)
            self._shutdown(reason="deadline")
            obs_metrics.REGISTRY.counter(
                "subprocess_deadline_kills_total",
                "Workers killed for exceeding the request deadline",
            ).inc(1, method=method)
            raise WorkerError(
                f"worker exceeded its {self._deadline:g}s deadline on "
                f"{method}; process group killed", cause="deadline")
        result = box[0] if box else ""
        if isinstance(result, Exception):
            self._shutdown(reason="pipe-broken")
            raise WorkerError(f"worker pipe broke during {method}: {result}",
                              cause=type(result).__name__) from result
        return result

    def _shutdown(self, reason: Optional[str] = None) -> None:
        self._down_reason = reason
        proc, self._proc = self._proc, None
        if proc is not None:
            with _SHARED_LOCK:
                # A torn-down worker must not be handed to the next
                # keep-alive checkout.
                for key, (shared, _) in list(_SHARED.items()):
                    if shared is proc:
                        del _SHARED[key]
            try:
                if proc.poll() is None:
                    proc.stdin.close()
                    proc.wait(timeout=5)
            except Exception:
                kill_process_group(proc)

    # --- Backend protocol --------------------------------------------------

    @staticmethod
    def _files(snap: Snapshot):
        return [{"path": f["path"], "content": f["content"]}
                for f in snap.files]

    def build_and_diff(self, base: Snapshot, left: Snapshot, right: Snapshot,
                       *, base_rev: str = "base", seed: str = "0",
                       timestamp: str | None = None,
                       change_signature: bool = False,
                       structured_apply: bool = False,
                       signature_matcher=None,
                       statement_ops: bool = False) -> BuildAndDiffResult:
        if signature_matcher is not None:
            raise WorkerError(
                "signature_matcher is in-process only; the subprocess "
                "backend's worker owns its own matcher configuration")
        result = self._call("buildAndDiff", {
            "base": self._files(base), "left": self._files(left),
            "right": self._files(right), "baseRev": base_rev, "seed": seed,
            "timestamp": timestamp, "changeSignature": change_signature,
            "structuredApply": structured_apply,
            "statementOps": statement_ops,
        })
        return BuildAndDiffResult(
            op_log_left=[Op.from_dict(o) for o in result["opLogLeft"]],
            op_log_right=[Op.from_dict(o) for o in result["opLogRight"]],
            symbol_maps=result.get("symbolMaps", {}),
            diagnostics=result.get("diagnostics", []),
        )

    def diff(self, base: Snapshot, right: Snapshot,
             *, base_rev: str = "base", seed: str = "0",
             timestamp: str | None = None,
             change_signature: bool = False,
             structured_apply: bool = False,
             signature_matcher=None,
             statement_ops: bool = False) -> List[Op]:
        result = self._call("diff", {
            "base": self._files(base), "right": self._files(right),
            "baseRev": base_rev, "seed": seed, "timestamp": timestamp,
            "changeSignature": change_signature,
            "structuredApply": structured_apply,
            "statementOps": statement_ops,
        })
        return [Op.from_dict(o) for o in result["opLog"]]

    def compose(self, delta_a: List[Op], delta_b: List[Op]):
        result = self._call("compose", {
            "deltaA": [op.to_dict() for op in delta_a],
            "deltaB": [op.to_dict() for op in delta_b],
        })
        composed = [Op.from_dict(o) for o in result["composed"]]
        conflicts = [Conflict(**c) for c in result["conflicts"]]
        return composed, conflicts

    def close(self) -> None:
        if self._proc is not None and _keepalive_enabled():
            # Keep-alive mode: the worker outlives this backend instance
            # (the daemon owns its lifetime via shutdown_shared()).
            self._proc = None
            return
        if self._proc is not None and self._proc.poll() is not None:
            self._proc = None  # already dead: nothing to hand shutdown to
        if self._proc is not None:
            # Shutdown is best-effort and must not inherit a long
            # request deadline: give a wedged worker 5 s, then kill.
            deadline, self._deadline = self._deadline, min(
                self._deadline or 5.0, 5.0)
            try:
                self._call_once("shutdown", {})
            except WorkerError:
                pass
            finally:
                self._deadline = deadline
            self._shutdown()


register_backend("subprocess", SubprocessBackend)
register_backend("worker", SubprocessBackend)
