"""Subprocess language backend — the client half of the worker seam.

Speaks the newline JSON-RPC protocol of
:mod:`semantic_merge_tpu.runtime.worker` to a child process (reference
``semmerge/lang/ts/bridge.py:21-47`` spawns its Node worker the same
way). Crash isolation is the point: a dying worker raises a clean
:class:`WorkerError` here, which the CLI's backend-fallback path turns
into a host-engine retry instead of a corrupted merge.

The worker command is configurable (``[engine] worker_cmd`` in
``.semmerge.toml``), so ANY external implementation of the protocol can
serve a language — including a future Node worker wrapping the real
TypeScript compiler, which would turn the golden-corpus fixtures into a
live oracle. Default: this package's own worker over the host engine.
"""
from __future__ import annotations

import json
import subprocess
import sys
from typing import Dict, List, Optional

from ..core.conflict import Conflict
from ..core.ops import Op
from ..frontend.snapshot import TS_EXTENSIONS, Snapshot
from .base import BuildAndDiffResult, register_backend


class WorkerError(RuntimeError):
    """The worker died or answered with a protocol error."""


class SubprocessBackend:
    name = "subprocess"
    extensions = frozenset(TS_EXTENSIONS)

    def __init__(self, worker_cmd: Optional[List[str]] = None) -> None:
        self._cmd = worker_cmd or [
            sys.executable, "-m", "semantic_merge_tpu.runtime.worker",
            "--backend", "host"]
        self._proc: Optional[subprocess.Popen] = None
        self._next_id = 0

    def configure(self, config) -> None:
        cmd = getattr(config.engine, "worker_cmd", None)
        if cmd:
            self._cmd = list(cmd)
            self._shutdown()

    # --- protocol plumbing -------------------------------------------------

    def _ensure_proc(self) -> subprocess.Popen:
        if self._proc is None or self._proc.poll() is not None:
            # The default worker imports this package; make that work
            # from any cwd (the CLI usually runs inside a user repo).
            import os
            import pathlib
            env = dict(os.environ)
            pkg_root = str(pathlib.Path(__file__).resolve().parents[2])
            parts = [pkg_root, env.get("PYTHONPATH", "")]
            env["PYTHONPATH"] = os.pathsep.join(p for p in parts if p)
            self._proc = subprocess.Popen(
                self._cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                text=True, bufsize=1, env=env)
        return self._proc

    def _call(self, method: str, params: Dict) -> Dict:
        proc = self._ensure_proc()
        self._next_id += 1
        request = {"id": self._next_id, "method": method, "params": params}
        try:
            proc.stdin.write(json.dumps(request) + "\n")
            proc.stdin.flush()
            line = proc.stdout.readline()
        except (BrokenPipeError, OSError) as exc:
            self._shutdown()
            raise WorkerError(f"worker pipe broke during {method}: {exc}") from exc
        if not line:
            code = proc.poll()
            self._shutdown()
            raise WorkerError(
                f"worker exited (rc={code}) without answering {method}")
        try:
            response = json.loads(line)
        except json.JSONDecodeError as exc:
            self._shutdown()
            raise WorkerError(f"worker spoke non-JSON: {line[:200]!r}") from exc
        if response.get("id") != request["id"]:
            self._shutdown()
            raise WorkerError(
                f"worker answered id {response.get('id')} to {request['id']}")
        if "error" in response:
            # The worker survived — only this request failed.
            raise WorkerError(str(response["error"].get("message", "unknown")))
        return response.get("result", {})

    def _shutdown(self) -> None:
        proc, self._proc = self._proc, None
        if proc is not None:
            try:
                if proc.poll() is None:
                    proc.stdin.close()
                    proc.wait(timeout=5)
            except Exception:
                proc.kill()

    # --- Backend protocol --------------------------------------------------

    @staticmethod
    def _files(snap: Snapshot):
        return [{"path": f["path"], "content": f["content"]}
                for f in snap.files]

    def build_and_diff(self, base: Snapshot, left: Snapshot, right: Snapshot,
                       *, base_rev: str = "base", seed: str = "0",
                       timestamp: str | None = None,
                       change_signature: bool = False,
                       structured_apply: bool = False,
                       signature_matcher=None,
                       statement_ops: bool = False) -> BuildAndDiffResult:
        if signature_matcher is not None:
            raise WorkerError(
                "signature_matcher is in-process only; the subprocess "
                "backend's worker owns its own matcher configuration")
        result = self._call("buildAndDiff", {
            "base": self._files(base), "left": self._files(left),
            "right": self._files(right), "baseRev": base_rev, "seed": seed,
            "timestamp": timestamp, "changeSignature": change_signature,
            "structuredApply": structured_apply,
            "statementOps": statement_ops,
        })
        return BuildAndDiffResult(
            op_log_left=[Op.from_dict(o) for o in result["opLogLeft"]],
            op_log_right=[Op.from_dict(o) for o in result["opLogRight"]],
            symbol_maps=result.get("symbolMaps", {}),
            diagnostics=result.get("diagnostics", []),
        )

    def diff(self, base: Snapshot, right: Snapshot,
             *, base_rev: str = "base", seed: str = "0",
             timestamp: str | None = None,
             change_signature: bool = False,
             structured_apply: bool = False,
             signature_matcher=None,
             statement_ops: bool = False) -> List[Op]:
        result = self._call("diff", {
            "base": self._files(base), "right": self._files(right),
            "baseRev": base_rev, "seed": seed, "timestamp": timestamp,
            "changeSignature": change_signature,
            "structuredApply": structured_apply,
            "statementOps": statement_ops,
        })
        return [Op.from_dict(o) for o in result["opLog"]]

    def compose(self, delta_a: List[Op], delta_b: List[Op]):
        result = self._call("compose", {
            "deltaA": [op.to_dict() for op in delta_a],
            "deltaB": [op.to_dict() for op in delta_b],
        })
        composed = [Op.from_dict(o) for o in result["composed"]]
        conflicts = [Conflict(**c) for c in result["conflicts"]]
        return composed, conflicts

    def close(self) -> None:
        if self._proc is not None:
            try:
                self._call("shutdown", {})
            except WorkerError:
                pass
            self._shutdown()


register_backend("subprocess", SubprocessBackend)
register_backend("worker", SubprocessBackend)
