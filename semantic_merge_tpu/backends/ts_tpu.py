"""TPU TypeScript backend — the device execution path.

Same contract as :mod:`semantic_merge_tpu.backends.ts_host`, but the
diff join and op-stream enumeration run as fused XLA programs over
interned int32 tensors (:mod:`semantic_merge_tpu.ops.diff`). Host work
is reduced to scanning (parsing) and string interning; the per-symbol
join — the reference worker's per-file hot path (reference
``workers/ts/src/diff.ts``, ``workers/ts/src/lift.ts``) — happens on
the accelerator. The device op stream is decoded back into the same
``Diff`` records the host backend produces and lifted by the shared
:func:`semantic_merge_tpu.core.difflift.lift`, so op logs are
bit-identical by construction (same deterministic ids, same enumeration
order) and every lift-level feature (e.g. changeSignature refinement)
applies to both backends identically.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from functools import partial
from typing import Dict, List

from ..core.difflift import (Diff, lift, lift_statements,
                             refine_signature_changes, source_maps)
from ..core.encode import Interner, encode_decls_keyed
from ..core.ids import EPOCH_ISO
from ..core.ops import Op
from ..frontend.scanner import DeclNode, scan_snapshot_keyed
from ..frontend.snapshot import Snapshot
from ..frontend.snapshot import TS_EXTENSIONS
from ..obs import device as obs_device
from ..obs import spans as obs_spans
from .ts_host import ts_files
from ..ops.diff import (KIND_ADD, KIND_DELETE, KIND_MOVE, KIND_RENAME,
                        DiffOpsTensor, diff_lift_device, diff_lift_device_pair)
from .base import BuildAndDiffResult, register_backend, symbol_map


#: Process-shared interner for warm-residency deployments. The daemon
#: constructs a fresh backend per request (``get_backend`` is not
#: memoized; backend instances hold unlocked per-merge caches that are
#: unsafe to share across concurrent worker threads), but residency
#: entries store tensors of *interned ids* — a lookup can only hit when
#: the requesting backend speaks the same id space. So under
#: ``SEMMERGE_RESIDENCY_CACHE`` every backend in the process adopts this
#: one Interner (thread-safe by construction, see core/encode.py) and
#: residency survives backend lifetimes. Replaced only by the growth
#: guard (:meth:`TpuTSBackend._maybe_reset_interner`).
_SHARED_INTERNER: Interner | None = None
_SHARED_LOCK = threading.Lock()


def _shared_interner() -> Interner:
    global _SHARED_INTERNER
    with _SHARED_LOCK:
        if _SHARED_INTERNER is None:
            it = Interner()
            it.shared = True
            _SHARED_INTERNER = it
        return _SHARED_INTERNER


class TpuTSBackend:
    name = "tpu"
    extensions = frozenset(TS_EXTENSIONS)
    #: The applier batches CRDT materialization on device for this
    #: backend (capability flag — survives MultiBackend wrapping).
    device_crdt = True

    def __init__(self, mesh=None) -> None:
        # Probe JAX init at construction so the CLI's host-fallback path
        # (cli._resolve_backend) catches a broken plugin/runtime here
        # instead of deep inside the first merge. XLA-on-CPU (no
        # accelerator present) is a supported degraded mode, not an error.
        import jax
        devices = jax.devices()
        # JAX is definitively up here: mirror compile/compile-cache
        # monitoring into the shared metrics registry.
        obs_device.ensure_jax_listeners()
        if mesh is None:
            mesh = self._posture_mesh(devices)
        self._mesh = mesh or None  # mesh=False forces the single-device path
        # Persistent across merges: encoded ids are stable for the
        # interner's lifetime, so per-file encoded columns cache in the
        # shared decl cache (keyed by scan identity + interner token).
        # With warm residency on, adopt the process-shared interner so
        # residency entries written by an earlier request's backend are
        # still in this backend's id space (the daemon builds a fresh
        # backend per request).
        from ..service import residency
        if residency.residency_enabled():
            self._interner = _shared_interner()
        else:
            self._interner = Interner()
        self._fused = None
        # [engine] host_workers — host-tail pipeline width for the
        # fused path (None until configure(); the engine resolves the
        # SEMMERGE_HOST_WORKERS env override and the auto default).
        self._host_workers: int | None = None
        # Snapshot-level encode cache: (interner token, per-file scan
        # keys) → (DeclTensor, flat node list). Repeated merges against
        # an unchanged tree skip interning + concatenation entirely
        # (values are treated as immutable downstream). Kept tiny (a
        # 3-way merge touches 3 snapshots, +1 slack) because entries pin
        # node lists outside the decl cache's byte budget; cleared on
        # interner reset.
        self._snap_cache: "OrderedDict" = OrderedDict()
        # symbolMaps payloads by snapshot identity: pure functions of
        # the node list (~28 ms per 45k-decl revision to rebuild), so
        # warm merges reuse them. Same lifecycle and immutability
        # contract as the snapshot cache.
        self._symmap_cache: "OrderedDict" = OrderedDict()

    @staticmethod
    def _posture_mesh(devices, configured=None):
        """The engine mesh the ``SEMMERGE_MESH`` posture asks for
        (:data:`semantic_merge_tpu.parallel.mesh.MESH_POSTURES`):
        ``False`` pins the single-device kernels, a dp mesh shards the
        decl/op axis over a multi-chip host. With the batching
        subsystem active the engine stays single-device regardless —
        merges must be batch-eligible, and the mesh rides the batched
        dispatcher's packed merge axis instead of one merge's decl
        axis. ``require`` raises :class:`MeshFault` when neither path
        can use a mesh (single-chip host, build failure)."""
        from ..parallel.mesh import mesh_posture
        posture = mesh_posture(configured)
        if posture == "off":
            return False
        from .. import batch as batch_mod
        if batch_mod.current() is not None:
            # The batch dispatcher enforces (and, under require,
            # raises for) the mesh contract itself per dispatch.
            return False
        if len(devices) > 1:
            try:
                from ..parallel.mesh import build_mesh
                return build_mesh(devices, dp=len(devices),
                                  pp=1, sp=1, tp=1, ep=1).mesh
            except Exception as exc:
                if posture == "require":
                    from ..errors import MeshFault
                    raise MeshFault(f"engine mesh build failed: {exc}",
                                    cause=type(exc).__name__) from exc
                from ..utils.loggingx import logger
                logger.warning("engine mesh build failed, using "
                               "single-device kernels: %s", exc)
                return False
        if posture == "require":
            from ..errors import MeshFault
            raise MeshFault(
                f"SEMMERGE_MESH=require but the host has {len(devices)} "
                f"device(s) and no batch scheduler is active",
                cause="single-device")
        return False

    def _symbol_map_cached(self, nodes, key):
        if key is not None:
            hit = self._symmap_cache.get(key)
            if hit is not None:
                self._symmap_cache.move_to_end(key)
                return hit
        m = symbol_map(nodes)
        if key is not None:
            self._symmap_cache[key] = m
            while len(self._symmap_cache) > 4:
                self._symmap_cache.popitem(last=False)
        return m

    def _fused_engine(self):
        from ..ops.fused import FusedMergeEngine
        if (self._fused is None or self._fused.interner is not self._interner
                or self._fused.mesh is not self._mesh
                or self._fused.host_workers_cfg != self._host_workers):
            self._fused = FusedMergeEngine(self._interner, mesh=self._mesh,
                                           host_workers=self._host_workers)
        return self._fused

    def _scan_encode(self, snapshot: Snapshot):
        t, nodes, _ = self._scan_encode_keyed(snapshot)
        return t, nodes

    def _maybe_reset_interner(self) -> None:
        """Unbounded growth guard for long-lived processes; the new
        token invalidates every cached column naturally. Must run only
        *between* merges — never between the three snapshot scans of
        one merge, whose interned ids must share one id space."""
        if len(self._interner) > 4_000_000:
            if self._interner.shared:
                # Swap the process-shared instance so later backends
                # adopt the replacement too; first resetter wins —
                # concurrent callers adopt whatever is current.
                global _SHARED_INTERNER
                with _SHARED_LOCK:
                    if _SHARED_INTERNER is self._interner:
                        it = Interner()
                        it.shared = True
                        _SHARED_INTERNER = it
                    self._interner = _SHARED_INTERNER
            else:
                self._interner = Interner()
            # Every snapshot-cache entry is keyed by the dead token and
            # can never hit again — drop them now, not by LRU attrition.
            self._snap_cache.clear()
            self._symmap_cache.clear()

    def _scan_encode_keyed(self, snapshot: Snapshot):
        """Scan+encode, also returning the snapshot's stable identity
        (the tuple of per-file decl-cache keys + interner token) — the
        key under which the fused path caches device-resident decl
        columns. ``None`` when any file lacks a stable key.

        Warm repeats skip identity RECOMPUTATION too: the identity is
        cached on the Snapshot object, guarded by a content
        fingerprint built from the files' ``hash()`` values — Python
        strings cache their hash, so verification is O(n_files) after
        the first pass, and replacing any path/content string (the
        only way str content changes) invalidates it. At the 10k-file
        rung this removes ~60 ms of per-merge cache-key bookkeeping
        the snapshot cache's own lookup used to pay."""
        from ..frontend.declcache import global_cache
        tok = self._interner.token
        fp = None
        cached = snapshot.__dict__.get("_semmerge_identity")
        if cached is not None:
            cident, cfp = cached
            if cident[0] == tok:
                fp = _snapshot_fingerprint(snapshot)
                if cfp == fp:
                    hit = self._snap_cache.get(cident)
                    if hit is not None:
                        self._snap_cache.move_to_end(cident)
                        return hit[0], hit[1], cident
        # Warm residency (service/residency.py): an annotated snapshot
        # — the base tree of a repeat merge, keyed by (repo, tree_oid,
        # scope) — may already be resident from an earlier request in
        # this process. A hit hands back the encoded tensor AND the
        # decl-cache identity, so the fused engine's device columns are
        # reused too (scan, encode, and h2d all skipped); only the
        # changed side of the merge pays residency.encode_delta below.
        from ..service import residency
        res_key = residency.resident_key(snapshot) \
            if residency.residency_enabled() else None
        if res_key is not None:
            t0 = time.perf_counter()
            rhit = residency.cache().lookup(res_key, token=tok)
            if rhit is not None:
                obs_spans.record("residency.hit",
                                 time.perf_counter() - t0, layer="frontend",
                                 t_start=t0, repo=res_key[0] or "synthetic")
                self._snap_cache[rhit.identity] = (rhit.t, rhit.nodes)
                while len(self._snap_cache) > 4:
                    self._snap_cache.popitem(last=False)
                _store_identity(snapshot, rhit.identity, fp)
                return rhit.t, rhit.nodes, rhit.identity
        t0 = time.perf_counter()
        keyed = scan_snapshot_keyed(ts_files(snapshot))
        identity = None
        keys = [k for k, _ in keyed]
        if keys and all(k is not None for k in keys):
            identity = (self._interner.token, tuple(keys))
        elif not keys:
            identity = (self._interner.token, ())
        if identity is not None:
            hit = self._snap_cache.get(identity)
            if hit is not None:
                self._snap_cache.move_to_end(identity)
                # Content-aliased snapshot objects (e.g. an unchanged
                # side equal to base) get the object-level fast path
                # too, not just the one that populated the cache.
                _store_identity(snapshot, identity, fp)
                if res_key is not None:
                    residency.cache().put(res_key, hit[0], hit[1], identity)
                return hit[0], hit[1], identity
        t, nodes = encode_decls_keyed(keyed, self._interner, global_cache())
        if res_key is not None:
            obs_spans.record("residency.encode_delta",
                             time.perf_counter() - t0, layer="frontend",
                             t_start=t0, repo=res_key[0] or "synthetic")
            residency.cache().put(res_key, t, nodes, identity)
        if identity is not None:
            self._snap_cache[identity] = (t, nodes)
            while len(self._snap_cache) > 4:
                self._snap_cache.popitem(last=False)
            _store_identity(snapshot, identity, fp)
        return t, nodes, identity

    def configure(self, config) -> None:
        """Apply ``.semmerge.toml`` settings (called by the CLI): the
        ``[engine] mesh`` posture re-resolves the auto dp mesh (env
        still wins inside :func:`mesh_posture`), an explicit
        ``[engine] mesh_shape = "dp=4,tp=2"`` overrides it, and
        ``"hybrid:dcn=dp,dp=4,..."`` builds the multi-slice mesh whose
        ``dcn`` axis crosses slices over DCN while every other axis
        rides ICI."""
        workers = int(getattr(config.engine, "host_workers", 0) or 0)
        self._host_workers = workers if workers > 0 else None
        from ..parallel.mesh import mesh_posture
        configured = getattr(config.engine, "mesh", None)
        import jax
        self._mesh = self._posture_mesh(jax.devices(), configured) or None
        if mesh_posture(configured) == "off":
            return  # posture pins single-device; mesh_shape is moot
        shape = getattr(config.engine, "mesh_shape", "auto")
        try:
            from ..parallel.mesh import build_mesh, parse_mesh_spec
            kind, dcn_axis, sizes = parse_mesh_spec(shape)
            if kind == "hybrid":
                import jax

                from ..parallel.distributed import build_hybrid_mesh
                self._mesh = build_hybrid_mesh(jax.devices(),
                                               dcn_axis=dcn_axis,
                                               **sizes).mesh
            elif sizes:
                import jax
                self._mesh = build_mesh(jax.devices(), **sizes).mesh
        except ValueError as exc:
            from ..utils.loggingx import logger
            logger.warning("invalid mesh_shape %r ignored: %s", shape, exc)

    def _diff_pair_fn(self):
        if self._mesh is not None:
            from ..ops.sharded import diff_lift_device_pair_sharded
            return partial(diff_lift_device_pair_sharded, mesh=self._mesh)
        return diff_lift_device_pair

    def _diff_fn(self):
        if self._mesh is not None:
            from ..ops.sharded import diff_lift_device_sharded
            return partial(diff_lift_device_sharded, mesh=self._mesh)
        return diff_lift_device

    def build_and_diff(self, base: Snapshot, left: Snapshot, right: Snapshot,
                       *, base_rev: str = "base", seed: str = "0",
                       timestamp: str | None = None,
                       change_signature: bool = False,
                       structured_apply: bool = False,
                       signature_matcher=None,
                       statement_ops: bool = False) -> BuildAndDiffResult:
        ts = timestamp or EPOCH_ISO
        self._maybe_reset_interner()
        base_t, base_nodes, base_key = self._scan_encode_keyed(base)
        left_t, left_nodes, left_key = self._scan_encode_keyed(left)
        right_t, right_nodes, right_key = self._scan_encode_keyed(right)
        t_l, t_r = self._diff_pair_fn()(base_t, left_t, right_t)
        diffs_l = decode_diffs(t_l, base_t, left_t, base_nodes, left_nodes)
        diffs_r = decode_diffs(t_r, base_t, right_t, base_nodes, right_nodes)
        want_sources = structured_apply or (change_signature
                                            and signature_matcher is not None)
        src_l = source_maps(ts_files(base), ts_files(left)) if want_sources else None
        src_r = source_maps(ts_files(base), ts_files(right)) if want_sources else None
        if change_signature:
            diffs_l = refine_signature_changes(diffs_l, src_l, signature_matcher)
            diffs_r = refine_signature_changes(diffs_r, src_r, signature_matcher)
        stmt_l = stmt_r = []
        if statement_ops:
            stmt_l = lift_statements(
                diffs_l, base_nodes, left_nodes, src_l,
                (ts_files(base), ts_files(left)),
                base_rev=base_rev, seed=seed, side="L", timestamp=ts)
            stmt_r = lift_statements(
                diffs_r, base_nodes, right_nodes, src_r,
                (ts_files(base), ts_files(right)),
                base_rev=base_rev, seed=seed, side="R", timestamp=ts)
        if not structured_apply:
            src_l = src_r = None
        return BuildAndDiffResult(
            op_log_left=lift(base_rev, diffs_l, seed=seed + "/L", timestamp=ts,
                             sources=src_l) + stmt_l,
            op_log_right=lift(base_rev, diffs_r, seed=seed + "/R", timestamp=ts,
                              sources=src_r) + stmt_r,
            symbol_maps={
                "base": self._symbol_map_cached(base_nodes, base_key),
                "left": self._symbol_map_cached(left_nodes, left_key),
                "right": self._symbol_map_cached(right_nodes, right_key),
            },
        )

    def diff(self, base: Snapshot, right: Snapshot,
             *, base_rev: str = "base", seed: str = "0",
             timestamp: str | None = None,
             change_signature: bool = False,
             structured_apply: bool = False,
             signature_matcher=None,
             statement_ops: bool = False) -> List[Op]:
        ts = timestamp or EPOCH_ISO
        self._maybe_reset_interner()
        if (self._mesh is None and not change_signature
                and not structured_apply and not statement_ops):
            base_t, base_nodes, base_key = self._scan_encode_keyed(base)
            right_t, right_nodes, right_key = self._scan_encode_keyed(right)
            fused = self._fused_engine().diff(
                base_t, base_key, base_nodes, right_t, right_key, right_nodes,
                seed=seed, base_rev=base_rev, timestamp=ts)
            if fused is not None:
                return fused
            t = self._diff_fn()(base_t, right_t)
            diffs = decode_diffs(t, base_t, right_t, base_nodes, right_nodes)
            return lift(base_rev, diffs, seed=seed + "/R", timestamp=ts)
        base_t, base_nodes = self._scan_encode(base)
        right_t, right_nodes = self._scan_encode(right)
        t = self._diff_fn()(base_t, right_t)
        diffs = decode_diffs(t, base_t, right_t, base_nodes, right_nodes)
        want_sources = structured_apply or (change_signature
                                            and signature_matcher is not None)
        sources = source_maps(ts_files(base), ts_files(right)) if want_sources else None
        if change_signature:
            diffs = refine_signature_changes(diffs, sources, signature_matcher)
        stmt = []
        if statement_ops:
            stmt = lift_statements(
                diffs, base_nodes, right_nodes, sources,
                (ts_files(base), ts_files(right)),
                base_rev=base_rev, seed=seed, side="R", timestamp=ts)
        if not structured_apply:
            sources = None
        return lift(base_rev, diffs, seed=seed + "/R", timestamp=ts,
                    sources=sources) + stmt

    def compose(self, delta_a: List[Op], delta_b: List[Op]):
        """Device-composed stream; since the columnar-applier round the
        non-empty result is a lazy ``ComposedOpView`` over the sorted
        object streams (decode hands the view through instead of a
        materialized list) — consumers that never need full ``Op`` rows
        skip the override clones."""
        if self._mesh is not None:
            from ..ops.sharded import compose_oplogs_device_sharded
            return compose_oplogs_device_sharded(delta_a, delta_b, self._mesh)
        from ..ops.compose import compose_oplogs_device
        return compose_oplogs_device(delta_a, delta_b)

    def merge(self, base: Snapshot, left: Snapshot, right: Snapshot,
              *, base_rev: str = "base", seed: str = "0",
              timestamp: str | None = None,
              change_signature: bool = False,
              structured_apply: bool = False,
              signature_matcher=None,
              statement_ops: bool = False):
        """Full 3-way merge in ONE device round trip when eligible (see
        :mod:`semantic_merge_tpu.ops.fused`): diff, deterministic op
        identity, and composition all stay on device; one compact fetch.
        With a mesh active the same program runs dp-sharded (distributed
        diff sort-join, row-sharded SHA). Ineligible configurations —
        structured-apply, statement ops, or a changeSignature merge
        whose rows actually contain a foldable delete+add pair — fall
        back to the two-program path with identical observable output.
        Phase timings flow through :mod:`semantic_merge_tpu.obs`.
        Returns ``(BuildAndDiffResult, composed_ops, conflicts)``.

        ``composed_ops`` is handed through COLUMNAR: the fused path's
        ``ComposedOpView`` (op-stream columns + tail-plan shards) feeds
        the columnar applier (``runtime/applier.py``) and the columnar
        touched-path scope directly — the default CLI merge
        materializes zero composed ``Op`` objects end-to-end
        (``SEMMERGE_OBJECT_APPLY=1`` forces the object oracle)."""
        ts = timestamp or EPOCH_ISO
        self._maybe_reset_interner()
        if not structured_apply and not statement_ops:
            # changeSignature no longer forfeits the fused path: the
            # refinement only *changes* anything when a deleted and an
            # added decl share (file, name, kind) (exact-key pass of
            # core.difflift.refine_signature_changes) — checked
            # columnar-ly on the fetched rows below; the overwhelmingly
            # common no-candidate merge keeps the one-round-trip result
            # (its op stream is bit-identical to the refined one).
            with obs_spans.span("scan_encode", layer="frontend"):
                base_t, base_nodes, base_key = self._scan_encode_keyed(base)
                left_t, left_nodes, left_key = self._scan_encode_keyed(left)
                right_t, right_nodes, right_key = self._scan_encode_keyed(right)
            # symbolMaps are independent host work — build them while
            # the device executes the fused program (pipeline staging).
            maps: Dict[str, list] = {}

            def build_symbol_maps():
                maps["base"] = self._symbol_map_cached(base_nodes, base_key)
                maps["left"] = self._symbol_map_cached(left_nodes, left_key)
                maps["right"] = self._symbol_map_cached(right_nodes,
                                                        right_key)

            with obs_spans.span("fused_merge", layer="backend",
                                backend=self.name):
                fused = self._fused_engine().merge(
                    base_t, base_key, base_nodes, left_t, left_key,
                    left_nodes, right_t, right_key, right_nodes,
                    seed=seed, base_rev=base_rev, timestamp=ts,
                    overlap_work=build_symbol_maps)
            if fused is not None:
                ops_l, ops_r, composed, conflicts = fused
                if change_signature and (
                        _changesig_candidates(ops_l, signature_matcher)
                        or _changesig_candidates(ops_r, signature_matcher)):
                    # A foldable delete+add pair exists: refinement
                    # would rewrite the stream (and re-index op ids),
                    # so this merge takes the two-program path below.
                    pass
                else:
                    result = BuildAndDiffResult(
                        op_log_left=ops_l, op_log_right=ops_r,
                        symbol_maps=maps,
                    )
                    return result, composed, conflicts
        from .. import batch as batch_mod
        if batch_mod.posture() == "require":
            # Only reachable when the fused (batchable) path was not
            # taken: ineligible configuration, a foldable
            # changeSignature pair, or exhausted capacity retries.
            from ..errors import BatchFault
            raise BatchFault(
                "SEMMERGE_BATCH=require but this merge is ineligible for "
                "the batched fused path", stage="batch")
        with obs_spans.span("build_and_diff", layer="backend",
                            backend=self.name):
            result = self.build_and_diff(
                base, left, right, base_rev=base_rev, seed=seed, timestamp=ts,
                change_signature=change_signature,
                structured_apply=structured_apply,
                signature_matcher=signature_matcher,
                statement_ops=statement_ops)
        with obs_spans.span("compose", layer="backend", backend=self.name):
            composed, conflicts = self.compose(result.op_log_left,
                                               result.op_log_right)
        return result, composed, conflicts

    def close(self) -> None:
        pass


def _store_identity(snapshot: Snapshot, identity, fp) -> None:
    """Attach the identity-cache record ``(identity, fingerprint)`` to
    the snapshot object (``identity[0]`` is the interner token). ``fp``
    reuses a fingerprint the guard already computed, if any."""
    if fp is None:
        fp = _snapshot_fingerprint(snapshot)
    snapshot.__dict__["_semmerge_identity"] = (identity, fp)


def _snapshot_fingerprint(snapshot: Snapshot) -> int:
    """Content fingerprint for the snapshot-object identity cache:
    hashes every (path, content) pair of the TS-indexed subset — the
    same file set the guarded identity derives from, so other
    languages' edits don't invalidate the TS identity. Strings cache
    their hash, so after the first computation this is an O(n_files)
    pointer walk; any in-place replacement of a path/content string
    changes it."""
    files = ts_files(snapshot)
    return hash((len(files),)
                + tuple((f["path"], f["content"]) for f in files))


def _changesig_candidates(view, matcher) -> bool:
    """Columnar twin of the changeSignature eligibility question: could
    ``refine_signature_changes`` rewrite this op stream at all?

    Exact-key pass: a deleted decl and an added decl sharing
    ``(file, name, kind)`` (names non-null). With a model ``matcher``
    the residual pass keys by ``(kind, file)`` — conservatively, any
    delete+add pair at all forfeits the fused result. ``view`` is an
    :class:`~semantic_merge_tpu.ops.oplog_view.OpStreamView`; only the
    delete/add rows' nodes are touched."""
    import numpy as np

    from ..ops.oplog_view import KIND_ADD as V_ADD, KIND_DELETE as V_DEL
    kinds = view.kind
    del_rows = np.nonzero(kinds == V_DEL)[0]
    add_rows = np.nonzero(kinds == V_ADD)[0]
    if not len(del_rows) or not len(add_rows):
        return False
    if matcher is not None:
        return True
    dels = set()
    for i in view.a_slot[del_rows].tolist():
        a = view.base_nodes[i]
        if a.name:
            dels.add((a.file, a.name, a.kind))
    for j in view.b_slot[add_rows].tolist():
        b = view.side_nodes[j]
        if b.name and (b.file, b.name, b.kind) in dels:
            return True
    return False


def decode_diffs(t: DiffOpsTensor,
                 base_t, side_t,
                 base_nodes: List[DeclNode],
                 side_nodes: List[DeclNode]) -> List[Diff]:
    """Device op stream → the host backend's ``Diff`` records.

    Rows carry interned addressIds; the full node data (kind, signature
    — needed by lift and by changeSignature refinement) is recovered by
    addressId lookup. addressIds embed ``file::name::pos`` so they are
    unique per node within a snapshot (reference
    ``workers/ts/src/sast.ts:65-67``); under Map last-wins collisions
    the device join already selected the surviving occurrence's address.

    The lookup is columnar: the encoded ``DeclTensor`` rows align with
    the node lists, so int-keyed maps resolve interned ids to nodes
    directly — no per-row string round-trip through the interner (the
    round-1 per-op Python loop this replaces was slower than the pure
    host path at the 1k-file rung).
    """
    base_by_id: Dict[int, DeclNode] = dict(
        zip(base_t.addr.tolist(), base_nodes))
    side_by_id: Dict[int, DeclNode] = dict(
        zip(side_t.addr.tolist(), side_nodes))

    kinds = {KIND_RENAME: "rename", KIND_MOVE: "move",
             KIND_ADD: "add", KIND_DELETE: "delete"}
    n = t.n_ops
    bget, sget = base_by_id.get, side_by_id.get
    return [Diff(kinds[k], a=bget(a), b=sget(b))
            for k, a, b in zip(t.kind[:n].tolist(), t.a_addr[:n].tolist(),
                               t.b_addr[:n].tolist())]


register_backend("tpu", TpuTSBackend)
register_backend("ts_tpu", TpuTSBackend)
