"""TPU TypeScript backend — the device execution path.

Same contract as :mod:`semantic_merge_tpu.backends.ts_host`, but the
diff join and op lifting run as fused XLA programs over interned int32
tensors (:mod:`semantic_merge_tpu.ops.diff`). Host work is reduced to
scanning (parsing) and string interning; the per-symbol join — the
reference worker's per-file hot path (reference
``workers/ts/src/diff.ts``, ``workers/ts/src/lift.ts``) — happens on
the accelerator. Output op logs are bit-identical to the host backend
(same deterministic ids, same enumeration order).
"""
from __future__ import annotations

from typing import List

from ..core.encode import NULL_ID, Interner, encode_decls
from ..core.ids import EPOCH_ISO, deterministic_op_id
from ..core.ops import Op, Target
from ..frontend.scanner import scan_snapshot
from ..frontend.snapshot import Snapshot
from ..ops.diff import (KIND_ADD, KIND_DELETE, KIND_MOVE, KIND_RENAME,
                        DiffOpsTensor, diff_lift_device, diff_lift_device_pair)
from .base import BuildAndDiffResult, register_backend, symbol_map


class TpuTSBackend:
    name = "tpu"

    def __init__(self) -> None:
        # Probe JAX init at construction so the CLI's host-fallback path
        # (cli._resolve_backend) catches a broken plugin/runtime here
        # instead of deep inside the first merge. XLA-on-CPU (no
        # accelerator present) is a supported degraded mode, not an error.
        import jax
        jax.devices()

    def build_and_diff(self, base: Snapshot, left: Snapshot, right: Snapshot,
                       *, base_rev: str = "base", seed: str = "0",
                       timestamp: str | None = None) -> BuildAndDiffResult:
        ts = timestamp or EPOCH_ISO
        base_nodes = scan_snapshot(base.files)
        left_nodes = scan_snapshot(left.files)
        right_nodes = scan_snapshot(right.files)
        interner = Interner()
        base_t = encode_decls(base_nodes, interner)
        left_t = encode_decls(left_nodes, interner)
        right_t = encode_decls(right_nodes, interner)
        t_l, t_r = diff_lift_device_pair(base_t, left_t, right_t)
        ops_l = decode_diff_ops(t_l, interner, base_rev, seed + "/L", ts)
        ops_r = decode_diff_ops(t_r, interner, base_rev, seed + "/R", ts)
        return BuildAndDiffResult(
            op_log_left=ops_l,
            op_log_right=ops_r,
            symbol_maps={
                "base": symbol_map(base_nodes),
                "left": symbol_map(left_nodes),
                "right": symbol_map(right_nodes),
            },
        )

    def diff(self, base: Snapshot, right: Snapshot,
             *, base_rev: str = "base", seed: str = "0",
             timestamp: str | None = None) -> List[Op]:
        ts = timestamp or EPOCH_ISO
        base_nodes = scan_snapshot(base.files)
        right_nodes = scan_snapshot(right.files)
        interner = Interner()
        base_t = encode_decls(base_nodes, interner)
        right_t = encode_decls(right_nodes, interner)
        return decode_diff_ops(diff_lift_device(base_t, right_t), interner,
                               base_rev, seed + "/R", ts)

    def compose(self, delta_a: List[Op], delta_b: List[Op]):
        from ..ops.compose import compose_oplogs_device
        return compose_oplogs_device(delta_a, delta_b)

    def close(self) -> None:
        pass


def decode_diff_ops(t: DiffOpsTensor, interner: Interner, base_rev: str,
                    seed: str, timestamp: str) -> List[Op]:
    """Device op tensor → Op records, byte-identical to the host lift
    (:func:`semantic_merge_tpu.core.difflift.lift`)."""
    ops: List[Op] = []
    prov = {"rev": base_rev, "timestamp": timestamp}

    def s(idx: int) -> str | None:
        return interner.lookup(int(idx)) if idx != NULL_ID else None

    for i in range(t.n_ops):
        kind = int(t.kind[i])
        sym = s(t.sym[i])
        a_addr = s(t.a_addr[i]) or ""
        b_addr = s(t.b_addr[i]) or ""
        if kind == KIND_RENAME:
            op_type = "renameSymbol"
            op = Op.new(
                op_type, Target(symbolId=sym, addressId=a_addr),
                params={"oldName": s(t.a_name[i]), "newName": s(t.b_name[i]),
                        "file": s(t.b_file[i])},
                guards={"exists": True, "addressMatch": a_addr},
                effects={"summary": f"rename {s(t.a_name[i])}→{s(t.b_name[i])}"},
                provenance=dict(prov),
                op_id=deterministic_op_id(seed, base_rev, i, op_type, sym, a_addr, b_addr),
            )
        elif kind == KIND_MOVE:
            op_type = "moveDecl"
            op = Op.new(
                op_type, Target(symbolId=sym, addressId=a_addr),
                params={"oldAddress": a_addr, "newAddress": b_addr,
                        "oldFile": s(t.a_file[i]), "newFile": s(t.b_file[i])},
                guards={"exists": True, "addressMatch": a_addr},
                effects={"summary": f"move {a_addr}→{b_addr}"},
                provenance=dict(prov),
                op_id=deterministic_op_id(seed, base_rev, i, op_type, sym, a_addr, b_addr),
            )
        elif kind == KIND_ADD:
            op_type = "addDecl"
            op = Op.new(
                op_type, Target(symbolId=sym, addressId=b_addr),
                params={"file": s(t.b_file[i])},
                guards={},
                effects={"summary": "add decl"},
                provenance=dict(prov),
                op_id=deterministic_op_id(seed, base_rev, i, op_type, sym, "", b_addr),
            )
        elif kind == KIND_DELETE:
            op_type = "deleteDecl"
            op = Op.new(
                op_type, Target(symbolId=sym, addressId=a_addr),
                params={"file": s(t.a_file[i])},
                guards={},
                effects={"summary": "delete decl"},
                provenance=dict(prov),
                op_id=deterministic_op_id(seed, base_rev, i, op_type, sym, a_addr, ""),
            )
        else:  # padding rows should never appear below n_ops
            raise AssertionError(f"bad kind {kind} at row {i}")
        ops.append(op)
    return ops


register_backend("tpu", TpuTSBackend)
register_backend("ts_tpu", TpuTSBackend)
