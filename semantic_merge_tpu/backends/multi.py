"""Per-language routing: one merge, several language backends.

The reference's ``semmerge/lang/`` plugin slot implies per-file-type
dispatch, but its CLI binds a single bridge per run — a mixed
``.ts``+``.java`` repository semantically merges only one language.
Here a :class:`MultiBackend` fans the snapshot out to every routed
backend (each filters to its own extensions internally), concatenates
the per-language op logs in deterministic backend order, and composes
the combined log once — so one ``semmerge`` invocation semantically
merges every enabled language, with the text fallback covering only
genuinely un-indexed files.

Selected by the CLI when ``.semmerge.toml`` enables languages beyond
TypeScript (``[languages.java] enabled = true``); the ``[engine]
backend`` choice (host/tpu) still powers the TypeScript route.
"""
from __future__ import annotations

from typing import Dict, List

from ..core.ops import Op
from ..frontend.snapshot import Snapshot
from .base import BuildAndDiffResult, host_compose

#: ``[languages.<name>]`` config key → registered backend name.
LANGUAGE_BACKENDS: Dict[str, str] = {
    "java": "java",
    "csharp": "cs",
    "cs": "cs",
}


class MultiBackend:
    name = "multi"

    def __init__(self, backends: List) -> None:
        assert backends, "MultiBackend needs at least one backend"
        self.backends = backends
        exts: set = set()
        for b in backends:
            exts |= set(getattr(b, "extensions", ()) or ())
        self.extensions = frozenset(exts)
        # Capability union: device-batched CRDT apply stays on when any
        # routed backend provides it.
        self.device_crdt = any(getattr(b, "device_crdt", False)
                               for b in backends)

    def build_and_diff(self, base: Snapshot, left: Snapshot, right: Snapshot,
                       **kwargs) -> BuildAndDiffResult:
        results = [b.build_and_diff(base, left, right, **kwargs)
                   for b in self.backends]
        merged = BuildAndDiffResult(
            op_log_left=[], op_log_right=[],
            symbol_maps={"base": [], "left": [], "right": []})
        for r in results:
            merged.op_log_left.extend(r.op_log_left)
            merged.op_log_right.extend(r.op_log_right)
            for k in merged.symbol_maps:
                merged.symbol_maps[k].extend(r.symbol_maps.get(k, []))
            merged.diagnostics.extend(r.diagnostics)
        return merged

    def diff(self, base: Snapshot, right: Snapshot, **kwargs) -> List[Op]:
        ops: List[Op] = []
        for b in self.backends:
            ops.extend(b.diff(base, right, **kwargs))
        return ops

    def compose(self, delta_a: List[Op], delta_b: List[Op]):
        """One composition over the combined multi-language log — chain
        state and conflict detection see every op, exactly as a single
        backend would (symbol ids are signature hashes, so languages
        interleave without a namespace)."""
        for b in self.backends:
            compose = getattr(b, "compose", None)
            if compose is not None:
                return compose(delta_a, delta_b)
        return host_compose(delta_a, delta_b)

    def configure(self, config) -> None:
        for b in self.backends:
            configure = getattr(b, "configure", None)
            if configure is not None:
                configure(config)

    def close(self) -> None:
        for b in self.backends:
            b.close()


def route_backends(primary, config) -> "MultiBackend | None":
    """Build the multi-language route from config: the primary backend
    (TypeScript engine choice) plus one backend per additionally
    enabled language, in deterministic name order. ``None`` when no
    extra language is enabled (single-backend fast path)."""
    from .base import get_backend

    extra: List[str] = []
    for lang, lcfg in sorted(config.languages.items()):
        backend_name = LANGUAGE_BACKENDS.get(lang)
        if backend_name and getattr(lcfg, "enabled", False):
            if backend_name not in extra:
                extra.append(backend_name)
    if not extra:
        return None
    return MultiBackend([primary, *[get_backend(n) for n in extra]])
