from .base import Backend, BuildAndDiffResult, get_backend, register_backend

__all__ = ["Backend", "BuildAndDiffResult", "get_backend", "register_backend"]
