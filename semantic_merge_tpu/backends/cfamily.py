"""Shared backend body for the C-family language frontends.

Java and C# differ only in their :class:`LanguageSpec`; everything from
snapshot filtering through diff/lift/compose is identical and lives
here, parallel to the shared scanner in
:mod:`semantic_merge_tpu.frontend.cfamily`.
"""
from __future__ import annotations

from typing import List

from ..core.difflift import (diff_nodes, lift, lift_statements,
                             refine_signature_changes, source_maps)
from ..core.ids import EPOCH_ISO
from ..core.ops import Op
from ..frontend.cfamily import LanguageSpec, scan_snapshot_cfamily
from ..frontend.snapshot import Snapshot, filter_files
from .base import BuildAndDiffResult, host_compose, symbol_map


class CFamilyBackend:
    """Backend over the C-family scanner; subclasses set ``spec``."""

    spec: LanguageSpec

    @property
    def extensions(self) -> frozenset:
        return self.spec.extensions

    def _filter(self, snap: Snapshot):
        return filter_files(snap, self.spec.extensions)

    def build_and_diff(self, base: Snapshot, left: Snapshot, right: Snapshot,
                       *, base_rev: str = "base", seed: str = "0",
                       timestamp: str | None = None,
                       change_signature: bool = False,
                       structured_apply: bool = False,
                       signature_matcher=None,
                       statement_ops: bool = False) -> BuildAndDiffResult:
        ts = timestamp or EPOCH_ISO
        base_nodes = scan_snapshot_cfamily(self._filter(base), self.spec)
        left_nodes = scan_snapshot_cfamily(self._filter(left), self.spec)
        right_nodes = scan_snapshot_cfamily(self._filter(right), self.spec)
        diffs_l = diff_nodes(base_nodes, left_nodes)
        diffs_r = diff_nodes(base_nodes, right_nodes)
        want_sources = structured_apply or (change_signature
                                            and signature_matcher is not None)
        src_l = (source_maps(self._filter(base), self._filter(left))
                 if want_sources else None)
        src_r = (source_maps(self._filter(base), self._filter(right))
                 if want_sources else None)
        if change_signature:
            diffs_l = refine_signature_changes(diffs_l, src_l, signature_matcher)
            diffs_r = refine_signature_changes(diffs_r, src_r, signature_matcher)
        stmt_l = stmt_r = []
        if statement_ops:
            stmt_l = lift_statements(
                diffs_l, base_nodes, left_nodes, src_l,
                (self._filter(base), self._filter(left)),
                base_rev=base_rev, seed=seed, side="L", timestamp=ts)
            stmt_r = lift_statements(
                diffs_r, base_nodes, right_nodes, src_r,
                (self._filter(base), self._filter(right)),
                base_rev=base_rev, seed=seed, side="R", timestamp=ts)
        if not structured_apply:
            src_l = src_r = None
        return BuildAndDiffResult(
            op_log_left=lift(base_rev, diffs_l, seed=seed + "/L", timestamp=ts,
                             sources=src_l) + stmt_l,
            op_log_right=lift(base_rev, diffs_r, seed=seed + "/R", timestamp=ts,
                              sources=src_r) + stmt_r,
            symbol_maps={
                "base": symbol_map(base_nodes),
                "left": symbol_map(left_nodes),
                "right": symbol_map(right_nodes),
            },
        )

    def diff(self, base: Snapshot, right: Snapshot,
             *, base_rev: str = "base", seed: str = "0",
             timestamp: str | None = None,
             change_signature: bool = False,
             structured_apply: bool = False,
             signature_matcher=None,
             statement_ops: bool = False) -> List[Op]:
        ts = timestamp or EPOCH_ISO
        base_nodes = scan_snapshot_cfamily(self._filter(base), self.spec)
        right_nodes = scan_snapshot_cfamily(self._filter(right), self.spec)
        diffs = diff_nodes(base_nodes, right_nodes)
        want_sources = structured_apply or (change_signature
                                            and signature_matcher is not None)
        sources = (source_maps(self._filter(base), self._filter(right))
                   if want_sources else None)
        if change_signature:
            diffs = refine_signature_changes(diffs, sources, signature_matcher)
        stmt = []
        if statement_ops:
            stmt = lift_statements(
                diffs, base_nodes, right_nodes, sources,
                (self._filter(base), self._filter(right)),
                base_rev=base_rev, seed=seed, side="R", timestamp=ts)
        if not structured_apply:
            sources = None
        return lift(base_rev, diffs, seed=seed + "/R", timestamp=ts,
                    sources=sources) + stmt

    def compose(self, delta_a: List[Op], delta_b: List[Op]):
        return host_compose(delta_a, delta_b)

    def close(self) -> None:
        pass
