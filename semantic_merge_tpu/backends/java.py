"""Java backend stub (reference ``semmerge/lang/java/bridge.py:4-8``)."""
from __future__ import annotations

from .base import register_backend


class JavaBackend:
    name = "java"

    def build_and_diff(self, *args, **kwargs):
        raise NotImplementedError("Java backend not implemented (P1)")

    def diff(self, *args, **kwargs):
        raise NotImplementedError("Java backend not implemented (P1)")

    def close(self) -> None:
        pass


register_backend("java", JavaBackend)
