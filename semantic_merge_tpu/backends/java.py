"""Java language backend.

A stub raising ``NotImplementedError`` in the reference (reference
``semmerge/lang/java/bridge.py:4-8``; the real design is deferred to its
P1 roadmap) — implemented for real here. The Java frontend
(:mod:`semantic_merge_tpu.frontend.cfamily`) indexes declarations into
the same ``DeclNode`` records as the TypeScript frontend, so diff, lift,
composition, conflict detection, and the device kernels are shared — a
new language costs a scanner, not a pipeline.
"""
from __future__ import annotations

from .base import register_backend
from .cfamily import CFamilyBackend


class JavaBackend(CFamilyBackend):
    name = "java"

    def __init__(self) -> None:
        from ..frontend.cfamily import JAVA
        self.spec = JAVA


register_backend("java", JavaBackend)
