"""C# backend stub (reference ``semmerge/lang/cs/bridge.py:4-8``)."""
from __future__ import annotations

from .base import register_backend


class CSBackend:
    name = "cs"

    def build_and_diff(self, *args, **kwargs):
        raise NotImplementedError("C# backend not implemented (P1)")

    def diff(self, *args, **kwargs):
        raise NotImplementedError("C# backend not implemented (P1)")

    def close(self) -> None:
        pass


register_backend("cs", CSBackend)
register_backend("csharp", CSBackend)
