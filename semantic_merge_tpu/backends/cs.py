"""C# language backend.

A stub raising ``NotImplementedError`` in the reference (reference
``semmerge/lang/cs/bridge.py:4-8``) — implemented for real here on the
shared C-family frontend (:mod:`semantic_merge_tpu.frontend.cfamily`),
including C#-specific constructs: namespaces (block and file-scoped),
properties, structs, attributes, and expression-bodied members.
"""
from __future__ import annotations

from .base import register_backend
from .cfamily import CFamilyBackend


class CSharpBackend(CFamilyBackend):
    name = "cs"

    def __init__(self) -> None:
        from ..frontend.cfamily import CSHARP
        self.spec = CSHARP


register_backend("cs", CSharpBackend)
register_backend("csharp", CSharpBackend)
