"""Host (CPU) TypeScript backend — the parity oracle.

Plays the role of the reference's Node.js worker behind the bridge
(reference ``workers/ts/src/index.ts:16-44``): scan all three snapshot
trees, diff left and right against base, lift to op logs, and report the
per-revision ``symbolMaps`` of ``{symbolId, addressId}`` pairs. Pure
Python end to end; the TPU backend is tested bit-for-bit against this
implementation.
"""
from __future__ import annotations

from typing import List

from ..core.difflift import (diff_nodes, lift, lift_statements,
                             refine_signature_changes, source_maps)
from ..core.ids import EPOCH_ISO
from ..core.ops import Op
from ..frontend.scanner import scan_snapshot
from ..frontend.snapshot import TS_EXTENSIONS, Snapshot, filter_files
from .base import (BuildAndDiffResult, host_compose, register_backend,
                   symbol_map)


def ts_files(snap: Snapshot):
    """The TS/JS subset of a snapshot — the exact file set the reference
    bridge snapshots (reference ``semmerge/lang/ts/bridge.py:75``);
    snapshots may also carry other backends' languages."""
    return filter_files(snap, TS_EXTENSIONS)


class HostTSBackend:
    name = "host"
    extensions = frozenset(TS_EXTENSIONS)

    def build_and_diff(self, base: Snapshot, left: Snapshot, right: Snapshot,
                       *, base_rev: str = "base", seed: str = "0",
                       timestamp: str | None = None,
                       change_signature: bool = False,
                       structured_apply: bool = False,
                       signature_matcher=None,
                       statement_ops: bool = False) -> BuildAndDiffResult:
        ts = timestamp or EPOCH_ISO
        base_nodes = scan_snapshot(ts_files(base))
        left_nodes = scan_snapshot(ts_files(left))
        right_nodes = scan_snapshot(ts_files(right))
        diffs_l = diff_nodes(base_nodes, left_nodes)
        diffs_r = diff_nodes(base_nodes, right_nodes)
        want_sources = structured_apply or (change_signature
                                            and signature_matcher is not None)
        src_l = source_maps(ts_files(base), ts_files(left)) if want_sources else None
        src_r = source_maps(ts_files(base), ts_files(right)) if want_sources else None
        if change_signature:
            diffs_l = refine_signature_changes(diffs_l, src_l, signature_matcher)
            diffs_r = refine_signature_changes(diffs_r, src_r, signature_matcher)
        stmt_l = stmt_r = []
        if statement_ops:
            stmt_l = lift_statements(
                diffs_l, base_nodes, left_nodes, src_l,
                (ts_files(base), ts_files(left)),
                base_rev=base_rev, seed=seed, side="L", timestamp=ts)
            stmt_r = lift_statements(
                diffs_r, base_nodes, right_nodes, src_r,
                (ts_files(base), ts_files(right)),
                base_rev=base_rev, seed=seed, side="R", timestamp=ts)
        if not structured_apply:
            src_l = src_r = None
        return BuildAndDiffResult(
            op_log_left=lift(base_rev, diffs_l, seed=seed + "/L", timestamp=ts,
                             sources=src_l) + stmt_l,
            op_log_right=lift(base_rev, diffs_r, seed=seed + "/R", timestamp=ts,
                              sources=src_r) + stmt_r,
            symbol_maps={
                "base": symbol_map(base_nodes),
                "left": symbol_map(left_nodes),
                "right": symbol_map(right_nodes),
            },
        )

    def diff(self, base: Snapshot, right: Snapshot,
             *, base_rev: str = "base", seed: str = "0",
             timestamp: str | None = None,
             change_signature: bool = False,
             structured_apply: bool = False,
             signature_matcher=None,
             statement_ops: bool = False) -> List[Op]:
        ts = timestamp or EPOCH_ISO
        base_nodes = scan_snapshot(ts_files(base))
        right_nodes = scan_snapshot(ts_files(right))
        diffs = diff_nodes(base_nodes, right_nodes)
        want_sources = structured_apply or (change_signature
                                            and signature_matcher is not None)
        sources = source_maps(ts_files(base), ts_files(right)) if want_sources else None
        if change_signature:
            diffs = refine_signature_changes(diffs, sources, signature_matcher)
        stmt = []
        if statement_ops:
            stmt = lift_statements(
                diffs, base_nodes, right_nodes, sources,
                (ts_files(base), ts_files(right)),
                base_rev=base_rev, seed=seed, side="R", timestamp=ts)
        if not structured_apply:
            sources = None
        return lift(base_rev, diffs, seed=seed + "/R", timestamp=ts,
                    sources=sources) + stmt

    def compose(self, delta_a: List[Op], delta_b: List[Op]):
        return host_compose(delta_a, delta_b)

    def close(self) -> None:
        pass


register_backend("host", HostTSBackend)
register_backend("ts_host", HostTSBackend)
