"""Language-backend plugin interface.

The reference reaches its language backend through a per-language bridge
class speaking JSON-RPC to a Node child process (reference
``semmerge/lang/ts/bridge.py:21-47``; stubs for Java/C# at
``semmerge/lang/java/bridge.py`` and ``semmerge/lang/cs/bridge.py``).
Here the same seam is an in-process registry: backends implement
``build_and_diff`` / ``diff`` over snapshots and are selected by name
via ``.semmerge.toml`` ``[engine] backend`` — the configuration hook the
reference documents but never wires (reference ``semmerge/config.py``
is dead code; the BASELINE north star makes it the backend selector).

The data contract matches the reference worker protocol
(reference ``workers/ts/src/protocol.ts:15-27``):
``(base, left, right snapshots) → {opLogLeft, opLogRight, symbolMaps,
diagnostics}``.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Protocol

from ..core.ops import Op
from ..frontend.snapshot import Snapshot
from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans


@dataclass
class BuildAndDiffResult:
    op_log_left: List[Op]
    op_log_right: List[Op]
    symbol_maps: Dict[str, List[dict]]
    diagnostics: List[object] = field(default_factory=list)


class Backend(Protocol):
    name: str

    def build_and_diff(self, base: Snapshot, left: Snapshot, right: Snapshot,
                       *, base_rev: str = "base", seed: str = "0",
                       timestamp: str | None = None,
                       change_signature: bool = False,
                       structured_apply: bool = False,
                       statement_ops: bool = False) -> BuildAndDiffResult: ...

    def diff(self, base: Snapshot, right: Snapshot,
             *, base_rev: str = "base", seed: str = "0",
             timestamp: str | None = None,
             change_signature: bool = False,
             structured_apply: bool = False,
             statement_ops: bool = False) -> List[Op]: ...

    def compose(self, delta_a: List[Op], delta_b: List[Op]):
        """Compose two op logs; backends override to run composition on
        their own execution engine (default: host composer)."""
        ...

    def close(self) -> None: ...


def host_compose(delta_a: List[Op], delta_b: List[Op]):
    from ..core.compose import compose_oplogs
    return compose_oplogs(delta_a, delta_b)


def run_merge(backend: Backend, base: Snapshot, left: Snapshot,
              right: Snapshot, *, base_rev: str = "base", seed: str = "0",
              timestamp: str | None = None, change_signature: bool = False,
              structured_apply: bool = False, signature_matcher=None,
              statement_ops: bool = False):
    """Full 3-way merge through a backend: uses the backend's fused
    ``merge`` entry point when it has one (the TPU backend's
    one-round-trip program), otherwise ``build_and_diff`` + ``compose``.
    Phase wall-times flow into :mod:`semantic_merge_tpu.obs` (spans +
    the shared metrics registry) — the single timing spine both
    ``--trace`` and ``bench.py`` read.
    Returns ``(BuildAndDiffResult, composed_ops, conflicts)``."""
    name = getattr(backend, "name", "?")
    merge = getattr(backend, "merge", None)
    if merge is not None:
        result, composed, conflicts = merge(
            base, left, right, base_rev=base_rev, seed=seed,
            timestamp=timestamp, change_signature=change_signature,
            structured_apply=structured_apply,
            signature_matcher=signature_matcher,
            statement_ops=statement_ops)
    else:
        with obs_spans.span("build_and_diff", layer="backend", backend=name):
            result = backend.build_and_diff(
                base, left, right, base_rev=base_rev, seed=seed,
                timestamp=timestamp, change_signature=change_signature,
                structured_apply=structured_apply,
                signature_matcher=signature_matcher,
                statement_ops=statement_ops)
        compose = getattr(backend, "compose", None) or host_compose
        with obs_spans.span("compose", layer="backend", backend=name):
            composed, conflicts = compose(result.op_log_left,
                                          result.op_log_right)
    reg = obs_metrics.REGISTRY
    reg.counter("semmerge_merges_total",
                "Three-way merges run, by backend").inc(1, backend=name)
    reg.counter("semmerge_ops_total",
                "Ops emitted by diff, by side").inc(
        len(result.op_log_left), side="left")
    reg.counter("semmerge_ops_total").inc(len(result.op_log_right),
                                          side="right")
    conflict_list = conflicts if isinstance(conflicts, list) else list(conflicts)
    reg.counter("semmerge_conflicts_total",
                "Merge conflicts surfaced").inc(len(conflict_list))
    return result, composed, conflict_list


def symbol_map(nodes) -> List[dict]:
    """SymbolMaps payload entry (reference ``workers/ts/src/index.ts:30-35``)."""
    return [{"symbolId": n.symbolId, "addressId": n.addressId} for n in nodes]


_REGISTRY: Dict[str, Callable[[], Backend]] = {}


def register_backend(name: str, factory: Callable[[], Backend]) -> None:
    _REGISTRY[name] = factory


def get_backend(name: str) -> Backend:
    # Import side registers the built-in backends lazily so that the
    # host-only path never pays a JAX import.
    if name not in _REGISTRY:
        try:
            if name in ("host", "ts_host"):
                from . import ts_host  # noqa: F401
            elif name in ("tpu", "ts_tpu"):
                from . import ts_tpu  # noqa: F401
            elif name == "java":
                from . import java  # noqa: F401
            elif name in ("cs", "csharp"):
                from . import cs  # noqa: F401
            elif name in ("subprocess", "worker"):
                from . import subproc  # noqa: F401
        except ImportError as exc:
            raise KeyError(f"Backend {name!r} failed to load: {exc}") from exc
    if name not in _REGISTRY:
        raise KeyError(f"Unknown backend {name!r}; registered: {sorted(_REGISTRY)}")
    return _REGISTRY[name]()
