"""``.semmerge.toml`` configuration — loaded and actually wired.

The reference ships a complete TOML loader that the live CLI never calls
(reference ``semmerge/config.py`` is dead code; the worker always
receives ``config: {}``, reference ``semmerge/lang/ts/bridge.py:33``).
Here the config is the real control surface: it selects the language
backend (``tpu`` vs ``host``), fixes the deterministic seed, and carries
the device-batching knobs.

Schema (superset of the reference's documented schema at reference
``implementation.md:86-106``):

    [core]
    deterministic_seed = "auto"   # "auto" => derived from the base rev
    memory_cap_mb = 4096
    formatter = "prettier"

    [engine]                       # new: TPU execution knobs
    backend = "tpu"                # "tpu" | "host"
    parity_mode = true             # reproduce reference quirks bit-for-bit
    change_signature = false       # detect changeSignature ops (off in parity mode:
                                   # the reference emits delete+add instead)
    conflict_mode = "parity"       # "parity" (head-vs-head DivergentRename only)
                                   # | "strict" (all [CFR-002] categories)
    text_fallback = true           # [FBK-001]: 3-way text merge for files no
                                   # backend indexes (off => those stay at base)
    incremental = true             # scope scan/diff to changed files
                                   # (false => full-tree, collision-exact)
    statement_ops = false          # extract editStmtBlock body-edit ops
                                   # (implied by conflict_mode = "strict")
    structured_apply = false       # ops carry decl text/spans; applier splices
                                   # add/delete/changeSignature structurally
    host_workers = 0               # host-tail pipeline worker threads
                                   # (0 => auto: min(8, cpu_count);
                                   # SEMMERGE_HOST_WORKERS overrides)
    max_nodes_per_bucket = 2048    # padding bucket sizes, powers of two
    mesh = "auto"                  # mesh posture: "off" (single-device
                                   # programs everywhere) | "auto"
                                   # (mesh when usable, fall back on
                                   # 1-chip hosts / build failure) |
                                   # "require" (MeshFault, exit 18,
                                   # when no mesh can be used);
                                   # SEMMERGE_MESH overrides
    mesh_shape = "auto"            # or e.g. "dp=4,tp=2"

    [languages.typescript]
    enabled = true
    project_globs = ["**/tsconfig.json"]
    formatter_cmd = ["npx", "prettier", "--write"]

    [ci]
    require_typecheck = true
    require_tests = false
"""
from __future__ import annotations

import pathlib

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: same API under the old name
    import tomli as tomllib  # type: ignore[no-redef]
from dataclasses import dataclass, field
from typing import Any, Dict, List


@dataclass
class CoreConfig:
    deterministic_seed: str = "auto"
    memory_cap_mb: int = 4096
    formatter: str | None = None


@dataclass
class EngineConfig:
    backend: str = "tpu"
    parity_mode: bool = True
    change_signature: bool = False
    conflict_mode: str = "parity"
    text_fallback: bool = True
    # Scope scanning/diffing to files either side changed vs base
    # (reference architecture.md:202-204; see runtime.git.merge_scope
    # for the collision caveat that motivates the off switch).
    incremental: bool = True
    # Extract editStmtBlock ops for body-only decl edits (implied by
    # conflict_mode = "strict"; parity mode keeps the reference's op
    # vocabulary, so this is opt-in).
    statement_ops: bool = False
    structured_apply: bool = False
    # "tree" (parity: prettier runs over the whole merged tree, the
    # reference's behavior) or "touched": format only files the merge
    # actually wrote — untouched files keep their bytes (comment/format
    # preservation for the 99% of a large repo a merge never visits).
    formatter_scope: str = "tree"
    # Host-tail pipeline worker threads (chunked decode/materialize/
    # serialize of the fused merge's post-kernel tail). 0 = auto
    # (min(8, cpu_count)); the SEMMERGE_HOST_WORKERS env var overrides
    # both (see ops.fused.resolve_host_workers).
    host_workers: int = 0
    max_nodes_per_bucket: int = 2048
    # Mesh posture (shared by the one-shot engine and the batching
    # daemon's sharded dispatcher; the SEMMERGE_MESH env var — read
    # through the per-request overlay — wins over this row). See
    # parallel.mesh.MESH_POSTURES for the off|auto|require semantics.
    mesh: str = "auto"
    mesh_shape: str = "auto"
    # Model-scored changeSignature pairing for renamed+retyped decls
    # (reference design architecture.md:145-153; needs change_signature).
    signature_matcher: bool = False
    signature_threshold: float = 0.85
    matcher_ckpt_dir: str | None = None
    # Out-of-process worker command for backend = "subprocess" — any
    # program speaking the runtime.worker JSON-RPC protocol (default:
    # this package's own worker over the host engine).
    worker_cmd: List[str] | None = None


@dataclass
class LanguageConfig:
    enabled: bool = False
    project_globs: List[str] = field(default_factory=list)
    formatter_cmd: List[str] | None = None


@dataclass
class CiConfig:
    require_typecheck: bool = True
    require_tests: bool = False


@dataclass
class SloConfig:
    # Objective spec, same grammar as SEMMERGE_SLO (which overrides it):
    # e.g. "merge:p99<800ms,err<1%; diff:p99<200ms". A TOML list of
    # objective strings is also accepted and joined with ";".
    objectives: str | None = None
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0


@dataclass
class Config:
    root: pathlib.Path
    core: CoreConfig = field(default_factory=CoreConfig)
    engine: EngineConfig = field(default_factory=EngineConfig)
    languages: Dict[str, LanguageConfig] = field(default_factory=dict)
    ci: CiConfig = field(default_factory=CiConfig)
    slo: SloConfig = field(default_factory=SloConfig)


def find_config_file(start: pathlib.Path) -> pathlib.Path | None:
    """Search ``start`` and its parents for ``.semmerge.toml``
    (upward search per reference ``semmerge/config.py:98-105``)."""
    for directory in [start, *start.parents]:
        candidate = directory / ".semmerge.toml"
        if candidate.is_file():
            return candidate
    return None


def load_config(start: pathlib.Path | None = None) -> Config:
    if start is not None:
        start = pathlib.Path(start)
    else:
        # Scoped request root inside a merge service request, process
        # cwd otherwise (utils/workdir).
        from .utils import workdir
        start = workdir.root()
    cfg_path = find_config_file(start)
    config = Config(root=cfg_path.parent if cfg_path else start)
    if cfg_path is None:
        return config

    with cfg_path.open("rb") as fh:
        data = tomllib.load(fh)

    core = data.get("core", {})
    config.core = CoreConfig(
        deterministic_seed=str(core.get("deterministic_seed", config.core.deterministic_seed)),
        memory_cap_mb=int(core.get("memory_cap_mb", config.core.memory_cap_mb)),
        formatter=core.get("formatter", config.core.formatter),
    )

    engine = data.get("engine", {})
    config.engine = EngineConfig(
        backend=str(engine.get("backend", config.engine.backend)),
        parity_mode=bool(engine.get("parity_mode", config.engine.parity_mode)),
        change_signature=bool(
            engine.get("change_signature", config.engine.change_signature)),
        conflict_mode=_validated(
            str(engine.get("conflict_mode", config.engine.conflict_mode)),
            "engine.conflict_mode", ("parity", "strict")),
        text_fallback=bool(engine.get("text_fallback", config.engine.text_fallback)),
        incremental=bool(engine.get("incremental", config.engine.incremental)),
        statement_ops=bool(
            engine.get("statement_ops", config.engine.statement_ops)),
        structured_apply=bool(
            engine.get("structured_apply", config.engine.structured_apply)),
        formatter_scope=_validated(
            str(engine.get("formatter_scope", config.engine.formatter_scope)),
            "engine.formatter_scope", ("tree", "touched")),
        host_workers=int(
            engine.get("host_workers", config.engine.host_workers)),
        max_nodes_per_bucket=int(
            engine.get("max_nodes_per_bucket", config.engine.max_nodes_per_bucket)
        ),
        mesh=_validated(
            str(engine.get("mesh", config.engine.mesh)).strip().lower(),
            "engine.mesh", ("off", "auto", "require")),
        mesh_shape=str(engine.get("mesh_shape", config.engine.mesh_shape)),
        signature_matcher=bool(
            engine.get("signature_matcher", config.engine.signature_matcher)),
        signature_threshold=float(
            engine.get("signature_threshold", config.engine.signature_threshold)),
        matcher_ckpt_dir=(str(engine["matcher_ckpt_dir"])
                          if engine.get("matcher_ckpt_dir") else None),
        worker_cmd=([str(c) for c in _as_list(engine.get("worker_cmd", []))]
                    or None),
    )

    for lang, ldata in data.get("languages", {}).items():
        config.languages[lang] = LanguageConfig(
            enabled=bool(ldata.get("enabled", False)),
            project_globs=[str(g) for g in _as_list(ldata.get("project_globs", []))],
            formatter_cmd=[str(c) for c in _as_list(ldata.get("formatter_cmd", []))] or None,
        )

    ci = data.get("ci", {})
    config.ci = CiConfig(
        require_typecheck=bool(ci.get("require_typecheck", config.ci.require_typecheck)),
        require_tests=bool(ci.get("require_tests", config.ci.require_tests)),
    )

    slo = data.get("slo", {})
    objectives = slo.get("objectives")
    if isinstance(objectives, (list, tuple)):
        objectives = ";".join(str(o) for o in objectives if o)
    config.slo = SloConfig(
        objectives=str(objectives) if objectives else None,
        fast_window_s=float(slo.get("fast_window_s", config.slo.fast_window_s)),
        slow_window_s=float(slo.get("slow_window_s", config.slo.slow_window_s)),
    )
    return config


def _validated(value: str, key: str, allowed: tuple) -> str:
    if value not in allowed:
        raise ValueError(f"{key} must be one of {allowed}, got {value!r}")
    return value


def _as_list(value: Any) -> List[Any]:
    if isinstance(value, (list, tuple)):
        return [v for v in value if v is not None]
    return [value] if value else []
