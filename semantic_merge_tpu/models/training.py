"""Matcher training loop: data synthesis, checkpoints, resume.

Checkpoint/resume is a required auxiliary subsystem (SURVEY.md §5.4):
the reference's only persistence is op logs in git notes; training state
here persists via **orbax** — sharding-aware, async, multi-host-safe —
so a preempted TPU job resumes at the last saved step. The data side
synthesizes contrastive pairs the way the merge pipeline encounters
them: a declaration and its renamed/edited twin (positive), everything
else in the batch (negatives).
"""
from __future__ import annotations

import pathlib
from dataclasses import dataclass
from typing import Iterator, Optional, Tuple

import numpy as np

from ..utils.loggingx import logger
from .features import encode_batch
from .matcher import MatcherConfig, init_matcher, make_sharded_train_step


@dataclass(frozen=True)
class TrainConfig:
    matcher: MatcherConfig = MatcherConfig()
    batch: int = 32
    seq: int = 64
    steps: int = 200
    seed: int = 0
    ckpt_dir: Optional[str] = None
    ckpt_every: int = 50
    keep: int = 3


_TYPES = ("number", "string", "boolean", "void", "string[]", "number[]")
_VERBS = ("get", "set", "make", "load", "store", "filter", "map", "merge",
          "resolve", "apply", "lift", "scan", "encode", "index")
_NOUNS = ("user", "node", "decl", "file", "symbol", "op", "tree", "batch",
          "merge", "config", "token", "chunk", "shard", "mesh")


def synth_pair(rng: np.random.RandomState) -> Tuple[str, str]:
    """One (decl, edited twin) pair: same structure, renamed symbol and
    light body edits — the signal the matcher must learn to keep
    together; parameter/return types stay (changeSignature candidates
    score through the structural channel)."""
    verb, noun = rng.choice(_VERBS), rng.choice(_NOUNS)
    n_params = int(rng.randint(1, 4))
    params = ", ".join(
        f"p{k}: {rng.choice(_TYPES)}" for k in range(n_params))
    ret = rng.choice(_TYPES)
    body_const = int(rng.randint(0, 100))
    name_a = f"{verb}{noun.capitalize()}"
    name_b = f"{rng.choice(_VERBS)}{noun.capitalize()}V2"
    src = (f"export function {name_a}({params}): {ret} {{\n"
           f"  const k = {body_const};\n  return undefined as any;\n}}\n")
    edited = src.replace(name_a, name_b).replace(
        f"const k = {body_const}", f"const k = {body_const + 1}")
    return src, edited


def batches(cfg: TrainConfig) -> Iterator[dict]:
    rng = np.random.RandomState(cfg.seed)
    vocab = cfg.matcher.encoder.vocab
    while True:
        pairs = [synth_pair(rng) for _ in range(cfg.batch)]
        ta, ma = encode_batch([p[0] for p in pairs], vocab, cfg.seq)
        tb, mb = encode_batch([p[1] for p in pairs], vocab, cfg.seq)
        yield {"tokens_a": ta, "mask_a": ma, "tokens_b": tb, "mask_b": mb}


def _manager(cfg: TrainConfig):
    import orbax.checkpoint as ocp
    path = pathlib.Path(cfg.ckpt_dir).resolve()
    path.mkdir(parents=True, exist_ok=True)
    options = ocp.CheckpointManagerOptions(max_to_keep=cfg.keep,
                                           create=True)
    return ocp.CheckpointManager(path, options=options)


def train_matcher(cfg: TrainConfig, mesh=None, *, resume: bool = True):
    """Run the training loop; returns ``(params, opt_state, last_loss,
    steps_run)``. With ``ckpt_dir`` set, saves every ``ckpt_every``
    steps and resumes from the latest checkpoint when ``resume``."""
    import jax

    from ..parallel.mesh import build_mesh
    if mesh is None:
        mesh = build_mesh()

    params, opt_state = init_matcher(jax.random.PRNGKey(cfg.seed), cfg.matcher)
    start_step = 0
    manager = None
    if cfg.ckpt_dir:
        import orbax.checkpoint as ocp
        manager = _manager(cfg)
        latest = manager.latest_step()
        if resume and latest is not None:
            template = {"params": params, "opt_state": opt_state}
            restored = manager.restore(
                latest, args=ocp.args.StandardRestore(template))
            params, opt_state = restored["params"], restored["opt_state"]
            # Orbax restores onto single devices; re-lay the trees out on
            # the mesh (the jitted step pins explicit in_shardings).
            from .encoder import param_specs
            specs = param_specs(cfg.matcher.encoder)
            params = {k: jax.device_put(v, mesh.sharding(*specs[k]))
                      for k, v in params.items()}
            # The opt_state must stay UNCOMMITTED (host arrays): the
            # jitted step leaves its opt_state shardings unpinned, so
            # GSPMD chooses layouts that follow the backward pass — not
            # the param specs — and donation requires the input buffer
            # to carry the exact per-device shape of its aliased
            # output. Committing restored moments to any pre-chosen
            # sharding (replicated or param-spec) trips the resume-only
            # "Expected aliased input ... same size" XLA crash; host
            # arrays let the step lay them out exactly as the
            # uninterrupted run's first step did.
            opt_state = jax.tree.map(
                lambda leaf: np.asarray(jax.device_get(leaf)), opt_state)
            start_step = latest
            logger.info("resumed matcher training at step %d from %s",
                        start_step, cfg.ckpt_dir)

    step_fn = make_sharded_train_step(cfg.matcher, mesh)
    data = batches(cfg)
    # Fast-forward the generator so a resumed run sees the same stream
    # it would have seen uninterrupted (determinism across preemption).
    for _ in range(start_step):
        next(data)

    loss = None
    step = start_step
    for step in range(start_step + 1, cfg.steps + 1):
        params, opt_state, loss = step_fn(params, opt_state, next(data))
        if manager is not None and (step % cfg.ckpt_every == 0
                                    or step == cfg.steps):
            import orbax.checkpoint as ocp

            # Drain in-flight step collectives first: orbax's async
            # save issues its own device transfers, and on a
            # multi-device host (virtual CPU mesh) two concurrent
            # multi-participant XLA programs can deadlock each other's
            # rendezvous (observed: ring-attention permute vs save-time
            # all-gather, fatal after 40 s).
            import jax
            jax.block_until_ready((params, opt_state))
            manager.save(step, args=ocp.args.StandardSave(
                {"params": params, "opt_state": opt_state}))
    if manager is not None:
        manager.wait_until_finished()
        manager.close()
    if loss is not None:
        loss = float(loss)
    return params, opt_state, loss, step - start_step
