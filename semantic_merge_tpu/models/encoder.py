"""Declaration-token sequence encoder (pure JAX, mesh-sharded).

A compact pre-norm transformer encoder designed TPU-first:

- all matmuls in bfloat16 with float32 accumulation (MXU-shaped);
- attention is :func:`semantic_merge_tpu.parallel.ring.ring_attention`
  — sequence-parallel over the ``sp`` mesh axis, so files longer than
  one device's token budget shard block-wise instead of OOMing;
- the FFN is a soft-merged mixture of experts whose expert axis shards
  over ``ep`` (XLA inserts the psum);
- layers are stacked on a leading axis sharded over ``pp`` and driven
  by ``lax.scan`` — stage-parallel execution without Python loops;
- heads/hidden features shard over ``tp``; batch over ``dp``.

Sharding specs for every parameter live in :func:`param_specs`, so
training and inference jit with identical layouts.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import MergeMesh
from ..parallel.ring import ring_attention


@dataclass(frozen=True)
class EncoderConfig:
    vocab: int = 4096
    d_model: int = 256
    n_heads: int = 8
    d_head: int = 32
    n_layers: int = 4
    d_ff: int = 512
    n_experts: int = 4
    dtype: Any = jnp.bfloat16
    # Sequence-parallel attention strategy over the `sp` axis:
    # "ring" (K/V chunks rotate via ppermute; O(L/n) memory) or
    # "ulysses" (head/sequence all-to-all; full-L per head subset).
    attn_mode: str = "ring"
    # FFN mixture mode over the `ep`-sharded expert axis:
    # "soft"  — expert-sharded dense mixture: every expert computes,
    #           outputs blend by the gate (static, routing-free);
    # "topk"  — routed expert parallelism: GShard-style top-k routing
    #           with capacity-bounded one-hot dispatch/combine; tokens
    #           move to their experts through the einsum contractions,
    #           which XLA lowers to all-to-all over `ep`.
    moe_mode: str = "soft"
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25

    def __post_init__(self):
        if self.attn_mode not in ("ring", "ulysses"):
            raise ValueError(
                f"attn_mode must be 'ring' or 'ulysses', got {self.attn_mode!r}")
        if self.moe_mode not in ("soft", "topk"):
            raise ValueError(
                f"moe_mode must be 'soft' or 'topk', got {self.moe_mode!r}")
        if not (1 <= self.moe_top_k <= self.n_experts):
            raise ValueError("moe_top_k must be in [1, n_experts]")


def init_encoder(rng: jax.Array, cfg: EncoderConfig) -> Dict[str, jax.Array]:
    """Parameter pytree. Layer params carry a leading ``n_layers`` axis
    (the ``pp`` shard axis)."""
    k_emb, k_q, k_k, k_v, k_o, k_g, k_w1, k_w2 = jax.random.split(rng, 8)
    L, D, H, Dh, F, E = (cfg.n_layers, cfg.d_model, cfg.n_heads,
                         cfg.d_head, cfg.d_ff, cfg.n_experts)

    def dense(key, shape, fan_in):
        return jax.random.normal(key, shape, jnp.float32) * (fan_in ** -0.5)

    return {
        "embed": dense(k_emb, (cfg.vocab, D), D),
        "wq": dense(k_q, (L, D, H, Dh), D),
        "wk": dense(k_k, (L, D, H, Dh), D),
        "wv": dense(k_v, (L, D, H, Dh), D),
        "wo": dense(k_o, (L, H, Dh, D), H * Dh),
        "gate": dense(k_g, (L, D, E), D),
        "w1": dense(k_w1, (L, E, D, F), D),
        "w2": dense(k_w2, (L, E, F, D), F),
        "ln1": jnp.ones((L, D), jnp.float32),
        "ln2": jnp.ones((L, D), jnp.float32),
        "ln_out": jnp.ones((D,), jnp.float32),
    }


def param_specs(cfg: EncoderConfig) -> Dict[str, P]:
    """PartitionSpec per parameter — the single source of truth for the
    model's mesh layout."""
    return {
        "embed": P(None, "tp"),
        "wq": P("pp", None, "tp", None),
        "wk": P("pp", None, "tp", None),
        "wv": P("pp", None, "tp", None),
        "wo": P("pp", "tp", None, None),
        "gate": P("pp", None, "ep"),
        "w1": P("pp", "ep", None, "tp"),
        "w2": P("pp", "ep", "tp", None),
        "ln1": P("pp", None),
        "ln2": P("pp", None),
        "ln_out": P(None),
    }


ACT_SPEC = P("dp", "sp", None)      # activations (B, L, D)
TOK_SPEC = P("dp", "sp")            # token ids / mask (B, L)


def _rms_norm(x, scale):
    x32 = x.astype(jnp.float32)
    rms = jnp.sqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + 1e-6)
    return (x32 / rms * scale).astype(x.dtype)


def _routed_moe(h: jax.Array, gate_logits: jax.Array, w1: jax.Array,
                w2: jax.Array, cfg: EncoderConfig,
                mask: jax.Array | None = None) -> jax.Array:
    """Routed expert parallelism: top-k gating with capacity-bounded
    one-hot dispatch/combine (the GShard/Switch formulation).

    Every shape is static: each expert owns ``C = ceil(capacity_factor
    * k * tokens / E)`` slots; a token beyond its expert's capacity is
    dropped for that pick (its combine weight is zero, so it simply
    contributes no FFN delta — the residual stream carries it). The
    ``e`` axis of the dispatched activations inherits the ``ep``
    sharding of ``w1``/``w2`` through the einsum contractions, which
    XLA lowers to all-to-all dispatch/combine over the mesh.
    """
    import math
    B, L, D = h.shape
    E = gate_logits.shape[-1]
    N = B * L
    C = max(1, math.ceil(cfg.moe_capacity_factor * cfg.moe_top_k * N / E))
    hf = h.reshape(N, D)
    probs = jax.nn.softmax(gate_logits, axis=-1).reshape(N, E)
    # Padding tokens route nowhere: they must neither consume expert
    # capacity (displacing real tokens) nor contribute output.
    maskf = (mask.reshape(N).astype(jnp.float32) if mask is not None
             else jnp.ones((N,), jnp.float32))

    counts = jnp.zeros((E,), jnp.float32)
    dispatch = jnp.zeros((N, E, C), jnp.float32)
    combine = jnp.zeros((N, E, C), jnp.float32)
    remaining = probs
    for _ in range(cfg.moe_top_k):
        choice = jnp.argmax(remaining, axis=-1)                      # [N]
        prob = jnp.take_along_axis(remaining, choice[:, None], -1)[:, 0]
        onehot_e = (jax.nn.one_hot(choice, E, dtype=jnp.float32)
                    * maskf[:, None])                                # [N, E]
        # Slot index at the chosen expert: earlier tokens this pick,
        # plus slots consumed by earlier picks.
        pos = (jnp.cumsum(onehot_e, axis=0) - onehot_e
               + counts[None, :])                                    # [N, E]
        slot = jnp.sum(pos * onehot_e, axis=-1)                      # [N]
        onehot_c = jax.nn.one_hot(slot.astype(jnp.int32), C,
                                  dtype=jnp.float32)                 # [N, C]
        mask_ec = onehot_e[:, :, None] * onehot_c[:, None, :]
        dispatch = dispatch + mask_ec
        combine = combine + mask_ec * prob[:, None, None]
        counts = counts + jnp.sum(onehot_e, axis=0)
        remaining = remaining * (1.0 - onehot_e)

    d16 = dispatch.astype(cfg.dtype)
    expert_in = jnp.einsum("nec,nd->ecd", d16, hf)                   # [E, C, D]
    up = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, w1))
    down = jnp.einsum("ecf,efd->ecd", up, w2)
    out = jnp.einsum("nec,ecd->nd", combine.astype(cfg.dtype), down)
    return out.reshape(B, L, D)


def encoder_forward(params: Dict[str, jax.Array], tokens: jax.Array,
                    mask: jax.Array, cfg: EncoderConfig,
                    mesh: MergeMesh) -> jax.Array:
    """tokens (B, L) int32, mask (B, L) bool → hidden states (B, L, D)."""
    x = params["embed"].astype(cfg.dtype)[tokens]
    x = x * mask[..., None].astype(cfg.dtype)

    def layer(x, lp):
        h = _rms_norm(x, lp["ln1"])
        q = jnp.einsum("bld,dhk->blhk", h, lp["wq"].astype(cfg.dtype))
        k = jnp.einsum("bld,dhk->blhk", h, lp["wk"].astype(cfg.dtype))
        v = jnp.einsum("bld,dhk->blhk", h, lp["wv"].astype(cfg.dtype))
        if cfg.attn_mode == "ulysses":
            from ..parallel.ulysses import ulysses_attention
            attn = ulysses_attention(q, k, v, mask, mesh.mesh)
        else:
            attn = ring_attention(q, k, v, mask, mesh.mesh)
        x = x + jnp.einsum("blhk,hkd->bld", attn, lp["wo"].astype(cfg.dtype))

        h = _rms_norm(x, lp["ln2"])
        gate_logits = jnp.einsum(
            "bld,de->ble", h, lp["gate"].astype(cfg.dtype)).astype(jnp.float32)
        if cfg.moe_mode == "topk":
            x = x + _routed_moe(h, gate_logits,
                                lp["w1"].astype(cfg.dtype),
                                lp["w2"].astype(cfg.dtype), cfg, mask)
        else:
            # Expert-sharded dense mixture ("soft"): every expert
            # computes, outputs blend by the gate distribution — static
            # shapes, no data-dependent routing. The expert axis still
            # shards over `ep`; routed EP is `moe_mode="topk"`.
            gate = jax.nn.softmax(gate_logits, axis=-1).astype(cfg.dtype)
            up = jax.nn.gelu(jnp.einsum("bld,edf->blef", h, lp["w1"].astype(cfg.dtype)))
            down = jnp.einsum("blef,efd->bled", up, lp["w2"].astype(cfg.dtype))
            x = x + jnp.einsum("bled,ble->bld", down, gate)
        return x, None

    layer_params = {k: params[k] for k in
                    ("wq", "wk", "wv", "wo", "gate", "w1", "w2", "ln1", "ln2")}
    x, _ = lax.scan(lambda carry, lp: layer(carry, lp), x, layer_params)
    return _rms_norm(x, params["ln_out"])
