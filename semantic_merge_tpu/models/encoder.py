"""Declaration-token sequence encoder (pure JAX, mesh-sharded).

A compact pre-norm transformer encoder designed TPU-first:

- all matmuls in bfloat16 with float32 accumulation (MXU-shaped);
- attention is :func:`semantic_merge_tpu.parallel.ring.ring_attention`
  — sequence-parallel over the ``sp`` mesh axis, so files longer than
  one device's token budget shard block-wise instead of OOMing;
- the FFN is a soft-merged mixture of experts whose expert axis shards
  over ``ep`` (XLA inserts the psum);
- layers are stacked on a leading axis sharded over ``pp`` and driven
  by ``lax.scan`` — stage-parallel execution without Python loops;
- heads/hidden features shard over ``tp``; batch over ``dp``.

Sharding specs for every parameter live in :func:`param_specs`, so
training and inference jit with identical layouts.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import MergeMesh
from ..parallel.ring import ring_attention


@dataclass(frozen=True)
class EncoderConfig:
    vocab: int = 4096
    d_model: int = 256
    n_heads: int = 8
    d_head: int = 32
    n_layers: int = 4
    d_ff: int = 512
    n_experts: int = 4
    dtype: Any = jnp.bfloat16
    # Sequence-parallel attention strategy over the `sp` axis:
    # "ring" (K/V chunks rotate via ppermute; O(L/n) memory) or
    # "ulysses" (head/sequence all-to-all; full-L per head subset).
    attn_mode: str = "ring"

    def __post_init__(self):
        if self.attn_mode not in ("ring", "ulysses"):
            raise ValueError(
                f"attn_mode must be 'ring' or 'ulysses', got {self.attn_mode!r}")


def init_encoder(rng: jax.Array, cfg: EncoderConfig) -> Dict[str, jax.Array]:
    """Parameter pytree. Layer params carry a leading ``n_layers`` axis
    (the ``pp`` shard axis)."""
    k_emb, k_q, k_k, k_v, k_o, k_g, k_w1, k_w2 = jax.random.split(rng, 8)
    L, D, H, Dh, F, E = (cfg.n_layers, cfg.d_model, cfg.n_heads,
                         cfg.d_head, cfg.d_ff, cfg.n_experts)

    def dense(key, shape, fan_in):
        return jax.random.normal(key, shape, jnp.float32) * (fan_in ** -0.5)

    return {
        "embed": dense(k_emb, (cfg.vocab, D), D),
        "wq": dense(k_q, (L, D, H, Dh), D),
        "wk": dense(k_k, (L, D, H, Dh), D),
        "wv": dense(k_v, (L, D, H, Dh), D),
        "wo": dense(k_o, (L, H, Dh, D), H * Dh),
        "gate": dense(k_g, (L, D, E), D),
        "w1": dense(k_w1, (L, E, D, F), D),
        "w2": dense(k_w2, (L, E, F, D), F),
        "ln1": jnp.ones((L, D), jnp.float32),
        "ln2": jnp.ones((L, D), jnp.float32),
        "ln_out": jnp.ones((D,), jnp.float32),
    }


def param_specs(cfg: EncoderConfig) -> Dict[str, P]:
    """PartitionSpec per parameter — the single source of truth for the
    model's mesh layout."""
    return {
        "embed": P(None, "tp"),
        "wq": P("pp", None, "tp", None),
        "wk": P("pp", None, "tp", None),
        "wv": P("pp", None, "tp", None),
        "wo": P("pp", "tp", None, None),
        "gate": P("pp", None, "ep"),
        "w1": P("pp", "ep", None, "tp"),
        "w2": P("pp", "ep", "tp", None),
        "ln1": P("pp", None),
        "ln2": P("pp", None),
        "ln_out": P(None),
    }


ACT_SPEC = P("dp", "sp", None)      # activations (B, L, D)
TOK_SPEC = P("dp", "sp")            # token ids / mask (B, L)


def _rms_norm(x, scale):
    x32 = x.astype(jnp.float32)
    rms = jnp.sqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + 1e-6)
    return (x32 / rms * scale).astype(x.dtype)


def encoder_forward(params: Dict[str, jax.Array], tokens: jax.Array,
                    mask: jax.Array, cfg: EncoderConfig,
                    mesh: MergeMesh) -> jax.Array:
    """tokens (B, L) int32, mask (B, L) bool → hidden states (B, L, D)."""
    x = params["embed"].astype(cfg.dtype)[tokens]
    x = x * mask[..., None].astype(cfg.dtype)

    def layer(x, lp):
        h = _rms_norm(x, lp["ln1"])
        q = jnp.einsum("bld,dhk->blhk", h, lp["wq"].astype(cfg.dtype))
        k = jnp.einsum("bld,dhk->blhk", h, lp["wk"].astype(cfg.dtype))
        v = jnp.einsum("bld,dhk->blhk", h, lp["wv"].astype(cfg.dtype))
        if cfg.attn_mode == "ulysses":
            from ..parallel.ulysses import ulysses_attention
            attn = ulysses_attention(q, k, v, mask, mesh.mesh)
        else:
            attn = ring_attention(q, k, v, mask, mesh.mesh)
        x = x + jnp.einsum("blhk,hkd->bld", attn, lp["wo"].astype(cfg.dtype))

        h = _rms_norm(x, lp["ln2"])
        # Soft-merged MoE: every expert computes, outputs blend by the
        # gate distribution. Dense on purpose — static shapes, no
        # data-dependent routing, expert axis shards over `ep`.
        gate = jax.nn.softmax(
            jnp.einsum("bld,de->ble", h, lp["gate"].astype(cfg.dtype))
            .astype(jnp.float32), axis=-1).astype(cfg.dtype)
        up = jax.nn.gelu(jnp.einsum("bld,edf->blef", h, lp["w1"].astype(cfg.dtype)))
        down = jnp.einsum("blef,efd->bled", up, lp["w2"].astype(cfg.dtype))
        x = x + jnp.einsum("bled,ble->bld", down, gate)
        return x, None

    layer_params = {k: params[k] for k in
                    ("wq", "wk", "wv", "wo", "gate", "w1", "w2", "ln1", "ln2")}
    x, _ = lax.scan(lambda carry, lp: layer(carry, lp), x, layer_params)
    return _rms_norm(x, params["ln_out"])
