"""Similarity matcher: embeddings, contrastive training, pair scoring.

The model half of changeSignature detection (reference design
``architecture.md:145-153``; the live differ reports a changed
signature as delete+add — SURVEY.md §3.4). Declarations embed via the
encoder; matched pairs (rename/edit survivors) train with a symmetric
InfoNCE loss so that edited-but-same declarations land close and
unrelated ones far. Inference scores candidate (deleted, added) pairs
by cosine similarity; the differ accepts matches above a threshold.

Everything jits against the shardings in
:func:`semantic_merge_tpu.models.encoder.param_specs` — the same code
runs single-chip or across a dp/pp/sp/tp/ep mesh.
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax

from ..parallel.mesh import MergeMesh
from .encoder import (ACT_SPEC, TOK_SPEC, EncoderConfig, encoder_forward,
                      init_encoder, param_specs)


@dataclass(frozen=True)
class MatcherConfig:
    encoder: EncoderConfig = EncoderConfig()
    temperature: float = 0.07
    learning_rate: float = 3e-4
    weight_decay: float = 0.01


def init_matcher(rng: jax.Array, cfg: MatcherConfig):
    params = init_encoder(rng, cfg.encoder)
    tx = optimizer(cfg)
    return params, tx.init(params)


def optimizer(cfg: MatcherConfig) -> optax.GradientTransformation:
    return optax.adamw(cfg.learning_rate, weight_decay=cfg.weight_decay)


def embed(params, tokens, mask, cfg: EncoderConfig, mesh: MergeMesh) -> jax.Array:
    """(B, L) tokens → (B, D) L2-normalized embeddings (masked mean pool)."""
    h = encoder_forward(params, tokens, mask, cfg, mesh).astype(jnp.float32)
    denom = jnp.maximum(mask.sum(axis=-1, keepdims=True), 1).astype(jnp.float32)
    pooled = (h * mask[..., None]).sum(axis=1) / denom
    return pooled / jnp.maximum(jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-6)


def info_nce(za: jax.Array, zb: jax.Array, temperature: float) -> jax.Array:
    """Symmetric InfoNCE: row i of ``za`` matches row i of ``zb``."""
    logits = za @ zb.T / temperature
    labels = jnp.arange(za.shape[0])
    loss_ab = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    loss_ba = optax.softmax_cross_entropy_with_integer_labels(logits.T, labels)
    return (loss_ab.mean() + loss_ba.mean()) / 2


def loss_fn(params, batch, cfg: MatcherConfig, mesh: MergeMesh) -> jax.Array:
    za = embed(params, batch["tokens_a"], batch["mask_a"], cfg.encoder, mesh)
    zb = embed(params, batch["tokens_b"], batch["mask_b"], cfg.encoder, mesh)
    return info_nce(za, zb, cfg.temperature)


def train_step(params, opt_state, batch, cfg: MatcherConfig, mesh: MergeMesh):
    """One full training step: forward, backward, AdamW update."""
    loss, grads = jax.value_and_grad(loss_fn)(params, batch, cfg, mesh)
    updates, opt_state = optimizer(cfg).update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss


def make_sharded_train_step(cfg: MatcherConfig, mesh: MergeMesh):
    """Jit ``train_step`` with the canonical mesh shardings."""
    specs = param_specs(cfg.encoder)
    p_shard = {k: mesh.sharding(*spec) for k, spec in specs.items()}
    batch_shard = {
        "tokens_a": mesh.sharding(*TOK_SPEC), "mask_a": mesh.sharding(*TOK_SPEC),
        "tokens_b": mesh.sharding(*TOK_SPEC), "mask_b": mesh.sharding(*TOK_SPEC),
    }
    step = partial(train_step, cfg=cfg, mesh=mesh)
    return jax.jit(
        step,
        in_shardings=(p_shard, None, batch_shard),
        out_shardings=(p_shard, None, None),
        donate_argnums=(0, 1),
    )


def make_scorer(cfg: MatcherConfig, mesh: MergeMesh):
    """Jitted cosine-similarity scorer for candidate decl pairs."""
    specs = param_specs(cfg.encoder)
    p_shard = {k: mesh.sharding(*spec) for k, spec in specs.items()}
    tok = mesh.sharding(*TOK_SPEC)

    @partial(jax.jit, in_shardings=(p_shard, tok, tok, tok, tok),
             out_shardings=None)
    def score(params, tokens_a, mask_a, tokens_b, mask_b):
        za = embed(params, tokens_a, mask_a, cfg.encoder, mesh)
        zb = embed(params, tokens_b, mask_b, cfg.encoder, mesh)
        return jnp.sum(za * zb, axis=-1)

    return score
