"""Decl source → model token ids.

Reuses the frontend tokenizer (the same one the scanner indexes with,
:mod:`semantic_merge_tpu.frontend.tokenizer`) so model features see
exactly the token stream the differ saw. Identifiers and literals hash
into a fixed vocabulary (stable across runs — plain fnv1a, no Python
``hash`` randomization); punctuation and keywords get reserved ids so
structural tokens never collide with names.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from ..frontend.tokenizer import tokenize

PAD = 0
_RESERVED = 2  # PAD + UNK

_FNV_OFFSET = np.uint64(14695981039346656037)
_FNV_PRIME = np.uint64(1099511628211)


def _fnv1a(text: str) -> int:
    h = _FNV_OFFSET
    for byte in text.encode("utf-8"):
        h = np.uint64((int(h) ^ byte) * int(_FNV_PRIME) & 0xFFFFFFFFFFFFFFFF)
    return int(h)


def encode_source(content: str, vocab: int, max_len: int) -> Tuple[np.ndarray, np.ndarray]:
    """One decl's source text → (ids (max_len,), mask (max_len,))."""
    ids = np.zeros((max_len,), np.int32)
    mask = np.zeros((max_len,), bool)
    toks = tokenize(content)
    for i, tok in enumerate(toks[:max_len]):
        ids[i] = _RESERVED + _fnv1a(f"{tok.type}:{tok.text}") % (vocab - _RESERVED)
        mask[i] = True
    return ids, mask


def encode_batch(sources: Sequence[str], vocab: int, max_len: int) -> Tuple[np.ndarray, np.ndarray]:
    """Batch of decl sources → (B, max_len) ids + mask arrays."""
    ids = np.zeros((len(sources), max_len), np.int32)
    mask = np.zeros((len(sources), max_len), bool)
    for i, src in enumerate(sources):
        ids[i], mask[i] = encode_source(src, vocab, max_len)
    return ids, mask
