"""Embedding-scored changeSignature pairing — the matcher in the product.

The reference *designs* model-assisted signature matching
(reference ``architecture.md:145-153``) but its live differ reports a
changed signature as delete+add. The exact-key refinement pass
(:func:`semantic_merge_tpu.core.difflift.refine_signature_changes`)
recovers pairs that kept their ``(file, name, kind)``; this module
recovers the rest — declarations that were renamed *and* retyped — by
scoring residual (deleted, added) candidates with the contrastive
matcher's embeddings (:mod:`semantic_merge_tpu.models.matcher`) and
accepting cosine matches above a configured threshold.

Deterministic by construction: parameters come from the latest orbax
checkpoint when one exists (``semmerge train-matcher``) or from the
seeded initializer, candidate order is stream order, and ties break by
``(score desc, delete idx, add idx)`` — so every backend produces
identical op logs, which the parity gate requires. Opt-in via
``[engine] signature_matcher`` (off in parity mode: the reference
emits delete+add).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..utils.loggingx import logger


class EmbeddingSignatureMatcher:
    """Scores residual delete/add decl pairs by embedding similarity.

    Lazy: jax, the encoder parameters, and the jitted embed function
    are materialized on first use, so constructing the matcher (e.g.
    from CLI config) costs nothing if no residual candidates appear.
    """

    def __init__(self, threshold: float = 0.85, ckpt_dir: str | None = None,
                 seed: int = 0, seq_len: int = 64,
                 max_candidates: int = 512,
                 allow_untrained: bool = False,
                 cfg=None) -> None:
        self.threshold = threshold
        #: Optional MatcherConfig override (default: the product
        #: config) — must match the checkpoint's shapes.
        self._cfg_override = cfg
        self.ckpt_dir = ckpt_dir
        self.seed = seed
        self.seq_len = seq_len
        self.max_candidates = max_candidates
        #: Whether parameters came from a trained checkpoint. Scoring
        #: with seeded-random parameters produces deterministic but
        #: semantically arbitrary pairings, so the product path refuses
        #: it (pair() falls back to exact-key-only) unless
        #: ``allow_untrained`` opts in (tests, evaluation harnesses).
        self.trained = False
        self.allow_untrained = allow_untrained
        self._embed = None
        self._params = None
        self._cfg = None

    def _ensure(self) -> bool:
        if self._embed is not None:
            return True
        try:
            import jax

            from ..parallel.mesh import build_mesh
            from .matcher import MatcherConfig, init_matcher
            from .matcher import embed as embed_fn
        except Exception as exc:  # degraded mode: exact-key pairs only
            logger.warning("signature matcher unavailable (%s); "
                           "falling back to exact-key pairing", exc)
            return False
        cfg = self._cfg_override or MatcherConfig()
        mesh = build_mesh()
        params = None
        if self.ckpt_dir:
            try:
                from .training import TrainConfig, _manager
                tcfg = TrainConfig(matcher=cfg, ckpt_dir=self.ckpt_dir)
                manager = _manager(tcfg)
                latest = manager.latest_step()
                if latest is not None:
                    import orbax.checkpoint as ocp
                    p0, o0 = init_matcher(jax.random.PRNGKey(self.seed), cfg)
                    restored = manager.restore(
                        latest, args=ocp.args.StandardRestore(
                            {"params": p0, "opt_state": o0}))
                    params = restored["params"]
                    self.trained = True
            except Exception as exc:
                logger.warning("matcher checkpoint restore failed (%s); "
                               "using seeded init", exc)
        if params is None:
            params, _ = init_matcher(jax.random.PRNGKey(self.seed), cfg)
        # Params must live replicated on the mesh the embed's shard_map
        # runs over — a checkpoint restore (and some init paths) leaves
        # them committed to device 0, which jit rejects.
        params = jax.tree.map(
            lambda leaf: jax.device_put(leaf, mesh.replicated()), params)

        import functools

        @functools.partial(jax.jit)
        def _embed_batch(p, tokens, mask):
            return embed_fn(p, tokens, mask, cfg.encoder, mesh)

        self._params = params
        self._cfg = cfg
        self._embed = _embed_batch
        return True

    def _embed_texts(self, texts: Sequence[str]) -> Optional[np.ndarray]:
        from .features import encode_batch
        from ..core.encode import bucket_size
        vocab = self._cfg.encoder.vocab
        ids, mask = encode_batch(list(texts), vocab, self.seq_len)
        pad = bucket_size(max(len(texts), 1))  # stable compile shapes
        ids = np.pad(ids, ((0, pad - len(texts)), (0, 0)))
        mask = np.pad(mask, ((0, pad - len(texts)), (0, 0)))
        z = np.asarray(self._embed(self._params, ids, mask))
        return z[:len(texts)]

    def pair(self, deletes: List[Tuple[object, str]],
             adds: List[Tuple[object, str]]) -> List[Tuple[int, int]]:
        """``deletes``/``adds`` are ``(routing_key, source_text)`` in
        stream order — the routing key is any equatable value (the
        differ passes ``(kind, file)``); only candidates with equal
        keys may pair. Returns matched ``(delete_idx, add_idx)`` pairs
        with cosine similarity above the threshold, each side consumed
        at most once, ties broken by score then stream position."""
        if not deletes or not adds:
            return []
        if (len(deletes) > self.max_candidates
                or len(adds) > self.max_candidates):
            logger.warning("signature matcher: %d/%d residual candidates "
                           "exceed cap %d; skipping model pairing",
                           len(deletes), len(adds), self.max_candidates)
            return []
        if not self._ensure():
            return []
        if not self.trained and not self.allow_untrained:
            logger.warning(
                "signature matcher has NO trained checkpoint (ckpt_dir=%r): "
                "refusing to score with seeded-random parameters; only "
                "exact-key pairs will be used. Train one with "
                "'semmerge train-matcher --ckpt-dir DIR' and set "
                "[engine] matcher_ckpt_dir.", self.ckpt_dir)
            return []
        zd = self._embed_texts([t for _, t in deletes])
        za = self._embed_texts([t for _, t in adds])
        scores = zd @ za.T  # cosine: embeddings are L2-normalized
        candidates = []
        for i, (dk, _) in enumerate(deletes):
            for j, (ak, _) in enumerate(adds):
                if dk == ak and scores[i, j] >= self.threshold:
                    candidates.append((-float(scores[i, j]), i, j))
        candidates.sort()
        used_d: set = set()
        used_a: set = set()
        out: List[Tuple[int, int]] = []
        for _, i, j in candidates:
            if i in used_d or j in used_a:
                continue
            used_d.add(i)
            used_a.add(j)
            out.append((i, j))
        out.sort()
        return out
