"""Neural models for similarity matching.

The reference's P1 design calls for a *similarity matcher* that pairs
declarations across revisions when exact structural signatures diverge
(reference ``architecture.md:145-153``: "similarity matching on
normalized bodies"; the live differ's TODO at
``implementation.md:902`` — ``changeSig`` is never emitted because
there is no matcher). This package is the TPU-native answer: a
sequence encoder over declaration token streams producing embeddings
whose cosine similarity drives rename/changeSignature matching at
repo scale, trained and served across a device mesh (DP/TP/PP/SP/EP —
see :mod:`semantic_merge_tpu.parallel.mesh`).
"""
from .encoder import EncoderConfig, init_encoder, encoder_forward  # noqa: F401
from .matcher import (MatcherConfig, init_matcher, make_scorer,  # noqa: F401
                      make_sharded_train_step, train_step)
