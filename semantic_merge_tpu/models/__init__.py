"""Neural models for similarity matching.

A structural symbolId changes whenever a declaration's signature does,
so exact-key joins cannot pair a decl across revisions once it has
been renamed *and* retyped — those edits surface as unrelated
delete+add pairs. This package supplies the similarity matcher that
closes the gap: a sequence encoder over declaration token streams
producing embeddings whose cosine similarity drives
rename/changeSignature pairing at repo scale, trained and served
across a device mesh (DP/TP/PP/SP/EP — see
:mod:`semantic_merge_tpu.parallel.mesh`). The exact-key half of the
pairing lives in :func:`semantic_merge_tpu.core.difflift.refine_signature_changes`;
the matcher scores only its residuals.
"""
from .encoder import EncoderConfig, init_encoder, encoder_forward  # noqa: F401
from .matcher import (MatcherConfig, init_matcher, make_scorer,  # noqa: F401
                      make_sharded_train_step, train_step)
