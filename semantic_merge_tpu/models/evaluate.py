"""Held-out matcher evaluation — pairing precision/recall.

The signature matcher's product job is pairing a deleted declaration
with its renamed+retyped twin among distractors
(:mod:`semantic_merge_tpu.models.signature`). This harness measures
exactly that, on a held-out synthetic set drawn from the same
generator the training loop uses (``models.training.synth_pair``)
with a disjoint seed: ``n`` true (delete, add) pairs are shuffled into
one candidate pool and the matcher's predicted pairing is scored
against the known correspondence.

Reported per run: predicted-pair count, precision (correct predicted /
predicted), recall (correct predicted / n), at the matcher's
configured threshold. ``semmerge train-matcher --eval`` prints this
after training; ``tests/test_signature_matcher.py`` pins the
qualitative contract (trained beats untrained; untrained refuses by
default).
"""
from __future__ import annotations

from typing import Dict

import numpy as np


def evaluate_matcher(matcher, n: int = 48, seed: int = 991) -> Dict:
    """Score ``matcher`` on ``n`` held-out pairs; returns the metrics
    dict. The matcher must be willing to score (trained, or
    ``allow_untrained=True``) — a refusal scores as zero recall, which
    is itself the honest number for the product's degraded mode."""
    from .training import synth_pair

    rng = np.random.RandomState(seed)
    pairs = [synth_pair(rng) for _ in range(n)]
    perm = rng.permutation(n)
    # One shared routing key: every candidate is admissible, the
    # embedding alone must discriminate.
    deletes = [(("function", "eval.ts"), src) for src, _ in pairs]
    adds = [(("function", "eval.ts"), pairs[j][1]) for j in perm]
    truth = {(int(j), k) for k, j in enumerate(perm)}
    got = matcher.pair(deletes, adds)
    correct = sum(1 for p in got if (int(p[0]), int(p[1])) in truth)
    return {
        "n": n,
        "predicted": len(got),
        "correct": correct,
        "precision": round(correct / len(got), 3) if got else 0.0,
        "recall": round(correct / n, 3),
        "threshold": matcher.threshold,
        "trained": bool(getattr(matcher, "trained", False)),
    }
