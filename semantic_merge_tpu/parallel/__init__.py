"""Device-mesh parallelism: mesh construction, shardings, and the
sequence-parallel ring-attention collective.

The reference has no parallelism at all (SURVEY.md §2.3; its only
concurrency control is a merge-driver lock file, reference
``scripts/semmerge-driver.py:32-44``). This package is where the TPU
framework gets its first-class scale-out: every strategy in the
DP/TP/PP/SP/EP map of SURVEY.md §2.3 has a concrete implementation
here or in :mod:`semantic_merge_tpu.models`.
"""
from .mesh import MergeMesh, build_mesh  # noqa: F401
