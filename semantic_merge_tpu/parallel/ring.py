"""Ring attention: sequence-parallel attention over the ``sp`` mesh axis.

Long-context support for the encoder (SURVEY.md §5.7: the framework's
sequence dimensions must scale past a single device). Keys/values live
sharded along the sequence; instead of all-gathering them (O(L) memory
per device), each device computes flash-style blockwise attention
against its resident K/V chunk while the chunks rotate around the ring
via ``lax.ppermute`` — ICI traffic overlaps with compute, per-device
memory stays O(L/n). Online-softmax running max/sum accumulators make
the result exactly equal (up to float assoc.) to full attention.

Non-causal (the matcher encoder is bidirectional), with a key padding
mask that travels the ring alongside its K/V chunk.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _ring_attention_local(q, k, v, kmask, *, axis_name: str):
    """Per-shard body under shard_map.

    q, k, v: (B, Lq_local, H, Dh) / (B, Lk_local, H, Dh); kmask:
    (B, Lk_local) True on real tokens. Accumulates attention of the
    local queries over every K/V chunk in the ring.
    """
    axis_size = lax.psum(1, axis_name)
    scale = q.shape[-1] ** -0.5
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def step(carry, _):
        o, m, l, k_cur, v_cur, mask_cur = carry
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_cur,
                       preferred_element_type=jnp.float32) * scale
        s = jnp.where(mask_cur[:, None, None, :], s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        correction = jnp.exp(m - m_new)
        l_new = l * correction + p.sum(axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_cur,
                        preferred_element_type=jnp.float32)
        o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        mask_next = lax.ppermute(mask_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_next, v_next, mask_next), None

    b, lq, h, dh = q.shape
    init = (
        jnp.zeros((b, lq, h, dh), jnp.float32),
        jnp.full((b, h, lq), NEG_INF, jnp.float32),
        jnp.zeros((b, h, lq), jnp.float32),
        k, v, kmask,
    )
    (o, m, l, *_), _ = lax.scan(step, init, None, length=axis_size)
    l = l.transpose(0, 2, 1)[..., None]  # (B, Lq, H, 1)
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention(q, k, v, kmask, mesh: Mesh, *, axis_name: str = "sp"):
    """Sequence-parallel attention over ``axis_name`` of ``mesh``.

    Inputs are global arrays (B, L, H, Dh) with the L axis sharded over
    ``axis_name``; heads may be sharded over ``tp``; batch over ``dp``.
    """
    qkv_spec = P("dp", axis_name, "tp", None)
    mask_spec = P("dp", axis_name)
    return jax.shard_map(
        partial(_ring_attention_local, axis_name=axis_name),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )(q, k, v, kmask)
