"""Ring attention: sequence-parallel attention over the ``sp`` mesh axis.

Long-context support for the encoder (SURVEY.md §5.7: the framework's
sequence dimensions must scale past a single device). Keys/values live
sharded along the sequence; instead of all-gathering them (O(L) memory
per device), each device computes flash-style blockwise attention
against its resident K/V chunk while the chunks rotate around the ring
via ``lax.ppermute`` — ICI traffic overlaps with compute, per-device
memory stays O(L/n). Online-softmax running max/sum accumulators make
the result exactly equal (up to float assoc.) to full attention.

Non-causal (the matcher encoder is bidirectional), with a key padding
mask that travels the ring alongside its K/V chunk.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _chunk_stats_einsum(q, k_cur, v_cur, mask_cur, scale):
    """Partial softmax stats of q over one K/V chunk — XLA einsum path.

    Returns ``(pv, m_c, l_c)``: unnormalised weighted values
    (B, Lq, H, Dh) f32 and running max/sum (B, H, Lq) relative to
    ``m_c`` — the same contract as the Pallas kernel
    (:func:`semantic_merge_tpu.parallel.flash.flash_chunk_attention`).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_cur,
                   preferred_element_type=jnp.float32) * scale
    s = jnp.where(mask_cur[:, None, None, :], s, NEG_INF)
    m_c = s.max(axis=-1)
    p = jnp.exp(s - m_c[..., None])
    l_c = p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_cur,
                    preferred_element_type=jnp.float32)
    return pv, m_c, l_c


def _ring_attention_local(q, k, v, kmask, *, axis_name: str,
                          pallas: str | None = None):
    """Per-shard body under shard_map.

    q, k, v: (B, Lq_local, H, Dh) / (B, Lk_local, H, Dh); kmask:
    (B, Lk_local) True on real tokens. Accumulates attention of the
    local queries over every K/V chunk in the ring. The per-chunk
    QKᵀ/softmax/PV block runs as a fused Pallas kernel on TPU
    (``pallas="compiled"``; ``"interpret"`` for CPU testing) or as the
    einsum path otherwise.
    """
    axis_size = lax.psum(1, axis_name)
    scale = q.shape[-1] ** -0.5
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def step(carry, _):
        o, m, l, k_cur, v_cur, mask_cur = carry
        if pallas is not None:
            from .flash import flash_chunk_attention
            pv, m_c, l_c = flash_chunk_attention(
                q, k_cur, v_cur, mask_cur, interpret=(pallas == "interpret"))
        else:
            pv, m_c, l_c = _chunk_stats_einsum(q, k_cur, v_cur, mask_cur, scale)
        m_new = jnp.maximum(m, m_c)
        corr = jnp.exp(m - m_new)
        corr_c = jnp.exp(m_c - m_new)
        l_new = l * corr + l_c * corr_c
        o_new = (o * corr.transpose(0, 2, 1)[..., None]
                 + pv * corr_c.transpose(0, 2, 1)[..., None])
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        mask_next = lax.ppermute(mask_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_next, v_next, mask_next), None

    b, lq, h, dh = q.shape
    init = (
        jnp.zeros((b, lq, h, dh), jnp.float32),
        jnp.full((b, h, lq), NEG_INF, jnp.float32),
        jnp.zeros((b, h, lq), jnp.float32),
        k, v, kmask,
    )
    (o, m, l, *_), _ = lax.scan(step, init, None, length=axis_size)
    l = l.transpose(0, 2, 1)[..., None]  # (B, Lq, H, 1)
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention(q, k, v, kmask, mesh: Mesh, *, axis_name: str = "sp",
                   pallas: str | None = "auto"):
    """Sequence-parallel attention over ``axis_name`` of ``mesh``.

    Inputs are global arrays (B, L, H, Dh) with the L axis sharded over
    ``axis_name``; heads may be sharded over ``tp``; batch over ``dp``.
    ``pallas``: ``"auto"`` (kernel on TPU, einsum elsewhere),
    ``"compiled"`` / ``"interpret"`` to force the Pallas chunk kernel,
    ``None`` for the einsum path.
    """
    if pallas == "auto":
        from .flash import pallas_mode
        pallas = pallas_mode()
    qkv_spec = P("dp", axis_name, "tp", None)
    mask_spec = P("dp", axis_name)
    from ..obs import spans as obs_spans
    # Span covers the dispatch (JAX execution is async — the collective
    # itself overlaps whatever the host does next); per-step ring cost
    # shows up in the profiler timeline, not here.
    with obs_spans.span("ring_attention", layer="parallel", axis=axis_name,
                        seq=int(q.shape[1]), pallas=str(pallas)):
        from ..utils.jaxenv import shard_map_compat
        return shard_map_compat(
            partial(_ring_attention_local, axis_name=axis_name, pallas=pallas),
            mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
            out_specs=qkv_spec,
            check_vma=False,
        )(q, k, v, kmask)
