"""Pallas flash-attention kernel for the per-chunk attention step.

The MXU hot op of the encoder (SURVEY.md §7: "pallas kernels for the
hot ops"). Ring attention (:mod:`semantic_merge_tpu.parallel.ring`)
rotates K/V chunks around the ``sp`` ring; for each resident chunk every
device computes blockwise attention of its local queries over that
chunk. This module runs that chunk computation as a fused Pallas TPU
kernel — QKᵀ, masking, online softmax and PV accumulation never leave
VMEM — instead of materialising the (B, H, Lq, Lk) score tensor in HBM
the way the reference-shaped einsum path does.

The kernel returns *partial* softmax statistics ``(pv, m, l)`` — the
unnormalised weighted values, the running row max and the running row
sum — so the caller can merge chunks across ring steps with the
standard online-softmax combination. This is exactly the quantity the
einsum path in ``ring.py`` carries, so the two paths are
interchangeable (and parity-tested in interpret mode on CPU).

Grid layout: ``(B, H, Lq/block_q, Lk/block_k)`` with the k axis
innermost ("arbitrary" semantics — sequential accumulation into VMEM
scratch); float32 accumulation, bfloat16-friendly inputs; the key
padding mask rides a ``(B, Lk)`` block spec broadcast over heads.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: jax-version compat: the TPU compiler-params dataclass is
#: ``CompilerParams`` on newer jax, ``TPUCompilerParams`` before.
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")

NEG_INF = -1e30

# Lane width of the VPU; scratch row-stat tiles replicate across it.
_LANES = 128


def _chunk_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, m_ref, l_ref,
                  acc_scr, m_scr, l_scr, *, scale: float, n_k_blocks: int):
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    q = q_ref[0, 0].astype(jnp.float32)            # (bq, dh)
    k = k_ref[0, 0].astype(jnp.float32)            # (bk, dh)
    v = v_ref[0, 0].astype(jnp.float32)            # (bk, dh)
    mask = mask_ref[0, 0] != 0                     # (bk,)

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    s = jnp.where(mask[None, :], s, NEG_INF)       # (bq, bk)

    m_prev = m_scr[:, 0]                           # (bq,)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
    p = jnp.exp(s - m_new[:, None])
    correction = jnp.exp(m_prev - m_new)
    l_new = l_scr[:, 0] * correction + jnp.sum(p, axis=-1)
    pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    acc_scr[:] = acc_scr[:] * correction[:, None] + pv
    m_scr[:] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    @pl.when(ki == n_k_blocks - 1)
    def _emit():
        o_ref[0, 0] = acc_scr[:]
        m_ref[0, 0] = m_scr[:]
        l_ref[0, 0] = l_scr[:]


def _round_up(n: int, mult: int) -> int:
    return ((n + mult - 1) // mult) * mult


@functools.partial(jax.jit, static_argnames=("block_q", "block_k", "interpret"))
def flash_chunk_attention(q, k, v, kmask, *, block_q: int = 128,
                          block_k: int = 128, interpret: bool = False):
    """Partial-softmax attention of ``q`` over one resident K/V chunk.

    q: (B, Lq, H, Dh); k, v: (B, Lk, H, Dh); kmask: (B, Lk) bool.
    Returns ``(pv, m, l)`` with pv (B, Lq, H, Dh) float32 unnormalised,
    m/l (B, H, Lq) float32 — the same partial statistics as one ring
    step of the einsum path in :mod:`semantic_merge_tpu.parallel.ring`.
    """
    b, lq, h, dh = q.shape
    lk = k.shape[1]
    scale = dh ** -0.5

    block_q = min(block_q, _round_up(lq, 8))
    block_k = min(block_k, _round_up(lk, 8))
    lq_p = _round_up(lq, block_q)
    lk_p = _round_up(lk, block_k)

    # (B, H, L, Dh) layout: heads become a grid axis, rows are the
    # sublane axis of each tile.
    qt = jnp.pad(q.transpose(0, 2, 1, 3), ((0, 0), (0, 0), (0, lq_p - lq), (0, 0)))
    kt = jnp.pad(k.transpose(0, 2, 1, 3), ((0, 0), (0, 0), (0, lk_p - lk), (0, 0)))
    vt = jnp.pad(v.transpose(0, 2, 1, 3), ((0, 0), (0, 0), (0, lk_p - lk), (0, 0)))
    # (B, 1, Lk) int32 — a singleton sublane axis satisfies the Mosaic
    # block-shape rule (block dim == array dim) for the mask operand.
    maskp = jnp.pad(kmask, ((0, 0), (0, lk_p - lk)))[:, None, :].astype(jnp.int32)

    n_q = lq_p // block_q
    n_k = lk_p // block_k
    grid = (b, h, n_q, n_k)

    out = pl.pallas_call(
        functools.partial(_chunk_kernel, scale=scale, n_k_blocks=n_k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, dh), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, dh), lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, dh), lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k), lambda bi, hi, qi, ki: (bi, 0, ki)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, dh), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            # Row stats come back lane-replicated (bq, 128) tiles — the
            # lane axis cannot be narrower than a tile on TPU.
            pl.BlockSpec((1, 1, block_q, _LANES), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, _LANES), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, lq_p, dh), jnp.float32),
            jax.ShapeDtypeStruct((b, h, lq_p, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((b, h, lq_p, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, dh), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt, maskp)

    pv, m, l = out
    pv = pv[:, :, :lq].transpose(0, 2, 1, 3)  # (B, Lq, H, Dh)
    return pv, m[:, :, :lq, 0], l[:, :, :lq, 0]


def pallas_mode() -> str | None:
    """How the chunk kernel should run here: ``"compiled"`` on TPU,
    ``"interpret"`` when forced via ``SEMMERGE_PALLAS=interpret`` (CPU
    testing), ``None`` → use the einsum path."""
    env = os.environ.get("SEMMERGE_PALLAS", "auto").lower()
    if env in ("0", "off", "none"):
        return None
    if env == "interpret":
        return "interpret"
    if env in ("1", "on", "compiled"):
        return "compiled"
    return "compiled" if jax.default_backend() == "tpu" else None
