"""Mesh construction and sharding helpers.

Five named axes cover the parallelism strategies (SURVEY.md §2.3):

- ``dp``  — data parallel: the file/decl-batch axis of merge kernels and
  the example-batch axis of matcher training.
- ``pp``  — pipeline parallel: the stacked-layer axis of the encoder
  (stage sharding; XLA moves activations between stages).
- ``sp``  — sequence parallel: the token axis; attention runs as a ring
  collective over this axis (:mod:`semantic_merge_tpu.parallel.ring`).
- ``tp``  — tensor parallel: attention heads and FFN hidden features.
- ``ep``  — expert parallel: the expert axis of the MoE FFN.

Axes of size 1 are kept in the mesh so sharding specs are uniform
regardless of how many devices participate.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MESH_AXES = ("dp", "pp", "sp", "tp", "ep")

#: Axis name of the continuous-batching dispatch mesh — the packed
#: merge axis of the batched fused program (batch/dispatcher.py) shards
#: over it, one lane group per chip. Distinct from the 5-axis engine
#: mesh above: the batch mesh is 1-D by construction.
BATCH_AXIS = "batch"

#: Documented ``SEMMERGE_MESH`` postures (``[engine] mesh`` in
#: ``.semmerge.toml``; the env var, read through the per-request
#: overlay, wins over the config row):
#:
#: - ``off``     — pin the single-device programs everywhere: the merge
#:   kernels stay unsharded even on a multi-chip host and the batched
#:   dispatcher keeps its single-device vmapped program;
#: - ``auto``    — (default) use a mesh when one is usable: the one-shot
#:   engine dp-shards a merge's decl axis, the batching daemon shards
#:   the packed merge axis across chips; 1-chip hosts and any
#:   mesh-build failure fall back to the single-device programs
#:   (byte-identical output, never worse than ``off``);
#: - ``require`` — a mesh must be used; failure raises a typed
#:   :class:`~semantic_merge_tpu.errors.MeshFault` (exit 18 strict).
MESH_POSTURES = ("off", "auto", "require")

#: Pre-posture spellings of "off" (kept working; a deprecation note is
#: logged once per process so deployments migrate to the posture
#: vocabulary).
_LEGACY_OFF_ALIASES = ("none", "single", "0")

_warned_aliases: set = set()


def mesh_posture(configured: str | None = None) -> str:
    """The effective ``SEMMERGE_MESH`` posture: the env var (through the
    per-request overlay, so a daemon honors a client's setting) when
    set, else the ``[engine] mesh`` config value, else ``auto``.
    Legacy aliases ``none``/``single``/``0`` read as ``off`` with a
    one-time deprecation note; unknown values read as ``auto``."""
    from ..utils import reqenv
    raw = (reqenv.get("SEMMERGE_MESH") or "").strip().lower()
    if not raw:
        raw = (configured or "auto").strip().lower()
    if raw in _LEGACY_OFF_ALIASES:
        if raw not in _warned_aliases:
            _warned_aliases.add(raw)
            from ..utils.loggingx import logger
            logger.warning(
                "SEMMERGE_MESH=%s is a deprecated alias of 'off' — use "
                "off|auto|require (see runbook 'Environment variables')",
                raw)
        return "off"
    return raw if raw in MESH_POSTURES else "auto"


def batch_mesh_shards(devices: Sequence[jax.Device] | None = None) -> int:
    """Batch-axis size for :func:`build_batch_mesh`: the largest power
    of two ≤ the local device count (the merge-axis bucket ladder is
    power-of-two, so a pow2 axis always divides the padded batch)."""
    n = len(jax.devices() if devices is None else devices)
    shards = 1
    while shards * 2 <= n:
        shards *= 2
    return shards


def build_batch_mesh(devices: Sequence[jax.Device] | None = None,
                     *, shards: int | None = None) -> Mesh:
    """The 1-axis dispatch mesh of the continuous-batching subsystem:
    ``shards`` devices (default :func:`batch_mesh_shards`) under the
    single :data:`BATCH_AXIS` axis. The batched fused program shards
    its packed leading merge axis over it; lanes are independent, so
    no collectives cross the axis and the rows are bit-identical to
    the single-device vmapped program's."""
    if devices is None:
        devices = jax.devices()
    if shards is None:
        shards = batch_mesh_shards(devices)
    if shards < 1 or shards > len(devices):
        raise ValueError(f"batch mesh wants {shards} of "
                         f"{len(devices)} devices")
    arr = np.asarray(list(devices[:shards]))
    from ..obs import event as obs_event, metrics as obs_metrics
    obs_metrics.REGISTRY.gauge(
        "semmerge_batch_mesh_shards",
        "Batch-axis size of the last batch dispatch mesh built"
    ).set(shards)
    obs_event("batch_mesh_built", devices=len(devices), shards=shards)
    return Mesh(arr, (BATCH_AXIS,))


@dataclass
class MergeMesh:
    """A mesh plus canonical sharding constructors."""

    mesh: Mesh

    def spec(self, *axes: str | None) -> P:
        return P(*axes)

    def sharding(self, *axes: str | None) -> NamedSharding:
        return NamedSharding(self.mesh, P(*axes))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    @property
    def axis_sizes(self) -> Dict[str, int]:
        return dict(zip(self.mesh.axis_names, self.mesh.devices.shape))

    def __enter__(self):
        self._ctx = self.mesh
        self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        return self._ctx.__exit__(*exc)


def _factor(n: int, weights: Sequence[int]) -> list[int]:
    """Greedily factor ``n`` devices over the axes, preferring axes with
    higher weight. Sizes multiply to exactly ``n`` (n must be 2^k)."""
    sizes = [1] * len(weights)
    remaining = n
    order = sorted(range(len(weights)), key=lambda i: -weights[i])
    while remaining > 1:
        progressed = False
        for i in order:
            if remaining <= 1:
                break
            if weights[i] > 0:
                sizes[i] *= 2
                remaining //= 2
                progressed = True
        if not progressed:
            sizes[order[0]] *= remaining
            remaining = 1
    return sizes


def parse_mesh_spec(spec: str) -> tuple:
    """Parse ``[engine] mesh_shape`` into ``(kind, dcn_axis, sizes)``.

    - ``"auto"`` / empty → ``("flat", None, {})``;
    - ``"dp=4,tp=2"`` → ``("flat", None, {...})``;
    - ``"hybrid:dcn=dp,dp=4,tp=2"`` → ``("hybrid", "dp", {...})`` — the
      ``dcn`` axis spans slices over DCN
      (:func:`semantic_merge_tpu.parallel.distributed.build_hybrid_mesh`),
      every other axis stays within a slice on ICI.
    """
    spec = (spec or "").strip()
    kind = "flat"
    dcn_axis = None
    if spec.startswith("hybrid"):
        kind = "hybrid"
        _, _, spec = spec.partition(":")
        parts = []
        for part in spec.split(","):
            name, _, value = part.partition("=")
            if name.strip() == "dcn":
                dcn_axis = value.strip()
                if dcn_axis not in MESH_AXES:
                    raise ValueError(
                        f"mesh_shape dcn axis {dcn_axis!r} not one of {MESH_AXES}")
            elif part.strip():
                parts.append(part)
        spec = ",".join(parts)
        dcn_axis = dcn_axis or "dp"
    return kind, dcn_axis, parse_mesh_shape(spec)


def parse_mesh_shape(spec: str) -> Dict[str, int]:
    """Parse a ``.semmerge.toml`` ``[engine] mesh_shape`` value like
    ``"dp=4,tp=2"`` into :func:`build_mesh` axis kwargs. ``"auto"`` (or
    empty) returns ``{}`` — let :func:`build_mesh` infer."""
    spec = (spec or "").strip()
    if not spec or spec == "auto":
        return {}
    sizes: Dict[str, int] = {}
    for part in spec.split(","):
        name, _, value = part.partition("=")
        name = name.strip()
        if name not in MESH_AXES:
            raise ValueError(
                f"mesh_shape axis {name!r} not one of {MESH_AXES}")
        try:
            sizes[name] = int(value)
        except ValueError as exc:
            raise ValueError(f"mesh_shape {part!r}: size must be an int") from exc
    return sizes


def build_mesh(devices: Sequence[jax.Device] | None = None,
               *, dp: int | None = None, pp: int | None = None,
               sp: int | None = None, tp: int | None = None,
               ep: int | None = None) -> MergeMesh:
    """Build a 5-axis mesh over ``devices``.

    Unspecified axis sizes are inferred: fully-specified axes are
    honored, the remainder goes to ``dp`` first, then ``sp``, then
    ``tp``. For a v4-8 (4 chips / 8 cores) the default is
    ``dp=4, sp=2`` — merge batches shard over chips, long token
    sequences over cores, ICI carries the ring.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    requested = {"dp": dp, "pp": pp, "sp": sp, "tp": tp, "ep": ep}
    fixed = math.prod(v for v in requested.values() if v)
    if fixed and n % fixed != 0:
        raise ValueError(f"requested axis sizes {requested} do not divide {n} devices")
    free = n // fixed if fixed else n
    auto = _factor(free, [3 if requested["dp"] is None else 0,
                          0,
                          2 if requested["sp"] is None else 0,
                          1 if requested["tp"] is None else 0,
                          0])
    sizes = []
    for i, name in enumerate(MESH_AXES):
        sizes.append(requested[name] if requested[name] else auto[i])
    if math.prod(sizes) != n:
        # E.g. every axis explicitly given but their product < n: the
        # remainder has no auto slot to land in.
        raise ValueError(
            f"axis sizes {dict(zip(MESH_AXES, sizes))} use "
            f"{math.prod(sizes)} of {n} devices")
    arr = np.asarray(devices).reshape(sizes)
    from ..obs import event as obs_event, metrics as obs_metrics
    axis_sizes = dict(zip(MESH_AXES, sizes))
    gauge = obs_metrics.REGISTRY.gauge(
        "semmerge_mesh_axis_size", "Device-mesh axis sizes of the last "
        "mesh built (shard counts per parallelism axis)")
    for name, size in axis_sizes.items():
        gauge.set(size, axis=name)
    obs_metrics.REGISTRY.gauge(
        "semmerge_mesh_devices", "Devices in the last mesh built").set(n)
    obs_event("mesh_built", devices=n, **axis_sizes)
    return MergeMesh(mesh=Mesh(arr, MESH_AXES))
