"""Multi-host / multi-slice execution scaffolding.

The reference's only "communication backend" is newline-delimited
JSON-RPC over stdio pipes to one local Node child (reference
``semmerge/lang/ts/bridge.py:80-118``; ``workers/ts/src/index.ts:9-51``)
— single host, single worker, one in-flight request. The TPU-native
equivalent is ``jax.distributed`` + XLA collectives: every host runs the
same program, arrays are sharded over a global mesh, and cross-chip
exchange (symbol-table all-gathers for the DivergentRename join,
shard-to-shard op routing) rides ICI within a slice and DCN across
slices.

Two pieces:

- :func:`init_distributed` — process bring-up. Wraps
  ``jax.distributed.initialize`` with environment-driven defaults
  (coordinator address, process count/index) so the same CLI entry
  point works single-host (no-op) and multi-host (launched once per
  host by the job scheduler).
- :func:`build_hybrid_mesh` — a mesh whose leading ``dcn`` axis spans
  slices and whose inner axes (dp/pp/sp/tp/ep) stay inside a slice, so
  only the axes explicitly placed on ``dcn`` ever generate DCN
  traffic. Data parallelism (the file-batch axis of merge kernels)
  goes over DCN — per-file merge work is embarrassingly parallel with
  one small all-gather at compose time — while tp/sp/ep collectives
  (per-token, per-feature) stay on ICI.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Sequence

from ..utils.loggingx import logger


@dataclass(frozen=True)
class DistributedConfig:
    """Resolved bring-up parameters (all optional single-host)."""

    coordinator_address: Optional[str]
    num_processes: int
    process_id: int

    @property
    def multi_host(self) -> bool:
        return self.num_processes > 1


def resolve_distributed_config(env: Optional[dict] = None) -> DistributedConfig:
    """Environment contract (the scheduler-agnostic subset every TPU
    launcher provides): ``SEMMERGE_COORDINATOR`` (host:port),
    ``SEMMERGE_NUM_PROCESSES``, ``SEMMERGE_PROCESS_ID`` — falling back
    to the JAX standard ``JAX_COORDINATOR_ADDRESS`` etc., then to
    single-host."""
    env = env if env is not None else dict(os.environ)

    def pick(*names: str, default: Optional[str] = None) -> Optional[str]:
        for name in names:
            value = env.get(name)
            if value:
                return value
        return default

    coord = pick("SEMMERGE_COORDINATOR", "JAX_COORDINATOR_ADDRESS")
    n = int(pick("SEMMERGE_NUM_PROCESSES", "JAX_NUM_PROCESSES", default="1"))
    pid = int(pick("SEMMERGE_PROCESS_ID", "JAX_PROCESS_ID", default="0"))
    if n > 1 and coord is None:
        raise ValueError(
            "multi-process run (num_processes > 1) needs a coordinator "
            "address (SEMMERGE_COORDINATOR=host:port)")
    return DistributedConfig(coordinator_address=coord, num_processes=n,
                             process_id=pid)


_initialized = False


def init_distributed(config: Optional[DistributedConfig] = None) -> DistributedConfig:
    """Bring up ``jax.distributed`` once per process; no-op single-host.

    Safe to call from every entry point — the CLI calls it before
    building the mesh so the same binary serves laptops and pods.
    """
    global _initialized
    config = config or resolve_distributed_config()
    if config.multi_host and not _initialized:
        from ..obs import metrics as obs_metrics, spans as obs_spans
        import jax
        with obs_spans.span("init_distributed", layer="parallel",
                            processes=config.num_processes,
                            process_id=config.process_id):
            jax.distributed.initialize(
                coordinator_address=config.coordinator_address,
                num_processes=config.num_processes,
                process_id=config.process_id,
            )
        _initialized = True
        obs_metrics.REGISTRY.gauge(
            "semmerge_distributed_processes",
            "Process count of the jax.distributed job").set(
            config.num_processes)
        logger.info("jax.distributed up: process %d/%d via %s",
                    config.process_id, config.num_processes,
                    config.coordinator_address)
    return config


def build_hybrid_mesh(devices: Optional[Sequence] = None, *,
                      num_slices: Optional[int] = None,
                      dcn_axis: str = "dp",
                      slice_ids: Optional[Sequence[int]] = None,
                      dp: Optional[int] = None, pp: Optional[int] = None,
                      sp: Optional[int] = None, tp: Optional[int] = None,
                      ep: Optional[int] = None):
    """A :class:`~semantic_merge_tpu.parallel.mesh.MergeMesh` whose
    ``dcn_axis`` factor spans slices (DCN) and all other axes stay
    within a slice (ICI).

    ``num_slices`` defaults to the distinct ``device.slice_index``
    count (1 when the runtime does not report slices — e.g. the CPU
    test mesh — which degrades to the plain single-slice mesh). The
    per-slice device order interleaves so that for the returned mesh,
    ``reshape(sizes)`` puts slice-crossing strides only on the
    ``dcn_axis``: consecutive devices along every other axis are
    same-slice neighbours.
    """
    import math

    import jax
    import numpy as np

    from .mesh import MESH_AXES, MergeMesh, build_mesh

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if slice_ids is None:  # explicit ids support tests on flat CPU meshes
        slice_ids = [getattr(d, "slice_index", 0) or 0 for d in devices]
    if num_slices is None:
        num_slices = len(set(slice_ids))
    if num_slices <= 1:
        return build_mesh(devices, dp=dp, pp=pp, sp=sp, tp=tp, ep=ep)

    by_slice: dict = {}
    for d, s in zip(devices, slice_ids):
        by_slice.setdefault(s, []).append(d)
    groups = [by_slice[s] for s in sorted(by_slice)]
    per_slice = len(groups[0])
    if any(len(g) != per_slice for g in groups):
        raise ValueError("slices expose unequal device counts: "
                         f"{[len(g) for g in groups]}")

    requested = {"dp": dp, "pp": pp, "sp": sp, "tp": tp, "ep": ep}
    intra = dict(requested)
    if requested[dcn_axis] is None:
        intra[dcn_axis] = None  # inferred per-slice; total = inferred * num_slices
    elif requested[dcn_axis] % num_slices != 0:
        raise ValueError(
            f"{dcn_axis}={requested[dcn_axis]} must be a multiple of "
            f"num_slices={num_slices} (the slice factor rides DCN)")
    else:
        intra[dcn_axis] = requested[dcn_axis] // num_slices

    # Build the single-slice factorization for the intra-slice factors.
    inner = build_mesh(groups[0], **intra)
    inner_sizes = dict(zip(inner.mesh.axis_names, inner.mesh.devices.shape))

    sizes = dict(inner_sizes)
    sizes[dcn_axis] = inner_sizes[dcn_axis] * num_slices
    if math.prod(sizes.values()) != len(devices):
        raise ValueError(f"axis sizes {sizes} do not cover {len(devices)} devices")

    # Device layout: axis order (slice, *inner axes) reshaped so the
    # slice factor is the outermost factor of `dcn_axis`.
    arr = np.stack([np.asarray(g).reshape(inner.mesh.devices.shape)
                    for g in groups])  # (num_slices, *inner)
    axis_idx = MESH_AXES.index(dcn_axis)
    # Move the slice axis next to (in front of) its inner counterpart.
    arr = np.moveaxis(arr, 0, axis_idx)
    shape = [sizes[name] for name in MESH_AXES]
    arr = arr.reshape(shape)
    from jax.sharding import Mesh
    return MergeMesh(mesh=Mesh(arr, MESH_AXES))
