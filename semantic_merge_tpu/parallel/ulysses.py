"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

The second long-context strategy (SURVEY.md §2.3): where ring attention
(:mod:`semantic_merge_tpu.parallel.ring`) keeps K/V sharded and rotates
chunks around the ``sp`` ring, Ulysses re-shards — one all-to-all turns
the sequence sharding into a *head* sharding, every device then holds
the **full sequence for a subset of heads**, computes ordinary (flash)
attention locally with zero inner-loop communication, and a second
all-to-all restores sequence sharding.

Trade-off vs ring: 2 all-to-alls of activation size per layer
(latency-bound, great on ICI) instead of ``n`` ppermute rounds
overlapped with compute; but heads must divide the ``sp`` axis size,
and per-device memory is O(L) for its head subset. Ring wins when
L/device is tight; Ulysses wins when head count is ample and the
sequence is extreme. Both are exact (no approximation), so the encoder
can switch per config (``EncoderConfig.attn_mode``).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _ulysses_local(q, k, v, kmask, *, axis_name: str):
    """Per-shard body: q/k/v (B, L_loc, H_loc, Dh); kmask (B, L_loc)."""
    n = lax.psum(1, axis_name)
    h_loc = q.shape[2]
    if h_loc % n != 0:
        raise ValueError(
            f"Ulysses needs heads-per-shard ({h_loc}) divisible by the "
            f"{axis_name!r} axis size ({n}); use ring attention instead")

    def seq_to_head(x):
        # (B, L_loc, H_loc, Dh) → (B, L, H_loc/n, Dh): split heads n ways,
        # gather all sequence chunks of one head group.
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def head_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg = seq_to_head(q)
    kg = seq_to_head(k)
    vg = seq_to_head(v)
    mask_g = lax.all_gather(kmask, axis_name, axis=1, tiled=True)  # (B, L)

    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", qg.astype(jnp.float32),
                   kg.astype(jnp.float32)) * scale
    s = jnp.where(mask_g[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, vg.astype(jnp.float32))
    return head_to_seq(out.astype(q.dtype))


def ulysses_attention(q, k, v, kmask, mesh: Mesh, *, axis_name: str = "sp"):
    """Exact attention with the sequence axis sharded over ``axis_name``
    via head/sequence all-to-all. Same signature and semantics as
    :func:`semantic_merge_tpu.parallel.ring.ring_attention`."""
    qkv_spec = P("dp", axis_name, "tp", None)
    mask_spec = P("dp", axis_name)
    from ..utils.jaxenv import shard_map_compat
    return shard_map_compat(
        partial(_ulysses_local, axis_name=axis_name),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )(q, k, v, kmask)
