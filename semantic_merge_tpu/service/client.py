"""Merge-service client: delegate merge-shaped CLI invocations to a
warm daemon, with a hard never-worse-than-one-shot guarantee.

``SEMMERGE_DAEMON`` selects the posture:

- ``off`` (default) — never delegate; plain one-shot CLI.
- ``auto`` — connect to a running daemon, spawn one if absent (with a
  startup handshake), and on ANY transport failure — no daemon, spawn
  timeout, protocol garbage, connection died mid-request — fall back
  to the in-process one-shot path. The work tree is never left worse
  than a one-shot run: delegation failures happen before this process
  touches the tree, and a daemon killed mid-``--inplace`` leaves the
  journaled state the one-shot path's ``recover()`` resolves first.
- ``require`` — delegate or fail with the ``WorkerFault`` exit (12);
  for tests and deployments that must not silently run cold.

A *typed* wire error (``exit_code`` present) is a final answer in both
auto and require modes — the daemon executed the request and the fault
is the result, exactly as a one-shot run with the same injected fault
would have exited; falling back and re-running would turn a
deterministic typed failure into a double execution.

``SEMMERGE_FLEET`` layers fleet discovery on top: ``auto`` prefers an
already-listening fleet router on the service socket (never spawns
one) and falls back to the plain ``SEMMERGE_DAEMON`` posture;
``require`` demands the socket answer *as a fleet router* (its hello
carries ``fleet: true``) and fails with the ``FleetFault`` exit (19)
otherwise. ``off`` (default) leaves this module byte-identical to the
fleet-less client.

The fleet dial itself goes through :mod:`fleet.transport` (stdlib-only,
so the milliseconds-fast client path keeps its import set): a
``tcp://host:port`` service socket reaches a remote router (mTLS when
``SEMMERGE_FLEET_TLS_*`` is configured), and the ``net:*`` fault stages
fire at this seam. A :class:`~semantic_merge_tpu.errors.TransportFault`
raised here is the network refusing to carry the request: under
``SEMMERGE_FLEET=require`` it exits 21 with the work tree untouched;
under ``auto`` the client degrades through the existing ladder
(single daemon, then in-process) — byte-identical output.

:func:`delegate` is called from ``__main__`` BEFORE ``cli`` (and
therefore jax) is imported — the client path costs milliseconds, which
is the whole point of the warm daemon.
"""
from __future__ import annotations

import contextlib
import os
import random
import socket
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import protocol
from ..errors import TransportFault  # stdlib-only module: stays cheap

#: Exit for ``require`` mode with no usable daemon — the WorkerFault
#: code (errors.EXIT_CODES), hardcoded so this module never imports
#: the heavy package half.
_REQUIRE_FAILED_EXIT = 12

#: Exit for ``SEMMERGE_FLEET=require`` with no fleet router — the
#: FleetFault code (errors.EXIT_CODES), hardcoded for the same reason.
_FLEET_REQUIRE_EXIT = 19

_Conn = Tuple[socket.socket, Any, Any]  # (sock, rfile, wfile)


class DaemonUnavailable(Exception):
    """No daemon could be reached/spawned, or the transport broke."""


class _RetryableRejection(Exception):
    """A typed admission rejection carrying a ``retry_after_ms`` hint —
    transient overload, not a final answer: the client may retry the
    daemon after the hinted delay."""

    def __init__(self, exit_code: int, message: str,
                 retry_after_ms: int) -> None:
        super().__init__(message)
        self.exit_code = exit_code
        self.message = message
        self.retry_after_ms = retry_after_ms


def mode() -> str:
    return os.environ.get("SEMMERGE_DAEMON", "off").strip().lower()


def fleet_mode() -> str:
    """The ``SEMMERGE_FLEET`` posture. Parsed locally (not via
    ``fleet.mode``) so the hot client path keeps its import set."""
    raw = os.environ.get("SEMMERGE_FLEET", "").strip().lower()
    if raw in ("auto", "require"):
        return raw
    return "off"


def delegate(argv: Sequence[str]) -> Optional[int]:
    """Run ``argv`` (full CLI argv, ``argv[0]`` the subcommand) on the
    daemon. Returns the exit code, or ``None`` when the invocation
    should proceed in-process (daemon mode off, non-verb command, or
    auto-mode transport failure)."""
    argv = [str(a) for a in argv]
    if not argv or argv[0] not in protocol.VERBS:
        return None
    if os.environ.get("_SEMMERGE_IN_DAEMON"):
        return None  # belt and suspenders: the daemon never re-delegates
    fm = fleet_mode()
    if fm in ("auto", "require"):
        # Fleet discovery: reach for a listening router first. Never
        # spawns — a client-spawned daemon would not be a fleet — and
        # only a socket that answers AS a fleet router counts; a plain
        # daemon squatting the path routes via the daemon posture.
        try:
            return _run_on_daemon(argv[0], argv[1:], spawn=False,
                                  require_fleet=True)
        except TransportFault as exc:
            # The transport itself refused to carry the request (an
            # injected net:* fault, a mid-handshake break). Nothing has
            # executed and the work tree is untouched: require exits
            # with the TransportFault code, auto degrades through the
            # same ladder a missing router takes.
            if fm == "require":
                sys.stderr.write(f"semmerge: fleet transport failed: "
                                 f"{exc} (exit {exc.exit_code})\n")
                return exc.exit_code
        except DaemonUnavailable as exc:
            if fm == "require":
                sys.stderr.write(f"semmerge: fleet required but "
                                 f"unavailable: {exc} "
                                 f"(exit {_FLEET_REQUIRE_EXIT})\n")
                return _FLEET_REQUIRE_EXIT
            # fleet auto: no router listening — fall through to the
            # plain daemon posture below, never worse than fleet-less.
    m = mode()
    if m not in ("auto", "require"):
        return None
    try:
        return _run_on_daemon(argv[0], argv[1:])
    except DaemonUnavailable as exc:
        if m == "require":
            sys.stderr.write(f"semmerge: daemon required but unavailable: "
                             f"{exc} (exit {_REQUIRE_FAILED_EXIT})\n")
            return _REQUIRE_FAILED_EXIT
        return None  # auto: warm path failed, run one-shot


def _run_on_daemon(verb: str, rest: List[str], *, spawn: bool = True,
                   require_fleet: bool = False) -> int:
    """Delegate with bounded retries. Two retry-worthy outcomes exist:

    - a **transient admission rejection** (``retry_after_ms`` on the
      wire error — queue full, load shed): sleep the hinted delay
      (jittered, so a herd of rejected clients does not re-arrive in
      lockstep) and retry; exhausted retries fall back in-process in
      ``auto`` (the merge still happens, never worse than one-shot)
      and exit with the typed code in ``require``;
    - a **transport failure** (daemon died mid-request, spawn lost a
      race): retry against a fresh connection with short backoff. The
      idempotency key makes the resend safe — a daemon that already
      completed the first execution replays the recorded response
      instead of executing twice.

    Typed errors without ``retry_after_ms`` stay FINAL answers."""
    deadline = _env_float("SEMMERGE_SERVICE_DEADLINE", 0.0)
    retries = max(0, int(_env_float("SEMMERGE_SERVICE_RETRIES", 2)))
    idem_key = f"{os.getpid():x}-{os.urandom(8).hex()}"
    # One trace id per REQUEST (not per retry attempt): a replayed
    # idempotent response and the original execution share one trace.
    trace_id = os.urandom(8).hex()
    attempt = 0
    backoff = 0.0
    while True:
        try:
            return _attempt_on_daemon(verb, rest, deadline, idem_key,
                                      trace_id, spawn=spawn,
                                      require_fleet=require_fleet)
        except _RetryableRejection as rej:
            if attempt >= retries:
                if mode() == "require" or require_fleet:
                    if rej.message:
                        sys.stderr.write(f"semmerge: {rej.message} "
                                         f"(exit {rej.exit_code})\n")
                    return rej.exit_code
                raise DaemonUnavailable(
                    f"daemon still shedding after {attempt + 1} "
                    f"attempts: {rej.message}")
            time.sleep(min((rej.retry_after_ms / 1000.0)
                           * random.uniform(0.5, 1.5), 5.0))
        except DaemonUnavailable:
            if attempt >= retries:
                raise
            backoff = _reconnect_backoff_s(backoff)
            time.sleep(backoff)
        attempt += 1


def _reconnect_backoff_s(prev: float, base: float = 0.05,
                         cap: float = 2.0) -> float:
    """Decorrelated-jitter reconnect backoff: ``min(cap, uniform(base,
    prev * 3))``. The old fixed exponential schedule kept a herd of
    clients that failed together re-arriving in lockstep (its ±50%
    jitter band still clusters around the same powers of two); each
    draw here depends on the *previous draw*, so the herd spreads out
    within a retry or two."""
    return min(cap, random.uniform(base, max(prev * 3.0, base)))


def _attempt_on_daemon(verb: str, rest: List[str], deadline: float,
                       idem_key: str, trace_id: str, *,
                       spawn: bool = True,
                       require_fleet: bool = False) -> int:
    sock, rfile, wfile = _connect_or_spawn(spawn=spawn,
                                           require_fleet=require_fleet)
    try:
        params: Dict[str, Any] = {
            "argv": rest,
            "cwd": os.getcwd(),
            "env": protocol.request_env(),
            "idempotency_key": idem_key,
            "trace_id": trace_id,
        }
        if deadline > 0:
            params["deadline_s"] = deadline
            # Transport timeout trails the request deadline: the daemon
            # answers deadline expiry itself (typed DeadlineFault); the
            # socket timeout only catches a wedged daemon.
            sock.settimeout(deadline + 30.0)
        try:
            protocol.write_message(wfile, {"id": 1, "method": verb,
                                           "params": params})
            resp = protocol.read_message(rfile)
        except (OSError, ValueError, protocol.ProtocolError) as exc:
            raise DaemonUnavailable(f"transport failed: {exc}") from exc
        if resp is None:
            raise DaemonUnavailable("daemon closed the connection "
                                    "mid-request")
        if resp.get("id") != 1:
            raise DaemonUnavailable("response id mismatch")
        error = resp.get("error")
        if error is not None:
            exit_code = error.get("exit_code")
            retry_after = error.get("retry_after_ms")
            if isinstance(exit_code, int) and isinstance(retry_after, int):
                raise _RetryableRejection(exit_code,
                                          error.get("message", ""),
                                          retry_after)
            if isinstance(exit_code, int):
                # Typed fault: a FINAL answer (see module docstring).
                # The trace id on the stderr line is the postmortem
                # bundle name (.semmerge-postmortem/<trace_id>.json).
                message = error.get("message", "")
                if message:
                    tid = error.get("trace_id") or trace_id
                    sys.stderr.write(f"semmerge: {message} "
                                     f"(exit {exit_code}) "
                                     f"[trace {tid}]\n")
                return exit_code
            raise DaemonUnavailable(
                f"protocol error: {error.get('message', 'unknown')}")
        result = resp.get("result")
        if not isinstance(result, dict) or "exit_code" not in result:
            raise DaemonUnavailable("malformed result frame")
        sys.stdout.write(result.get("stdout", ""))
        sys.stderr.write(result.get("stderr", ""))
        sys.stdout.flush()
        sys.stderr.flush()
        return int(result["exit_code"])
    finally:
        _close(sock, rfile, wfile)


# ----------------------------------------------------------------------
# connection management


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def _close(sock, rfile, wfile) -> None:
    for closable in (rfile, wfile, sock):
        try:
            closable.close()
        except OSError:
            pass


def _try_connect(path: str, timeout: float = 5.0,
                 require_fleet: bool = False) -> Optional[_Conn]:
    """Connect + ``hello`` handshake. ``None`` means nothing usable is
    listening (absent socket, stale socket, or a peer that cannot
    complete the handshake). With ``require_fleet`` the peer must
    answer as a fleet router (``fleet: true`` in its hello) — a plain
    daemon on the path counts as unusable.

    Fleet dials (and any ``tcp://`` address) go through the transport
    seam, which handles TLS and fires the ``net:*`` fault stages: an
    injected fault raises :class:`TransportFault` out of here (the
    posture seam in :func:`delegate` turns it into exit 21 or ladder
    fallthrough), while a *real* dead address stays ``None`` — the
    same no-router shape as before."""
    check_read = None
    if require_fleet or path.startswith("tcp://"):
        from ..fleet import transport as fleet_transport
        sock = fleet_transport.dial(path, timeout=timeout)
        if sock is None:
            return None
        check_read = fleet_transport.check_read_faults
    else:
        if not os.path.exists(path):
            return None
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(timeout)
        try:
            sock.connect(path)
        except OSError:
            with contextlib.suppress(OSError):
                sock.close()
            return None
    try:
        sock.settimeout(timeout)
        rfile = sock.makefile("r", encoding="utf-8")
        wfile = sock.makefile("w", encoding="utf-8")
        protocol.write_message(wfile, {
            "id": 0, "method": "hello",
            "params": {"version": protocol.PROTOCOL_VERSION}})
        if check_read is not None:
            check_read()
        resp = protocol.read_message(rfile)
    except (OSError, ValueError, protocol.ProtocolError):
        with contextlib.suppress(OSError):
            sock.close()
        return None
    if not (isinstance(resp, dict) and resp.get("id") == 0
            and isinstance(resp.get("result"), dict)
            and resp["result"].get("ok")):
        _close(sock, rfile, wfile)
        return None
    if require_fleet and not resp["result"].get("fleet"):
        _close(sock, rfile, wfile)
        return None
    sock.settimeout(None)
    return sock, rfile, wfile


def _spawn_daemon(path: str) -> subprocess.Popen:
    """Start a detached daemon on ``path``. Its cwd is ``/`` so any
    repo-relative work missing the request working-dir scope fails
    loudly instead of landing in whichever repo spawned the daemon.
    ``SEMMERGE_FAULT`` is stripped — injection is per-request (it rides
    the request env overlay), not a property of the daemon process."""
    env = dict(os.environ)
    env.pop("SEMMERGE_FAULT", None)
    env.pop("SEMMERGE_DAEMON", None)
    log_path = path + ".log"
    with open(log_path, "ab") as log:
        return subprocess.Popen(
            [sys.executable, "-m", "semantic_merge_tpu", "serve",
             "--socket", path],
            stdin=subprocess.DEVNULL, stdout=log, stderr=log,
            cwd="/", env=env, start_new_session=True)


def _connect_or_spawn(*, spawn: bool = True,
                      require_fleet: bool = False) -> _Conn:
    path = protocol.socket_path()
    conn = _try_connect(path, require_fleet=require_fleet)
    if conn is not None:
        return conn
    if not spawn:
        raise DaemonUnavailable(
            f"no {'fleet router' if require_fleet else 'daemon'} "
            f"listening on {path}")
    spawn_timeout = _env_float("SEMMERGE_SERVICE_SPAWN_TIMEOUT", 30.0)
    proc = _spawn_daemon(path)
    t0 = time.monotonic()
    while time.monotonic() - t0 < spawn_timeout:
        conn = _try_connect(path)
        if conn is not None:
            return conn
        if proc.poll() is not None:
            # The spawned process exited — usually because it lost the
            # startup bind race to a concurrent spawner. The winner may
            # still be warming up (it binds its socket well before it
            # can answer the handshake), so a single probe here turned
            # real winners into spurious cold-path fallbacks. Keep
            # reconnecting for a bounded window instead.
            reconnect = _env_float("SEMMERGE_SERVICE_RECONNECT", 2.0)
            r0 = time.monotonic()
            while time.monotonic() - r0 < reconnect:
                conn = _try_connect(path)
                if conn is not None:
                    return conn
                time.sleep(0.1)
            raise DaemonUnavailable(
                f"daemon exited rc={proc.returncode} during startup "
                f"(log: {path}.log)")
        time.sleep(0.1)
    raise DaemonUnavailable(
        f"daemon did not come up within {spawn_timeout:g}s "
        f"(log: {path}.log)")


# ----------------------------------------------------------------------
# control plane (status / shutdown — used by the CLI, bench, tests)


def call_control(method: str, params: Optional[dict] = None,
                 path: Optional[str] = None, timeout: float = 10.0) -> dict:
    """One control-method round trip against a RUNNING daemon (never
    spawns). Raises :class:`DaemonUnavailable` when none is reachable
    or the answer is not a result frame."""
    resolved = protocol.socket_path(path)
    conn = _try_connect(resolved, timeout=timeout)
    if conn is None:
        raise DaemonUnavailable(f"no daemon on {resolved}")
    sock, rfile, wfile = conn
    try:
        sock.settimeout(timeout)
        try:
            protocol.write_message(wfile, {"id": 1, "method": method,
                                           "params": params or {}})
            resp = protocol.read_message(rfile)
        except (OSError, ValueError, protocol.ProtocolError) as exc:
            raise DaemonUnavailable(f"transport failed: {exc}") from exc
        if not (isinstance(resp, dict) and resp.get("id") == 1
                and isinstance(resp.get("result"), dict)):
            raise DaemonUnavailable(f"malformed {method} response")
        return resp["result"]
    finally:
        _close(sock, rfile, wfile)


def capture_profile(seconds: float, out_dir: Optional[str] = None,
                    path: Optional[str] = None) -> dict:
    """Ask a RUNNING daemon for an on-demand profile capture. The
    daemon blocks the control connection for the capture window, so
    the transport timeout trails ``seconds`` by a wide margin. Returns
    the capture result dict (``ok``/``dir``/``files`` or
    ``ok=False``/``error``)."""
    params: Dict[str, Any] = {"seconds": float(seconds)}
    if out_dir:
        params["out_dir"] = str(out_dir)
    return call_control("profile", params=params, path=path,
                        timeout=float(seconds) + 30.0)


def call_verb(verb: str, params: dict, path: Optional[str] = None,
              timeout: Optional[float] = None) -> dict:
    """Raw verb request against a RUNNING daemon, returning the full
    response frame (``result`` or ``error``) — the bench and the
    concurrency tests drive the protocol directly with this."""
    resolved = protocol.socket_path(path)
    conn = _try_connect(resolved, timeout=timeout or 10.0)
    if conn is None:
        raise DaemonUnavailable(f"no daemon on {resolved}")
    sock, rfile, wfile = conn
    try:
        sock.settimeout(timeout)
        try:
            protocol.write_message(wfile, {"id": 1, "method": verb,
                                           "params": params})
            resp = protocol.read_message(rfile)
        except (OSError, ValueError, protocol.ProtocolError) as exc:
            raise DaemonUnavailable(f"transport failed: {exc}") from exc
        if resp is None:
            raise DaemonUnavailable("daemon closed the connection")
        return resp
    finally:
        _close(sock, rfile, wfile)
