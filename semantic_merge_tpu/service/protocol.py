"""Merge-service wire protocol: newline-delimited JSON-RPC over a unix
socket — the same framing the out-of-process language worker speaks on
stdio (:mod:`semantic_merge_tpu.runtime.worker`), so both process seams
in the system read the same on the wire.

Request/response shapes::

    → {"id": 1, "method": "semmerge",
       "params": {"argv": ["BASE", "A", "B", "--inplace"],
                  "cwd": "/abs/repo", "env": {"SEMMERGE_STRICT": "1"},
                  "deadline_s": 30.0, "trace_id": "9f2ab34cc01d77e6"}}
    ← {"id": 1, "result": {"exit_code": 0, "stdout": "…", "stderr": "…",
                           "meta": {"queue_wait_s": 0.001,
                                    "trace_id": "9f2ab34cc01d77e6", …}}}

``trace_id`` is minted by the client (one per request, not per retry
attempt) and threads through the daemon executor, the batch
dispatcher, and the subprocess-worker frames, naming that request's
spans, artifacts, and postmortem bundle
(``.semmerge-postmortem/<trace_id>.json``).

Verb methods are the three merge-shaped CLI commands; control methods
are ``hello`` (startup/liveness handshake carrying the protocol
version), ``status``, ``metrics`` (live registry: Prometheus text +
health JSON), ``profile`` (bounded on-demand JAX profiler capture into
a timestamped bundle directory, serialized by a daemon-side
single-capture lock), and ``shutdown``. Errors come back as
``{"id": n, "error": {"message", "fault", "stage", "exit_code",
"trace_id"}}`` — a *typed* error (``exit_code`` present) is a final
answer the client exits with; an untyped or malformed response is a
transport failure the client treats as daemon-unavailable.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

PROTOCOL_VERSION = 1

#: CLI commands a client may delegate.
VERBS = ("semdiff", "semmerge", "semrebase")

#: Env vars NOT shipped with a request: daemon-routing knobs would
#: recurse, SEMMERGE_METRICS is a process-atexit artifact of whichever
#: process owns it, the service socket is connection metadata, and the
#: SLO engine is daemon-lifetime state — a client's objectives must not
#: reconfigure a shared daemon per request (the OTLP exporter is a
#: process-lifetime background shipper with the same ownership rule).
_UNSHIPPED_PREFIXES = ("SEMMERGE_SERVICE_", "SEMMERGE_SLO",
                       "SEMMERGE_FLEET", "SEMMERGE_OTLP")
_UNSHIPPED = frozenset({"SEMMERGE_DAEMON", "SEMMERGE_METRICS",
                        "SEMMERGE_METRICS_PORT"})


class ProtocolError(Exception):
    """The peer spoke something that is not the protocol."""


def socket_path(explicit: Optional[str] = None) -> str:
    """Resolve the service socket path: explicit argument, then
    ``SEMMERGE_SERVICE_SOCKET``, then ``$XDG_RUNTIME_DIR/semmerge.sock``,
    then a per-uid path under ``/tmp`` (world-writable dir, so the name
    carries the uid and the daemon binds with a 0700-style unlink/bind
    on a path only this user should own)."""
    if explicit:
        return explicit
    env = os.environ.get("SEMMERGE_SERVICE_SOCKET", "").strip()
    if env:
        return env
    runtime_dir = os.environ.get("XDG_RUNTIME_DIR", "").strip()
    if runtime_dir:
        return os.path.join(runtime_dir, "semmerge.sock")
    uid = os.getuid() if hasattr(os, "getuid") else 0
    return f"/tmp/semmerge-{uid}.sock"


def request_env() -> Dict[str, str]:
    """The client's ``SEMMERGE_*`` environment, minus the unshipped set
    — this rides with each request and is applied daemon-side as a
    per-request overlay (:mod:`semantic_merge_tpu.utils.reqenv`), so a
    client's ``SEMMERGE_STRICT`` / ``SEMMERGE_FAULT`` scope to its own
    request instead of leaking into the daemon process."""
    out: Dict[str, str] = {}
    for key, value in os.environ.items():
        if not key.startswith("SEMMERGE_"):
            continue
        if key in _UNSHIPPED or key.startswith(_UNSHIPPED_PREFIXES):
            continue
        out[key] = value
    return out


def write_message(wfile, obj: Dict[str, Any]) -> None:
    """One JSON object, one line, flushed — a message is visible to the
    peer the moment this returns."""
    wfile.write(json.dumps(obj, separators=(",", ":"),
                           default=str) + "\n")
    wfile.flush()


def read_message(rfile) -> Optional[Dict[str, Any]]:
    """The next message, ``None`` on EOF. Blank lines are skipped
    (keepalive-friendly); a non-JSON or non-object line is a
    :class:`ProtocolError`."""
    while True:
        line = rfile.readline()
        if line == "":
            return None
        line = line.strip()
        if not line:
            continue
        try:
            msg = json.loads(line)
        except ValueError as exc:
            raise ProtocolError(f"undecodable frame: {exc}") from exc
        if not isinstance(msg, dict):
            raise ProtocolError(f"frame is not an object: {type(msg).__name__}")
        return msg


def fault_error(fault, retry_after_ms: Optional[int] = None,
                trace_id: Optional[str] = None) -> Dict[str, Any]:
    """The wire form of a typed :class:`~semantic_merge_tpu.errors.
    MergeFault`: everything the client needs to reproduce the one-shot
    behavior (stderr line + documented exit code). ``retry_after_ms``
    rides on *transient* admission rejections (queue-full, overload)
    and invites the client to retry against the daemon after that
    delay instead of treating the rejection as final. ``trace_id``
    echoes the request's id so the client-visible error names the same
    trace the daemon's spans and postmortem bundle carry."""
    err = {
        "message": fault.describe(),
        "fault": type(fault).__name__,
        "stage": fault.stage,
        "exit_code": fault.exit_code,
    }
    if retry_after_ms is not None:
        err["retry_after_ms"] = int(retry_after_ms)
    if trace_id:
        err["trace_id"] = str(trace_id)
    return err
