"""The merge service daemon: warm state behind a unix socket.

One process holds everything a one-shot CLI rebuilds per invocation —
the jitted fused program and XLA compile cache, the process-global decl
cache, the keep-alive subprocess worker, prettier/tsc discovery — and
executes merge-shaped requests against it. Requests flow:

    accept (handler thread)  →  bounded queue  →  executor thread

- **Admission**: ``service.accept`` span; a full queue rejects with a
  typed ``WorkerFault`` (exit 12, ``cause="queue-full"``) instead of
  unbounded buffering.
- **Dispatch**: the executor records ``service.queue_wait``, enforces
  the request deadline (expiry → ``DeadlineFault``, exit 15 — the
  PR-4 ladder's deadline semantics over the wire), and serializes
  same-repo ``--inplace`` requests behind a per-repo lock; the
  cross-process half of that exclusion is the ``O_EXCL`` lockfile the
  CLI's commit path takes (:func:`runtime.inplace.repo_lock`).
- **Execute**: ``service.execute`` span around the real CLI ``main``
  under the request's working-dir scope (:mod:`utils.workdir`) and env
  overlay (:mod:`utils.reqenv`), stdout/stderr routed per-thread back
  to the client. Every ``MergeFault`` — including injected
  ``service:*`` stage faults — becomes a typed wire error; the daemon
  itself never dies of a request.

Lifecycle: SIGTERM/SIGINT stop admission, drain in-flight work
(bounded by ``SEMMERGE_SERVICE_DRAIN_TIMEOUT``), then exit. A stale
socket left by a dead daemon is detected by a probe connect and
replaced; a live daemon on the socket makes a second ``serve`` exit 0
immediately. An idle daemon exits after ``SEMMERGE_SERVICE_IDLE_EXIT``
seconds; idle per-repo state is reaped after ``SEMMERGE_SERVICE_TTL``.

Cross-host membership (``fleet/transport.py``): a ``tcp://host:port``
socket path listens on TCP (mTLS when ``SEMMERGE_FLEET_TLS_*`` is set;
``:0`` picks an ephemeral port, resolved before anything is
advertised). ``--join ROUTER_ADDR`` announces this daemon to a fleet
router with a ``join`` handshake carrying the advertised address,
capacity, and an announce epoch, re-announces every
``SEMMERGE_FLEET_JOIN_INTERVAL`` seconds (so an ejected member rejoins
by itself once reachable again), stops announcing while draining, and
sends a best-effort ``leave`` on shutdown. The router prewarms moved
repo keys onto their new owners through the cheap ``prewarm`` wire
verb below.
"""
from __future__ import annotations

import contextlib
import io
import json
import os
import pathlib
import queue
import signal
import socket
import ssl
import sys
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Optional

from ..errors import DeadlineFault, MergeFault, WorkerFault, fault_boundary
from ..fleet import transport as fleet_transport
from ..obs import agg as obs_agg
from ..obs import anomaly as obs_anomaly
from ..obs import export as obs_export
from ..obs import metrics as obs_metrics
from ..obs import sampling as obs_sampling
from ..obs import slo as obs_slo
from ..obs import spans as obs_spans
from ..obs import flight as obs_flight
from ..utils import faults, reqenv, workdir
from ..utils.loggingx import logger
from ..utils.procs import env_seconds
from . import protocol, resilience, telemetry
from . import residency as residency_mod

_OUTCOME_BY_EXIT = {0: "ok", 1: "conflicts", 2: "typecheck", 3: "git-error"}

_REQUESTS_HELP = "Service requests, by verb and outcome"
_LATENCY_HELP = "End-to-end service request seconds, by verb"
_QUEUE_DEPTH_HELP = "Requests currently waiting in the admission queue"
_SHED_HELP = "Requests shed by admission control, by reason"
_RSS_HELP = "Daemon resident set size (MiB), sampled by the pressure monitor"
_IDEM_HELP = "Requests answered from the idempotency cache"

#: Pressure levels the RSS monitor publishes (watermark crossings).
_PRESSURE_NONE, _PRESSURE_SOFT, _PRESSURE_HARD = 0, 1, 2


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


def _rss_mb() -> float:
    """Resident set size in MiB, from ``/proc/self/status`` (Linux);
    best-effort 0.0 elsewhere."""
    try:
        with open("/proc/self/status", encoding="ascii") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
    except (OSError, ValueError, IndexError):
        pass
    return 0.0


def _request_batches() -> bool:
    """Will the current request's fused dispatches join the batch
    scheduler? Evaluated under the request's env overlay, so a
    client-shipped ``SEMMERGE_BATCH=off`` reads as non-batched."""
    from .. import batch
    return batch.posture() != "off" and batch.current() is not None


class _ThreadTee(io.TextIOBase):
    """A stdout/stderr stand-in routing writes to a per-thread buffer
    when one is pushed (an executor running a request) and to the real
    stream otherwise (daemon logs, stray prints from handler threads).
    ``print``/``sys.stdout`` resolve at call time, so swapping this in
    once at startup covers every write the CLI makes."""

    def __init__(self, fallback) -> None:
        self._fallback = fallback
        self._tls = threading.local()

    def push(self, buf: io.StringIO) -> None:
        self._tls.buf = buf

    def pop(self) -> None:
        self._tls.buf = None

    def _target(self):
        return getattr(self._tls, "buf", None) or self._fallback

    def write(self, s: str) -> int:
        return self._target().write(s)

    def flush(self) -> None:
        try:
            self._target().flush()
        except (OSError, ValueError):
            pass

    def writable(self) -> bool:
        return True

    @property
    def encoding(self):  # some libraries sniff it off sys.stdout
        return getattr(self._fallback, "encoding", "utf-8")


class _Request:
    __slots__ = ("id", "verb", "argv", "cwd", "env", "deadline_s",
                 "idem_key", "trace_id", "recorder", "t_accept", "done",
                 "response")

    def __init__(self, req_id, verb: str, params: Dict[str, Any]) -> None:
        self.id = req_id
        self.verb = verb
        self.argv = [str(a) for a in (params.get("argv") or [])]
        self.cwd = str(params.get("cwd") or "/")
        env = params.get("env") or {}
        self.env = {str(k): str(v) for k, v in env.items()}
        raw_deadline = params.get("deadline_s")
        self.deadline_s = float(raw_deadline) if raw_deadline else 0.0
        raw_idem = params.get("idempotency_key")
        self.idem_key = str(raw_idem) if raw_idem else None
        # Client-minted request trace id (a pre-trace_id client gets one
        # minted here); every span, artifact, worker frame, and
        # postmortem bundle of this request carries it.
        raw_trace = params.get("trace_id")
        self.trace_id = str(raw_trace) if raw_trace else os.urandom(8).hex()
        self.recorder = obs_spans.SpanRecorder(detailed=False)
        self.t_accept = time.monotonic()
        self.done = threading.Event()
        self.response: Optional[Dict[str, Any]] = None


class Daemon:
    """One ``semmerge serve`` process. Construct, then
    :meth:`serve_forever`."""

    def __init__(self, socket_path: Optional[str] = None,
                 workers: Optional[int] = None,
                 queue_size: Optional[int] = None,
                 idle_exit: Optional[float] = None,
                 repo_ttl: Optional[float] = None,
                 events_path: Optional[str] = None,
                 join: Optional[str] = None,
                 advertise: Optional[str] = None,
                 capacity: Optional[int] = None,
                 member_id: Optional[str] = None) -> None:
        self._socket_path = protocol.socket_path(socket_path)
        # Elastic membership: announce to a fleet router instead of
        # being a router-spawned subprocess. The advertised address
        # defaults to the bound socket (resolved after an ephemeral
        # :0 bind), so `--socket tcp://0.0.0.0:0 --join ...` just works
        # on one host.
        self._join_addr = (join or
                           os.environ.get("SEMMERGE_FLEET_JOIN",
                                          "").strip() or None)
        self._advertise = (advertise or
                           os.environ.get("SEMMERGE_FLEET_ADVERTISE",
                                          "").strip() or None)
        self._capacity = max(1, capacity if capacity is not None else
                             _env_int("SEMMERGE_FLEET_CAPACITY", 1))
        self._member_id = (member_id or
                           os.environ.get("SEMMERGE_FLEET_MEMBER_ID",
                                          "").strip() or None)
        self._join_epoch = 0
        self._joined_as: Optional[str] = None
        self._workers_n = workers if workers is not None else \
            max(1, _env_int("SEMMERGE_SERVICE_WORKERS", 4))
        qsize = queue_size if queue_size is not None else \
            _env_int("SEMMERGE_SERVICE_QUEUE", 16)
        self._queue: "queue.Queue[_Request]" = queue.Queue(maxsize=max(1, qsize))
        self._idle_exit = idle_exit if idle_exit is not None else \
            env_seconds("SEMMERGE_SERVICE_IDLE_EXIT", 900.0)
        self._repo_ttl = repo_ttl if repo_ttl is not None else \
            env_seconds("SEMMERGE_SERVICE_TTL", 600.0)
        self._events_path = events_path
        self._recorder: Optional[obs_spans.SpanRecorder] = None
        self._stop = threading.Event()
        self._locks_lock = threading.Lock()
        self._repo_locks: Dict[str, Dict[str, Any]] = {}
        self._state_lock = threading.Lock()
        self._in_flight = 0
        self._served = 0
        self._last_activity = time.monotonic()
        self._t0 = time.time()
        # Admission control / load shedding state (see runbook,
        # "Overload & self-healing").
        self._exec_ewma = 0.0  # EWMA of one request's execute seconds
        self._soft_mb, self._hard_mb = resilience.rss_watermarks()
        self._pressure = _PRESSURE_NONE
        self._idem_cap = max(0, _env_int("SEMMERGE_SERVICE_IDEM_CACHE", 256))
        # Idempotency entries older than the TTL are dropped on lookup:
        # a resend after that long is treated as a fresh request (safe —
        # merges are deterministic and --inplace is journal-protected).
        # 0 (the default) keeps the pre-TTL behavior: size-only LRU.
        self._idem_ttl = max(0.0, env_seconds("SEMMERGE_SERVICE_IDEM_TTL",
                                              0.0))
        self._idem_lock = threading.Lock()
        self._idem: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        # Draining: admission closed (new requests get a *retryable*
        # typed rejection), in-flight work finishes. Set by the `drain`
        # wire verb (fleet handoff) and by the signal handler.
        self._draining = False
        self._fleet_member = os.environ.get("SEMMERGE_FLEET_MEMBER") or None
        self._telemetry: Optional[telemetry.TelemetryServer] = None
        # SLO engine: SEMMERGE_SLO env wins, then the [slo] config
        # table found from the daemon's cwd; None = no objectives, no
        # per-request overhead. A malformed spec raises here — at
        # startup, visibly — instead of silently serving unmonitored.
        cfg_objectives = cfg_fast = cfg_slow = None
        try:
            from ..config import load_config
            cfg = load_config()
            cfg_objectives = cfg.slo.objectives
            cfg_fast, cfg_slow = cfg.slo.fast_window_s, cfg.slo.slow_window_s
        except obs_slo.SloParseError:
            raise
        except Exception:
            pass  # unreadable config: env-only SLO setup still applies
        self._slo = obs_slo.from_env(cfg_objectives,
                                     config_fast_window=cfg_fast,
                                     config_slow_window=cfg_slow)
        # One capture at a time: the JAX profiler session is
        # process-global (runtime.trace), so concurrent `profile`
        # requests would corrupt each other.
        self._profile_lock = threading.Lock()
        self._autoprofiled = False
        # Telemetry pipeline (PR 20): windowed rollups feed /metrics and
        # the status `window` block; the sampling policy mints one
        # keep/drop verdict per terminal outcome (propagated in wire
        # meta); the anomaly bank escalates sustained per-phase
        # regressions into triage bundles. The trace store is only
        # live when SEMMERGE_TRACE_DIR points somewhere.
        self._window = obs_agg.WindowAggregator()
        self._sampler = obs_sampling.SamplingPolicy(
            minted_by=self._fleet_member or "daemon")
        self._anomaly = obs_anomaly.AnomalyTriage()
        self._trace_store = obs_sampling.TraceStore.from_env()

    # ------------------------------------------------------------------
    # lifecycle

    def serve_forever(self) -> int:
        self._configure_process_env()
        sock = self._bind()
        if sock is None:
            # A live daemon already owns the socket: not an error —
            # whoever raced us to it serves the requests.
            print(f"semmerge serve: daemon already running on "
                  f"{self._socket_path}")
            return 0
        if self._events_path:
            self._recorder = obs_spans.SpanRecorder()
            obs_spans.activate(self._recorder)
        self._install_stdio_router()
        self._install_signal_handlers()
        from ..utils.jaxenv import enable_compile_cache
        enable_compile_cache()
        # Continuous batching: one process-global micro-batch scheduler
        # coalesces concurrent requests' fused dispatches. Activated
        # only here — one-shot CLI processes never batch. The [engine]
        # mesh posture rides along so the dispatcher can shard the
        # packed merge axis across the host's chips (SEMMERGE_MESH
        # still wins inside mesh_posture).
        from .. import batch
        from ..config import load_config
        from ..parallel.mesh import mesh_posture
        try:
            mesh_cfg = load_config().engine.mesh
        except Exception:  # config errors surface per request, not here
            mesh_cfg = None
        batch.activate(mesh=mesh_cfg)
        import jax
        logger.info("batch dispatch mesh posture: %s (%d local device(s))",
                    mesh_posture(mesh_cfg), len(jax.devices()))
        for _ in range(self._workers_n):
            threading.Thread(target=self._executor, daemon=True).start()
        if self._repo_ttl > 0:
            threading.Thread(target=self._reaper, daemon=True).start()
        if self._soft_mb > 0 or self._hard_mb > 0:
            threading.Thread(target=self._pressure_monitor,
                             daemon=True).start()
        if self._slo is not None:
            threading.Thread(target=self._slo_monitor,
                             daemon=True).start()
            logger.info("SLO engine active: %s",
                        "; ".join(c.text for c in self._slo.clauses))
        self._telemetry = telemetry.maybe_start(self.status,
                                                self._render_metrics)
        if self._telemetry is not None:
            logger.info("telemetry listening on 127.0.0.1:%d "
                        "(/metrics, /healthz)", self._telemetry.port)
        if self._join_addr:
            threading.Thread(target=self._join_loop, daemon=True,
                             name="svc-fleet-join").start()
        logger.info("merge service listening on %s (%d workers, queue %d)",
                    self._socket_path, self._workers_n, self._queue.maxsize)
        try:
            self._accept_loop(sock)
        finally:
            self._teardown(sock)
        return 0

    def _configure_process_env(self) -> None:
        """The daemon's own process posture: never self-delegate, keep
        normal GC cadence (``utils/gctune``: freezing per-request
        garbage into the permanent generation would leak it), share one
        supervised subprocess worker across requests."""
        os.environ["_SEMMERGE_IN_DAEMON"] = "1"
        os.environ["SEMMERGE_DAEMON"] = "off"
        os.environ["SEMMERGE_GC_TUNE"] = "0"
        os.environ["SEMMERGE_WORKER_KEEPALIVE"] = "1"

    def _bind(self) -> Optional[socket.socket]:
        path = self._socket_path
        if fleet_transport.is_tcp(path):
            try:
                sock = fleet_transport.listen(path)
            except OSError:
                # Port taken: a live daemon already serving there is
                # the same "whoever raced us serves" outcome as the
                # unix path; anything else is a real bind error.
                probe = fleet_transport.dial(path, timeout=2.0)
                if probe is not None:
                    with contextlib.suppress(OSError):
                        probe.close()
                    return None
                raise
            # An ephemeral :0 bind resolves here so logs, status, and
            # the join announce all advertise something dialable.
            self._socket_path = fleet_transport.bound_address(sock, path)
            return sock
        if os.path.exists(path):
            probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            probe.settimeout(2.0)
            try:
                probe.connect(path)
            except OSError:
                # Nothing listening: a dead daemon's leftover. Replace.
                logger.warning("replacing stale service socket %s", path)
                with contextlib.suppress(OSError):
                    os.unlink(path)
            else:
                probe.close()
                return None
            finally:
                with contextlib.suppress(OSError):
                    probe.close()
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.bind(path)
        with contextlib.suppress(OSError):
            os.chmod(path, 0o600)
        sock.listen(64)
        return sock

    def _install_stdio_router(self) -> None:
        if not isinstance(sys.stdout, _ThreadTee):
            sys.stdout = _ThreadTee(sys.stdout)
        if not isinstance(sys.stderr, _ThreadTee):
            sys.stderr = _ThreadTee(sys.stderr)

    def _install_signal_handlers(self) -> None:
        def _on_signal(signum, frame):
            logger.info("signal %d: draining and shutting down", signum)
            self._draining = True
            self._stop.set()
        try:
            signal.signal(signal.SIGTERM, _on_signal)
            signal.signal(signal.SIGINT, _on_signal)
        except ValueError:
            pass  # not the main thread (embedded/test use)

    def _accept_loop(self, sock: socket.socket) -> None:
        sock.settimeout(0.5)
        while not self._stop.is_set():
            try:
                conn, _addr = sock.accept()
            except socket.timeout:
                self._maybe_idle_exit()
                continue
            except ssl.SSLError:
                # One client's failed TLS handshake (no cert under
                # mTLS, plaintext against a TLS listener) must not
                # stop the accept loop.
                continue
            except OSError:
                break
            threading.Thread(target=self._handle_conn, args=(conn,),
                             daemon=True).start()

    def _maybe_idle_exit(self) -> None:
        if self._idle_exit <= 0:
            return
        with self._state_lock:
            busy = self._in_flight > 0
        if busy or not self._queue.empty():
            return
        if time.monotonic() - self._last_activity > self._idle_exit:
            logger.info("idle for %.0fs: exiting", self._idle_exit)
            self._stop.set()

    def _teardown(self, sock: socket.socket) -> None:
        # Socket handoff: close + unlink FIRST, then drain — a
        # supervisor's replacement daemon can bind the path while this
        # process finishes its in-flight work, so new requests land on
        # the replacement instead of racing the shutdown. Clients
        # already connected keep their established connections.
        with contextlib.suppress(OSError):
            sock.close()
        if not fleet_transport.is_tcp(self._socket_path):
            with contextlib.suppress(OSError):
                os.unlink(self._socket_path)
        if self._join_addr and self._joined_as:
            # Deliberate departure: tell the router so the ring update
            # is a "leave" (draining), not a heartbeat-timeout eject.
            with contextlib.suppress(Exception):
                fleet_transport.call(
                    self._join_addr, "leave",
                    {"member": self._joined_as}, timeout=2.0, retries=0)
        drain = env_seconds("SEMMERGE_SERVICE_DRAIN_TIMEOUT", 30.0)
        deadline = time.monotonic() + drain if drain > 0 else None
        while True:
            with self._state_lock:
                busy = self._in_flight > 0
            if not busy and self._queue.empty():
                break
            if deadline is not None and time.monotonic() > deadline:
                logger.warning("drain timeout: abandoning in-flight work")
                break
            time.sleep(0.05)
        from .. import batch
        batch.deactivate()
        from ..backends.subproc import shutdown_shared
        shutdown_shared()
        if self._telemetry is not None:
            self._telemetry.stop()
        if self._recorder is not None:
            obs_spans.deactivate(self._recorder)
            with contextlib.suppress(OSError):
                self._recorder.write_jsonl(pathlib.Path(self._events_path))
        # Flush diagnostics inside the drain handler: the
        # ``SEMMERGE_METRICS`` atexit hook does not fire reliably on
        # signal-initiated shutdowns (and never on a supervisor
        # respawn's SIGTERM), so a drained daemon writes its registry —
        # and, when a postmortem directory is configured, its flight
        # ring — here, where the shutdown path is guaranteed to pass.
        metrics_path = os.environ.get("SEMMERGE_METRICS")
        if metrics_path:
            with contextlib.suppress(OSError):
                obs_metrics.dump(metrics_path)
        if os.environ.get(obs_flight.ENV_DIR):
            obs_flight.dump(None, "daemon-drain",
                            breakers=resilience.breakers().snapshot())
        logger.info("merge service stopped (%d requests served)",
                    self._served)

    # ------------------------------------------------------------------
    # connection handling (one thread per client connection)

    def _handle_conn(self, conn: socket.socket) -> None:
        rfile = conn.makefile("r", encoding="utf-8")
        wfile = conn.makefile("w", encoding="utf-8")
        try:
            while True:
                msg = protocol.read_message(rfile)
                if msg is None:
                    break
                self._last_activity = time.monotonic()
                req_id = msg.get("id")
                method = msg.get("method")
                params = msg.get("params") or {}
                if method == "hello":
                    # The hello doubles as the fleet heartbeat: the
                    # router's health probe reads `draining` off it to
                    # tell a deliberate departure from a failure, so it
                    # is always present — router-spawned, self-joined,
                    # and standalone daemons alike.
                    hello = {"ok": True, "pid": os.getpid(),
                             "version": protocol.PROTOCOL_VERSION,
                             "draining": self._draining}
                    member = self._fleet_member or self._joined_as
                    if member is not None:
                        hello["fleet_member"] = member
                    protocol.write_message(wfile,
                                           {"id": req_id, "result": hello})
                    continue
                if method == "drain":
                    # Fleet handoff: close admission but keep serving
                    # in-flight and queued work. New requests get a
                    # retryable typed rejection so clients re-route.
                    self._draining = True
                    with self._state_lock:
                        in_flight = self._in_flight
                    protocol.write_message(wfile, {
                        "id": req_id,
                        "result": {"ok": True, "draining": True,
                                   "in_flight": in_flight,
                                   "queue_depth": self._queue.qsize()}})
                    continue
                if method == "status":
                    protocol.write_message(wfile,
                                           {"id": req_id,
                                            "result": self.status()})
                    continue
                if method == "metrics":
                    # Live telemetry without waiting for process exit:
                    # the same payloads the HTTP listener serves.
                    protocol.write_message(wfile, {
                        "id": req_id,
                        "result": {
                            "prometheus":
                                obs_metrics.REGISTRY.render_prometheus(),
                            "metrics": obs_metrics.REGISTRY.to_dict(),
                            "health": self.status(),
                        }})
                    continue
                if method == "profile":
                    # Blocks this connection thread for the capture
                    # window; merge traffic keeps flowing through the
                    # executor pool meanwhile — that traffic is what
                    # the capture is *of*.
                    protocol.write_message(wfile, {
                        "id": req_id,
                        "result": self._capture_profile(params)})
                    continue
                if method == "prewarm":
                    # Incremental affinity handoff: the router warms a
                    # rehashed repo key onto its new owner before real
                    # traffic lands there. Deliberately cheap — resolve
                    # the repo's HEAD tree (priming the OS page cache
                    # over .git) without touching jax or the decl
                    # cache; the first real request pays the rest.
                    protocol.write_message(wfile, {
                        "id": req_id,
                        "result": self._prewarm(params)})
                    continue
                if method == "shutdown":
                    protocol.write_message(wfile,
                                           {"id": req_id,
                                            "result": {"ok": True}})
                    self._stop.set()
                    break
                if method not in protocol.VERBS:
                    protocol.write_message(wfile, {
                        "id": req_id,
                        "error": {"message": f"unknown method {method!r}"}})
                    continue
                self._serve_request(req_id, method, params, wfile)
        except (protocol.ProtocolError, OSError, ValueError):
            pass  # client went away or spoke garbage: drop the connection
        finally:
            with contextlib.suppress(OSError):
                conn.close()

    def _serve_request(self, req_id, verb: str, params: Dict[str, Any],
                       wfile) -> None:
        req = _Request(req_id, verb, params)
        with obs_spans.request_scope(req.trace_id, req.recorder), \
                reqenv.overlay(req.env):
            cached = self._idem_lookup(req)
            if cached is not None:
                # A retried request whose first execution completed:
                # answer from the idempotency cache — never re-execute.
                self._count_request(verb, "replayed")
                protocol.write_message(wfile, cached)
                return
            try:
                with obs_spans.span("service.accept", layer="service",
                                    verb=verb), \
                        fault_boundary("service:accept"):
                    faults.check("service:accept")
                    self._admit(req)
            except MergeFault as fault:
                self._count_request(verb, "rejected")
                if self._slo is not None:
                    # Shed work never ran, but the client still saw an
                    # error — it burns the error budget at zero latency.
                    self._slo.observe(verb, 0.0, error=True)
                protocol.write_message(wfile, {
                    "id": req.id,
                    "error": protocol.fault_error(
                        fault,
                        retry_after_ms=self._retry_after_for(fault),
                        trace_id=req.trace_id)})
                return
        self._publish_queue_depth()
        req.done.wait()
        self._last_activity = time.monotonic()
        if req.response is not None:
            protocol.write_message(wfile, req.response)

    #: Rejection causes a client may retry against this daemon after
    #: ``retry_after_ms`` — transient overload, not request-shaped
    #: failures.
    _RETRYABLE_CAUSES = frozenset(
        {"queue-full", "overload", "projected-deadline", "draining"})

    def _admit(self, req: _Request) -> None:
        """Admission control, cheapest checks first: hard-watermark
        pressure sheds everything, soft pressure sheds work that will
        not batch (batched work amortizes device cost; inline work
        pays full price at the worst time), a projected queue wait
        past the request deadline is rejected up front instead of
        timing out in the queue, and finally the bounded queue itself."""
        if self._draining:
            self._shed("draining")
            raise WorkerFault(
                "daemon is draining: admission closed",
                stage="service:accept", cause="draining")
        if self._pressure >= _PRESSURE_HARD:
            self._shed("rss-hard")
            raise WorkerFault(
                f"shedding load: RSS above the {self._hard_mb:g} MiB "
                f"hard watermark", stage="service:accept",
                cause="overload")
        if self._pressure >= _PRESSURE_SOFT and not _request_batches():
            self._shed("rss-soft")
            raise WorkerFault(
                f"shedding non-batched work: RSS above the "
                f"{self._soft_mb:g} MiB soft watermark",
                stage="service:accept", cause="overload")
        projected = self._projected_wait()
        if req.deadline_s and projected > req.deadline_s:
            self._shed("projected-deadline")
            raise DeadlineFault(
                f"projected queue wait {projected:.2f}s exceeds the "
                f"{req.deadline_s:g}s deadline",
                stage="service:accept", cause="projected-deadline")
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            raise WorkerFault(
                f"admission queue full "
                f"({self._queue.maxsize} waiting)",
                stage="service:accept", cause="queue-full")

    def _projected_wait(self) -> float:
        """Expected queue wait for a request admitted now: queue depth
        × the EWMA of execute time, spread over the worker pool."""
        with self._state_lock:
            ewma = self._exec_ewma
        if ewma <= 0:
            return 0.0
        return self._queue.qsize() * ewma / max(1, self._workers_n)

    def _retry_after_ms(self) -> int:
        """How long a rejected client should wait before retrying:
        the projected drain time of the current queue, clamped to
        [100 ms, 5 s]."""
        with self._state_lock:
            ewma = self._exec_ewma
        per_slot = ewma if ewma > 0 else 0.25
        projected = ((self._queue.qsize() + 1) * per_slot
                     / max(1, self._workers_n))
        return int(min(max(projected * 1000.0, 100.0), 5000.0))

    def _retry_after_for(self, fault: MergeFault) -> Optional[int]:
        if getattr(fault, "cause", None) not in self._RETRYABLE_CAUSES:
            return None
        return self._retry_after_ms()

    def _shed(self, reason: str) -> None:
        obs_metrics.REGISTRY.counter(
            "service_shed_total", _SHED_HELP).inc(1, reason=reason)

    # -- idempotency cache -------------------------------------------------

    def _idem_lookup(self, req: _Request) -> Optional[Dict[str, Any]]:
        if not req.idem_key or not self._idem_cap:
            return None
        with self._idem_lock:
            entry = self._idem.get(req.idem_key)
            if entry is None:
                return None
            if self._idem_ttl > 0 and \
                    time.monotonic() - entry["t"] > self._idem_ttl:
                # Expired: the resend re-executes as a fresh request —
                # safe (deterministic merges; --inplace is protected by
                # the commit journal + repo lockfile), and it frees the
                # slot instead of replaying arbitrarily stale output.
                del self._idem[req.idem_key]
                return None
            self._idem.move_to_end(req.idem_key)
            cached = entry["response"]
        obs_metrics.REGISTRY.counter(
            "service_idempotent_replays_total", _IDEM_HELP).inc(1)
        resp = dict(cached)
        resp["id"] = req.id
        return resp

    def _idem_store(self, req: _Request) -> None:
        if not req.idem_key or not self._idem_cap or req.response is None:
            return
        with self._idem_lock:
            self._idem[req.idem_key] = {"response": req.response,
                                        "t": time.monotonic()}
            self._idem.move_to_end(req.idem_key)
            while len(self._idem) > self._idem_cap:
                self._idem.popitem(last=False)

    # ------------------------------------------------------------------
    # execution (executor thread pool)

    def _executor(self) -> None:
        while True:
            try:
                req = self._queue.get(timeout=0.3)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            self._publish_queue_depth()
            with self._state_lock:
                self._in_flight += 1
            try:
                self._execute(req)
            finally:
                self._idem_store(req)
                with self._state_lock:
                    self._in_flight -= 1
                    self._served += 1
                self._last_activity = time.monotonic()
                req.done.set()

    def _execute(self, req: _Request) -> None:
        verb = req.verb
        queue_wait = time.monotonic() - req.t_accept
        outcome = "fault"
        with obs_spans.request_scope(req.trace_id, req.recorder), \
                reqenv.overlay(req.env):
            obs_spans.record("service.queue_wait", queue_wait,
                             layer="service", verb=verb)
            try:
                if req.deadline_s and queue_wait > req.deadline_s:
                    raise DeadlineFault(
                        f"request waited {queue_wait:.3f}s past its "
                        f"{req.deadline_s:g}s deadline",
                        stage="service:dispatch", cause="deadline")
                with fault_boundary("service:dispatch"):
                    faults.check("service:dispatch")
                with self._repo_lock_for(req):
                    code, out, err, t_start, t_end = self._run_cli(req)
                duration = t_end - t_start
                with self._state_lock:
                    self._exec_ewma = (
                        duration if self._exec_ewma <= 0
                        else 0.3 * duration + 0.7 * self._exec_ewma)
                outcome = _OUTCOME_BY_EXIT.get(code, f"exit-{code}")
                req.response = {
                    "id": req.id,
                    "result": {
                        "exit_code": code,
                        "stdout": out,
                        "stderr": err,
                        "meta": {
                            "pid": os.getpid(),
                            "queue_wait_s": round(queue_wait, 6),
                            "t_execute_start": t_start,
                            "t_execute_end": t_end,
                            "trace_id": req.trace_id,
                        },
                    },
                }
                if self._fleet_member is not None and os.environ.get(
                        "SEMMERGE_FLEET_STITCH", "on").strip() != "off":
                    # Fleet member: ship this request's span tree (the
                    # member's service/engine/worker spans) back over
                    # the wire so the router can graft it into the one
                    # stitched tree per trace_id.
                    req.response["result"]["meta"]["spans"] = \
                        req.recorder.span_dicts()
                obs_metrics.REGISTRY.histogram(
                    "service_request_seconds", _LATENCY_HELP).observe(
                        queue_wait + duration, exemplar=req.trace_id,
                        verb=verb)
                if self._slo is not None:
                    # Conflicts/typecheck exits are request-shaped
                    # answers, not service errors — only faults and
                    # unexpected exit codes burn the error budget.
                    self._slo.observe(
                        verb, queue_wait + duration,
                        error=outcome not in ("ok", "conflicts",
                                              "typecheck"))
            except MergeFault as fault:
                req.response = {"id": req.id,
                                "error": protocol.fault_error(
                                    fault, trace_id=req.trace_id)}
                if self._slo is not None:
                    self._slo.observe(
                        verb, time.monotonic() - req.t_accept,
                        error=True)
            finally:
                from ..frontend.declcache import publish_metrics
                publish_metrics()
                self._count_request(verb, outcome)
                self._finish_telemetry(req, verb, outcome, queue_wait)
                if self._recorder is not None:
                    # --events: graft the request's scoped spans into
                    # the daemon-lifetime recorder, tagged by trace_id,
                    # so the events artifact still covers everything.
                    self._recorder.absorb(req.recorder,
                                          trace_id=req.trace_id)
                if self._fleet_member is None:
                    # Standalone daemon: export this request's trace
                    # directly (fleet members ship spans to the router
                    # instead — the stitched tree is exported once).
                    exporter = obs_export.maybe_exporter()
                    if exporter is not None:
                        exporter.export_trace(req.trace_id,
                                              req.recorder.span_dicts())

    def _run_cli(self, req: _Request):
        """The actual CLI invocation: ``service.execute`` span, request
        working-dir scope, per-thread stdout/stderr capture. The span
        opens AFTER the per-repo lock is held, so two same-repo
        requests' execute windows never overlap — the serialization
        test asserts exactly that."""
        out_buf, err_buf = io.StringIO(), io.StringIO()
        routed = isinstance(sys.stdout, _ThreadTee) and \
            isinstance(sys.stderr, _ThreadTee)
        if routed:
            sys.stdout.push(out_buf)
            sys.stderr.push(err_buf)
        t_start = time.monotonic()
        try:
            with obs_spans.span("service.execute", layer="service",
                                verb=req.verb), \
                    fault_boundary("service:execute"), \
                    workdir.scoped(req.cwd):
                faults.check("service:execute")
                from ..cli import main as cli_main
                try:
                    code = cli_main([req.verb, *req.argv])
                except SystemExit as exc:  # argparse usage errors
                    code = exc.code if isinstance(exc.code, int) else 2
        finally:
            t_end = time.monotonic()
            if routed:
                sys.stdout.pop()
                sys.stderr.pop()
        return code, out_buf.getvalue(), err_buf.getvalue(), t_start, t_end

    def _finish_telemetry(self, req: _Request, verb: str, outcome: str,
                          queue_wait: float) -> None:
        """Terminal-outcome telemetry: mint the sampling verdict,
        attach it to wire ``meta``, feed the window rollups and the
        anomaly bank, and persist kept traces. Runs inside the
        request's scope finally-block; must never raise."""
        try:
            total_s = time.monotonic() - req.t_accept
            rows = req.recorder.span_dicts()
            phases: Dict[str, float] = {}
            for row in rows:
                name = str(row.get("name") or "?")
                try:
                    phases[name] = phases.get(name, 0.0) + \
                        float(row.get("seconds") or 0.0)
                except (TypeError, ValueError):
                    continue
            flags = obs_sampling.outcome_flags(rows)
            error_flag = flags["error"] or outcome not in (
                "ok", "conflicts", "typecheck")
            decision = self._sampler.decide(
                req.trace_id, verb, total_s, error=error_flag,
                degraded=flags["degraded"], breaker=flags["breaker"],
                resolver=flags["resolver"])
            self._window.observe(verb, total_s, error=error_flag,
                                 phases=phases)
            self._anomaly.observe(
                req.trace_id, verb, phases, seconds=total_s,
                spans=rows if rows else None, root=req.cwd)
            if isinstance(req.response, dict) and \
                    isinstance(req.response.get("result"), dict):
                req.response["result"].setdefault("meta", {})[
                    obs_sampling.META_KEY] = decision.to_meta()
            if decision.keep and self._trace_store is not None:
                self._trace_store.write(req.trace_id, {
                    "schema": 1,
                    "kind": "trace",
                    "trace_id": req.trace_id,
                    "verb": verb,
                    "outcome": outcome,
                    "seconds": round(total_s, 6),
                    "queue_wait_s": round(queue_wait, 6),
                    "spans": rows,
                }, decision=decision)
        except Exception:
            logger.debug("telemetry pipeline error", exc_info=True)

    def _render_metrics(self) -> str:
        """Live ``/metrics`` exposition with the window gauges freshly
        published — scrapes see current-window p50/p99/QPS, not the
        values from the last request."""
        self._window.publish()
        return obs_metrics.REGISTRY.render_prometheus()

    def _repo_lock_for(self, req: _Request):
        """Same-repo ``--inplace`` requests serialize; everything else
        (read-only verbs, different repos) overlaps freely. The lock
        key is the resolved request root — the tree being mutated."""
        if req.verb not in ("semmerge", "semrebase") or \
                "--inplace" not in req.argv:
            return contextlib.nullcontext()
        key = str(pathlib.Path(req.cwd).resolve())
        with self._locks_lock:
            entry = self._repo_locks.setdefault(
                key, {"lock": threading.Lock(), "last": 0.0})
            entry["last"] = time.time()
        return entry["lock"]

    def _pressure_monitor(self) -> None:
        """Sample RSS against the watermarks (1 Hz): publish the
        ``service_rss_mb`` gauge, raise/lower the pressure level, and
        apply the mitigations — shrink the batch in-flight bound while
        under pressure (running batches finish; new ones serialize),
        and clear the decl cache at the hard watermark. Admission-side
        shedding reads ``self._pressure`` (see :meth:`_admit`)."""
        from .. import batch
        from ..frontend.declcache import global_cache
        while not self._stop.wait(1.0):
            rss = _rss_mb()
            obs_metrics.REGISTRY.gauge("service_rss_mb", _RSS_HELP).set(
                round(rss, 3))
            level = _PRESSURE_NONE
            if self._hard_mb > 0 and rss >= self._hard_mb:
                level = _PRESSURE_HARD
            elif self._soft_mb > 0 and rss >= self._soft_mb:
                level = _PRESSURE_SOFT
            if level == self._pressure:
                continue
            prev, self._pressure = self._pressure, level
            logger.warning(
                "memory pressure %d -> %d (rss %.0f MiB, "
                "soft %.0f, hard %.0f)", prev, level, rss,
                self._soft_mb, self._hard_mb)
            sched = batch.current()
            if sched is not None:
                sched.set_inflight_cap(
                    1 if level > _PRESSURE_NONE else sched.max_inflight)
            if level >= _PRESSURE_HARD:
                cache = global_cache()
                if cache is not None:
                    cache.clear()
                # Resident encoded snapshots are the other large host
                # allocation this process owns outright — drop them too.
                residency_mod.cache().clear(reason="rss-hard")

    def _slo_monitor(self) -> None:
        """Evaluate the SLO engine on a fixed cadence
        (``SEMMERGE_SLO_EVAL_INTERVAL``), publishing the burn-rate
        gauges. On the edge of a trip (both windows at/above the
        threshold): log it, dump an ``slo-burn`` postmortem bundle with
        the verdict attached, and — with ``SEMMERGE_SLO_AUTOPROFILE``
        set — capture one profile bundle for the first trip of the
        daemon's life (one, not per trip: a burning daemon must spend
        its cycles serving, not profiling)."""
        interval = max(0.1, obs_slo._env_float(
            obs_slo.ENV_EVAL_INTERVAL, obs_slo.DEFAULT_EVAL_INTERVAL))
        autoprofile = os.environ.get(
            obs_slo.ENV_AUTOPROFILE, "").strip().lower() \
            not in ("", "0", "off", "false")
        while not self._stop.wait(interval):
            try:
                verdict = self._slo.evaluate(consume_edges=True)
            except Exception:
                continue  # evaluation must never kill the monitor
            newly = verdict.get("newly_tripped") or []
            if not newly:
                continue
            logger.warning(
                "SLO burn: %s",
                "; ".join(f"{r['objective']} (fast {r['burn_fast']}x, "
                          f"slow {r['burn_slow']}x)" for r in newly))
            obs_flight.dump(None, "slo-burn",
                            breakers=resilience.breakers().snapshot(),
                            extra={"slo": verdict})
            if autoprofile and not self._autoprofiled:
                self._autoprofiled = True
                threading.Thread(
                    target=self._capture_profile,
                    args=({"seconds": 1.0},),
                    name="svc-autoprofile", daemon=True).start()

    def _capture_profile(self, params: Dict[str, Any]) -> Dict[str, Any]:
        """On-demand profile capture from the live daemon: a bounded
        JAX profiler window over whatever traffic flows during it,
        plus a metrics before/after delta, the flight-ring span
        sample, and the SLO verdict, written into a timestamped
        bundle directory. Serialized by ``_profile_lock`` — the
        profiler session is process-global, and a second concurrent
        ``start_trace`` would poison it."""
        try:
            seconds = float(params.get("seconds") or 1.0)
        except (TypeError, ValueError):
            seconds = 1.0
        seconds = min(60.0, max(0.1, seconds))
        out_base = str(params.get("out_dir") or "").strip() \
            or os.environ.get("SEMMERGE_PROFILE_DIR", "").strip()
        if not out_base:
            import tempfile
            out_base = os.path.join(tempfile.gettempdir(),
                                    "semmerge-profiles")
        captures = obs_metrics.REGISTRY.counter(
            "profile_captures_total",
            "On-demand daemon profile captures, by result")
        if not self._profile_lock.acquire(blocking=False):
            captures.inc(1, result="busy")
            return {"ok": False,
                    "error": "a profile capture is already in progress"}
        try:
            from ..runtime import trace as rt_trace
            stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
            bundle_dir = pathlib.Path(out_base) / (
                f"profile-{stamp}-{os.getpid()}-{os.urandom(2).hex()}")
            bundle_dir.mkdir(parents=True, exist_ok=True)
            before = obs_metrics.REGISTRY.to_dict()
            t0 = time.time()
            started = rt_trace.start_profiler_session(str(bundle_dir))
            # The capture window: sample whatever the daemon serves
            # meanwhile (interruptible so shutdown never waits on it).
            self._stop.wait(seconds)
            if started:
                rt_trace.stop_profiler_session()
            bundle = {
                "schema": 1,
                "ok": True,
                "profiler_started": started,
                "seconds": seconds,
                "t_start": round(t0, 3),
                "t_end": round(time.time(), 3),
                "pid": os.getpid(),
                "metrics_before": before,
                "metrics_after": obs_metrics.REGISTRY.to_dict(),
                "spans": obs_flight.snapshot(),
                "slo": (self._slo.status()
                        if self._slo is not None else None),
            }
            (bundle_dir / "bundle.json").write_text(
                json.dumps(bundle, indent=2, default=str),
                encoding="utf-8")
            files = sorted(str(p.relative_to(bundle_dir))
                           for p in bundle_dir.rglob("*") if p.is_file())
            captures.inc(1, result="ok")
            return {"ok": True, "dir": str(bundle_dir),
                    "profiler_started": started, "seconds": seconds,
                    "files": files}
        except Exception as exc:  # capture failure must not kill the conn
            captures.inc(1, result="error")
            return {"ok": False,
                    "error": f"{type(exc).__name__}: {exc}"}
        finally:
            self._profile_lock.release()

    def _prewarm(self, params: Dict[str, Any]) -> Dict[str, Any]:
        """Warm a repo key this daemon just became the owner of: one
        ``git rev-parse`` against the repo, bounded and contained —
        a prewarm failure is an answer, never a fault."""
        cwd = str(params.get("cwd") or "").strip()
        if not cwd or not os.path.isdir(cwd):
            return {"ok": False, "cwd": cwd, "error": "no such directory"}
        import subprocess
        try:
            proc = subprocess.run(
                ["git", "-C", cwd, "rev-parse", "HEAD^{tree}"],
                capture_output=True, text=True, timeout=10.0)
        except (OSError, subprocess.SubprocessError) as exc:
            return {"ok": False, "cwd": cwd,
                    "error": f"{type(exc).__name__}: {exc}"}
        if proc.returncode != 0:
            return {"ok": False, "cwd": cwd,
                    "error": (proc.stderr or "").strip()[:200]}
        with self._locks_lock:
            entry = self._repo_locks.setdefault(
                cwd, {"lock": threading.Lock(), "last": 0.0})
            entry["last"] = time.time()
        return {"ok": True, "cwd": cwd,
                "tree_oid": proc.stdout.strip()}

    def _join_loop(self) -> None:
        """Announce this daemon to the fleet router, then keep
        re-announcing — the re-announce is also the rejoin path after
        a partition-eject (the router resets the member's fail streak
        and puts it back in the ring). Draining suppresses the
        announce so a deliberate departure never looks alive-again."""
        advertise = self._advertise or self._socket_path
        interval = max(0.2, env_seconds("SEMMERGE_FLEET_JOIN_INTERVAL",
                                        5.0))
        while True:
            if not self._draining:
                self._join_epoch += 1
                params = {"address": advertise,
                          "capacity": self._capacity,
                          "epoch": self._join_epoch}
                if self._member_id:
                    params["member"] = self._member_id
                elif self._joined_as:
                    params["member"] = self._joined_as
                result = fleet_transport.call(
                    self._join_addr, "join", params,
                    timeout=fleet_transport.connect_timeout(),
                    retries=0)
                if result and result.get("ok"):
                    member = str(result.get("member") or "")
                    if member and member != self._joined_as:
                        self._joined_as = member
                        logger.info(
                            "joined fleet %s as member %s "
                            "(advertising %s)", self._join_addr,
                            member, advertise)
                elif result is not None:
                    logger.warning("fleet join rejected: %s",
                                   result.get("error"))
            if self._stop.wait(interval):
                return

    def _reaper(self) -> None:
        """Evict per-repo state idle past the TTL."""
        interval = max(1.0, min(self._repo_ttl / 2.0, 60.0))
        while not self._stop.wait(interval):
            cutoff = time.time() - self._repo_ttl
            with self._locks_lock:
                for key in [k for k, e in self._repo_locks.items()
                            if e["last"] < cutoff
                            and not e["lock"].locked()]:
                    del self._repo_locks[key]

    # ------------------------------------------------------------------
    # introspection

    def _count_request(self, verb: str, outcome: str) -> None:
        obs_metrics.REGISTRY.counter(
            "service_requests_total", _REQUESTS_HELP).inc(
                1, verb=verb, outcome=outcome)

    def _publish_queue_depth(self) -> None:
        obs_metrics.REGISTRY.gauge(
            "service_queue_depth", _QUEUE_DEPTH_HELP).set(
                self._queue.qsize())

    def status(self) -> Dict[str, Any]:
        from ..frontend.declcache import global_cache
        cache = global_cache()
        decl = cache.stats() if cache is not None else {}
        hits = decl.get("hits", 0)
        lookups = hits + decl.get("misses", 0)
        with self._state_lock:
            in_flight, served = self._in_flight, self._served
        from .. import batch
        scheduler = batch.current()
        return {
            "ok": True,
            "pid": os.getpid(),
            "version": protocol.PROTOCOL_VERSION,
            "socket": self._socket_path,
            "uptime_s": round(time.time() - self._t0, 3),
            "queue_depth": self._queue.qsize(),
            "in_flight": in_flight,
            "served_total": served,
            "workers": self._workers_n,
            "draining": self._draining,
            "fleet_member": self._fleet_member or self._joined_as,
            "fleet_join": ({"router": self._join_addr,
                            "advertise": (self._advertise
                                          or self._socket_path),
                            "capacity": self._capacity,
                            "joined_as": self._joined_as,
                            "announces": self._join_epoch}
                           if self._join_addr else None),
            "transport": ("tcp+tls" if fleet_transport.is_tcp(
                self._socket_path) and fleet_transport.tls_enabled()
                else "tcp" if fleet_transport.is_tcp(self._socket_path)
                else "unix"),
            "repos_tracked": len(self._repo_locks),
            "rss_mb": round(_rss_mb(), 3),
            "metrics_port": (self._telemetry.port
                             if self._telemetry is not None else None),
            "declcache": decl,
            "declcache_hit_rate": (hits / lookups) if lookups else 0.0,
            "batch": scheduler.stats() if scheduler is not None else None,
            "residency": residency_mod.cache().stats(),
            "slo": self._slo.status() if self._slo is not None else None,
            "window": self._window.window(),
            "sampling": self._sampler.stats(),
            "anomaly": self._anomaly.stats(),
            "trace_store": (self._trace_store.stats()
                            if self._trace_store is not None else None),
            "resilience": {
                "pressure": self._pressure,
                "rss_soft_mb": self._soft_mb,
                "rss_hard_mb": self._hard_mb,
                "exec_ewma_s": round(self._exec_ewma, 6),
                "projected_wait_s": round(self._projected_wait(), 6),
                "idempotency_cached": len(self._idem),
                "breakers": resilience.breakers().snapshot(),
            },
            "metrics": obs_metrics.REGISTRY.to_dict(),
        }


def main(argv=None) -> int:  # pragma: no cover - thin alias
    """``python -m semantic_merge_tpu.service.daemon`` convenience."""
    import argparse
    parser = argparse.ArgumentParser(prog="semmerge-daemon")
    parser.add_argument("--socket", default=None)
    parser.add_argument("--join", default=None)
    parser.add_argument("--advertise", default=None)
    parser.add_argument("--capacity", type=int, default=None)
    parser.add_argument("--member-id", default=None)
    args = parser.parse_args(argv)
    return Daemon(socket_path=args.socket, join=args.join,
                  advertise=args.advertise, capacity=args.capacity,
                  member_id=args.member_id).serve_forever()


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
