"""Warm snapshot residency: encoded-base reuse across daemon requests.

A merge daemon serving a repository sees the same *base* tree over and
over — every merge of a feature branch against ``main`` re-ships the
identical base snapshot, and the PR-2 pipeline re-pays ``scan_encode``
(+ the transitive h2d of the decl columns) for it on every request.
This module keeps the encoded form *resident*: a process-global,
byte-bounded LRU keyed by ``(repo_root, tree_oid, scope_fp)`` mapping
to the encoded decl tensor, scanned nodes, and the decl-cache identity
under which the fused engine holds the device-resident columns. A hit
skips the scan and the encode entirely, and — because the identity is
reused — the engine's decl-column cache hit skips the h2d re-ship too;
only the changed (delta) side of the merge is encoded.

Keys are *content* addresses (a git tree oid names exact bytes), but
three things can silently invalidate a resident entry, and every
lookup revalidates against all of them:

- **interner reset** (``outcome="stale-interner"``): the backend's
  unbounded-growth guard replaced the interner; every cached id is
  meaningless under the new token.
- **repo GC** (``outcome="stale-tree"``): the tree object is gone from
  the repository (``git cat-file -e`` fails), so nothing can verify
  the entry still describes reachable history — drop it rather than
  serve bytes no ref can reproduce.
- **epoch bump** (``outcome="stale-epoch"``): fleet failover handed
  this member a repo it may have served before under a different
  routing epoch; :func:`bump_epoch` invalidates every resident handle
  so the rehashed member re-encodes from the repository of record.

Posture (``SEMMERGE_RESIDENCY_CACHE``): ``auto`` (default — on inside
the merge service daemon, off in one-shot processes, where a
process-global cache would never see a second request), ``on``,
``off``. Budget: ``SEMMERGE_RESIDENCY_CACHE_MB`` (default 256) bounds
the host-side estimate of resident bytes; the daemon's RSS pressure
monitor additionally clears the cache at the hard watermark
(``reason="rss-hard"``), mirroring how it already drops the engine
decl cache (``service/resilience.py`` owns the watermark knobs).

Telemetry (pinned by ``scripts/check_trace_schema.py
validate_device_render``): ``snapshot_residency_hits_total{outcome}``,
``snapshot_residency_bytes`` gauge,
``snapshot_residency_evictions_total{reason}``, and the
``residency.hit`` / ``residency.encode_delta`` spans recorded at the
lookup seam in ``backends/ts_tpu.py``.
"""
from __future__ import annotations

import hashlib
import os
import subprocess
import threading
from collections import OrderedDict
from typing import Dict, Optional, Tuple

from ..obs import metrics as obs_metrics

#: ``Snapshot.__dict__`` attribute carrying the residency key.
ATTR = "_semmerge_residency"

ENV_POSTURE = "SEMMERGE_RESIDENCY_CACHE"
ENV_BUDGET_MB = "SEMMERGE_RESIDENCY_CACHE_MB"
DEFAULT_BUDGET_MB = 256.0

_HITS_HELP = "Snapshot residency-cache lookups, by outcome"
_BYTES_HELP = "Host-side byte estimate of resident encoded snapshots"
_EVICTIONS_HELP = "Snapshot residency-cache evictions, by reason"

#: Per-node host estimate (scanned decl node + list slot) added to the
#: decl tensor's exact column bytes when budgeting an entry.
_NODE_COST = 160


def residency_enabled() -> bool:
    """``SEMMERGE_RESIDENCY_CACHE`` posture: ``on`` / ``off`` /
    ``auto`` (default — enabled only inside the daemon process)."""
    raw = os.environ.get(ENV_POSTURE, "auto").strip().lower()
    if raw in ("on", "1"):
        return True
    if raw in ("off", "0"):
        return False
    return bool(os.environ.get("_SEMMERGE_IN_DAEMON"))


def budget_bytes() -> int:
    raw = os.environ.get(ENV_BUDGET_MB, "").strip()
    try:
        mb = float(raw) if raw else DEFAULT_BUDGET_MB
    except ValueError:
        mb = DEFAULT_BUDGET_MB
    return max(0, int(mb * 1024 * 1024))


def scope_fingerprint(paths) -> str:
    """Stable fingerprint of an incremental-merge scope. The encoded
    base under a restricted scope is a different tensor than the full
    tree's, so the scope participates in the residency key."""
    if paths is None:
        return ""
    h = hashlib.sha1()
    for p in sorted(paths):
        h.update(p.encode("utf-8", "surrogatepass"))
        h.update(b"\0")
    return h.hexdigest()[:16]


def annotate(snapshot, repo_root: str, tree_oid: str, scope=None) -> None:
    """Attach a residency key to a snapshot object. ``repo_root`` may
    be ``""`` for synthetic snapshots (benches, tests) — the GC
    revalidation is skipped for those, everything else applies."""
    if not tree_oid:
        return
    snapshot.__dict__[ATTR] = (str(repo_root), str(tree_oid),
                               scope_fingerprint(scope))


def resident_key(snapshot) -> Optional[Tuple[str, str, str]]:
    key = snapshot.__dict__.get(ATTR)
    if (isinstance(key, tuple) and len(key) == 3
            and all(isinstance(p, str) for p in key)):
        return key
    return None


class _Entry:
    __slots__ = ("t", "nodes", "identity", "nbytes", "epoch")

    def __init__(self, t, nodes, identity, nbytes: int, epoch: int) -> None:
        self.t = t
        self.nodes = nodes
        self.identity = identity
        self.nbytes = nbytes
        self.epoch = epoch


def entry_nbytes(t, nodes) -> int:
    """Host-side byte estimate of one resident entry: the decl
    tensor's exact column bytes plus a flat per-node cost for the
    scanned node objects."""
    total = 0
    for col in (getattr(t, "sym", None), getattr(t, "addr", None),
                getattr(t, "name", None), getattr(t, "file", None)):
        nb = getattr(col, "nbytes", None)
        if nb is not None:
            total += int(nb)
    return total + _NODE_COST * len(nodes)


def _tree_exists(repo_root: str, tree_oid: str) -> bool:
    try:
        proc = subprocess.run(
            ["git", "-C", repo_root, "cat-file", "-e", tree_oid],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            timeout=10)
        return proc.returncode == 0
    except (OSError, subprocess.SubprocessError):
        return False


class ResidencyCache:
    """Byte-bounded LRU of encoded base snapshots. Thread-safe; every
    lookup outcome and eviction publishes its counter, and the byte
    gauge tracks the resident total."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple[str, str, str], _Entry]" = \
            OrderedDict()
        self._bytes = 0
        self._epoch = 0
        self._lookups = 0
        self._hits = 0
        self._evictions: Dict[str, int] = {}

    # -- metrics ------------------------------------------------------------

    def _count(self, outcome: str) -> None:
        obs_metrics.REGISTRY.counter(
            "snapshot_residency_hits_total", _HITS_HELP).inc(
                1, outcome=outcome)

    def _publish_bytes(self) -> None:
        obs_metrics.REGISTRY.gauge(
            "snapshot_residency_bytes", _BYTES_HELP).set(self._bytes)

    def _evict(self, key, entry, reason: str) -> None:
        """Drop one entry. Caller holds the lock."""
        self._entries.pop(key, None)
        self._bytes -= entry.nbytes
        self._evictions[reason] = self._evictions.get(reason, 0) + 1
        obs_metrics.REGISTRY.counter(
            "snapshot_residency_evictions_total", _EVICTIONS_HELP).inc(
                1, reason=reason)
        self._publish_bytes()

    # -- cache protocol -----------------------------------------------------

    def lookup(self, key: Tuple[str, str, str], *,
               token) -> Optional[_Entry]:
        """The resident entry for ``key``, revalidated, or ``None``.
        ``token`` is the backend interner's current token; entries
        encoded under any other token are dead."""
        repo_root = key[0]
        with self._lock:
            self._lookups += 1
            entry = self._entries.get(key)
            if entry is None:
                self._count("miss")
                return None
            if entry.identity[0] != token:
                self._evict(key, entry, "stale")
                self._count("stale-interner")
                return None
            if entry.epoch != self._epoch:
                self._evict(key, entry, "stale")
                self._count("stale-epoch")
                return None
        # The GC probe shells out to git — never under the lock.
        if repo_root and not _tree_exists(repo_root, key[1]):
            with self._lock:
                cur = self._entries.get(key)
                if cur is entry:
                    self._evict(key, entry, "stale")
            self._count("stale-tree")
            return None
        with self._lock:
            if self._entries.get(key) is not entry:
                self._count("miss")
                return None
            self._entries.move_to_end(key)
            self._hits += 1
        self._count("hit")
        return entry

    def put(self, key: Tuple[str, str, str], t, nodes, identity) -> None:
        if identity is None:
            return
        nbytes = entry_nbytes(t, nodes)
        budget = budget_bytes()
        if nbytes > budget:
            return  # one entry over budget: never admit it
        with self._lock:
            old = self._entries.get(key)
            if old is not None:
                self._bytes -= old.nbytes
                del self._entries[key]
            self._entries[key] = _Entry(t, nodes, identity, nbytes,
                                        self._epoch)
            self._bytes += nbytes
            while self._bytes > budget and len(self._entries) > 1:
                victim_key, victim = next(iter(self._entries.items()))
                if victim_key == key:
                    break
                self._evict(victim_key, victim, "lru")
            self._publish_bytes()

    def clear(self, reason: str = "clear") -> int:
        """Drop every entry (RSS hard watermark, tests). Returns the
        number of entries dropped."""
        with self._lock:
            dropped = len(self._entries)
            for key in list(self._entries):
                self._evict(key, self._entries[key], reason)
            self._publish_bytes()
        return dropped

    def bump_epoch(self) -> None:
        """Invalidate every resident handle without dropping the
        byte accounting eagerly — entries lazily evict as
        ``stale-epoch`` on next lookup. Called on fleet failover
        rehash, where this member may hold handles for repos it last
        served under a different routing epoch."""
        with self._lock:
            self._epoch += 1

    # -- introspection ------------------------------------------------------

    def stats(self) -> dict:
        """Status-endpoint block: entry count, resident bytes, hit
        rate over process lifetime, evictions by reason."""
        with self._lock:
            lookups = self._lookups
            return {
                "enabled": residency_enabled(),
                "entries": len(self._entries),
                "bytes": self._bytes,
                "budget_bytes": budget_bytes(),
                "lookups": lookups,
                "hits": self._hits,
                "hit_rate": (self._hits / lookups) if lookups else 0.0,
                "evictions": dict(self._evictions),
                "epoch": self._epoch,
            }

    def reset(self) -> None:
        """Tests only: drop entries AND lifetime counters."""
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self._epoch = 0
            self._lookups = 0
            self._hits = 0
            self._evictions.clear()
            self._publish_bytes()


_CACHE = ResidencyCache()


def cache() -> ResidencyCache:
    return _CACHE
