"""Service-resilience primitives: per-rung circuit breakers and the
RSS watermark knobs the daemon's load shedding reads.

The degradation ladder (``cli._merge_ladder``) contains one request's
fault: a broken rung costs that request a full attempt (spawn, compile,
deadline) before the ladder moves down. Under sustained failure — a
wedged TPU runtime, a worker binary that dies on startup — every
request re-pays that cost. The circuit breaker amortizes it: after
``SEMMERGE_BREAKER_THRESHOLD`` failures inside a
``SEMMERGE_BREAKER_WINDOW``-second window the rung's breaker *opens*
and the ladder skips the rung immediately (recorded as a degradation
with ``cause="breaker-open"``); after ``SEMMERGE_BREAKER_COOLDOWN``
seconds one probe request is let through (*half-open*) — success closes
the breaker and restores the rung, failure re-opens it.

States are published as the ``breaker_state`` gauge per rung
(0 = closed, 1 = open, 2 = half-open) and every transition increments
``breaker_transitions_total{rung,to}`` —
``scripts/check_trace_schema.py validate_resilience`` pins both shapes.

Posture (``SEMMERGE_BREAKER``): ``auto`` (default — on inside the
merge service daemon, off in one-shot processes, where cross-request
state would leak between unrelated invocations of an embedding test
or library caller), ``on``, ``off``. The breaker board is
process-global like the ladder's backends; the daemon is the process
whose requests share fate.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Deque, Dict, Optional

from ..obs import flight as obs_flight
from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans

#: ``breaker_state`` gauge values, by state name.
STATE_VALUES = {"closed": 0, "open": 1, "half-open": 2}

_STATE_HELP = "Circuit-breaker state per ladder rung (0 closed, 1 open, 2 half-open)"
_TRANSITIONS_HELP = "Circuit-breaker state transitions, by rung and target state"


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def breaker_enabled() -> bool:
    """``SEMMERGE_BREAKER`` posture: ``on`` / ``off`` / ``auto``
    (default — enabled only inside the daemon process)."""
    raw = os.environ.get("SEMMERGE_BREAKER", "auto").strip().lower()
    if raw in ("on", "1"):
        return True
    if raw in ("off", "0"):
        return False
    return bool(os.environ.get("_SEMMERGE_IN_DAEMON"))


def rss_watermarks() -> tuple:
    """``(soft_mb, hard_mb)`` memory watermarks for the daemon's load
    shedding; 0 disables a watermark."""
    return (_env_float("SEMMERGE_RSS_SOFT_MB", 0.0),
            _env_float("SEMMERGE_RSS_HARD_MB", 0.0))


class CircuitBreaker:
    """One rung's breaker. Thread-safe; every state change publishes
    the gauge and the transition counter."""

    def __init__(self, rung: str, *, window_s: Optional[float] = None,
                 threshold: Optional[int] = None,
                 cooldown_s: Optional[float] = None) -> None:
        self.rung = rung
        self.window_s = window_s if window_s is not None else \
            _env_float("SEMMERGE_BREAKER_WINDOW", 30.0)
        self.threshold = max(1, int(threshold if threshold is not None else
                                    _env_float("SEMMERGE_BREAKER_THRESHOLD",
                                               3.0)))
        self.cooldown_s = cooldown_s if cooldown_s is not None else \
            _env_float("SEMMERGE_BREAKER_COOLDOWN", 5.0)
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures: Deque[float] = deque()
        self._opened_at = 0.0
        self._probing = False
        self._pending_dumps: list = []
        self._publish_state()

    # -- state machine ------------------------------------------------------

    def _publish_state(self) -> None:
        obs_metrics.REGISTRY.gauge("breaker_state", _STATE_HELP).set(
            STATE_VALUES[self._state], rung=self.rung)

    def _transition(self, to: str) -> None:
        if to == self._state:
            return
        self._state = to
        self._publish_state()
        obs_metrics.REGISTRY.counter(
            "breaker_transitions_total", _TRANSITIONS_HELP).inc(
                1, rung=self.rung, to=to)
        # Postmortem evidence for every transition — queued here (we
        # hold self._lock; dumping snapshots every breaker's state,
        # which re-enters locks) and flushed by the public methods
        # after the lock is released.
        self._pending_dumps.append(to)

    def _flush_dumps(self) -> None:
        """Write queued transition bundles. Called WITHOUT the lock."""
        while True:
            with self._lock:
                if not self._pending_dumps:
                    return
                to = self._pending_dumps.pop(0)
            from ..utils import workdir
            obs_flight.dump(
                obs_spans.trace_id(), "breaker-transition",
                breakers=breakers().snapshot(), root=workdir.root(),
                extra={"breaker": {"rung": self.rung, "to": to}})

    def allow(self) -> bool:
        """May the ladder attempt this rung now? Open breakers refuse;
        a cooled-down open breaker admits exactly one half-open probe
        at a time."""
        now = time.monotonic()
        try:
            with self._lock:
                if self._state == "closed":
                    return True
                if self._state == "open":
                    if now - self._opened_at < self.cooldown_s:
                        return False
                    self._transition("half-open")
                    self._probing = True
                    return True
                # half-open: one probe in flight at a time.
                if self._probing:
                    return False
                self._probing = True
                return True
        finally:
            self._flush_dumps()

    def record_success(self) -> None:
        with self._lock:
            self._failures.clear()
            self._probing = False
            self._transition("closed")
        self._flush_dumps()

    def record_failure(self) -> None:
        now = time.monotonic()
        with self._lock:
            if self._state == "half-open":
                # The probe failed: back to open, restart the cooldown.
                self._probing = False
                self._opened_at = now
                self._transition("open")
            else:
                self._failures.append(now)
                cutoff = now - self.window_s
                while self._failures and self._failures[0] < cutoff:
                    self._failures.popleft()
                if len(self._failures) >= self.threshold:
                    self._opened_at = now
                    self._transition("open")
        self._flush_dumps()

    @property
    def state(self) -> str:
        with self._lock:
            return self._state


class BreakerBoard:
    """The process-global registry of per-rung breakers. All methods
    are no-ops (``allow`` always ``True``) when the posture is off, so
    the ladder's call sites stay unconditional."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._breakers: Dict[str, CircuitBreaker] = {}

    def _get(self, rung: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(rung)
            if br is None:
                br = self._breakers[rung] = CircuitBreaker(rung)
            return br

    def allow(self, rung: str) -> bool:
        if not breaker_enabled():
            return True
        return self._get(rung).allow()

    def record_success(self, rung: str) -> None:
        if breaker_enabled():
            self._get(rung).record_success()

    def record_failure(self, rung: str) -> None:
        if breaker_enabled():
            self._get(rung).record_failure()

    def snapshot(self) -> Dict[str, str]:
        """Rung → state name, for the daemon status endpoint."""
        with self._lock:
            return {rung: br.state for rung, br in self._breakers.items()}

    def reset(self) -> None:
        """Drop all breaker state (tests; daemon never calls this)."""
        with self._lock:
            self._breakers.clear()


_BOARD = BreakerBoard()


def breakers() -> BreakerBoard:
    return _BOARD
