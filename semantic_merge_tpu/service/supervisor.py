"""Supervised daemon lifecycle: ``semmerge serve --supervise``.

The daemon is warm state — decl caches, compiled XLA programs, live
batch scheduler — and warm state dies with the process. A daemon lost
to an OOM kill, a fault-injection exit, or a plain crash turns every
subsequent client into a cold one-shot run until somebody restarts it.
The supervisor closes that gap: a deliberately *boring* parent process
(no jax, no engine imports — nothing in it can fail the way the child
does) that respawns the daemon with capped exponential backoff and
hands the socket over.

Handoff works without fd passing because of ordering on both sides:

- the daemon's teardown closes and unlinks its socket *before* the
  drain loop, so a replacement can bind while stragglers finish;
- the daemon's bind probe-replaces a dead socket path, so a SIGKILLed
  child's stale socket never wedges the replacement.

Clients connecting in the respawn window see connection-refused, which
the client layer already treats as daemon-unavailable: ``auto`` posture
falls back in-process or retries with jittered backoff, ``require``
surfaces exit 12. No request is silently dropped.

Exit contract: a child that exits 0 (idle-exit, ``shutdown`` verb, or
a drained SIGTERM) ends supervision — that exit was *asked for*. Any
other exit respawns, counted in ``supervisor_restarts_total{reason}``
(``reason="signal"`` for signal deaths, ``"crash"`` for nonzero exits)
and recorded as a ``supervisor.restart`` span. SIGTERM/SIGINT to the
supervisor forwards to the child (which drains) and ends supervision
once the child is gone.

The supervisor keeps ``SEMMERGE_METRICS`` for itself and strips it
from the child's environment: parent and child exiting would otherwise
race their atexit dumps onto one path. The supervisor's dump carries
the restart counters; daemon-side metrics are served live over the
``status`` verb, which is where they are useful anyway.
"""
from __future__ import annotations

import contextlib
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional, Sequence

from ..obs import flight as obs_flight
from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans
from ..utils.loggingx import logger

_RESTARTS_HELP = "Daemon children respawned by the supervisor, by reason"

#: A child that stayed up this long earned a fresh backoff ladder.
STABLE_SECONDS = 30.0


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def serve_argv(args) -> List[str]:
    """The child's command line: this interpreter, this package, the
    same ``serve`` flags — minus ``--supervise`` (the child must be a
    daemon, not another supervisor)."""
    argv = [sys.executable, "-m", "semantic_merge_tpu", "serve"]
    if getattr(args, "socket", None):
        argv += ["--socket", str(args.socket)]
    if getattr(args, "workers", None) is not None:
        argv += ["--workers", str(args.workers)]
    if getattr(args, "queue", None) is not None:
        argv += ["--queue", str(args.queue)]
    if getattr(args, "idle_exit", None) is not None:
        argv += ["--idle-exit", str(args.idle_exit)]
    if getattr(args, "events", None):
        argv += ["--events", str(args.events)]
    if getattr(args, "join", None):
        argv += ["--join", str(args.join)]
    if getattr(args, "advertise", None):
        argv += ["--advertise", str(args.advertise)]
    if getattr(args, "capacity", None) is not None:
        argv += ["--capacity", str(args.capacity)]
    if getattr(args, "member_id", None):
        argv += ["--member-id", str(args.member_id)]
    return argv


class MemberSupervisor:
    """Per-child respawn policy for the fleet router (``fleet/``).

    :class:`Supervisor` is a blocking run loop around one child; the
    fleet router supervises N member daemons from a single health
    thread, so this is the same policy — exponential backoff from
    ``SEMMERGE_SUPERVISE_BACKOFF`` capped at
    ``SEMMERGE_SUPERVISE_BACKOFF_CAP``, ladder reset after
    :data:`STABLE_SECONDS` of uptime — as a poll-style state machine.
    :meth:`ensure` is called periodically; it reaps a dead child,
    schedules the respawn, and spawns when the backoff elapses. Each
    member carries its own ladder: one crash-looping member settles at
    the cap without delaying its siblings' respawns.
    """

    def __init__(self, member_id: str, argv: Sequence[str], *,
                 env: Optional[dict] = None,
                 backoff: Optional[float] = None,
                 backoff_cap: Optional[float] = None) -> None:
        self.member_id = member_id
        self._argv = list(argv)
        self._env = dict(env) if env is not None else None
        self._backoff = backoff if backoff is not None else _env_float(
            "SEMMERGE_SUPERVISE_BACKOFF", 0.2)
        self._cap = backoff_cap if backoff_cap is not None else _env_float(
            "SEMMERGE_SUPERVISE_BACKOFF_CAP", 5.0)
        self.proc: Optional[subprocess.Popen] = None
        self.restarts = 0
        self.last_rc: Optional[int] = None
        self._attempt = 0
        self._started_at = 0.0
        self._respawn_at: Optional[float] = None

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    def running(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def ensure(self) -> Optional[str]:
        """Advance the state machine one tick.

        Returns ``"spawned"`` when this tick (re)spawned the child,
        ``"died"`` on the tick that reaped a death (the respawn is
        scheduled, not taken, so the caller can eject the member from
        the ring immediately), ``None`` otherwise.
        """
        now = time.monotonic()
        if self.proc is not None:
            rc = self.proc.poll()
            if rc is None:
                return None
            self.last_rc = rc
            self.proc = None
            if now - self._started_at >= STABLE_SECONDS:
                self._attempt = 0
            self._attempt += 1
            delay = min(self._backoff * (2 ** (self._attempt - 1)),
                        self._cap)
            self._respawn_at = now + delay
            logger.warning(
                "fleet member %s died (rc=%s); respawn in %.2fs "
                "(attempt %d)", self.member_id, rc, delay, self._attempt)
            return "died"
        if self._respawn_at is not None and now < self._respawn_at:
            return None
        self._respawn_at = None
        env = self._env if self._env is not None else dict(os.environ)
        env = dict(env)
        env.pop("SEMMERGE_METRICS", None)
        try:
            self.proc = subprocess.Popen(self._argv, env=env)
        except OSError as exc:
            logger.error("could not spawn fleet member %s: %s",
                         self.member_id, exc)
            self._respawn_at = now + self._cap
            return None
        self._started_at = now
        if self.last_rc is not None:
            self.restarts += 1
        logger.info("fleet member %s pid=%d up", self.member_id,
                    self.proc.pid)
        return "spawned"

    def terminate(self) -> None:
        if self.running():
            with contextlib.suppress(OSError):
                self.proc.send_signal(signal.SIGTERM)

    def kill(self) -> None:
        if self.proc is not None:
            with contextlib.suppress(OSError):
                self.proc.kill()


class Supervisor:
    """Respawn loop around one daemon child.

    Backoff is exponential from ``SEMMERGE_SUPERVISE_BACKOFF`` (default
    0.2s) capped at ``SEMMERGE_SUPERVISE_BACKOFF_CAP`` (default 5s); a
    child that survives :data:`STABLE_SECONDS` resets the ladder, so a
    daemon that crashes once a day restarts in 0.2s, while a
    crash-looping one settles at the cap instead of spinning.
    ``SEMMERGE_SUPERVISE_MAX_RESTARTS`` (default 0 = unlimited) bounds
    consecutive *unstable* restarts for harness use."""

    def __init__(self, child_argv: Sequence[str], *,
                 backoff: Optional[float] = None,
                 backoff_cap: Optional[float] = None,
                 max_restarts: Optional[int] = None) -> None:
        self._argv = list(child_argv)
        self._backoff = backoff if backoff is not None else _env_float(
            "SEMMERGE_SUPERVISE_BACKOFF", 0.2)
        self._cap = backoff_cap if backoff_cap is not None else _env_float(
            "SEMMERGE_SUPERVISE_BACKOFF_CAP", 5.0)
        if max_restarts is None:
            max_restarts = int(_env_float("SEMMERGE_SUPERVISE_MAX_RESTARTS",
                                          0))
        self._max_restarts = max(0, max_restarts)
        self._child: Optional[subprocess.Popen] = None
        self._stop_sig: Optional[int] = None

    # -- signals ----------------------------------------------------------

    def _on_signal(self, signum, frame) -> None:
        self._stop_sig = signum
        child = self._child
        if child is not None and child.poll() is None:
            with contextlib.suppress(OSError):
                child.send_signal(signum)

    # -- run loop ---------------------------------------------------------

    def _spawn(self) -> subprocess.Popen:
        env = dict(os.environ)
        # Parent and child atexit dumps would race onto one path; the
        # supervisor keeps the dump (restart counters live here).
        env.pop("SEMMERGE_METRICS", None)
        return subprocess.Popen(self._argv, env=env)

    def _sleep_interruptible(self, seconds: float) -> bool:
        """Backoff nap; returns ``True`` if a stop signal cut it short."""
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline:
            if self._stop_sig is not None:
                return True
            time.sleep(min(0.05, seconds))
        return self._stop_sig is not None

    def run(self) -> int:
        previous = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            previous[sig] = signal.signal(sig, self._on_signal)
        attempt = 0
        try:
            while True:
                started = time.monotonic()
                try:
                    self._child = self._spawn()
                except OSError as exc:
                    logger.error("supervisor could not spawn daemon: %s", exc)
                    return 12
                logger.info("supervising daemon pid=%d argv=%r",
                            self._child.pid, self._argv)
                rc = self._child.wait()
                uptime = time.monotonic() - started
                self._child = None
                if self._stop_sig is not None:
                    # The stop was ours (forwarded); the child drained.
                    return 0 if rc == 0 else rc
                if rc == 0:
                    # Idle-exit or shutdown verb: the exit was asked for.
                    logger.info("daemon exited cleanly; supervision ends")
                    return 0
                if uptime >= STABLE_SECONDS:
                    attempt = 0
                attempt += 1
                reason = "signal" if rc < 0 else "crash"
                if self._max_restarts and attempt > self._max_restarts:
                    logger.error(
                        "daemon died %d times without stabilizing (last "
                        "rc=%d); giving up", attempt, rc)
                    return rc if rc > 0 else 12
                obs_metrics.REGISTRY.counter(
                    "supervisor_restarts_total",
                    _RESTARTS_HELP).inc(1, reason=reason)
                delay = min(self._backoff * (2 ** (attempt - 1)), self._cap)
                obs_spans.record("supervisor.restart", delay, layer="service",
                                 reason=reason, attempt=attempt, rc=rc)
                obs_flight.dump(
                    None, "supervisor-restart",
                    extra={"restart": {"reason": reason, "rc": rc,
                                       "attempt": attempt,
                                       "uptime_s": round(uptime, 3),
                                       "delay_s": round(delay, 3)}})
                logger.warning(
                    "daemon died (%s, rc=%d, uptime %.1fs); respawning in "
                    "%.2fs (attempt %d)", reason, rc, uptime, delay, attempt)
                if self._sleep_interruptible(delay):
                    return 0
        finally:
            for sig, handler in previous.items():
                with contextlib.suppress((ValueError, OSError)):
                    signal.signal(sig, handler)
