"""Local-only HTTP telemetry listener for the merge service daemon.

``SEMMERGE_METRICS_PORT=<port>`` makes the daemon serve two read-only
endpoints on ``127.0.0.1`` (never a routable interface — this is an
operator loopback, not an ingress):

- ``GET /metrics`` — live Prometheus text exposition (format 0.0.4) of
  the process registry, scrape-ready;
- ``GET /healthz`` — one JSON object with the daemon's health surface
  (queue depth, in-flight count, breaker states, RSS, uptime — the
  same shape ``semmerge serve --status`` prints). When the daemon has
  SLO objectives configured and the SLO engine reports a tripped
  burn-rate clause, the endpoint answers **503** with
  ``"degraded": true`` so plain HTTP health checks (load balancers,
  systemd watchdogs) see the burn without parsing the body.

``SEMMERGE_METRICS_PORT=0`` binds an ephemeral port; the bound port is
reported in the daemon ``status()`` payload (``metrics_port``) so
tests and tooling can discover it. Unset/empty disables the listener
entirely — the daemon never opens a TCP socket unless asked.

``SEMMERGE_METRICS_BIND=<host>`` widens the bind address so cross-host
fleets can scrape members directly instead of tunneling loopback — but
only under TLS: a non-loopback bind is **refused** (the listener stays
dark, loudly) unless the PR-19 fleet TLS material
(``SEMMERGE_FLEET_TLS_CERT``/``_KEY``/``_CA``) is configured, in which
case the listener serves HTTPS with the same cert, and a configured CA
makes it mutual (scrapers must present a cert chaining to it).
"""
from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from ..fleet import transport as fleet_transport
from ..obs import metrics as obs_metrics
from ..utils.loggingx import logger

ENV_PORT = "SEMMERGE_METRICS_PORT"
ENV_BIND = "SEMMERGE_METRICS_BIND"

_LOOPBACK = ("127.0.0.1", "::1", "localhost", "")


def _bind_host() -> str:
    return os.environ.get(ENV_BIND, "").strip() or "127.0.0.1"


class _Handler(BaseHTTPRequestHandler):
    server_version = "semmerge-telemetry"
    protocol_version = "HTTP/1.1"

    def _send(self, code: int, content_type: str, body: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 (http.server contract)
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                metrics_fn = getattr(self.server, "semmerge_metrics", None)
                text = metrics_fn() if metrics_fn is not None \
                    else obs_metrics.REGISTRY.render_prometheus()
                self._send(200, "text/plain; version=0.0.4; charset=utf-8",
                           text.encode("utf-8"))
            elif path in ("/healthz", "/health"):
                health = self.server.semmerge_health()  # type: ignore[attr-defined]
                slo = health.get("slo") if isinstance(health, dict) else None
                degraded = bool(slo) and not slo.get("healthy", True)
                if isinstance(health, dict):
                    health = dict(health, degraded=degraded)
                self._send(503 if degraded else 200, "application/json",
                           json.dumps(health, default=str).encode("utf-8"))
            else:
                self._send(404, "text/plain; charset=utf-8", b"not found\n")
        except Exception as exc:  # serving telemetry must never crash a conn
            try:
                self._send(500, "text/plain; charset=utf-8",
                           f"{type(exc).__name__}: {exc}\n".encode("utf-8"))
            except OSError:
                pass

    def log_message(self, format: str, *args: object) -> None:
        pass  # scrape traffic does not belong on the daemon's stderr


class TelemetryServer:
    """A loopback-bound threading HTTP server; start/stop mirror the
    daemon's serve/teardown lifecycle."""

    def __init__(self, port: int,
                 health_fn: Callable[[], dict],
                 metrics_fn: Optional[Callable[[], str]] = None,
                 host: Optional[str] = None) -> None:
        bind = host if host is not None else _bind_host()
        tls_ctx = None
        if bind not in _LOOPBACK:
            # Widened bind: TLS or nothing. Serving plaintext metrics
            # on a routable interface leaks repo paths and member
            # topology; the PR-19 fleet material secures it for free.
            tls_ctx = fleet_transport.server_context()
            if tls_ctx is None:
                raise ValueError(
                    f"refusing non-loopback metrics bind {bind!r} "
                    f"without SEMMERGE_FLEET_TLS_CERT material")
        self._httpd = ThreadingHTTPServer((bind, port), _Handler)
        if tls_ctx is not None:
            self._httpd.socket = tls_ctx.wrap_socket(
                self._httpd.socket, server_side=True)
        self.tls = tls_ctx is not None
        self._httpd.daemon_threads = True
        self._httpd.semmerge_health = health_fn  # type: ignore[attr-defined]
        # Optional exposition override: the fleet router serves its
        # *federated* view (member scrapes + rollups) instead of the
        # process-local registry.
        self._httpd.semmerge_metrics = metrics_fn  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return int(self._httpd.server_address[1])

    def start(self) -> "TelemetryServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="svc-telemetry", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)


def maybe_start(health_fn: Callable[[], dict],
                metrics_fn: Optional[Callable[[], str]] = None
                ) -> Optional[TelemetryServer]:
    """Start the listener when ``SEMMERGE_METRICS_PORT`` is set; return
    ``None`` (and stay dark) when unset, unparsable, or unbindable —
    telemetry must never stop the daemon from serving merges."""
    raw = os.environ.get(ENV_PORT, "").strip()
    if not raw:
        return None
    try:
        port = int(raw)
    except ValueError:
        return None
    try:
        return TelemetryServer(port, health_fn, metrics_fn).start()
    except ValueError as exc:
        # Refused non-loopback bind: stay dark, but say why — a fleet
        # operator expecting remote scrapes should not debug silence.
        logger.error("telemetry listener disabled: %s", exc)
        return None
    except OSError:
        return None
