"""Warm-state merge service: ``semmerge serve`` daemon + thin client.

One-shot semmerge pays its worst costs before the first op is diffed:
interpreter + jax import, XLA compilation of the fused merge program,
a cold decl cache, prettier/tsc discovery, a fresh subprocess worker.
The reference's warm-cache budget (architecture.md:313 — "warm cache
e2e merge ≤ 10 s" vs 40 s cold) assumes exactly the long-lived process
this package provides: a daemon on a unix socket holding all of that
state across requests, and a client that delegates merge-shaped CLI
invocations to it.

Layout:

- :mod:`~semantic_merge_tpu.service.protocol` — wire format (newline
  JSON-RPC, the :mod:`runtime.worker` idiom), socket-path resolution,
  request-env capture;
- :mod:`~semantic_merge_tpu.service.daemon` — the server: bounded
  admission queue, executor threads, per-repo serialization of
  ``--inplace`` work, warm caches, graceful lifecycle;
- :mod:`~semantic_merge_tpu.service.client` — the client:
  ``SEMMERGE_DAEMON=auto|require|off`` delegation with
  spawn-if-absent and a hard guarantee that auto mode never fails a
  merge the one-shot path would have completed.

The contract throughout is *byte parity*: a request executed by the
daemon produces the same tree bytes, artifacts, exit code, and notes
payloads as the same argv run one-shot (``tests/test_service.py``
enforces this against the golden corpus).
"""
