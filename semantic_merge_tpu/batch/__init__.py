"""Continuous batching: coalesce concurrent merges into fused
multi-merge device dispatches.

The warm daemon (service mode) amortizes imports and compile state, but
every request still owns the device for a full fused dispatch, so
concurrent clients queue serially on the kernel+fetch window. This
package sits between the service daemon and the fused engine and packs
many *independent* merge requests into ONE shape-bucketed batched
program — the continuous-batching discipline of an inference stack,
applied to the merge kernel:

- :mod:`~semantic_merge_tpu.batch.scheduler` — a micro-batch window
  (``SEMMERGE_BATCH_WINDOW_MS``, bounded in-flight batches) admitting
  queued requests into shape buckets;
- :mod:`~semantic_merge_tpu.batch.packer` — stacks the already
  bucket-padded encoded snapshots along a new leading merge axis
  (the core/encode bucket ladder keeps the co-batch key space small);
- :mod:`~semantic_merge_tpu.batch.dispatcher` — runs one batched fused
  program (the single-merge kernel body vmapped over the merge axis;
  padding rows are inert replicas whose outputs are never scattered
  back) and scatters the packed per-merge rows to each request, whose
  host tail (``TailPlan`` decode → materialize → columnar apply) then
  runs per request, unchanged and byte-identical to an unbatched run.

The daemon activates ONE process-global :class:`BatchScheduler`
(:func:`activate` / :func:`deactivate`); the fused engine consults
:func:`plan_for_request` at its device-dispatch seam. Posture, read
through the per-request env overlay (``SEMMERGE_BATCH``):

- ``off``     — bypass the subsystem entirely (inline dispatch);
- ``auto``    — batch when a scheduler is active; any batching fault
  degrades *that request only* to the inline unbatched dispatch
  (never worse than one-shot); the default;
- ``require`` — must batch: an inactive scheduler, an ineligible
  request, or a batching fault raises a typed
  :class:`~semantic_merge_tpu.errors.BatchFault` (exit 16 in strict
  mode; otherwise the CLI ladder degrades the run).
"""
from __future__ import annotations

import threading
from typing import Optional

from .dispatcher import collect_request, submit_request
from .packer import BatchRequest, batch_bucket, pack_group
from .scheduler import BatchScheduler

__all__ = [
    "BatchRequest", "BatchScheduler", "activate", "batch_bucket",
    "collect_request", "current", "deactivate", "degrade_or_raise",
    "pack_group", "plan_for_request", "posture", "submit_request",
]

#: Per-request posture knob (carried by the daemon's request overlay).
ENV_POSTURE = "SEMMERGE_BATCH"

_lock = threading.Lock()
_active: Optional[BatchScheduler] = None


def activate(**kwargs) -> BatchScheduler:
    """Start (or return) the process-global batch scheduler. The
    service daemon calls this around executor spawn; one-shot runs
    never do, so the engine seam stays inert outside service mode."""
    global _active
    with _lock:
        if _active is not None and _active.alive():
            return _active
        _active = BatchScheduler(**kwargs).start()
        return _active


def deactivate() -> None:
    """Stop the process-global scheduler (daemon teardown). Queued
    requests are failed with a typed fault so waiting threads degrade
    to the inline dispatch instead of hanging."""
    global _active
    with _lock:
        sched = _active
        _active = None
    if sched is not None:
        sched.stop()


def current() -> Optional[BatchScheduler]:
    """The live scheduler, or ``None`` (stopped schedulers read as
    absent so racing requests fall through to inline dispatch)."""
    sched = _active
    return sched if sched is not None and sched.alive() else None


def posture() -> str:
    """``SEMMERGE_BATCH`` through the request overlay: ``off`` /
    ``auto`` (default) / ``require``; unknown values read as ``auto``."""
    from ..utils import reqenv
    value = (reqenv.get(ENV_POSTURE, "auto") or "auto").strip().lower()
    return value if value in ("off", "auto", "require") else "auto"


def plan_for_request(eligible: bool = True) -> Optional[BatchScheduler]:
    """Route one merge at the engine's dispatch seam: the scheduler to
    submit to, or ``None`` for the inline unbatched dispatch.
    ``eligible`` is the engine's shape condition (single-device only —
    the dp-sharded kernel has its own mesh program). Raises
    :class:`~semantic_merge_tpu.errors.BatchFault` when posture
    ``require`` cannot be satisfied."""
    from ..errors import BatchFault
    mode = posture()
    sched = current()
    if mode == "off":
        if sched is not None:
            _count_outcome("bypass")
        return None
    if sched is None:
        if mode == "require":
            raise BatchFault("SEMMERGE_BATCH=require but no batch "
                             "scheduler is active", stage="batch")
        return None
    if not eligible:
        if mode == "require":
            raise BatchFault("SEMMERGE_BATCH=require but the mesh-sharded "
                             "engine cannot join a batch", stage="batch")
        _count_outcome("bypass")
        return None
    return sched


def degrade_or_raise(fault) -> None:
    """Policy for a batching fault at the request seam: ``require``
    re-raises (typed, exit 16 strict); otherwise the caller falls back
    to the inline dispatch — affected request only, co-batched requests
    are untouched.

    A :class:`~semantic_merge_tpu.errors.MeshFault` under
    ``SEMMERGE_MESH=require`` also re-raises regardless of the batch
    posture: the mesh contract (exit 18 strict) is independent of
    whether batching itself may degrade."""
    if posture() == "require":
        raise fault
    from ..errors import MeshFault
    from ..parallel.mesh import mesh_posture
    if isinstance(fault, MeshFault) and mesh_posture() == "require":
        raise fault
    from ..utils.loggingx import logger
    logger.warning("batched dispatch degraded to inline: %s",
                   fault.describe())
    _count_outcome("degraded")


def _count_outcome(outcome: str) -> None:
    from ..obs import metrics as obs_metrics
    obs_metrics.REGISTRY.counter(
        "batch_requests_total",
        "Merge requests seen by the batching subsystem, by outcome",
    ).inc(1, outcome=outcome)
