"""Dispatcher: run one batched fused program and scatter its rows back
to the waiting requests.

Two halves live here, on two different threads:

- the **request side** (:func:`submit_request` / :func:`collect_request`)
  runs on the merge request's own executor thread, so the per-request
  env overlay (``utils/reqenv``) is in scope — fault injection
  (``batch:pack`` / ``batch:dispatch`` / ``batch:scatter``) and posture
  therefore scope to ONE request, never to its co-batched neighbors;
- the **leader side** (:func:`dispatch_group`) runs on the scheduler's
  dispatch pool: pack the group along the merge axis, fetch (or
  compile) the bucket's jitted program from the fused module's program
  cache, run it, and scatter row ``i`` of the packed output to request
  ``i``'s future. Each row is the single-merge kernel's one-buffer
  packed layout, so the engine's existing non-split decode — and the
  whole host tail behind it — runs per request, unchanged.

A leader-side error fails every member future; each request then
applies its own posture at :func:`collect_request` (auto → inline
unbatched dispatch, require → typed ``BatchFault``).
"""
from __future__ import annotations

import os
import time

import numpy as np

from ..errors import fault_boundary
from ..obs import device as obs_device
from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans
from .packer import BatchRequest, pack_group

#: Small-integer buckets for the per-dispatch valid-merge count.
BATCH_SIZE_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0)

#: Bound on a request's wait for its batch row — a wedged/killed leader
#: must degrade the request to the inline path, not hang the daemon.
_COLLECT_TIMEOUT_S = 300.0


def submit_request(scheduler, dev_b, dev_l, dev_r, hash_tab, dig_l, dig_r,
                   *, nb: int, nl: int, nr: int, C: int):
    """Request side, pre-dispatch: build the :class:`BatchRequest` and
    enqueue it. Runs in the request thread (overlay in scope); any
    failure is classified into a typed ``BatchFault``."""
    from ..utils import faults
    with fault_boundary("batch:pack"):
        faults.check("batch:pack")
        request = BatchRequest(
            dev_b, dev_l, dev_r, hash_tab, dig_l, dig_r,
            nb=nb, nl=nl, nr=nr, C=C)
        # Capture the submitting request's tracing scope: the leader
        # thread has no scope of its own, so batch spans reach each
        # member's trace only through these handles.
        request.recorder = obs_spans.current()
        request.trace_id = obs_spans.trace_id()
        return scheduler.submit(request)


def collect_request(future) -> np.ndarray:
    """Request side, post-dispatch: wait for this request's packed row.
    The wait is bounded; leader-side errors surface here (wrapped into
    ``BatchFault``) so the caller can apply posture per request."""
    from ..utils import faults
    with fault_boundary("batch:dispatch"):
        faults.check("batch:dispatch")
        row = future.result(timeout=_COLLECT_TIMEOUT_S)
    with fault_boundary("batch:scatter"):
        faults.check("batch:scatter")
        flat = np.asarray(row)
    from . import _count_outcome
    _count_outcome("batched")
    return flat


def _graft(members, batch_id: str, name: str, seconds: float,
           t_start: float, **meta) -> None:
    """Record one leader-side batch span into every member's captured
    request recorder, stamped with the shared ``batch_id`` and the
    member's own ``trace_id``. Artifact-only (``record_into``): the
    leader's own span already fed the histogram and flight ring."""
    for req, _fut in members:
        rec = getattr(req, "recorder", None)
        if rec is not None:
            obs_spans.record_into(
                rec, name, seconds, t_start=t_start, layer="batch",
                batch_id=batch_id, trace_id=getattr(req, "trace_id", None),
                **meta)


def dispatch_group(scheduler, members) -> None:
    """Leader side: pack → one batched program → scatter. ``members``
    is a same-bucket-key list of ``(BatchRequest, Future)`` pairs.
    Every phase span is grafted into each member's request trace under
    one shared ``batch_id``, so a co-batched request's artifact shows
    the fused dispatch it rode without absorbing its neighbors' ids."""
    reqs = [req for req, _fut in members]
    valid = len(reqs)
    batch_id = os.urandom(4).hex()
    t0 = time.perf_counter()
    with obs_spans.span("batch.pack", layer="batch", requests=valid,
                        batch_id=batch_id):
        arrays, padded = pack_group(reqs)
    _graft(members, batch_id, "batch.pack", time.perf_counter() - t0, t0,
           requests=valid)
    reg = obs_metrics.REGISTRY
    reg.histogram("batch_size",
                  "Valid merges per batched fused dispatch",
                  buckets=BATCH_SIZE_BUCKETS).observe(valid)
    reg.gauge("batch_padding_waste_ratio",
              "Merge-axis padding fraction of the last batched dispatch"
              ).set((padded - valid) / padded)
    geom = reqs[0]
    t0 = time.perf_counter()
    with obs_spans.span("batch.dispatch", layer="batch", requests=valid,
                        padded=padded, C=geom.C, batch_id=batch_id):
        from ..ops.fused import batched_fused_program
        program = batched_fused_program(padded, geom.nb, geom.nl,
                                        geom.nr, geom.C)
        flat = np.asarray(program(*arrays))
        obs_device.record_transfer("d2h", flat.nbytes)
    _graft(members, batch_id, "batch.dispatch", time.perf_counter() - t0, t0,
           requests=valid, padded=padded)
    t0 = time.perf_counter()
    with obs_spans.span("batch.scatter", layer="batch", requests=valid,
                        batch_id=batch_id):
        for i, (_req, fut) in enumerate(members):
            if not fut.done():
                fut.set_result(flat[i])
    _graft(members, batch_id, "batch.scatter", time.perf_counter() - t0, t0,
           requests=valid)
    scheduler.note_batch(valid, padded)
