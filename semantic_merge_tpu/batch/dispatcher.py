"""Dispatcher: run one batched fused program and scatter its rows back
to the waiting requests.

Two halves live here, on two different threads:

- the **request side** (:func:`submit_request` / :func:`collect_request`)
  runs on the merge request's own executor thread, so the per-request
  env overlay (``utils/reqenv``) is in scope — fault injection
  (``batch:pack`` / ``batch:mesh`` / ``batch:dispatch`` /
  ``batch:scatter``) and posture therefore scope to ONE request, never
  to its co-batched neighbors;
- the **leader side** (:func:`dispatch_group`) runs on the scheduler's
  dispatch pool: plan the dispatch mesh, pack the group along the
  merge axis, fetch (or compile) the bucket's jitted program from the
  fused module's program cache, run it, and scatter each request's
  packed output row to its future. Each row is the single-merge
  kernel's one-buffer packed layout, so the engine's existing
  non-split decode — and the whole host tail behind it — runs per
  request, unchanged.

Mesh posture (``SEMMERGE_MESH`` / ``[engine] mesh`` — see
:data:`semantic_merge_tpu.parallel.mesh.MESH_POSTURES`) decides the
program: ``off`` keeps the single-device vmapped program; ``auto`` and
``require`` shard the packed merge axis across the host's chips
(:func:`~semantic_merge_tpu.parallel.mesh.build_batch_mesh`). ``auto``
falls back to the single-device program on 1-chip hosts, mesh-build
failure, or a mesh dispatch error — every fallback increments
``batch_mesh_fallbacks_total{reason}`` — while ``require`` raises a
typed :class:`~semantic_merge_tpu.errors.MeshFault` (exit 18 strict).
Lanes are independent, so mesh rows are bit-identical to the
single-device program's.

A leader-side error fails every member future; each request then
applies its own posture at :func:`collect_request` (auto → inline
unbatched dispatch, require → typed ``BatchFault``).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

import numpy as np

from ..errors import MeshFault, fault_boundary
from ..obs import device as obs_device
from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans
from .packer import BatchRequest, batch_bucket, pack_group

#: Small-integer buckets for the per-dispatch valid-merge count.
BATCH_SIZE_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0)

#: Bound on a request's wait for its batch row — a wedged/killed leader
#: must degrade the request to the inline path, not hang the daemon.
_COLLECT_TIMEOUT_S = 300.0

_FALLBACKS_HELP = ("Mesh-sharded batch dispatches that fell back to "
                   "the single-device program, by reason")

_mesh_lock = threading.Lock()
_mesh_cache: Dict[int, object] = {}
_mesh_stats: Dict[str, object] = {
    "dispatches": 0, "mesh_dispatches": 0, "last_shape": None,
    "last_rows_per_chip": 0, "last_chip_rows": [], "fallbacks": {}}


def submit_request(scheduler, dev_b, dev_l, dev_r, hash_tab, dig_l, dig_r,
                   *, nb: int, nl: int, nr: int, C: int):
    """Request side, pre-dispatch: build the :class:`BatchRequest` and
    enqueue it. Runs in the request thread (overlay in scope); any
    failure is classified into a typed ``BatchFault``."""
    from ..utils import faults
    with fault_boundary("batch:pack"):
        faults.check("batch:pack")
        request = BatchRequest(
            dev_b, dev_l, dev_r, hash_tab, dig_l, dig_r,
            nb=nb, nl=nl, nr=nr, C=C)
        # Capture the submitting request's tracing scope: the leader
        # thread has no scope of its own, so batch spans reach each
        # member's trace only through these handles.
        request.recorder = obs_spans.current()
        request.trace_id = obs_spans.trace_id()
        return scheduler.submit(request)


def collect_request(future) -> np.ndarray:
    """Request side, post-dispatch: wait for this request's packed row.
    The wait is bounded; leader-side errors surface here (wrapped into
    ``BatchFault``) so the caller can apply posture per request. The
    ``batch:mesh`` stage is the request-side seam of the mesh-sharded
    program: an injected (or real) fault here degrades THIS request to
    the inline dispatch — co-batched neighbors keep their rows."""
    from ..utils import faults
    with fault_boundary("batch:mesh"):
        try:
            faults.check("batch:mesh")
        except Exception:
            _note_fallback("fault")
            raise
    with fault_boundary("batch:dispatch"):
        faults.check("batch:dispatch")
        row = future.result(timeout=_COLLECT_TIMEOUT_S)
    with fault_boundary("batch:scatter"):
        faults.check("batch:scatter")
        flat = np.asarray(row)
    from . import _count_outcome
    _count_outcome("batched")
    return flat


def _graft(members, batch_id: str, name: str, seconds: float,
           t_start: float, **meta) -> None:
    """Record one leader-side batch span into every member's captured
    request recorder, stamped with the shared ``batch_id`` and the
    member's own ``trace_id``. Artifact-only (``record_into``): the
    leader's own span already fed the histogram and flight ring."""
    for req, _fut in members:
        rec = getattr(req, "recorder", None)
        if rec is not None:
            obs_spans.record_into(
                rec, name, seconds, t_start=t_start, layer="batch",
                batch_id=batch_id, trace_id=getattr(req, "trace_id", None),
                **meta)


def _note_fallback(reason: str) -> None:
    obs_metrics.REGISTRY.counter(
        "batch_mesh_fallbacks_total", _FALLBACKS_HELP).inc(1, reason=reason)
    with _mesh_lock:
        fallbacks = _mesh_stats["fallbacks"]
        fallbacks[reason] = fallbacks.get(reason, 0) + 1


def _plan_mesh(posture: str):
    """Leader side: the dispatch mesh for this batch, or ``(None, 1)``
    for the single-device program. ``auto`` downgrades on 1-chip hosts
    and mesh-build failures (counted); ``require`` raises
    :class:`MeshFault` instead — the scheduler fails every member
    future with it, and each request's posture seam decides whether
    that is fatal (``SEMMERGE_MESH=require``) or a per-request inline
    degrade."""
    import jax
    devices = jax.devices()
    from ..parallel.mesh import batch_mesh_shards, build_batch_mesh
    shards = batch_mesh_shards(devices)
    if shards < 2:
        _note_fallback("single-device")
        if posture == "require":
            raise MeshFault(
                f"SEMMERGE_MESH=require but the host has "
                f"{len(devices)} device(s) — no batch mesh to shard "
                f"over", cause="single-device")
        return None, 1
    try:
        with _mesh_lock:
            mesh = _mesh_cache.get(shards)
        if mesh is None:
            mesh = build_batch_mesh(devices, shards=shards)
            with _mesh_lock:
                mesh = _mesh_cache.setdefault(shards, mesh)
    except Exception as exc:
        _note_fallback("build-error")
        if posture == "require":
            raise MeshFault(f"batch mesh build failed: {exc}",
                            cause=type(exc).__name__) from exc
        from ..utils.loggingx import logger
        logger.warning("batch mesh build failed, using single-device "
                       "program: %s", exc)
        return None, 1
    return mesh, shards


def mesh_stats() -> Dict[str, object]:
    """Status/stats block of the mesh-sharded dispatch path: the live
    posture, last mesh shape, per-chip real-row occupancy of the last
    mesh dispatch, and cumulative fallback counts by reason."""
    from ..parallel.mesh import mesh_posture
    with _mesh_lock:
        snap = {
            "posture": mesh_posture(),
            "dispatches": _mesh_stats["dispatches"],
            "mesh_dispatches": _mesh_stats["mesh_dispatches"],
            "last_shape": _mesh_stats["last_shape"],
            "last_rows_per_chip": _mesh_stats["last_rows_per_chip"],
            "last_chip_rows": list(_mesh_stats["last_chip_rows"]),
            "fallbacks": dict(_mesh_stats["fallbacks"]),
        }
    return snap


def dispatch_group(scheduler, members) -> None:
    """Leader side: plan mesh → pack → one batched program → scatter.
    ``members`` is a same-bucket-key list of ``(BatchRequest, Future)``
    pairs. Every phase span is grafted into each member's request trace
    under one shared ``batch_id``, so a co-batched request's artifact
    shows the fused dispatch it rode without absorbing its neighbors'
    ids."""
    reqs = [req for req, _fut in members]
    valid = len(reqs)
    batch_id = os.urandom(4).hex()

    from ..parallel.mesh import mesh_posture
    posture = mesh_posture(getattr(scheduler, "mesh_config", None))
    mesh, shards = (None, 1) if posture == "off" else _plan_mesh(posture)
    mesh_shape = f"batch={shards}" if mesh is not None else None
    if mesh is not None:
        rows_per_chip = batch_bucket(valid, shards) // shards
        t0 = time.perf_counter()
        with obs_spans.span("batch.mesh_build", layer="batch",
                            requests=valid, batch_id=batch_id,
                            mesh_shape=mesh_shape,
                            rows_per_chip=rows_per_chip):
            pass  # planned above; the span records the placement choice
        _graft(members, batch_id, "batch.mesh_build",
               time.perf_counter() - t0, t0, requests=valid,
               mesh_shape=mesh_shape, rows_per_chip=rows_per_chip)

    t0 = time.perf_counter()
    with obs_spans.span("batch.pack", layer="batch", requests=valid,
                        batch_id=batch_id):
        arrays, padded, placement = pack_group(reqs, shards)
    _graft(members, batch_id, "batch.pack", time.perf_counter() - t0, t0,
           requests=valid)
    reg = obs_metrics.REGISTRY
    reg.histogram("batch_size",
                  "Valid merges per batched fused dispatch",
                  buckets=BATCH_SIZE_BUCKETS).observe(valid)
    reg.gauge("batch_padding_waste_ratio",
              "Merge-axis padding fraction of the last batched dispatch"
              ).set((padded - valid) / padded)
    if mesh is not None:
        reg.gauge("batch_mesh_occupancy_ratio",
                  "Real-merge fraction of the last mesh-sharded batched "
                  "dispatch (valid rows / padded rows)"
                  ).set(valid / padded)
    geom = reqs[0]
    t0 = time.perf_counter()
    dispatch_meta = {"requests": valid, "padded": padded, "C": geom.C}
    if mesh is not None:
        dispatch_meta.update(mesh_shape=mesh_shape,
                             rows_per_chip=padded // shards)
    with obs_spans.span("batch.dispatch", layer="batch",
                        batch_id=batch_id, **dispatch_meta):
        from ..ops.fused import batched_fused_program
        flat = None
        if mesh is not None:
            try:
                import jax
                from jax.sharding import NamedSharding, PartitionSpec as P

                from ..parallel.mesh import BATCH_AXIS
                program = batched_fused_program(
                    padded, geom.nb, geom.nl, geom.nr, geom.C, mesh=mesh)
                sharded = jax.device_put(
                    arrays, NamedSharding(mesh, P(BATCH_AXIS)))
                flat = np.asarray(program(*sharded))
            except Exception as exc:
                _note_fallback("dispatch-error")
                if posture == "require":
                    raise MeshFault(
                        f"mesh-sharded batch dispatch failed: {exc}",
                        cause=type(exc).__name__) from exc
                from ..utils.loggingx import logger
                logger.warning("mesh-sharded dispatch failed, retrying "
                               "on the single-device program: %s", exc)
                mesh = None
        if flat is None:
            program = batched_fused_program(padded, geom.nb, geom.nl,
                                            geom.nr, geom.C)
            flat = np.asarray(program(*arrays))
        obs_device.record_transfer("d2h", flat.nbytes)
    _graft(members, batch_id, "batch.dispatch", time.perf_counter() - t0,
           t0, **dispatch_meta)
    with _mesh_lock:
        _mesh_stats["dispatches"] += 1
        if mesh is not None:
            _mesh_stats["mesh_dispatches"] += 1
            _mesh_stats["last_shape"] = mesh_shape
            _mesh_stats["last_rows_per_chip"] = padded // shards
            _mesh_stats["last_chip_rows"] = [
                sum(1 for i in range(valid) if i % shards == chip)
                for chip in range(shards)]
    t0 = time.perf_counter()
    with obs_spans.span("batch.scatter", layer="batch", requests=valid,
                        batch_id=batch_id):
        for i, (_req, fut) in enumerate(members):
            if not fut.done():
                fut.set_result(flat[placement[i]])
    _graft(members, batch_id, "batch.scatter", time.perf_counter() - t0, t0,
           requests=valid)
    scheduler.note_batch(valid, padded)
