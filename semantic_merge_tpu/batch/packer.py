"""Packer: stack co-batched merges' encoded snapshots along a new
leading merge axis.

Each request arrives with its decl columns already padded up the
core/encode bucket ladder (``FusedMergeEngine._device_decl`` →
``pad_to``), its op capacity ``C`` already bucketed, and its string
hash table grown in power-of-two steps — so the co-batch **bucket key**
``(nb, nl, nr, C, hash_cap)`` takes few distinct values and identical
keys stack with zero per-request reshaping. The merge axis itself is
padded up its own small ladder (:func:`batch_bucket`) so the jitted
batched program cache stays O(log) per bucket key.

Mesh-sharded dispatch adds two constraints the packer owns:

- the padded merge axis must be a **multiple of the mesh's batch-axis
  size** (each chip takes a contiguous ``padded // shards`` row block),
  so the ladder becomes ``shards × 2^k`` — 3 real merges on a 4-chip
  mesh pad to 4 rows, not 8;
- inert padding rows should land **evenly**: requests are placed
  round-robin across the chip blocks (:func:`placement_for`), so with
  5 valid rows in an 8-row bucket over 4 chips every chip holds at
  least one real merge instead of the last chip holding only padding.
"""
from __future__ import annotations

from typing import List, Tuple

import jax.numpy as jnp
import numpy as np


class BatchRequest:
    """One merge's kernel inputs at the fused engine's dispatch seam:
    the three bucket-padded decl-column device arrays, the device
    string-hash table, the two (seed, rev) prefix digests, and the
    static geometry the jitted program is specialized on."""

    __slots__ = ("dev_b", "dev_l", "dev_r", "hash_tab", "dig_l", "dig_r",
                 "nb", "nl", "nr", "C", "recorder", "trace_id")

    def __init__(self, dev_b, dev_l, dev_r, hash_tab, dig_l, dig_r,
                 *, nb: int, nl: int, nr: int, C: int) -> None:
        self.dev_b = dev_b
        self.dev_l = dev_l
        self.dev_r = dev_r
        self.hash_tab = hash_tab
        self.dig_l = dig_l
        self.dig_r = dig_r
        self.nb = nb
        self.nl = nl
        self.nr = nr
        self.C = C
        # Captured by the submitting request thread (``submit_request``)
        # so the leader can graft its batch spans into each member's
        # request-scoped trace; the bucket key ignores both.
        self.recorder = None
        self.trace_id = None

    @property
    def key(self) -> Tuple[int, int, int, int, int]:
        """The shape bucket this request can co-batch in. Requests with
        equal keys stack directly; the hash-table capacity is part of
        the key because it is a dynamic array dimension of the program."""
        return (self.nb, self.nl, self.nr, self.C,
                int(self.hash_tab.shape[0]))


def batch_bucket(n: int, multiple: int = 1) -> int:
    """Merge-axis ladder: the next ``multiple × 2^k`` ≥ ``n`` — a small
    rung set so batched program shapes, like the decl buckets, compile
    O(log) variants instead of one per batch size. ``multiple`` is the
    mesh batch-axis size (1 for the single-device program, giving the
    classic power-of-two ladder): every rung divides evenly into
    per-chip row blocks, and 3 real merges on a 4-chip mesh pad to 4
    rows (one block each), never 8."""
    multiple = max(1, int(multiple))
    bucket = multiple
    while bucket < n:
        bucket *= 2
    return bucket


def placement_for(valid: int, padded: int, shards: int = 1) -> List[int]:
    """Row index for each of the ``valid`` requests in a ``padded``-row
    batch sharded into ``shards`` contiguous chip blocks: request ``i``
    lands in block ``i % shards`` at slot ``i // shards`` — round-robin
    across chips, so real merges (and therefore inert padding) spread
    evenly instead of piling the padding onto the tail chips. With
    ``shards == 1`` this is the identity layout."""
    block = padded // max(1, shards)
    return [(i % shards) * block + (i // shards) for i in range(valid)]


def pack_group(reqs: List[BatchRequest], shards: int = 1):
    """Stack one co-batch group's inputs along a new leading merge
    axis, padded up :func:`batch_bucket` (rounded to a multiple of
    ``shards``) by replicating request 0 into every unplaced row —
    padding rows are inert by construction: every lane of the batched
    program is independent, and padded lanes' outputs are simply never
    scattered back to any request.

    Returns ``((b, l, r, hash_tabs, digs_l, digs_r), padded_size,
    placement)`` where ``placement[i]`` is the packed row carrying
    request ``i`` (see :func:`placement_for`).
    """
    valid = len(reqs)
    padded = batch_bucket(valid, shards)
    placement = placement_for(valid, padded, shards)
    order = [0] * padded
    for i, row in enumerate(placement):
        order[row] = i

    def stack(field: str):
        return jnp.stack([getattr(reqs[i], field) for i in order])

    digs_l = np.stack([np.asarray(reqs[i].dig_l) for i in order])
    digs_r = np.stack([np.asarray(reqs[i].dig_r) for i in order])
    return ((stack("dev_b"), stack("dev_l"), stack("dev_r"),
             stack("hash_tab"), digs_l, digs_r), padded, placement)
