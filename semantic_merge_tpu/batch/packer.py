"""Packer: stack co-batched merges' encoded snapshots along a new
leading merge axis.

Each request arrives with its decl columns already padded up the
core/encode bucket ladder (``FusedMergeEngine._device_decl`` →
``pad_to``), its op capacity ``C`` already bucketed, and its string
hash table grown in power-of-two steps — so the co-batch **bucket key**
``(nb, nl, nr, C, hash_cap)`` takes few distinct values and identical
keys stack with zero per-request reshaping. The merge axis itself is
padded up its own small ladder (:func:`batch_bucket`) so the jitted
batched program cache stays O(log) per bucket key.
"""
from __future__ import annotations

from typing import List, Tuple

import jax.numpy as jnp
import numpy as np


class BatchRequest:
    """One merge's kernel inputs at the fused engine's dispatch seam:
    the three bucket-padded decl-column device arrays, the device
    string-hash table, the two (seed, rev) prefix digests, and the
    static geometry the jitted program is specialized on."""

    __slots__ = ("dev_b", "dev_l", "dev_r", "hash_tab", "dig_l", "dig_r",
                 "nb", "nl", "nr", "C", "recorder", "trace_id")

    def __init__(self, dev_b, dev_l, dev_r, hash_tab, dig_l, dig_r,
                 *, nb: int, nl: int, nr: int, C: int) -> None:
        self.dev_b = dev_b
        self.dev_l = dev_l
        self.dev_r = dev_r
        self.hash_tab = hash_tab
        self.dig_l = dig_l
        self.dig_r = dig_r
        self.nb = nb
        self.nl = nl
        self.nr = nr
        self.C = C
        # Captured by the submitting request thread (``submit_request``)
        # so the leader can graft its batch spans into each member's
        # request-scoped trace; the bucket key ignores both.
        self.recorder = None
        self.trace_id = None

    @property
    def key(self) -> Tuple[int, int, int, int, int]:
        """The shape bucket this request can co-batch in. Requests with
        equal keys stack directly; the hash-table capacity is part of
        the key because it is a dynamic array dimension of the program."""
        return (self.nb, self.nl, self.nr, self.C,
                int(self.hash_tab.shape[0]))


def batch_bucket(n: int) -> int:
    """Merge-axis ladder: the next power of two ≥ ``n`` (1, 2, 4, 8, …)
    — a small rung set so batched program shapes, like the decl
    buckets, compile O(log) variants instead of one per batch size."""
    bucket = 1
    while bucket < n:
        bucket *= 2
    return bucket


def pack_group(reqs: List[BatchRequest]):
    """Stack one co-batch group's inputs along a new leading merge
    axis, padded up :func:`batch_bucket` by replicating request 0 —
    padding rows are inert by construction: every lane of the vmapped
    program is independent, and padded lanes' outputs are simply never
    scattered back to any request.

    Returns ``((b, l, r, hash_tabs, digs_l, digs_r), padded_size)``.
    """
    valid = len(reqs)
    padded = batch_bucket(valid)
    order = list(range(valid)) + [0] * (padded - valid)

    def stack(field: str):
        return jnp.stack([getattr(reqs[i], field) for i in order])

    digs_l = np.stack([np.asarray(reqs[i].dig_l) for i in order])
    digs_r = np.stack([np.asarray(reqs[i].dig_r) for i in order])
    return ((stack("dev_b"), stack("dev_l"), stack("dev_r"),
             stack("hash_tab"), digs_l, digs_r), padded)
