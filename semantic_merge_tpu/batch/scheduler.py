"""Micro-batch window scheduler.

One leader thread owns a bounded admission queue. The first request to
arrive opens a window of ``SEMMERGE_BATCH_WINDOW_MS``; everything that
lands inside it (up to ``SEMMERGE_BATCH_MAX``) joins the round, is
grouped by shape-bucket key, and each group is handed to the dispatch
pool (``SEMMERGE_BATCH_INFLIGHT`` bounds concurrently in-flight batched
programs — the leader keeps collecting the next window while earlier
batches run, which is what makes the batching *continuous* rather than
lock-step). Requests never block each other beyond the window: a
window with one request dispatches a batch of one.

The scheduler is posture-free by design — posture, fault injection and
degradation all happen on the request threads
(:mod:`~semantic_merge_tpu.batch.dispatcher`), where the per-request
env overlay is in scope.
"""
from __future__ import annotations

import os
import queue
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, Optional

from ..obs import spans as obs_spans

#: Scheduler knobs (process env at activation — daemon-side settings).
ENV_WINDOW_MS = "SEMMERGE_BATCH_WINDOW_MS"
ENV_MAX_BATCH = "SEMMERGE_BATCH_MAX"
ENV_INFLIGHT = "SEMMERGE_BATCH_INFLIGHT"


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if raw:
        try:
            return float(raw)
        except ValueError:
            from ..utils.loggingx import logger
            logger.warning("invalid %s=%r ignored", name, raw)
    return default


def _env_int(name: str, default: int) -> int:
    return int(_env_float(name, float(default)))


class BatchScheduler:
    """The daemon-side micro-batch window: admission queue + leader
    thread + bounded dispatch pool. One per process (see
    ``batch.activate``)."""

    def __init__(self, *, window_ms: Optional[float] = None,
                 max_batch: Optional[int] = None,
                 max_inflight: Optional[int] = None,
                 mesh: Optional[str] = None) -> None:
        if window_ms is None:
            window_ms = _env_float(ENV_WINDOW_MS, 5.0)
        #: Configured mesh posture (``[engine] mesh``) the dispatcher
        #: reads when the SEMMERGE_MESH env var is unset — the daemon
        #: threads its config through here so one posture governs both
        #: the one-shot engine and the sharded batch dispatch.
        self.mesh_config = mesh
        self.window_s = max(0.0, float(window_ms) / 1000.0)
        self.max_batch = max(1, max_batch if max_batch is not None
                             else _env_int(ENV_MAX_BATCH, 16))
        self.max_inflight = max(1, max_inflight if max_inflight is not None
                                else _env_int(ENV_INFLIGHT, 2))
        self._queue: "queue.Queue" = queue.Queue()
        self._stopping = threading.Event()
        self._sem = threading.Semaphore(self.max_inflight)
        self._pool = ThreadPoolExecutor(
            max_workers=self.max_inflight,
            thread_name_prefix="semmerge-batch")
        self._lock = threading.Lock()
        self._batches = 0
        self._requests = 0
        self._waste_sum = 0.0
        self._active = 0
        self._inflight_cap = self.max_inflight
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "BatchScheduler":
        self._thread = threading.Thread(
            target=self._run, name="semmerge-batch-window", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the leader and fail anything still queued — waiting
        request threads then degrade to the inline dispatch instead of
        hanging on an orphaned future."""
        self._stopping.set()
        self._queue.put(None)
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._pool.shutdown(wait=True)
        self._fail_pending()

    def alive(self) -> bool:
        return (self._thread is not None and self._thread.is_alive()
                and not self._stopping.is_set())

    # -- request side ------------------------------------------------------

    def submit(self, request) -> Future:
        from ..errors import BatchFault
        if not self.alive():
            raise BatchFault("batch scheduler is not running",
                             stage="batch:pack")
        fut: Future = Future()
        self._queue.put((request, fut))
        return fut

    def set_inflight_cap(self, cap: int) -> None:
        """Shrink (or restore) the effective in-flight bound without
        rebuilding the pool — the daemon's RSS-watermark response. The
        semaphore keeps its full count; the leader additionally honors
        this soft cap before dispatching, so a shrink takes effect as
        running batches finish."""
        with self._lock:
            self._inflight_cap = max(1, min(int(cap), self.max_inflight))

    # -- accounting --------------------------------------------------------

    def note_batch(self, valid: int, padded: int) -> None:
        with self._lock:
            self._batches += 1
            self._requests += valid
            self._waste_sum += (padded - valid) / padded

    def stats(self) -> Dict[str, object]:
        """Status-endpoint block: queue depth, mean batch size, padding
        waste, and the batched-program cache hit rate."""
        with self._lock:
            batches, requests = self._batches, self._requests
            waste_sum = self._waste_sum
        from ..ops.fused import batched_program_cache_stats
        from .dispatcher import mesh_stats
        return {
            "queue_depth": self._queue.qsize(),
            "mesh": mesh_stats(),
            "window_ms": self.window_s * 1e3,
            "max_batch": self.max_batch,
            "max_inflight": self.max_inflight,
            "inflight_cap": self._inflight_cap,
            "batches_total": batches,
            "requests_batched": requests,
            "mean_batch_size": (requests / batches) if batches else 0.0,
            "padding_waste_ratio": (waste_sum / batches) if batches else 0.0,
            "program_cache": batched_program_cache_stats(),
        }

    # -- leader ------------------------------------------------------------

    def _run(self) -> None:
        while True:
            item = self._queue.get()
            if item is None or self._stopping.is_set():
                break
            opened = time.perf_counter()
            group = [item]
            deadline = opened + self.window_s
            while len(group) < self.max_batch:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is None:
                    self._stopping.set()
                    break
                group.append(nxt)
            window_s = time.perf_counter() - opened
            obs_spans.record("batch.window", window_s, layer="batch",
                             t_start=opened, requests=len(group))
            # Graft the window wait into every member's request trace —
            # the leader thread has no request scope, so the members'
            # captured recorders are the only route in.
            for request, _fut in group:
                rec = getattr(request, "recorder", None)
                if rec is not None:
                    obs_spans.record_into(
                        rec, "batch.window", window_s, t_start=opened,
                        layer="batch", requests=len(group),
                        trace_id=getattr(request, "trace_id", None))
            by_key: Dict[tuple, list] = {}
            for request, fut in group:
                by_key.setdefault(request.key, []).append((request, fut))
            for members in by_key.values():
                self._acquire_slot()
                try:
                    self._pool.submit(self._dispatch, members)
                except RuntimeError as exc:  # pool shut down underneath
                    self._release_slot()
                    self._fail_members(members, exc)
            if self._stopping.is_set():
                break

    def _acquire_slot(self) -> None:
        self._sem.acquire()
        while True:
            with self._lock:
                if self._active < self._inflight_cap \
                        or self._stopping.is_set():
                    self._active += 1
                    return
            time.sleep(0.002)

    def _release_slot(self) -> None:
        with self._lock:
            self._active -= 1
        self._sem.release()

    def _dispatch(self, members) -> None:
        from .dispatcher import dispatch_group
        try:
            dispatch_group(self, members)
        except BaseException as exc:  # noqa: BLE001 — futures carry it
            self._fail_members(members, exc)
        finally:
            self._release_slot()

    def _fail_members(self, members, exc) -> None:
        for _request, fut in members:
            if not fut.done():
                fut.set_exception(exc)

    def _fail_pending(self) -> None:
        from ..errors import BatchFault
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                return
            if item is not None:
                self._fail_members([item], BatchFault(
                    "batch scheduler stopped", stage="batch:dispatch"))
