"""Per-file 3-way text-merge fallback — requirement [FBK-001].

The reference *requires* that files the semantic engine cannot handle
fall back to git's text 3-way merge for that file only (reference
``requirements.md:105`` [FBK-001]) but never implements it: its applier
starts from the base tree, so changes to non-indexed files silently
revert in ``--inplace`` merges (the e2e path survives only because git
routes just ``*.ts`` to the merge driver). This module implements the
requirement: after op application, every file *outside* the indexed
extension set merges textually — trivial resolutions (one side
unchanged, both sides identical) in-process, true both-sided edits via
``git merge-file``; marker conflicts surface as ``TextMergeConflict``
records in ``.semmerge-conflicts.json`` with the conflicting file as
the minimal slice.

Binary files (undecodable as UTF-8) resolve one-side changes and
report both-side changes as conflicts — never text-merged.
"""
from __future__ import annotations

import io
import pathlib
import subprocess
import tarfile
import tempfile
from typing import Dict, List, Optional, Tuple

from ..core.conflict import Conflict
from ..frontend.snapshot import SOURCE_EXTENSIONS
from ..utils.loggingx import logger


def tar_file_map(tar_bytes: bytes) -> Dict[str, bytes]:
    """Every regular file in an archive, path → raw bytes."""
    out: Dict[str, bytes] = {}
    with tarfile.open(fileobj=io.BytesIO(tar_bytes)) as tar:
        for member in tar.getmembers():
            if not member.isfile():
                continue
            fh = tar.extractfile(member)
            if fh is not None:
                out[member.name] = fh.read()
    return out


def apply_text_fallback(merged_tree: pathlib.Path, base_tar: bytes,
                        left_tar: bytes, right_tar: bytes, *,
                        indexed_extensions=None,
                        ) -> Tuple[List[Conflict], List[str], List[str]]:
    """Textually merge non-indexed files into ``merged_tree``.

    ``indexed_extensions`` is the *active backend's* extension set —
    only those files belong to the semantic pipeline; everything else
    (including other backends' languages) falls back to text merge.
    Returns ``(conflicts, deleted_paths, written_paths)``; the caller
    must propagate deletions when copying the merged tree elsewhere
    (``--inplace``), and ``written_paths`` feeds touched-scope
    formatting.
    """
    merged_tree = pathlib.Path(merged_tree)
    indexed = (frozenset(indexed_extensions) if indexed_extensions is not None
               else frozenset(SOURCE_EXTENSIONS))
    base = tar_file_map(base_tar)
    left = tar_file_map(left_tar)
    right = tar_file_map(right_tar)

    conflicts: List[Conflict] = []
    deleted: List[str] = []
    written: List[str] = []
    paths = sorted((set(left) | set(right) | set(base)))
    for path in paths:
        if pathlib.PurePosixPath(path).suffix in indexed:
            # The semantic pipeline owns indexed files — EXCEPT a file
            # that exists on a side but neither in base nor in the
            # op-applied tree: a pure one-sided add the op vocabulary
            # has no whole-file handler for (the reference applier
            # skips addDecl too, reference ``semmerge/applier.py:30-31``
            # — its real driver flow leans on git fast-forwarding pure
            # adds, which a standalone ``semmerge`` invocation cannot).
            # Those fall through to the text layer, which resolves a
            # one-sided add trivially and a both-sided divergent add as
            # a conflict.
            if path in base or (merged_tree / path).exists():
                continue
        base_c = base.get(path)
        resolved, conflict = _resolve(path, base_c, left.get(path),
                                      right.get(path))
        if conflict is not None:
            conflicts.append(conflict)
            continue
        target = merged_tree / path
        if resolved is None:
            if target.exists():
                target.unlink()
            if base_c is not None:
                deleted.append(path)
            continue
        if resolved == base_c:
            continue  # already on disk from the base tree
        target.parent.mkdir(parents=True, exist_ok=True)
        target.write_bytes(resolved)
        written.append(path)
    return conflicts, deleted, written


def _resolve(path: str, base: Optional[bytes], a: Optional[bytes],
             b: Optional[bytes]) -> Tuple[Optional[bytes], Optional[Conflict]]:
    """Classic 3-way per-file resolution; (content-or-None, conflict)."""
    if a == base and b == base:
        return base, None
    if a == base:
        return b, None
    if b == base:
        return a, None
    if a == b:
        return a, None
    # Both sides changed, differently. Delete-vs-edit or binary → conflict.
    if a is None or b is None or _is_binary(a) or _is_binary(b) \
            or (base is not None and _is_binary(base)):
        return None, _text_conflict(path, "both sides changed incompatibly")
    merged, clean, failure = _git_merge_file(base or b"", a, b)
    if clean:
        return merged, None
    return None, _text_conflict(path, failure or "overlapping text edits")


def _is_binary(data: Optional[bytes]) -> bool:
    if data is None:
        return False
    if b"\x00" in data[:8192]:
        return True
    try:
        data.decode("utf-8")
        return False
    except UnicodeDecodeError:
        return True


def _git_merge_file(base: bytes, a: bytes, b: bytes,
                    ) -> Tuple[bytes, bool, Optional[str]]:
    """3-way merge via ``git merge-file``; (result, was_clean,
    failure_reason) — ``failure_reason`` set only for environment
    failures (so a missing git is not reported as a content conflict)."""
    with tempfile.TemporaryDirectory(prefix="semmerge_txt_") as tmp:
        tmp_path = pathlib.Path(tmp)
        (tmp_path / "base").write_bytes(base)
        (tmp_path / "a").write_bytes(a)
        (tmp_path / "b").write_bytes(b)
        try:
            proc = subprocess.run(
                ["git", "merge-file", "--stdout", "-L", "A", "-L", "base",
                 "-L", "B", str(tmp_path / "a"), str(tmp_path / "base"),
                 str(tmp_path / "b")],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        except OSError as exc:  # git missing → conservative conflict
            logger.warning("git merge-file unavailable: %s", exc)
            return b"", False, f"text merge unavailable ({exc})"
        # Exit status: 0 clean, >0 = number of conflicts, <0 error.
        return proc.stdout, proc.returncode == 0, None


def _text_conflict(path: str, reason: str) -> Conflict:
    from ..core.ids import stable_hash_hex
    return Conflict(
        id=f"conf-{stable_hash_hex('text', path, n_hex=8)}-textmerg",
        category="TextMergeConflict",
        symbolId="",
        addressIds={"A": path, "B": path, "base": path},
        opA={}, opB={},
        minimalSlice={"path": path, "start": 0, "end": 0, "code": reason},
        suggestions=[],
    )
