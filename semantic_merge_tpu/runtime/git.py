"""Git plumbing (reference ``semmerge/git_api.py``).

Adds two things over the reference: commit timestamps (feeding the
deterministic provenance scheme), and a batched in-memory snapshot
reader (``snapshot_rev``) that goes through ``git archive`` piped to an
in-process tar reader instead of materializing a tree on disk — for
10k-file repos this skips one full filesystem round-trip per revision
(the reference always untars to a tempdir and re-reads every file,
reference ``semmerge/git_api.py:23-33`` + ``semmerge/lang/ts/bridge.py:66-78``).
"""
from __future__ import annotations

import io
import pathlib
import subprocess
import tarfile
import tempfile
from typing import Iterable, List

from ..frontend.snapshot import SOURCE_EXTENSIONS, Snapshot


def run_git(args: Iterable[str], cwd: pathlib.Path | None = None) -> str:
    proc = subprocess.run(["git", *args], check=True, stdout=subprocess.PIPE,
                          text=True, cwd=cwd)
    return proc.stdout.strip()


def resolve_rev(rev: str, cwd: pathlib.Path | None = None) -> str:
    return run_git(["rev-parse", rev], cwd=cwd)


def commit_timestamp_iso(rev: str, cwd: pathlib.Path | None = None) -> str:
    """The commit's committer time as a UTC ISO-8601 string — the
    deterministic replacement for the reference's wall-clock provenance
    (reference ``workers/ts/src/lift.ts:9``)."""
    try:
        epoch = int(run_git(["show", "-s", "--format=%ct", rev], cwd=cwd).splitlines()[0])
    except (subprocess.CalledProcessError, ValueError, IndexError):
        return "1970-01-01T00:00:00Z"
    import datetime
    dt = datetime.datetime.fromtimestamp(epoch, tz=datetime.timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%SZ")


def archive_bytes(rev: str, cwd: pathlib.Path | None = None) -> bytes:
    """One ``git archive`` round-trip for a revision's full tree."""
    resolved = resolve_rev(rev, cwd=cwd)
    proc = subprocess.run(["git", "archive", resolved], check=True,
                          stdout=subprocess.PIPE, cwd=cwd)
    return proc.stdout


def extract_tree_to_temp(tar_bytes: bytes) -> pathlib.Path:
    """Materialize already-fetched archive bytes into a temp dir."""
    tmpdir = pathlib.Path(tempfile.mkdtemp(prefix="semmerge_tree_"))
    with tarfile.open(fileobj=io.BytesIO(tar_bytes)) as tar:
        tar.extractall(tmpdir, filter="data")
    return tmpdir


def checkout_tree_to_temp(rev: str, cwd: pathlib.Path | None = None) -> pathlib.Path:
    """Materialize ``rev`` into a temp dir (reference
    ``semmerge/git_api.py:23-33``) — still needed for apply/format/verify,
    which operate on real files."""
    return extract_tree_to_temp(archive_bytes(rev, cwd=cwd))


def snapshot_from_bytes(tar_bytes: bytes, paths=None) -> Snapshot:
    """Parse archive bytes into a Snapshot. ``paths`` (a set) restricts
    the snapshot to those files — the incremental-merge scope — and
    skips the UTF-8 decode of everything else, which dominates
    snapshotting cost on large trees."""
    files = []
    with tarfile.open(fileobj=io.BytesIO(tar_bytes)) as tar:
        for member in tar.getmembers():
            if not member.isfile():
                continue
            if paths is not None and member.name not in paths:
                continue
            suffix = pathlib.PurePosixPath(member.name).suffix
            if suffix not in SOURCE_EXTENSIONS:
                continue
            fh = tar.extractfile(member)
            if fh is None:
                continue
            files.append({"path": member.name, "content": fh.read().decode("utf-8")})
    files.sort(key=lambda f: f["path"])
    return Snapshot(files=files)


def snapshot_rev(rev: str, cwd: pathlib.Path | None = None) -> Snapshot:
    """Read a revision's source files straight into a Snapshot without
    touching the filesystem (all supported languages; backends filter)."""
    return snapshot_from_bytes(archive_bytes(rev, cwd=cwd))


def changed_files_between(rev1: str, rev2: str, cwd: pathlib.Path | None = None) -> List[str]:
    """Paths touched between two revisions. ``--no-renames`` keeps a
    rename as its delete+add pair so BOTH paths land in the scope."""
    out = run_git(["diff", "--name-only", "--no-renames", f"{rev1}..{rev2}"],
                  cwd=cwd)
    return [line for line in out.splitlines() if line]


def diff_scope(rev1: str, rev2: str,
               cwd: pathlib.Path | None = None) -> "set[str] | None":
    """Two-revision incremental scope (the ``semdiff`` twin of
    :func:`merge_scope`); ``None`` → caller falls back to full-tree.
    Same fallback policy as merge_scope: only a failed git invocation
    disables incremental mode."""
    try:
        return set(changed_files_between(rev1, rev2, cwd=cwd))
    except subprocess.CalledProcessError:
        return None


def merge_scope(base: str, a: str, b: str,
                cwd: pathlib.Path | None = None) -> "set[str] | None":
    """The incremental-merge file scope: every path either side touched
    relative to base (reference ``architecture.md:202-204`` prunes the
    same way — its perf budgets assume ≤200 changed files of a 1M-LOC
    repo). Decls in files neither side touched are identical in all
    three snapshots and can contribute no diff row, and restriction
    preserves file order, so op streams and deterministic op ids are
    unchanged (see ``Snapshot.restrict``); symbolMaps naturally cover
    only the scoped files. Returns ``None`` (caller falls back to the
    full-tree scan) when git cannot answer.

    Known semantic caveat, shared with the reference's design: under
    symbolId *collisions* (two decls with identical structural
    signatures, JS-``Map`` last-wins — reference
    ``workers/ts/src/sast.ts:65-67``) the surviving occurrence can
    differ when the colliding twin lives outside the scope. Set
    ``[engine] incremental = false`` for collision-exact full scans."""
    try:
        changed = set(changed_files_between(base, a, cwd=cwd))
        changed |= set(changed_files_between(base, b, cwd=cwd))
        return changed
    except subprocess.CalledProcessError:
        return None
