"""Git plumbing (reference ``semmerge/git_api.py``).

Adds two things over the reference: commit timestamps (feeding the
deterministic provenance scheme), and a batched in-memory snapshot
reader (``snapshot_rev``) that goes through ``git archive`` piped to an
in-process tar reader instead of materializing a tree on disk — for
10k-file repos this skips one full filesystem round-trip per revision
(the reference always untars to a tempdir and re-reads every file,
reference ``semmerge/git_api.py:23-33`` + ``semmerge/lang/ts/bridge.py:66-78``).
"""
from __future__ import annotations

import contextlib
import io
import pathlib
import shutil
import subprocess
import tarfile
import tempfile
from collections import OrderedDict
from typing import Dict, Iterable, Iterator, List

from ..frontend.snapshot import SOURCE_EXTENSIONS, Snapshot
from ..utils import workdir


def run_git(args: Iterable[str], cwd: pathlib.Path | None = None) -> str:
    # cwd=None resolves to the scoped request root when inside a merge
    # service request, the process cwd otherwise (utils/workdir).
    proc = subprocess.run(["git", *args], check=True, stdout=subprocess.PIPE,
                          text=True, cwd=cwd if cwd is not None
                          else workdir.current())
    return proc.stdout.strip()


def resolve_rev(rev: str, cwd: pathlib.Path | None = None) -> str:
    return run_git(["rev-parse", rev], cwd=cwd)


def tree_oid(rev: str, cwd: pathlib.Path | None = None) -> str:
    """The tree object id a revision points at — the content address
    the warm residency cache (``service/residency.py``) keys encoded
    base snapshots under."""
    return run_git(["rev-parse", rev + "^{tree}"], cwd=cwd)


def commit_timestamp_iso(rev: str, cwd: pathlib.Path | None = None) -> str:
    """The commit's committer time as a UTC ISO-8601 string — the
    deterministic replacement for the reference's wall-clock provenance
    (reference ``workers/ts/src/lift.ts:9``)."""
    try:
        epoch = int(run_git(["show", "-s", "--format=%ct", rev], cwd=cwd).splitlines()[0])
    except (subprocess.CalledProcessError, ValueError, IndexError):
        return "1970-01-01T00:00:00Z"
    import datetime
    dt = datetime.datetime.fromtimestamp(epoch, tz=datetime.timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%SZ")


def archive_bytes(rev: str, cwd: pathlib.Path | None = None) -> bytes:
    """One ``git archive`` round-trip for a revision's full tree."""
    resolved = resolve_rev(rev, cwd=cwd)
    proc = subprocess.run(["git", "archive", resolved], check=True,
                          stdout=subprocess.PIPE,
                          cwd=cwd if cwd is not None else workdir.current())
    return proc.stdout


def extract_tree_to_temp(tar_bytes: bytes) -> pathlib.Path:
    """Materialize already-fetched archive bytes into a temp dir."""
    tmpdir = pathlib.Path(tempfile.mkdtemp(prefix="semmerge_tree_"))
    with tarfile.open(fileobj=io.BytesIO(tar_bytes)) as tar:
        tar.extractall(tmpdir, filter="data")
    return tmpdir


@contextlib.contextmanager
def temp_tree(tar_bytes: bytes) -> Iterator[pathlib.Path]:
    """:func:`extract_tree_to_temp` as a context manager: the temp tree
    is removed on EVERY exit path — exceptions, early returns, ladder
    degradations — not just the one ``finally`` a caller remembered."""
    tmpdir = extract_tree_to_temp(tar_bytes)
    try:
        yield tmpdir
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


def checkout_tree_to_temp(rev: str, cwd: pathlib.Path | None = None) -> pathlib.Path:
    """Materialize ``rev`` into a temp dir (reference
    ``semmerge/git_api.py:23-33``) — still needed for apply/format/verify,
    which operate on real files."""
    return extract_tree_to_temp(archive_bytes(rev, cwd=cwd))


def snapshot_from_bytes(tar_bytes: bytes, paths=None) -> Snapshot:
    """Parse archive bytes into a Snapshot. ``paths`` (a set) restricts
    the snapshot to those files — the incremental-merge scope — and
    skips the UTF-8 decode of everything else, which dominates
    snapshotting cost on large trees."""
    files = []
    with tarfile.open(fileobj=io.BytesIO(tar_bytes)) as tar:
        for member in tar.getmembers():
            if not member.isfile():
                continue
            if paths is not None and member.name not in paths:
                continue
            suffix = pathlib.PurePosixPath(member.name).suffix
            if suffix not in SOURCE_EXTENSIONS:
                continue
            fh = tar.extractfile(member)
            if fh is None:
                continue
            files.append({"path": member.name, "content": fh.read().decode("utf-8")})
    files.sort(key=lambda f: f["path"])
    return Snapshot(files=files)


def snapshot_rev(rev: str, cwd: pathlib.Path | None = None) -> Snapshot:
    """Read a revision's source files straight into a Snapshot without
    touching the filesystem (all supported languages; backends filter)."""
    return snapshot_from_bytes(archive_bytes(rev, cwd=cwd))


def changed_files_between(rev1: str, rev2: str, cwd: pathlib.Path | None = None) -> List[str]:
    """Paths touched between two revisions. ``--no-renames`` keeps a
    rename as its delete+add pair so BOTH paths land in the scope."""
    out = run_git(["diff", "--name-only", "--no-renames", f"{rev1}..{rev2}"],
                  cwd=cwd)
    return [line for line in out.splitlines() if line]


def diff_scope(rev1: str, rev2: str,
               cwd: pathlib.Path | None = None) -> "set[str] | None":
    """Two-revision incremental scope (the ``semdiff`` twin of
    :func:`merge_scope`); ``None`` → caller falls back to full-tree.
    Same fallback policy as merge_scope: only a failed git invocation
    disables incremental mode."""
    try:
        return set(changed_files_between(rev1, rev2, cwd=cwd))
    except subprocess.CalledProcessError:
        return None


def merge_scope(base: str, a: str, b: str,
                cwd: pathlib.Path | None = None) -> "set[str] | None":
    """The incremental-merge file scope: every path either side touched
    relative to base (reference ``architecture.md:202-204`` prunes the
    same way — its perf budgets assume ≤200 changed files of a 1M-LOC
    repo). Decls in files neither side touched are identical in all
    three snapshots and can contribute no diff row, and restriction
    preserves file order, so op streams and deterministic op ids are
    unchanged (see ``Snapshot.restrict``); symbolMaps naturally cover
    only the scoped files. Returns ``None`` (caller falls back to the
    full-tree scan) when git cannot answer.

    Semantic caveat, shared with the reference's design: under symbolId
    *collisions* (two decls with identical structural signatures,
    JS-``Map`` last-wins — reference ``workers/ts/src/sast.ts:65-67``)
    the surviving occurrence can differ when the colliding twin lives
    outside the scope. The CLI closes this hole automatically: after
    snapshotting it runs :func:`collision_safe_scope`, which keeps a
    full-tree symbolId multiset per base commit and falls back to the
    full scan whenever a scoped symbolId has an out-of-scope twin.
    ``[engine] incremental = false`` still forces full scans outright."""
    try:
        changed = set(changed_files_between(base, a, cwd=cwd))
        changed |= set(changed_files_between(base, b, cwd=cwd))
        return changed
    except subprocess.CalledProcessError:
        return None


# --------------------------------------------------------------------------
# Incremental-scope collision guard
# --------------------------------------------------------------------------
# The per-commit symbol index: resolved rev → {path: [symbolId, ...]}
# over the TS-indexed files of the FULL tree. Bounded; entries are pure
# functions of the commit's content.
_SYMID_INDEX_CACHE: "OrderedDict[str, Dict[str, List[str]]]" = OrderedDict()


def snapshot_symbol_index(snap: Snapshot) -> Dict[str, List[str]]:
    """Per-file symbolId lists of a snapshot's TS-indexed files (keyed
    by the raw snapshot path — the same strings a git scope carries).
    Scans go through the process-wide decl cache, so files the merge
    scans anyway are shared work, not duplicate work."""
    from ..frontend.scanner import scan_snapshot_keyed
    from ..frontend.snapshot import TS_EXTENSIONS, filter_files
    files = filter_files(snap, TS_EXTENSIONS)
    return {f["path"]: [n.symbolId for n in nodes]
            for f, (_, nodes) in zip(files, scan_snapshot_keyed(files))}


def full_tree_symbol_index(tar_bytes: bytes,
                           rev: str | None = None) -> Dict[str, List[str]]:
    """The symbol index of a revision's full tree, memoized per
    resolved commit — repeated merges against one base (watch mode,
    merge-driver repo runs, the bench) pay the full-tree scan once per
    process, and the decl cache absorbs most of even the cold scan."""
    if rev is not None:
        hit = _SYMID_INDEX_CACHE.get(rev)
        if hit is not None:
            _SYMID_INDEX_CACHE.move_to_end(rev)
            return hit
    index = snapshot_symbol_index(snapshot_from_bytes(tar_bytes))
    if rev is not None:
        _SYMID_INDEX_CACHE[rev] = index
        while len(_SYMID_INDEX_CACHE) > 8:
            _SYMID_INDEX_CACHE.popitem(last=False)
    return index


def scope_symbol_collisions(scope: "set[str]",
                            base_index: Dict[str, List[str]],
                            scoped_snaps: Iterable[Snapshot]) -> bool:
    """True when any symbolId indexed by a scoped file also occurs in
    an out-of-scope file of the base tree — the Map-last-wins hazard of
    :func:`merge_scope`: restriction could change which colliding
    occurrence survives the per-symbol join. Out-of-scope files are
    identical in every snapshot of the merge (that is what "out of
    scope" means), so the base tree's index is exact for them; scoped
    ids union over all restricted snapshots, so decls a side *added*
    count too."""
    scoped_ids: set = set()
    out_ids: set = set()
    for path, ids in base_index.items():
        (scoped_ids if path in scope else out_ids).update(ids)
    for snap in scoped_snaps:
        for ids in snapshot_symbol_index(snap).values():
            scoped_ids.update(ids)
    return bool(scoped_ids & out_ids)


def collision_safe_scope(scope: "set[str] | None", base_tar: bytes,
                         base_rev: str | None,
                         scoped_snaps: Iterable[Snapshot]
                         ) -> "set[str] | None":
    """``scope`` when the incremental restriction is collision-exact,
    else ``None`` — the caller falls back to full-tree snapshots.
    An empty scope (no changed files) trivially passes."""
    if not scope:
        return scope
    index = full_tree_symbol_index(base_tar, base_rev)
    if scope_symbol_collisions(scope, index, scoped_snaps):
        return None
    return scope
