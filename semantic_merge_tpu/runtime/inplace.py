"""Crash-safe ``--inplace`` apply: stage → journal → atomic commit.

The old in-place path copied the merged tree file-by-file straight into
the working tree, so a crash mid-copy (OOM-killed CLI, ctrl-C, power
loss) left a *torn* tree — half old, half new — which the git merge
driver would then happily publish as the merge result. This module
makes the commit two-phase:

1. **Stage**: every file of the merged tree is copied into a sibling
   ``.semmerge-stage/`` directory inside the target root (same
   filesystem, so the later renames are atomic). A crash here leaves
   only a stray stage directory; the work tree is bitwise untouched.
2. **Journal**: the intended writes and deletes are recorded in
   ``.semmerge-journal.json`` — written to a temp name, fsynced, then
   atomically renamed into place. The journal's existence IS the
   commit marker: from this instant the merge is redo-able.
3. **Commit**: each staged file is ``os.replace``d onto its target
   (atomic per file) and each journaled delete unlinked; the journal
   and stage directory are then removed.

A process killed at ANY point leaves one of two recoverable states:

- stage dir without journal → the commit never started; **rollback**
  (remove the stage dir, work tree untouched);
- journal present → the commit may be partial; **roll forward**
  (replay the remaining renames/deletes — ``os.replace`` of an
  already-moved file is skipped because its staged source is gone).

:func:`recover` implements both and is invoked automatically at the
start of every ``--inplace`` merge and explicitly by
``semmerge --resume``.

Cross-process exclusion: the stage/journal protocol is crash-safe but
not *concurrent*-safe — two simultaneous ``--inplace`` merges in the
same work tree would interleave on ``.semmerge-stage/`` and clobber
each other's journal. :func:`repo_lock` is the shared repo-level mutex:
an ``O_EXCL`` lockfile carrying ``pid mtime``, with the same staleness
heuristic as the merge driver's latch (old mtime, or a recorded pid
that no longer exists). The one-shot CLI takes it around every
``--inplace`` merge and the service daemon takes the same lock for its
requests, so daemon and one-shot runs exclude each other too.
"""
from __future__ import annotations

import contextlib
import json
import os
import pathlib
import shutil
import time
from typing import Iterable, Iterator, List, Tuple

from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans
from ..utils import faults, workdir
from ..utils.loggingx import logger

JOURNAL = ".semmerge-journal.json"
STAGE_DIR = ".semmerge-stage"
JOURNAL_SCHEMA = 1

LOCKFILE = ".semmerge-inplace.lock"
#: Same age cutoff as the merge driver's ``.git/.semmerge.lock`` latch.
STALE_LOCK_SECONDS = 3600.0


def _break_stale_lock(path: pathlib.Path) -> bool:
    """Break a stale lock **exactly once** across concurrent
    contenders. A bare ``unlink`` races: two contenders can both judge
    the lock stale, and between their unlinks a third contender's fresh
    ``O_EXCL`` create can land — the second unlink then destroys the
    *fresh* lock and two processes hold the mutex. Breakers therefore
    serialize on a guard file (``<lock>.breaker``, itself ``O_EXCL``):
    only the guard holder may unlink a lock it did not create, and its
    staleness recheck under the guard is authoritative — a live owner
    only ever unlinks its *own* lock, so a lock still stale inside the
    guarded section cannot have been replaced by a live one. Returns
    ``True`` when this call broke the lock."""
    guard = path.with_name(path.name + ".breaker")
    try:
        fd = os.open(guard, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
    except FileExistsError:
        # Another breaker is in its guarded section — let it win. A
        # guard abandoned by a killed breaker is itself reclaimed by
        # the same staleness test; the next loop iteration retries.
        if _lock_is_stale(guard):
            with contextlib.suppress(OSError):
                guard.unlink()
        return False
    except OSError:
        return False
    try:
        os.write(fd, f"{os.getpid()} {int(time.time())}\n".encode("ascii"))
    finally:
        os.close(fd)
    try:
        if not _lock_is_stale(path):
            return False  # released (or re-acquired live) since the probe
        path.unlink(missing_ok=True)
        logger.warning("reclaiming stale in-place lock %s", path)
        obs_metrics.REGISTRY.counter(
            "semmerge_inplace_lock_stale_total",
            "Stale repo-level in-place locks reclaimed").inc(1)
        return True
    finally:
        with contextlib.suppress(OSError):
            guard.unlink()


def _lock_is_stale(path: pathlib.Path) -> bool:
    """A lock left by a dead or long-gone process: old mtime (the
    driver-latch heuristic), or a recorded pid that no longer exists."""
    try:
        st = path.stat()
    except OSError:
        return False  # raced with the owner's own unlink
    if time.time() - st.st_mtime > STALE_LOCK_SECONDS:
        return True
    try:
        pid = int(path.read_text(encoding="utf-8").split()[0])
    except (OSError, ValueError, IndexError):
        return False  # unreadable content: trust mtime alone
    if pid == os.getpid():
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return True
    except OSError:
        pass
    return False


@contextlib.contextmanager
def repo_lock(root: pathlib.Path | None = None,
              timeout: float | None = None) -> Iterator[pathlib.Path]:
    """Repo-level ``--inplace`` mutex: ``O_CREAT|O_EXCL`` on
    ``.semmerge-inplace.lock`` under ``root`` (default: the scoped
    working directory). Blocks up to ``timeout`` seconds
    (``SEMMERGE_INPLACE_LOCK_TIMEOUT``, default 600; 0 waits forever),
    reclaiming stale locks on the way; expiry raises an
    :class:`~semantic_merge_tpu.errors.ApplyFault` (exit 13) so a
    wedged peer surfaces as a contained fault, not a silent hang."""
    root = pathlib.Path(root) if root is not None else workdir.root()
    path = root / LOCKFILE
    if timeout is None:
        from ..utils.procs import env_seconds
        timeout = env_seconds("SEMMERGE_INPLACE_LOCK_TIMEOUT", 600.0)
    deadline = time.monotonic() + timeout if timeout > 0 else None
    waited = False
    while True:
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
            break
        except FileExistsError:
            if _lock_is_stale(path):
                _break_stale_lock(path)
                continue
            if deadline is not None and time.monotonic() > deadline:
                from ..errors import ApplyFault
                raise ApplyFault(
                    f"timed out after {timeout:g}s waiting for the "
                    f"in-place lock {path}", stage="commit",
                    cause="lock-timeout")
            waited = True
            time.sleep(0.05)
    try:
        os.write(fd, f"{os.getpid()} {int(time.time())}\n".encode("ascii"))
    finally:
        os.close(fd)
    if waited:
        obs_metrics.REGISTRY.counter(
            "semmerge_inplace_lock_waits_total",
            "In-place merges that waited for the repo lock").inc(1)
    try:
        yield path
    finally:
        path.unlink(missing_ok=True)


def _safe_rel(rel: str) -> pathlib.PurePosixPath:
    """Validate a journaled relative path: inside the root, no tricks.
    (The journal is our own artifact, but recovery must not follow a
    corrupted or tampered one outside the work tree.)"""
    p = pathlib.PurePosixPath(rel)
    if p.is_absolute() or ".." in p.parts or not p.parts:
        raise ValueError(f"journal entry escapes the work tree: {rel!r}")
    return p


def commit_tree_inplace(tree: pathlib.Path, deletes: Iterable[str] = (),
                        root: pathlib.Path | None = None) -> None:
    """Publish ``tree`` into ``root`` (default cwd) crash-safely."""
    tree = pathlib.Path(tree)
    root = pathlib.Path(root) if root is not None else workdir.root()
    stage = root / STAGE_DIR
    if stage.exists():
        shutil.rmtree(stage)
    writes: List[str] = []
    with obs_spans.span("inplace_stage", layer="runtime"):
        for path in sorted(tree.rglob("*")):
            if not path.is_file():
                continue
            rel = path.relative_to(tree).as_posix()
            dst = stage / rel
            dst.parent.mkdir(parents=True, exist_ok=True)
            shutil.copy2(path, dst)
            writes.append(rel)
    journal = {
        "schema": JOURNAL_SCHEMA,
        "state": "committing",
        "writes": writes,
        "deletes": sorted({pathlib.PurePosixPath(d).as_posix()
                           for d in deletes}),
    }
    _write_journal(root, journal)
    faults.check("commit")
    with obs_spans.span("inplace_commit", layer="runtime",
                        writes=len(writes), deletes=len(journal["deletes"])):
        _roll_forward(root, journal)
    obs_metrics.REGISTRY.counter(
        "semmerge_inplace_commits_total",
        "Crash-safe in-place commits completed").inc(1)


def _write_journal(root: pathlib.Path, journal: dict) -> None:
    jpath = root / JOURNAL
    tmp = root / (JOURNAL + ".tmp")
    payload = json.dumps(journal, indent=0)
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(payload)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, jpath)


def _roll_forward(root: pathlib.Path, journal: dict) -> None:
    """Replay a journal to completion: idempotent, so it serves both
    the live commit and crash recovery."""
    stage = root / STAGE_DIR
    for rel in journal.get("writes", []):
        rel_p = _safe_rel(rel)
        src = stage / rel_p
        if not src.is_file():
            continue  # already committed before the interruption
        dst = root / rel_p
        dst.parent.mkdir(parents=True, exist_ok=True)
        os.replace(src, dst)
    for rel in journal.get("deletes", []):
        (root / _safe_rel(rel)).unlink(missing_ok=True)
    (root / JOURNAL).unlink(missing_ok=True)
    shutil.rmtree(stage, ignore_errors=True)


def pending_state(root: pathlib.Path | None = None) -> str:
    """``"none"`` | ``"committing"`` | ``"staged-only"`` — what an
    earlier interrupted in-place commit left behind."""
    root = pathlib.Path(root) if root is not None else workdir.root()
    if (root / JOURNAL).exists():
        return "committing"
    if (root / STAGE_DIR).exists():
        return "staged-only"
    return "none"


def recover(root: pathlib.Path | None = None) -> Tuple[str, int]:
    """Resolve any interrupted in-place commit under ``root``.

    Returns ``(action, n_writes)`` where action is ``"none"`` (nothing
    pending), ``"rolled-forward"`` (journal replayed to completion), or
    ``"rolled-back"`` (pre-journal stage discarded; work tree was never
    touched). A torn/unreadable journal rolls back: the journal write
    is atomic, so an unreadable one cannot have committed anything.
    """
    root = pathlib.Path(root) if root is not None else workdir.root()
    jpath = root / JOURNAL
    stage = root / STAGE_DIR
    if jpath.exists():
        try:
            journal = json.loads(jpath.read_text(encoding="utf-8"))
            if not isinstance(journal, dict):
                raise ValueError("journal is not an object")
        except (ValueError, OSError) as exc:
            logger.warning("discarding unreadable in-place journal: %s", exc)
            jpath.unlink(missing_ok=True)
            shutil.rmtree(stage, ignore_errors=True)
            return "rolled-back", 0
        n = len(journal.get("writes", []))
        logger.warning("resuming interrupted in-place commit (%d writes)", n)
        try:
            _roll_forward(root, journal)
        except ValueError as exc:
            # A journal entry escaping the work tree: refuse to act on
            # it (the journal stays for forensics) — a contained fault
            # with the documented ApplyFault exit, never a traversal.
            from ..errors import ApplyFault
            raise ApplyFault(str(exc), stage="commit",
                             cause="journal-tampered") from exc
        obs_metrics.REGISTRY.counter(
            "semmerge_inplace_recoveries_total",
            "Interrupted in-place commits resolved",
        ).inc(1, action="rolled-forward")
        return "rolled-forward", n
    if stage.exists():
        logger.warning("discarding pre-commit stage from an interrupted "
                       "merge (work tree was never touched)")
        shutil.rmtree(stage, ignore_errors=True)
        obs_metrics.REGISTRY.counter(
            "semmerge_inplace_recoveries_total",
            "Interrupted in-place commits resolved",
        ).inc(1, action="rolled-back")
        return "rolled-back", 0
    return "none", 0
