"""Crash-safe ``--inplace`` apply: stage → journal → atomic commit.

The old in-place path copied the merged tree file-by-file straight into
the working tree, so a crash mid-copy (OOM-killed CLI, ctrl-C, power
loss) left a *torn* tree — half old, half new — which the git merge
driver would then happily publish as the merge result. This module
makes the commit two-phase:

1. **Stage**: every file of the merged tree is copied into a sibling
   ``.semmerge-stage/`` directory inside the target root (same
   filesystem, so the later renames are atomic). A crash here leaves
   only a stray stage directory; the work tree is bitwise untouched.
2. **Journal**: the intended writes and deletes are recorded in
   ``.semmerge-journal.json`` — written to a temp name, fsynced, then
   atomically renamed into place. The journal's existence IS the
   commit marker: from this instant the merge is redo-able.
3. **Commit**: each staged file is ``os.replace``d onto its target
   (atomic per file) and each journaled delete unlinked; the journal
   and stage directory are then removed.

A process killed at ANY point leaves one of two recoverable states:

- stage dir without journal → the commit never started; **rollback**
  (remove the stage dir, work tree untouched);
- journal present → the commit may be partial; **roll forward**
  (replay the remaining renames/deletes — ``os.replace`` of an
  already-moved file is skipped because its staged source is gone).

:func:`recover` implements both and is invoked automatically at the
start of every ``--inplace`` merge and explicitly by
``semmerge --resume``.
"""
from __future__ import annotations

import json
import os
import pathlib
import shutil
from typing import Iterable, List, Tuple

from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans
from ..utils import faults
from ..utils.loggingx import logger

JOURNAL = ".semmerge-journal.json"
STAGE_DIR = ".semmerge-stage"
JOURNAL_SCHEMA = 1


def _safe_rel(rel: str) -> pathlib.PurePosixPath:
    """Validate a journaled relative path: inside the root, no tricks.
    (The journal is our own artifact, but recovery must not follow a
    corrupted or tampered one outside the work tree.)"""
    p = pathlib.PurePosixPath(rel)
    if p.is_absolute() or ".." in p.parts or not p.parts:
        raise ValueError(f"journal entry escapes the work tree: {rel!r}")
    return p


def commit_tree_inplace(tree: pathlib.Path, deletes: Iterable[str] = (),
                        root: pathlib.Path | None = None) -> None:
    """Publish ``tree`` into ``root`` (default cwd) crash-safely."""
    tree = pathlib.Path(tree)
    root = pathlib.Path(root) if root is not None else pathlib.Path.cwd()
    stage = root / STAGE_DIR
    if stage.exists():
        shutil.rmtree(stage)
    writes: List[str] = []
    with obs_spans.span("inplace_stage", layer="runtime"):
        for path in sorted(tree.rglob("*")):
            if not path.is_file():
                continue
            rel = path.relative_to(tree).as_posix()
            dst = stage / rel
            dst.parent.mkdir(parents=True, exist_ok=True)
            shutil.copy2(path, dst)
            writes.append(rel)
    journal = {
        "schema": JOURNAL_SCHEMA,
        "state": "committing",
        "writes": writes,
        "deletes": sorted({pathlib.PurePosixPath(d).as_posix()
                           for d in deletes}),
    }
    _write_journal(root, journal)
    faults.check("commit")
    with obs_spans.span("inplace_commit", layer="runtime",
                        writes=len(writes), deletes=len(journal["deletes"])):
        _roll_forward(root, journal)
    obs_metrics.REGISTRY.counter(
        "semmerge_inplace_commits_total",
        "Crash-safe in-place commits completed").inc(1)


def _write_journal(root: pathlib.Path, journal: dict) -> None:
    jpath = root / JOURNAL
    tmp = root / (JOURNAL + ".tmp")
    payload = json.dumps(journal, indent=0)
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(payload)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, jpath)


def _roll_forward(root: pathlib.Path, journal: dict) -> None:
    """Replay a journal to completion: idempotent, so it serves both
    the live commit and crash recovery."""
    stage = root / STAGE_DIR
    for rel in journal.get("writes", []):
        rel_p = _safe_rel(rel)
        src = stage / rel_p
        if not src.is_file():
            continue  # already committed before the interruption
        dst = root / rel_p
        dst.parent.mkdir(parents=True, exist_ok=True)
        os.replace(src, dst)
    for rel in journal.get("deletes", []):
        (root / _safe_rel(rel)).unlink(missing_ok=True)
    (root / JOURNAL).unlink(missing_ok=True)
    shutil.rmtree(stage, ignore_errors=True)


def pending_state(root: pathlib.Path | None = None) -> str:
    """``"none"`` | ``"committing"`` | ``"staged-only"`` — what an
    earlier interrupted in-place commit left behind."""
    root = pathlib.Path(root) if root is not None else pathlib.Path.cwd()
    if (root / JOURNAL).exists():
        return "committing"
    if (root / STAGE_DIR).exists():
        return "staged-only"
    return "none"


def recover(root: pathlib.Path | None = None) -> Tuple[str, int]:
    """Resolve any interrupted in-place commit under ``root``.

    Returns ``(action, n_writes)`` where action is ``"none"`` (nothing
    pending), ``"rolled-forward"`` (journal replayed to completion), or
    ``"rolled-back"`` (pre-journal stage discarded; work tree was never
    touched). A torn/unreadable journal rolls back: the journal write
    is atomic, so an unreadable one cannot have committed anything.
    """
    root = pathlib.Path(root) if root is not None else pathlib.Path.cwd()
    jpath = root / JOURNAL
    stage = root / STAGE_DIR
    if jpath.exists():
        try:
            journal = json.loads(jpath.read_text(encoding="utf-8"))
            if not isinstance(journal, dict):
                raise ValueError("journal is not an object")
        except (ValueError, OSError) as exc:
            logger.warning("discarding unreadable in-place journal: %s", exc)
            jpath.unlink(missing_ok=True)
            shutil.rmtree(stage, ignore_errors=True)
            return "rolled-back", 0
        n = len(journal.get("writes", []))
        logger.warning("resuming interrupted in-place commit (%d writes)", n)
        try:
            _roll_forward(root, journal)
        except ValueError as exc:
            # A journal entry escaping the work tree: refuse to act on
            # it (the journal stays for forensics) — a contained fault
            # with the documented ApplyFault exit, never a traversal.
            from ..errors import ApplyFault
            raise ApplyFault(str(exc), stage="commit",
                             cause="journal-tampered") from exc
        obs_metrics.REGISTRY.counter(
            "semmerge_inplace_recoveries_total",
            "Interrupted in-place commits resolved",
        ).inc(1, action="rolled-forward")
        return "rolled-forward", n
    if stage.exists():
        logger.warning("discarding pre-commit stage from an interrupted "
                       "merge (work tree was never touched)")
        shutil.rmtree(stage, ignore_errors=True)
        obs_metrics.REGISTRY.counter(
            "semmerge_inplace_recoveries_total",
            "Interrupted in-place commits resolved",
        ).inc(1, action="rolled-back")
        return "rolled-back", 0
    return "none", 0
