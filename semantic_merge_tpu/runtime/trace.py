"""Per-phase tracing and metrics.

The reference specifies a ``--trace`` mode dumping op logs, decisions,
and per-phase timings (reference ``requirements.md:182`` [NFR-OBS-002];
``architecture.md:248-249``) but implements none of it. Here every CLI
run can carry a :class:`Tracer`; with tracing enabled it writes a
machine-readable ``.semmerge-trace.json`` artifact containing phase
wall-times and counters, and can hand phases to the JAX profiler.
"""
from __future__ import annotations

import contextlib
import json
import pathlib
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List


@dataclass
class PhaseRecord:
    name: str
    seconds: float
    meta: Dict[str, Any] = field(default_factory=dict)


@dataclass
class Tracer:
    enabled: bool = False
    phases: List[PhaseRecord] = field(default_factory=list)
    counters: Dict[str, Any] = field(default_factory=dict)

    @contextlib.contextmanager
    def phase(self, name: str, **meta: Any):
        start = time.perf_counter()
        try:
            yield
        finally:
            self.phases.append(PhaseRecord(name, time.perf_counter() - start, dict(meta)))

    def count(self, key: str, value: Any) -> None:
        self.counters[key] = value

    def to_dict(self) -> Dict[str, Any]:
        return {
            "phases": [
                {"name": p.name, "seconds": round(p.seconds, 6), **({"meta": p.meta} if p.meta else {})}
                for p in self.phases
            ],
            "counters": self.counters,
            "total_seconds": round(sum(p.seconds for p in self.phases), 6),
        }

    def write(self, path: pathlib.Path | str = ".semmerge-trace.json") -> None:
        if not self.enabled:
            return
        pathlib.Path(path).write_text(json.dumps(self.to_dict(), indent=2), encoding="utf-8")
