"""Per-phase tracing — thin adapter over :mod:`semantic_merge_tpu.obs`.

The reference specifies a ``--trace`` mode dumping op logs, decisions,
and per-phase timings (reference ``requirements.md:182`` [NFR-OBS-002];
``architecture.md:248-249``). Every CLI run carries a :class:`Tracer`;
its public surface (``phase`` / ``count`` / ``write`` / ``close``) is
unchanged from the original CLI-local implementation, but the timing
now flows through the unified observability layer: ``phase`` opens an
:func:`obs.spans.span`, and while the tracer is *collecting* (``--trace``
or ``--profile``) a :class:`~semantic_merge_tpu.obs.spans.SpanRecorder`
is active process-wide, so spans emitted deep inside the scanner,
compose kernels, fused engine, backends, and applier all land in the
same artifact.

Artifacts written by :meth:`Tracer.write`:

- ``.semmerge-trace.json`` — top-level CLI phases (back-compat shape),
  counters, the full span tree, device telemetry
  (:func:`obs.device.snapshot`), and the metrics registry;
- ``.semmerge-events.jsonl`` — one JSON row per span/event, time-ordered;
- with ``--profile DIR``, the same trace JSON additionally lands in
  ``DIR/semmerge-trace.json`` **even without ``--trace``** — previously
  a profiled run silently discarded every phase wall-time.

With ``profile_dir`` set the run is also captured by the JAX profiler
(``jax.profiler.start_trace``/``stop_trace``) and every phase annotates
the timeline via ``jax.profiler.TraceAnnotation``, so device kernels
line up with engine phases in TensorBoard/XProf.
"""
from __future__ import annotations

import contextlib
import json
import pathlib
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List

from ..obs import device as obs_device
from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans

TRACE_SCHEMA_VERSION = 1

#: Counter recording every failed profiler start/stop, by reason — a
#: capture that silently goes dark is an observability bug in itself.
PROFILER_FAILURES = "profiler_capture_failures_total"


def start_profiler_session(profile_dir: str) -> bool:
    """Open the process-global JAX profiler session, recovering from a
    poisoned one.

    ``jax.profiler.start_trace`` raises when a previous session was
    never stopped (an aborted capture in a warm process — exactly the
    daemon's shape). Historically that failure was swallowed by a bare
    ``except``, silently disabling every later ``--profile`` and
    daemon capture. Instead: on failure, attempt a guarded
    ``stop_trace`` to clear the stale session and retry **once**;
    count every failure in ``profiler_capture_failures_total{reason}``
    so a dark profiler is at least visible in metrics."""
    failures = obs_metrics.REGISTRY.counter(
        PROFILER_FAILURES,
        "JAX profiler session start/stop failures, by reason")
    try:
        import jax
    except Exception:
        failures.inc(1, reason="jax-import")
        return False
    try:
        jax.profiler.start_trace(profile_dir)
        return True
    except Exception:
        failures.inc(1, reason="start")
    # Recovery: a stale session from an aborted capture is the common
    # cause — close it and retry once.
    try:
        jax.profiler.stop_trace()
    except Exception:
        failures.inc(1, reason="recovery-stop")
    try:
        jax.profiler.start_trace(profile_dir)
        return True
    except Exception:
        failures.inc(1, reason="start-retry")
        return False


def stop_profiler_session() -> bool:
    """Close the process-global profiler session; never raises."""
    try:
        import jax
        jax.profiler.stop_trace()
        return True
    except Exception:
        obs_metrics.REGISTRY.counter(
            PROFILER_FAILURES,
            "JAX profiler session start/stop failures, by reason").inc(
                1, reason="stop")
        return False


@dataclass
class PhaseRecord:
    name: str
    seconds: float
    meta: Dict[str, Any] = field(default_factory=dict)


@dataclass
class Tracer:
    enabled: bool = False
    profile_dir: str | None = None
    phases: List[PhaseRecord] = field(default_factory=list)
    counters: Dict[str, Any] = field(default_factory=dict)
    _profiling: bool = field(default=False, repr=False)
    _recorder: obs_spans.SpanRecorder | None = field(default=None, repr=False)

    def __post_init__(self) -> None:
        if self.enabled or self.profile_dir:
            self._recorder = obs_spans.SpanRecorder()
            obs_spans.activate(self._recorder)
        if self.profile_dir:
            self._profiling = start_profiler_session(self.profile_dir)

    @contextlib.contextmanager
    def phase(self, name: str, **meta: Any):
        annotation = contextlib.nullcontext()
        if self._profiling:
            try:
                import jax
                annotation = jax.profiler.TraceAnnotation(f"semmerge/{name}")
            except Exception:
                pass
        start = time.perf_counter()
        try:
            with annotation, obs_spans.span(name, layer="cli", **meta):
                yield
        finally:
            self.phases.append(PhaseRecord(name, time.perf_counter() - start, dict(meta)))

    def count(self, key: str, value: Any) -> None:
        self.counters[key] = value

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "schema": TRACE_SCHEMA_VERSION,
            "trace_id": obs_spans.trace_id(),
            "phases": [
                {"name": p.name, "seconds": round(p.seconds, 6), **({"meta": p.meta} if p.meta else {})}
                for p in self.phases
            ],
            "counters": self.counters,
            "total_seconds": round(sum(p.seconds for p in self.phases), 6),
            "device": obs_device.snapshot(),
            "metrics": obs_metrics.REGISTRY.to_dict(),
        }
        if self._recorder is not None:
            out["spans"] = self._recorder.span_dicts()
        return out

    def close(self) -> None:
        """Stop the profiler session if one is open and release the
        global span recorder. Idempotent; must run on every exit path
        (the CLI calls it in ``finally``) or an aborted run loses the
        capture and poisons later start_trace calls in the same
        process."""
        if self._profiling:
            stop_profiler_session()
            self._profiling = False
        if self._recorder is not None:
            obs_spans.deactivate(self._recorder)

    def write(self, path: pathlib.Path | str = ".semmerge-trace.json") -> None:
        self.close()
        if not self.enabled and not self.profile_dir:
            return
        payload = json.dumps(self.to_dict(), indent=2, default=str)
        if self.profile_dir:
            # A profiled run keeps its phase timings next to the device
            # capture, --trace or not (the device timeline is unreadable
            # without the engine phases that produced it).
            prof = pathlib.Path(self.profile_dir)
            try:
                prof.mkdir(parents=True, exist_ok=True)
                (prof / "semmerge-trace.json").write_text(
                    payload, encoding="utf-8")
            except OSError:
                pass
        if not self.enabled:
            return
        path = pathlib.Path(path)
        if not path.is_absolute():
            # Relative artifacts land in the request root when a merge
            # service request is in scope (utils/workdir), cwd otherwise.
            from ..utils import workdir
            path = workdir.root() / path
        path.write_text(payload, encoding="utf-8")
        if self._recorder is not None:
            self._recorder.write_jsonl(
                path.with_name(obs_spans.EVENTS_ARTIFACT))
