"""Per-phase tracing and metrics.

The reference specifies a ``--trace`` mode dumping op logs, decisions,
and per-phase timings (reference ``requirements.md:182`` [NFR-OBS-002];
``architecture.md:248-249``) but implements none of it. Here every CLI
run can carry a :class:`Tracer`; with tracing enabled it writes a
machine-readable ``.semmerge-trace.json`` artifact containing phase
wall-times and counters. With ``profile_dir`` set (CLI ``--profile
DIR``), the run is additionally captured by the JAX profiler: a
``jax.profiler.start_trace``/``stop_trace`` session wraps the run and
every tracer phase annotates the timeline via
``jax.profiler.TraceAnnotation``, so device kernels line up with
engine phases in TensorBoard/XProf.
"""
from __future__ import annotations

import contextlib
import json
import pathlib
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List


@dataclass
class PhaseRecord:
    name: str
    seconds: float
    meta: Dict[str, Any] = field(default_factory=dict)


@dataclass
class Tracer:
    enabled: bool = False
    profile_dir: str | None = None
    phases: List[PhaseRecord] = field(default_factory=list)
    counters: Dict[str, Any] = field(default_factory=dict)
    _profiling: bool = field(default=False, repr=False)

    def __post_init__(self) -> None:
        if self.profile_dir:
            try:
                import jax
                jax.profiler.start_trace(self.profile_dir)
                self._profiling = True
            except Exception:
                self._profiling = False

    @contextlib.contextmanager
    def phase(self, name: str, **meta: Any):
        annotation = contextlib.nullcontext()
        if self._profiling:
            try:
                import jax
                annotation = jax.profiler.TraceAnnotation(f"semmerge/{name}")
            except Exception:
                pass
        start = time.perf_counter()
        try:
            with annotation:
                yield
        finally:
            self.phases.append(PhaseRecord(name, time.perf_counter() - start, dict(meta)))

    def count(self, key: str, value: Any) -> None:
        self.counters[key] = value

    def to_dict(self) -> Dict[str, Any]:
        return {
            "phases": [
                {"name": p.name, "seconds": round(p.seconds, 6), **({"meta": p.meta} if p.meta else {})}
                for p in self.phases
            ],
            "counters": self.counters,
            "total_seconds": round(sum(p.seconds for p in self.phases), 6),
        }

    def close(self) -> None:
        """Stop the profiler session if one is open. Idempotent; must
        run on every exit path (the CLI calls it in ``finally``) or an
        aborted run loses the capture and poisons later start_trace
        calls in the same process."""
        if self._profiling:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._profiling = False

    def write(self, path: pathlib.Path | str = ".semmerge-trace.json") -> None:
        self.close()
        if not self.enabled:
            return
        pathlib.Path(path).write_text(json.dumps(self.to_dict(), indent=2), encoding="utf-8")
