"""Out-of-process language worker: newline JSON-RPC over stdio.

The reference's L3↔L2 boundary is a child process speaking
newline-delimited JSON-RPC (reference ``semmerge/lang/ts/bridge.py:80-118``
request writer/reader; ``workers/ts/src/index.ts:9-51`` dispatch loop):
a crashing worker cannot take down the CLI, and any external tool that
speaks the protocol can be a language backend. This module is our side
of that seam — both halves of it:

- ``python -m semantic_merge_tpu.runtime.worker [--backend host]`` runs
  a worker process serving the protocol on stdin/stdout, delegating to
  an in-process backend (so the same engine can be supervised,
  sandboxed, or scaled per-language);
- :class:`semantic_merge_tpu.backends.subproc.SubprocessBackend` is the
  client half, usable with THIS worker or any external implementation
  (e.g. a Node worker wrapping the real TypeScript compiler — the
  future live oracle of the golden-corpus fixtures).

Wire protocol (mirrors reference ``workers/ts/src/protocol.ts``):

    → {"id": 1, "method": "buildAndDiff", "params": {"base": [...],
       "left": [...], "right": [...], "baseRev": "…", "seed": "…",
       "timestamp": "…", "changeSignature": false,
       "structuredApply": false}}
    ← {"id": 1, "result": {"opLogLeft": [...], "opLogRight": [...],
       "symbolMaps": {...}, "diagnostics": []}}

Errors return ``{"id": n, "error": {"message": "…"}}``; the process
exits on EOF or a ``shutdown`` request.

Tracing: requests may carry a ``trace_id`` (ignored by external worker
implementations). Successful responses gain a ``_worker`` block —
``{"seconds": …, "phases": {name: seconds}, "trace_id": …}`` — holding
the worker-side wall time and the per-phase histogram delta for that
one request; the client grafts these as ``worker.<phase>`` child spans
into the request's trace, closing the cross-process timing gap.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Any, Dict


def _snapshot(files) -> "object":
    from ..frontend.snapshot import Snapshot
    return Snapshot(files=[{"path": f["path"], "content": f["content"]}
                           for f in files])


def _handle(backend, method: str, params: Dict[str, Any]) -> Dict[str, Any]:
    if method == "ping":
        return {"pong": True, "backend": backend.name}
    if method == "buildAndDiff":
        result = backend.build_and_diff(
            _snapshot(params["base"]), _snapshot(params["left"]),
            _snapshot(params["right"]),
            base_rev=params.get("baseRev", "base"),
            seed=params.get("seed", "0"),
            timestamp=params.get("timestamp"),
            change_signature=bool(params.get("changeSignature", False)),
            structured_apply=bool(params.get("structuredApply", False)),
            statement_ops=bool(params.get("statementOps", False)))
        return {
            "opLogLeft": [op.to_dict() for op in result.op_log_left],
            "opLogRight": [op.to_dict() for op in result.op_log_right],
            "symbolMaps": result.symbol_maps,
            "diagnostics": list(result.diagnostics),
        }
    if method == "diff":
        ops = backend.diff(
            _snapshot(params["base"]), _snapshot(params["right"]),
            base_rev=params.get("baseRev", "base"),
            seed=params.get("seed", "0"),
            timestamp=params.get("timestamp"),
            change_signature=bool(params.get("changeSignature", False)),
            structured_apply=bool(params.get("structuredApply", False)),
            statement_ops=bool(params.get("statementOps", False)))
        return {"opLog": [op.to_dict() for op in ops]}
    if method == "compose":
        from ..core.ops import Op
        compose = getattr(backend, "compose", None)
        if compose is None:
            from ..backends.base import host_compose
            compose = host_compose
        composed, conflicts = compose(
            [Op.from_dict(o) for o in params["deltaA"]],
            [Op.from_dict(o) for o in params["deltaB"]])
        return {"composed": [op.to_dict() for op in composed],
                "conflicts": [c.to_dict() for c in conflicts]}
    raise ValueError(f"unknown method {method!r}")


def serve(backend_name: str = "host",
          stdin=None, stdout=None) -> int:
    """Serve the protocol until EOF or ``shutdown``. Any per-request
    exception becomes an error *response* — the worker survives."""
    from ..backends.base import get_backend

    from ..utils import faults

    stdin = stdin if stdin is not None else sys.stdin
    stdout = stdout if stdout is not None else sys.stdout
    backend = get_backend(backend_name)
    try:
        for line in stdin:
            line = line.strip()
            if not line:
                continue
            # Supervision-test seam: SEMMERGE_FAULT=worker-serve:KIND
            # makes THIS process wedge (hang), die (exit/kill), or
            # answer garbage — the client's deadline/respawn logic is
            # exercised against a real misbehaving worker.
            if faults.check("worker-serve") == "garbage":
                stdout.write("this is not json\n")
                stdout.flush()
                continue
            req_id = None
            try:
                request = json.loads(line)
                req_id = request.get("id")
                method = request["method"]
                if method == "shutdown":
                    stdout.write(json.dumps({"id": req_id, "result": {}}) + "\n")
                    stdout.flush()
                    return 0
                from ..obs import metrics as obs_metrics
                before = obs_metrics.phase_totals()
                t0 = time.perf_counter()
                result = _handle(backend, method, request.get("params", {}))
                elapsed = time.perf_counter() - t0
                result["_worker"] = {
                    "seconds": round(elapsed, 6),
                    "phases": {name: round(secs, 6) for name, secs in
                               obs_metrics.phase_totals_since(before).items()},
                    "trace_id": request.get("trace_id"),
                }
                response = {"id": req_id, "result": result}
            except Exception as exc:  # noqa: BLE001 — becomes the error reply
                response = {"id": req_id,
                            "error": {"message": f"{type(exc).__name__}: {exc}"}}
            stdout.write(json.dumps(response) + "\n")
            stdout.flush()
    finally:
        backend.close()
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(prog="semmerge-worker")
    parser.add_argument("--backend", default="host",
                        help="in-process backend the worker delegates to")
    args = parser.parse_args()
    return serve(args.backend)


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
