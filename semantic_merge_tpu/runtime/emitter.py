"""Formatter hook (reference ``semmerge/emitter.py``).

Best-effort formatting of the merged tree. The formatter command comes
from config (``[core] formatter`` / per-language ``formatter_cmd``),
defaulting to Prettier via npx. A missing toolchain downgrades to a
debug log; a failing run to a warning — formatting never fails a merge
(reference ``semmerge/emitter.py:22-25``; ``requirements.md:107``
[FBK-003]).
"""
from __future__ import annotations

import pathlib
import subprocess
from typing import Sequence

from ..utils.loggingx import logger

DEFAULT_FORMATTER = ("npx", "prettier", "--write", ".")


def emit_files(tree_path: pathlib.Path, formatter_cmd: Sequence[str] | None = None) -> None:
    tree_path = pathlib.Path(tree_path)
    cmd = list(formatter_cmd) if formatter_cmd else list(DEFAULT_FORMATTER)
    try:
        subprocess.run(cmd, cwd=tree_path, check=True,
                       stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    except FileNotFoundError:
        logger.debug("Formatter %s not available; skipping", cmd[0])
    except subprocess.CalledProcessError as exc:
        logger.warning("Formatter exited with code %s", exc.returncode)
