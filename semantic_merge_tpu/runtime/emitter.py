"""Formatter hook (reference ``semmerge/emitter.py``).

Best-effort formatting of the merged tree. The formatter command comes
from config (``[core] formatter`` / per-language ``formatter_cmd``),
defaulting to Prettier via npx. A missing toolchain downgrades to a
debug log; a failing run to a warning — formatting never fails a merge
(reference ``semmerge/emitter.py:22-25``; ``requirements.md:107``
[FBK-003]).
"""
from __future__ import annotations

import pathlib
import subprocess
from typing import Sequence

from ..utils.loggingx import logger

import re

#: Target-free: emit_files appends "." (tree mode) or the touched paths.
DEFAULT_FORMATTER = ("npx", "prettier", "--write")

#: fast-glob metacharacters prettier would interpret in an explicit
#: path argument (e.g. Next.js route files like ``pages/[id].ts``).
_GLOB_CHARS = re.compile(r"[*?\[\]{}()!]")

#: Suffixes prettier can parse out of the box (its built-in language
#: set) — the touched-scope filter: a text-merged ``notes.txt`` or a
#: binary must never reach prettier as an explicit path argument.
PRETTIER_EXTENSIONS = frozenset((
    ".js", ".jsx", ".mjs", ".cjs", ".ts", ".tsx", ".mts", ".cts",
    ".json", ".json5", ".jsonc", ".css", ".scss", ".less", ".html",
    ".htm", ".vue", ".md", ".markdown", ".mdx", ".yaml", ".yml",
    ".graphql", ".gql", ".handlebars", ".hbs"))


def _escape_glob(path: str) -> str:
    """Backslash-escape fast-glob metacharacters so an explicit path
    argument (``pages/[id].ts``, ``app/(marketing)/page.tsx``) reaches
    prettier as a literal file, not a pattern. fast-glob honors
    ``\\``-escaping on every platform prettier runs it."""
    return _GLOB_CHARS.sub(lambda m: "\\" + m.group(0), path)


def emit_files(tree_path: pathlib.Path,
               formatter_cmd: Sequence[str] | None = None,
               paths: Sequence[str] | None = None) -> None:
    """Format the merged tree. ``formatter_cmd`` is target-free (no
    trailing ``.``). ``paths=None`` formats the whole tree (the
    reference's behavior); a list formats only those files —
    touched-scope mode (``[engine] formatter_scope = "touched"``), which
    leaves every unvisited file byte-identical. An empty list skips the
    formatter entirely. Touched paths containing glob metacharacters
    are backslash-escaped (fast-glob's literal-path escape), so
    Next.js-style routes format in place instead of degrading the whole
    merge to tree-wide formatting.

    The formatter runs under a process-group deadline
    (``SEMMERGE_FORMAT_TIMEOUT`` seconds, default 300): a wedged
    prettier is killed — whole process group, npx children included —
    and logged; per [FBK-003] even a deadline never fails the merge."""
    from ..errors import DeadlineFault
    from ..obs import spans as obs_spans
    from ..utils import faults
    from ..utils.procs import env_seconds, run_with_deadline
    faults.check("emit")
    tree_path = pathlib.Path(tree_path)
    base_cmd = list(formatter_cmd) if formatter_cmd else list(DEFAULT_FORMATTER)
    if paths is not None:
        existing = sorted(p for p in paths if (tree_path / p).is_file())
        if not existing:
            return
        cmd = base_cmd + [_escape_glob(p) for p in existing]
        scope = len(existing)
    else:
        cmd = base_cmd + ["."]
        scope = -1  # whole tree
    deadline = env_seconds("SEMMERGE_FORMAT_TIMEOUT", 300.0)
    with obs_spans.span("emit_files", layer="runtime", files=scope):
        try:
            run_with_deadline(cmd, timeout=deadline, stage="format",
                              cwd=tree_path, check=True,
                              stdout=subprocess.DEVNULL,
                              stderr=subprocess.DEVNULL)
        except FileNotFoundError:
            logger.debug("Formatter %s not available; skipping", cmd[0])
        except subprocess.CalledProcessError as exc:
            logger.warning("Formatter exited with code %s", exc.returncode)
        except DeadlineFault as exc:
            logger.warning("Formatter killed: %s", exc.describe())
        except OSError as exc:
            # E2BIG on huge touched lists and friends — formatting never
            # fails a merge ([FBK-003] posture).
            logger.warning("Formatter could not run: %s", exc)
