"""Materialize composed ops onto a tree (reference ``semmerge/applier.py``).

Applies a composed op stream to a copy of the base tree. Implemented
handlers (the reference's set): ``moveDecl`` moves the *whole file*
old→new; ``renameSymbol`` rewrites word-boundary occurrences across the
file; ``modifyImport`` is a literal replace; ``moveFile`` moves by
old/new path. Everything else is logged and skipped (reference
``semmerge/applier.py:30-31``). Additionally ``reorderImports`` is
applied via the RGA CRDT ordering (wired in here; dead code in the
reference, ``semmerge/crdt.py``).

Two dispatch paths, one contract:

- **Columnar** (default for the fused device path): a
  :class:`~semantic_merge_tpu.ops.oplog_view.ComposedOpView` backed by
  op-stream columns is consumed directly — dispatch on the int kind
  column, params read through the cached per-snapshot field tables,
  chain-file overrides applied exactly as ``_materialize_decoded``
  would. No ``Op`` objects materialize; the walk is shard-wise over the
  PR-2 tail plan, so early shards apply while later shards' chain
  decodes (and, split-fetch, the chain transfer itself) are still in
  flight. The fused path's op vocabulary is exactly the four diff kinds,
  none of which carry structured params, so the full-Op escape hatch
  (``view.materialize_row``) exists but is never needed on this path.
- **Object** (host composer output, ``semrebase`` replay, strict mode,
  and the parity oracle behind ``SEMMERGE_OBJECT_APPLY=1``): the
  original per-op handler loop, byte-identical trees by construction —
  both paths call the same file-edit primitives.

Parity (trees AND notes payloads) is property-tested in
``tests/test_applier_columnar.py``.
"""
from __future__ import annotations

import os
import pathlib
import re
import shutil
import tempfile
from typing import Iterable, List, Optional, Set

import numpy as np

from ..core.ops import Op
from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans
from ..utils.loggingx import logger


def apply_ops(base_tree: pathlib.Path, ops: Iterable[Op],
              *, device_crdt: bool = False) -> pathlib.Path:
    """Apply composed ops to a copy of ``base_tree``.

    Column-backed composed views take the columnar dispatch loop (no Op
    materialization); everything else takes the object loop.
    ``SEMMERGE_OBJECT_APPLY=1`` forces the object loop for any input —
    the parity oracle. With ``device_crdt`` (the tpu backend's path),
    every ``reorderImports`` op's RGA ordering in the merge resolves in
    ONE batched device materialization
    (:func:`semantic_merge_tpu.ops.crdt.materialize_batch`) instead of
    per-list host insert scans; output is identical (parity-tested).
    """
    from ..utils import faults
    faults.check("apply")
    view = _columnar_view(ops)
    counter = obs_metrics.REGISTRY.counter(
        "semmerge_ops_applied_total",
        "Composed ops handed to the tree applier")
    if view is not None and not _object_apply_forced():
        counter.inc(len(view))
        with obs_spans.span("apply_ops", layer="runtime", ops=len(view),
                            device_crdt=device_crdt, columnar=True):
            return _apply_columnar(pathlib.Path(base_tree), view)
    ops = list(ops)
    counter.inc(len(ops))
    with obs_spans.span("apply_ops", layer="runtime", ops=len(ops),
                        device_crdt=device_crdt):
        return _apply_ops(pathlib.Path(base_tree), ops, device_crdt)


def _object_apply_forced() -> bool:
    """``SEMMERGE_OBJECT_APPLY=1`` keeps the object-dispatch applier as
    a parity oracle: composed views materialize full ``Op`` objects and
    flow through the per-op handlers exactly as before the columnar
    path existed."""
    return os.environ.get("SEMMERGE_OBJECT_APPLY", "").strip() == "1"


def _columnar_view(ops):
    """``ops`` as a column-backed ComposedOpView, or ``None``."""
    from ..ops.oplog_view import ComposedOpView
    if isinstance(ops, ComposedOpView) and ops.supports_columns:
        return ops
    return None


# --------------------------------------------------------------------------
# Columnar dispatch
# --------------------------------------------------------------------------

#: OP_PRECEDENCE of the four columnar diff kinds, indexed by KIND_*
#: code (rename, move, add, delete) — the order the composed stream is
#: emitted in, and the order-check table for the bulk action assembly.
_PREC_OF_KIND = np.asarray([11, 10, 30, 31], dtype=np.int32)


def iter_columnar_actions(view):
    """Per-shard apply actions straight off a composed view's columns.

    Yields one list of ACTION GROUPS per tail-plan shard (contiguous
    ascending ranges). A group is ``("move", old_files, new_files)`` or
    ``("rename", files, old_names, new_names)`` — parallel column
    lists, already override-applied and validity-filtered, so consumers
    zip them row-wise without any per-row Python dispatch here. Rows
    with no tree effect — ``addDecl`` without structured text,
    ``deleteDecl`` tombstones (the object path's "no applier hook"
    skips), rows with missing required params — are simply absent.

    Chain-file overrides land exactly where ``_materialize_decoded``
    would put them. For RENAME rows that is the ``file`` param (the
    last preceding move's destination). For MOVE rows the override is a
    proven no-op and is skipped: the chain scan is inclusive and a live
    move always contributes its own (non-null) destination, so a move
    row's decoded chain-file IS its own ``newFile`` — parity with the
    object path is property-tested either way. The addr/name overrides
    only touch fields the tree applier never reads.

    Assembly is bulk per kind (C-speed map gathers over the cached
    field tables), exploiting the composed stream's canonical-order
    invariant: rows sort by op precedence, so within any contiguous
    slice every moveDecl precedes every renameSymbol and emitting
    moves-then-renames IS row order. The invariant is verified per
    shard (one vectorized monotonicity check); a violating stream
    falls back to exact row-order assembly (a ``("rows", actions)``
    group of per-row tuples).
    """
    from ..ops.oplog_view import KIND_MOVE, KIND_RENAME
    left, right = view.left, view.right
    b_name, b_file = left.base_fields()[2:4]
    l_name, l_file = left.side_fields()[2:4]
    r_name, r_file = right.side_fields()[2:4]
    kL, kR = left.kind, right.kind

    def merged(col_l, col_r, isL_k, rows):
        """Per-row gather from a per-stream int column pair, clamped so
        the other side's (never-selected) lane can't index out of an
        empty or shorter stream."""
        li = col_l[np.minimum(rows, max(col_l.shape[0] - 1, 0))] \
            if col_l.shape[0] else rows
        ri = col_r[np.minimum(rows, max(col_r.shape[0] - 1, 0))] \
            if col_r.shape[0] else rows
        return np.where(isL_k, li, ri)

    def gather_side(fields_l, fields_r, isL_k, slot):
        """Side-dependent string gather: two C-speed ``map`` passes over
        the per-side field lists, interleaved back to row order through
        an object-array scatter (returned as the object array — row
        iteration over it matches a list)."""
        out = np.empty(len(slot), dtype=object)
        wl_k = np.nonzero(isL_k)[0]
        wr_k = np.nonzero(~isL_k)[0]
        if len(wl_k):
            out[wl_k] = list(map(fields_l.__getitem__,
                                 slot[wl_k].tolist()))
        if len(wr_k):
            out[wr_k] = list(map(fields_r.__getitem__,
                                 slot[wr_k].tolist()))
        return out

    def with_override(vals: list, file_o, rows) -> list:
        ov = list(map(file_o.__getitem__, rows.tolist()))
        if any(o is not None for o in ov):
            return [v if o is None else o for o, v in zip(ov, vals)]
        return vals

    for lo, hi in view.apply_shard_ranges():
        sides, idxs = view.row_slices(lo, hi)
        _, file_o, _ = view.override_rows(lo, hi)
        sides = np.asarray(sides, dtype=np.int32)
        idxs = np.asarray(idxs, dtype=np.int32)
        n = hi - lo
        isL = sides == 0
        kind_row = merged(kL, kR, isL, idxs)
        prec = _PREC_OF_KIND[kind_row]
        if n > 1 and not bool((prec[1:] >= prec[:-1]).all()):
            yield [("rows",
                    _row_order_actions(view, kind_row, isL, idxs, file_o))]
            continue
        groups: list = []
        mv = np.nonzero(kind_row == KIND_MOVE)[0]
        if len(mv):
            isL_k = isL[mv]
            a_row = merged(left.a_slot, right.a_slot, isL_k, idxs[mv])
            b_row = merged(left.b_slot, right.b_slot, isL_k, idxs[mv])
            # Move params are decl FILE fields, which the scanner never
            # leaves empty (every DeclNode carries its snapshot path) —
            # the object handler's falsy-param skip cannot fire, so the
            # validity scan is elided on this hot column.
            olds = list(map(b_file.__getitem__, a_row.tolist()))
            news = gather_side(l_file, r_file, isL_k, b_row)
            groups.append(("move", olds, news))
        ren = np.nonzero(kind_row == KIND_RENAME)[0]
        if len(ren):
            isL_k = isL[ren]
            a_row = merged(left.a_slot, right.a_slot, isL_k, idxs[ren])
            b_row = merged(left.b_slot, right.b_slot, isL_k, idxs[ren])
            olds = list(map(b_name.__getitem__, a_row.tolist()))
            news = gather_side(l_name, r_name, isL_k, b_row)
            files = with_override(
                gather_side(l_file, r_file, isL_k, b_row), file_o, ren)
            if all(olds) and all(news) and all(files):
                groups.append(("rename", files, olds, news))
            else:
                kept = [(f, o, nw)
                        for f, o, nw in zip(files, olds, news)
                        if f and o and nw]
                groups.append(("rename", [f for f, _, _ in kept],
                               [o for _, o, _ in kept],
                               [nw for _, _, nw in kept]))
        yield groups


def _row_order_actions(view, kind_row, isL, idxs, file_o) -> list:
    """Exact row-order assembly — the fallback for a composed stream
    that is not precedence-sorted (no producer emits one today; this
    keeps the bulk path honest rather than silently reordering)."""
    from ..ops.oplog_view import KIND_MOVE, KIND_RENAME
    left, right = view.left, view.right
    b_name, b_file = left.base_fields()[2:4]
    cols = ((left.a_slot, left.b_slot) + left.side_fields()[2:4],
            (right.a_slot, right.b_slot) + right.side_fields()[2:4])
    acts: list = []
    for w, (k, s, i) in enumerate(zip(kind_row.tolist(), isL.tolist(),
                                      idxs.tolist())):
        a_c, b_c, s_name, s_file = cols[0 if s else 1]
        if k == KIND_RENAME:
            f = file_o[w]
            if f is None:
                f = s_file[int(b_c[i])]
            old, new = b_name[int(a_c[i])], s_name[int(b_c[i])]
            if f and old and new:
                acts.append(("rename", f, old, new))
        elif k == KIND_MOVE:
            nf = file_o[w]
            if nf is None:
                nf = s_file[int(b_c[i])]
            of = b_file[int(a_c[i])]
            if of and nf:
                acts.append(("move", of, nf))
    return acts


def _apply_columnar(base_tree: pathlib.Path, view) -> pathlib.Path:
    out = pathlib.Path(tempfile.mkdtemp(prefix="semmerge_merged_"))
    shutil.copytree(base_tree, out, dirs_exist_ok=True)
    renames = moves = 0
    with obs_spans.span("apply_columnar", layer="runtime", rows=len(view)):
        for groups in iter_columnar_actions(view):
            for g in groups:
                if g[0] == "rename":
                    renames += len(g[1])
                    for f, old, new in zip(g[1], g[2], g[3]):
                        _rename_symbol_in_file(out, f, old, new)
                elif g[0] == "move":
                    moves += len(g[1])
                    for old, new in zip(g[1], g[2]):
                        _move_decl_path(out, old, new)
                else:  # ("rows", [...]) — the exact row-order fallback
                    for act in g[1]:
                        if act[0] == "rename":
                            _rename_symbol_in_file(out, *act[1:])
                            renames += 1
                        else:
                            _move_decl_path(out, *act[1:])
                            moves += 1
    skipped = len(view) - renames - moves
    rows = obs_metrics.REGISTRY.counter(
        "semmerge_columnar_apply_rows_total",
        "Composed rows consumed by the columnar applier, by action")
    rows.inc(renames, action="rename")
    rows.inc(moves, action="move")
    rows.inc(skipped, action="skip")
    return out


def consume_stream(ops) -> int:
    """Consume a composed stream the way ``cmd_semmerge``'s apply layer
    does, minus the tree I/O — the bench's honest device-path endpoint.

    Columnar views walk the full shard-wise action plan (forcing the
    chain decode and reading every param through the field tables);
    object streams — and any stream under ``SEMMERGE_OBJECT_APPLY=1`` —
    fully materialize, as the object applier's ``list(ops)`` does.
    Returns the number of actionable rows (renames + moves).
    """
    view = _columnar_view(ops)
    if view is not None and not _object_apply_forced():
        with obs_spans.span("apply_plan", layer="runtime", rows=len(view)):
            return sum(len(g[1]) for groups in iter_columnar_actions(view)
                       for g in groups)
    materialized = list(ops)
    return sum(op.type in ("renameSymbol", "moveDecl")
               for op in materialized)


def touched_paths(ops) -> Set[str]:
    """Normalized tree-relative paths of every file the composed stream
    can write — the ``[engine] formatter_scope = "touched"`` scope (the
    path-bearing params: ``file``/``oldFile``/``newFile``/``oldPath``/
    ``newPath``). Columnar views compute the set from their columns
    without materializing Ops; the object comprehension is the oracle
    (sets are equal by construction — parity-tested)."""
    view = _columnar_view(ops)
    if view is not None and not _object_apply_forced():
        return _touched_paths_columnar(view)
    return {str(_normalize_relpath(v))
            for op in ops
            for k in ("file", "oldFile", "newFile", "oldPath", "newPath")
            if isinstance((v := op.params.get(k)), str) and v}


def _touched_paths_columnar(view) -> Set[str]:
    from ..ops.oplog_view import (KIND_ADD, KIND_DELETE, KIND_MOVE,
                                  KIND_RENAME)
    left, right = view.left, view.right
    b_file = left.base_fields()[3]
    sources = (
        (left.kind, left.a_slot, left.b_slot, left.side_fields()[3]),
        (right.kind, right.a_slot, right.b_slot, right.side_fields()[3]),
    )
    raw: Set[str] = set()
    for lo, hi in view.apply_shard_ranges():
        sides, idxs = view.row_slices(lo, hi)
        _, file_o, _ = view.override_rows(lo, hi)
        sides = np.asarray(sides, dtype=np.int32)
        idxs = np.asarray(idxs, dtype=np.int32)
        for s, (kind_c, a_c, b_c, s_file) in enumerate(sources):
            on_side = np.nonzero(sides == s)[0]
            if not len(on_side):
                continue
            kind = kind_c[idxs[on_side]]
            # Rename `file` / move `newFile`: the side file, with the
            # chain-file override where _materialize_decoded puts it.
            ren_mv = on_side[(kind == KIND_RENAME) | (kind == KIND_MOVE)]
            for w, y in zip(ren_mv.tolist(), b_c[idxs[ren_mv]].tolist()):
                f = file_o[w]
                if f is None:
                    f = s_file[y]
                if f:
                    raw.add(f)
            # Add `file`: the raw side file (add/delete params keep it
            # even when the symbol's chain fired).
            adds = on_side[kind == KIND_ADD]
            for y in b_c[idxs[adds]].tolist():
                f = s_file[y]
                if f:
                    raw.add(f)
            # Move `oldFile` / delete `file`: the base file.
            base_rows = on_side[(kind == KIND_MOVE) | (kind == KIND_DELETE)]
            for x in a_c[idxs[base_rows]].tolist():
                f = b_file[x]
                if f:
                    raw.add(f)
    return {str(_normalize_relpath(p)) for p in raw}


# --------------------------------------------------------------------------
# Object dispatch (the oracle)
# --------------------------------------------------------------------------

def _apply_ops(base_tree: pathlib.Path, ops: list,
               device_crdt: bool) -> pathlib.Path:
    out = pathlib.Path(tempfile.mkdtemp(prefix="semmerge_merged_"))
    shutil.copytree(base_tree, out, dirs_exist_ok=True)
    resolved_orders = _resolve_reorder_orders(ops, device_crdt)

    # Structured-apply span edits (delete/changeSignature carrying
    # effects["decl"] payloads — the designed worker applyOps stage,
    # reference ``implementation.md:1258,1339``) run FIRST: their spans
    # are base-content offsets, so they must land before moves/renames
    # rewrite paths and text. Per file, descending start order keeps
    # earlier spans valid.
    span_ops = [op for op in ops
                if op.type in ("deleteDecl", "changeSignature")
                and isinstance(op.effects.get("decl"), dict)
                and "start" in op.effects["decl"]]
    _apply_span_edits(out, span_ops)
    structured = set(map(id, span_ops))

    add_ops = []
    for op in ops:
        if id(op) in structured:
            continue
        if (op.type == "addDecl"
                and isinstance(op.effects.get("decl"), dict)
                and "text" in op.effects["decl"]):
            add_ops.append(op)  # appends run after path-shaping ops
            continue
        if op.type == "reorderImports":
            _apply_reorder_imports(out, op, resolved_orders.get(id(op)))
            continue
        handler = _HANDLERS.get(op.type)
        if handler is None:
            logger.debug("No applier hook for op %s", op.type)
            continue
        handler(out, op)
    for op in add_ops:
        _apply_add_decl(out, op)
    return out


def _apply_span_edits(root: pathlib.Path, span_ops) -> None:
    by_file: dict = {}
    for op in span_ops:
        file_path = op.params.get("file")
        if file_path:
            by_file.setdefault(str(file_path), []).append(op)
    for file_path, file_ops in by_file.items():
        path = root / _normalize_relpath(file_path)
        if not path.exists():
            logger.debug("span-edit target missing: %s", path)
            continue
        code = path.read_text(encoding="utf-8")
        for op in sorted(file_ops,
                         key=lambda o: -int(o.effects["decl"]["start"])):
            decl = op.effects["decl"]
            start = max(0, int(decl["start"]))
            end = min(len(code), int(decl["end"]))
            if start > end:
                continue
            replacement = str(decl.get("text", ""))
            code = code[:start] + replacement + code[end:]
        path.write_text(code, encoding="utf-8")


def _apply_add_decl(root: pathlib.Path, op: Op) -> None:
    file_path = op.params.get("file")
    text = op.effects.get("decl", {}).get("text")
    if not file_path or text is None:
        return
    path = root / _normalize_relpath(file_path)
    path.parent.mkdir(parents=True, exist_ok=True)
    existing = path.read_text(encoding="utf-8") if path.exists() else ""
    if existing and not existing.endswith("\n"):
        existing += "\n"
    snippet = str(text)
    if not snippet.endswith("\n"):
        snippet += "\n"
    path.write_text(existing + snippet.lstrip("\n"), encoding="utf-8")


def _apply_move_decl(root: pathlib.Path, op: Op) -> None:
    old_file = op.params.get("oldFile") or op.params.get("file")
    new_file = op.params.get("newFile") or op.params.get("file")
    if not old_file or not new_file:
        return
    _move_decl_path(root, old_file, new_file)


def _move_decl_path(root: pathlib.Path, old_file, new_file) -> None:
    """The moveDecl edit primitive, shared by both dispatch paths."""
    src = root / _normalize_relpath(old_file)
    dst = root / _normalize_relpath(new_file)
    if src == dst:
        return
    if not src.exists():
        logger.debug("moveDecl source missing: %s", src)
        return
    dst.parent.mkdir(parents=True, exist_ok=True)
    shutil.move(src, dst)


def _apply_move_file(root: pathlib.Path, op: Op) -> None:
    old_path = op.params.get("oldPath")
    new_path = op.params.get("newPath")
    if not old_path or not new_path:
        return
    src = root / _normalize_relpath(old_path)
    dst = root / _normalize_relpath(new_path)
    if not src.exists():
        logger.debug("moveFile source missing: %s", src)
        return
    dst.parent.mkdir(parents=True, exist_ok=True)
    shutil.move(src, dst)


def _apply_rename_symbol(root: pathlib.Path, op: Op) -> None:
    file_path = op.params.get("file") or op.params.get("newFile")
    old_name = op.params.get("oldName")
    new_name = op.params.get("newName")
    if not file_path or not old_name or not new_name:
        return
    _rename_symbol_in_file(root, file_path, str(old_name), str(new_name))


def _rename_symbol_in_file(root: pathlib.Path, file_path,
                           old_name: str, new_name: str) -> None:
    """The renameSymbol edit primitive, shared by both dispatch paths."""
    path = root / _normalize_relpath(file_path)
    if not path.exists():
        logger.debug("renameSymbol target missing: %s", path)
        return
    code = path.read_text(encoding="utf-8")
    code = re.sub(rf"\b{re.escape(old_name)}\b", new_name, code)
    path.write_text(code, encoding="utf-8")


def _apply_edit_stmt_block(root: pathlib.Path, op: Op) -> None:
    """Splice an ``editStmtBlock``'s new body over its old one. The op
    carries both texts (core.difflift.statement_edits), so the splice
    is a single exact replacement — position-independent, surviving
    earlier edits that shifted offsets. A missing old body (the other
    side rewrote the decl some other way) degrades to a logged skip,
    consistent with the reference applier's unknown-op posture."""
    file_path = op.params.get("file")
    old_body = op.params.get("oldBody")
    new_body = op.params.get("newBody")
    if not file_path or old_body is None or new_body is None:
        return
    path = root / _normalize_relpath(file_path)
    if not path.exists():
        logger.debug("editStmtBlock target missing: %s", path)
        return
    code = path.read_text(encoding="utf-8")
    if str(old_body) not in code:
        logger.debug("editStmtBlock old body not found in %s; skipping", path)
        return
    path.write_text(code.replace(str(old_body), str(new_body), 1),
                    encoding="utf-8")


def _apply_modify_import(root: pathlib.Path, op: Op) -> None:
    file_path = op.params.get("file")
    old_import = op.params.get("oldImport")
    new_import = op.params.get("newImport")
    if not file_path or old_import is None or new_import is None:
        return
    path = root / _normalize_relpath(file_path)
    if not path.exists():
        logger.debug("modifyImport target missing: %s", path)
        return
    code = path.read_text(encoding="utf-8")
    path.write_text(code.replace(str(old_import), str(new_import)), encoding="utf-8")


def _build_rga(order) -> "object":
    from ..core.crdt import RGA, Key
    rga = RGA()
    for entry in order:
        rga.insert(Key(str(entry.get("anchor", "")), int(entry.get("t", 0)),
                       str(entry.get("author", "")), str(entry.get("opid", ""))),
                   str(entry.get("value", "")))
    return rga


def _resolve_reorder_orders(ops, device_crdt: bool) -> dict:
    """Resolve every reorderImports op's RGA ordering up front — the
    whole merge's lists in one batched device materialization on the
    tpu path, per-list host scans otherwise."""
    items = [op for op in ops
             if op.type == "reorderImports" and op.params.get("order")]
    if not items:
        return {}
    rgas = [_build_rga(op.params["order"]) for op in items]
    if device_crdt:
        try:
            from ..ops.crdt import materialize_batch
            ordered_lists = materialize_batch(rgas)
            return {id(op): lst for op, lst in zip(items, ordered_lists)}
        except Exception as exc:
            logger.warning("device CRDT batch failed (%s); host fallback", exc)
    return {id(op): list(rga.materialize()) for op, rga in zip(items, rgas)}


def _apply_reorder_imports(root: pathlib.Path, op: Op,
                           ordered=None) -> None:
    """Reorder a file's leading import block per the op's CRDT keys.

    The op's ``params["order"]`` is a list of ``{value, anchor, t,
    author, opid}`` records; ordering is resolved by the RGA CRDT
    (specified at reference ``requirements.md:71-75`` [CRD-001..004] and
    ``architecture.md:173-178`` but left dead in the reference). The
    order itself was resolved in the batched pre-pass of
    :func:`apply_ops`."""
    file_path = op.params.get("file")
    order = op.params.get("order")
    if not file_path or not order:
        return
    path = root / _normalize_relpath(file_path)
    if not path.exists():
        return
    lines = path.read_text(encoding="utf-8").splitlines(keepends=True)
    import_idx = [i for i, ln in enumerate(lines) if ln.lstrip().startswith("import ")]
    if not import_idx:
        return
    if ordered is None:  # direct handler call outside apply_ops
        ordered = list(_build_rga(order).materialize())
    by_text = {lines[i].strip(): i for i in import_idx}
    new_imports = [lines[by_text[v]] for v in ordered if v in by_text]
    remaining = [lines[i] for i in import_idx if lines[i].strip() not in set(ordered)]
    block = new_imports + remaining
    first = import_idx[0]
    kept = [ln for i, ln in enumerate(lines) if i not in set(import_idx)]
    kept[first:first] = block
    path.write_text("".join(kept), encoding="utf-8")


def _normalize_relpath(value: str) -> pathlib.Path:
    """Normalize an op-supplied path to a tree-relative path.

    Strips absolute anchors (reference ``semmerge/applier.py:97-104``)
    and additionally rejects ``..`` traversal segments — op logs can
    arrive from fetched git notes (``semrebase``), so a hostile note
    must not be able to address files outside the merge tree.
    """
    path = pathlib.Path(value)
    if path.is_absolute():
        try:
            path = path.relative_to(path.anchor)
        except ValueError:
            path = pathlib.Path(path.name)
    parts = [p for p in path.parts if p not in ("..", ".")]
    return pathlib.Path(*parts) if parts else pathlib.Path(path.name)


_HANDLERS = {
    "moveDecl": _apply_move_decl,
    "moveFile": _apply_move_file,
    "renameSymbol": _apply_rename_symbol,
    "modifyImport": _apply_modify_import,
    "reorderImports": _apply_reorder_imports,
    "editStmtBlock": _apply_edit_stmt_block,
}
