"""Materialize composed ops onto a tree (reference ``semmerge/applier.py``).

Applies a composed op list to a copy of the base tree. Implemented
handlers (the reference's set): ``moveDecl`` moves the *whole file*
old→new; ``renameSymbol`` rewrites word-boundary occurrences across the
file; ``modifyImport`` is a literal replace; ``moveFile`` moves by
old/new path. Everything else is logged and skipped (reference
``semmerge/applier.py:30-31``). Additionally ``reorderImports`` is
applied via the RGA CRDT ordering (wired in here; dead code in the
reference, ``semmerge/crdt.py``).
"""
from __future__ import annotations

import pathlib
import re
import shutil
import tempfile
from typing import Iterable

from ..core.ops import Op
from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans
from ..utils.loggingx import logger


def apply_ops(base_tree: pathlib.Path, ops: Iterable[Op],
              *, device_crdt: bool = False) -> pathlib.Path:
    """Apply composed ops to a copy of ``base_tree``.

    With ``device_crdt`` (the tpu backend's path), every
    ``reorderImports`` op's RGA ordering in the merge resolves in ONE
    batched device materialization
    (:func:`semantic_merge_tpu.ops.crdt.materialize_batch`) instead of
    per-list host insert scans; output is identical (parity-tested).
    """
    ops = list(ops)
    obs_metrics.REGISTRY.counter(
        "semmerge_ops_applied_total",
        "Composed ops handed to the tree applier").inc(len(ops))
    with obs_spans.span("apply_ops", layer="runtime", ops=len(ops),
                        device_crdt=device_crdt):
        return _apply_ops(pathlib.Path(base_tree), ops, device_crdt)


def _apply_ops(base_tree: pathlib.Path, ops: list,
               device_crdt: bool) -> pathlib.Path:
    out = pathlib.Path(tempfile.mkdtemp(prefix="semmerge_merged_"))
    shutil.copytree(base_tree, out, dirs_exist_ok=True)
    resolved_orders = _resolve_reorder_orders(ops, device_crdt)

    # Structured-apply span edits (delete/changeSignature carrying
    # effects["decl"] payloads — the designed worker applyOps stage,
    # reference ``implementation.md:1258,1339``) run FIRST: their spans
    # are base-content offsets, so they must land before moves/renames
    # rewrite paths and text. Per file, descending start order keeps
    # earlier spans valid.
    span_ops = [op for op in ops
                if op.type in ("deleteDecl", "changeSignature")
                and isinstance(op.effects.get("decl"), dict)
                and "start" in op.effects["decl"]]
    _apply_span_edits(out, span_ops)
    structured = set(map(id, span_ops))

    add_ops = []
    for op in ops:
        if id(op) in structured:
            continue
        if (op.type == "addDecl"
                and isinstance(op.effects.get("decl"), dict)
                and "text" in op.effects["decl"]):
            add_ops.append(op)  # appends run after path-shaping ops
            continue
        if op.type == "reorderImports":
            _apply_reorder_imports(out, op, resolved_orders.get(id(op)))
            continue
        handler = _HANDLERS.get(op.type)
        if handler is None:
            logger.debug("No applier hook for op %s", op.type)
            continue
        handler(out, op)
    for op in add_ops:
        _apply_add_decl(out, op)
    return out


def _apply_span_edits(root: pathlib.Path, span_ops) -> None:
    by_file: dict = {}
    for op in span_ops:
        file_path = op.params.get("file")
        if file_path:
            by_file.setdefault(str(file_path), []).append(op)
    for file_path, file_ops in by_file.items():
        path = root / _normalize_relpath(file_path)
        if not path.exists():
            logger.debug("span-edit target missing: %s", path)
            continue
        code = path.read_text(encoding="utf-8")
        for op in sorted(file_ops,
                         key=lambda o: -int(o.effects["decl"]["start"])):
            decl = op.effects["decl"]
            start = max(0, int(decl["start"]))
            end = min(len(code), int(decl["end"]))
            if start > end:
                continue
            replacement = str(decl.get("text", ""))
            code = code[:start] + replacement + code[end:]
        path.write_text(code, encoding="utf-8")


def _apply_add_decl(root: pathlib.Path, op: Op) -> None:
    file_path = op.params.get("file")
    text = op.effects.get("decl", {}).get("text")
    if not file_path or text is None:
        return
    path = root / _normalize_relpath(file_path)
    path.parent.mkdir(parents=True, exist_ok=True)
    existing = path.read_text(encoding="utf-8") if path.exists() else ""
    if existing and not existing.endswith("\n"):
        existing += "\n"
    snippet = str(text)
    if not snippet.endswith("\n"):
        snippet += "\n"
    path.write_text(existing + snippet.lstrip("\n"), encoding="utf-8")


def _apply_move_decl(root: pathlib.Path, op: Op) -> None:
    old_file = op.params.get("oldFile") or op.params.get("file")
    new_file = op.params.get("newFile") or op.params.get("file")
    if not old_file or not new_file:
        return
    src = root / _normalize_relpath(old_file)
    dst = root / _normalize_relpath(new_file)
    if src == dst:
        return
    if not src.exists():
        logger.debug("moveDecl source missing: %s", src)
        return
    dst.parent.mkdir(parents=True, exist_ok=True)
    shutil.move(src, dst)


def _apply_move_file(root: pathlib.Path, op: Op) -> None:
    old_path = op.params.get("oldPath")
    new_path = op.params.get("newPath")
    if not old_path or not new_path:
        return
    src = root / _normalize_relpath(old_path)
    dst = root / _normalize_relpath(new_path)
    if not src.exists():
        logger.debug("moveFile source missing: %s", src)
        return
    dst.parent.mkdir(parents=True, exist_ok=True)
    shutil.move(src, dst)


def _apply_rename_symbol(root: pathlib.Path, op: Op) -> None:
    file_path = op.params.get("file") or op.params.get("newFile")
    old_name = op.params.get("oldName")
    new_name = op.params.get("newName")
    if not file_path or not old_name or not new_name:
        return
    path = root / _normalize_relpath(file_path)
    if not path.exists():
        logger.debug("renameSymbol target missing: %s", path)
        return
    code = path.read_text(encoding="utf-8")
    code = re.sub(rf"\b{re.escape(str(old_name))}\b", str(new_name), code)
    path.write_text(code, encoding="utf-8")


def _apply_edit_stmt_block(root: pathlib.Path, op: Op) -> None:
    """Splice an ``editStmtBlock``'s new body over its old one. The op
    carries both texts (core.difflift.statement_edits), so the splice
    is a single exact replacement — position-independent, surviving
    earlier edits that shifted offsets. A missing old body (the other
    side rewrote the decl some other way) degrades to a logged skip,
    consistent with the reference applier's unknown-op posture."""
    file_path = op.params.get("file")
    old_body = op.params.get("oldBody")
    new_body = op.params.get("newBody")
    if not file_path or old_body is None or new_body is None:
        return
    path = root / _normalize_relpath(file_path)
    if not path.exists():
        logger.debug("editStmtBlock target missing: %s", path)
        return
    code = path.read_text(encoding="utf-8")
    if str(old_body) not in code:
        logger.debug("editStmtBlock old body not found in %s; skipping", path)
        return
    path.write_text(code.replace(str(old_body), str(new_body), 1),
                    encoding="utf-8")


def _apply_modify_import(root: pathlib.Path, op: Op) -> None:
    file_path = op.params.get("file")
    old_import = op.params.get("oldImport")
    new_import = op.params.get("newImport")
    if not file_path or old_import is None or new_import is None:
        return
    path = root / _normalize_relpath(file_path)
    if not path.exists():
        logger.debug("modifyImport target missing: %s", path)
        return
    code = path.read_text(encoding="utf-8")
    path.write_text(code.replace(str(old_import), str(new_import)), encoding="utf-8")


def _build_rga(order) -> "object":
    from ..core.crdt import RGA, Key
    rga = RGA()
    for entry in order:
        rga.insert(Key(str(entry.get("anchor", "")), int(entry.get("t", 0)),
                       str(entry.get("author", "")), str(entry.get("opid", ""))),
                   str(entry.get("value", "")))
    return rga


def _resolve_reorder_orders(ops, device_crdt: bool) -> dict:
    """Resolve every reorderImports op's RGA ordering up front — the
    whole merge's lists in one batched device materialization on the
    tpu path, per-list host scans otherwise."""
    items = [op for op in ops
             if op.type == "reorderImports" and op.params.get("order")]
    if not items:
        return {}
    rgas = [_build_rga(op.params["order"]) for op in items]
    if device_crdt:
        try:
            from ..ops.crdt import materialize_batch
            ordered_lists = materialize_batch(rgas)
            return {id(op): lst for op, lst in zip(items, ordered_lists)}
        except Exception as exc:
            logger.warning("device CRDT batch failed (%s); host fallback", exc)
    return {id(op): list(rga.materialize()) for op, rga in zip(items, rgas)}


def _apply_reorder_imports(root: pathlib.Path, op: Op,
                           ordered=None) -> None:
    """Reorder a file's leading import block per the op's CRDT keys.

    The op's ``params["order"]`` is a list of ``{value, anchor, t,
    author, opid}`` records; ordering is resolved by the RGA CRDT
    (specified at reference ``requirements.md:71-75`` [CRD-001..004] and
    ``architecture.md:173-178`` but left dead in the reference). The
    order itself was resolved in the batched pre-pass of
    :func:`apply_ops`."""
    file_path = op.params.get("file")
    order = op.params.get("order")
    if not file_path or not order:
        return
    path = root / _normalize_relpath(file_path)
    if not path.exists():
        return
    lines = path.read_text(encoding="utf-8").splitlines(keepends=True)
    import_idx = [i for i, ln in enumerate(lines) if ln.lstrip().startswith("import ")]
    if not import_idx:
        return
    if ordered is None:  # direct handler call outside apply_ops
        ordered = list(_build_rga(order).materialize())
    by_text = {lines[i].strip(): i for i in import_idx}
    new_imports = [lines[by_text[v]] for v in ordered if v in by_text]
    remaining = [lines[i] for i in import_idx if lines[i].strip() not in set(ordered)]
    block = new_imports + remaining
    first = import_idx[0]
    kept = [ln for i, ln in enumerate(lines) if i not in set(import_idx)]
    kept[first:first] = block
    path.write_text("".join(kept), encoding="utf-8")


def _normalize_relpath(value: str) -> pathlib.Path:
    """Normalize an op-supplied path to a tree-relative path.

    Strips absolute anchors (reference ``semmerge/applier.py:97-104``)
    and additionally rejects ``..`` traversal segments — op logs can
    arrive from fetched git notes (``semrebase``), so a hostile note
    must not be able to address files outside the merge tree.
    """
    path = pathlib.Path(value)
    if path.is_absolute():
        try:
            path = path.relative_to(path.anchor)
        except ValueError:
            path = pathlib.Path(path.name)
    parts = [p for p in path.parts if p not in ("..", ".")]
    return pathlib.Path(*parts) if parts else pathlib.Path(path.name)


_HANDLERS = {
    "moveDecl": _apply_move_decl,
    "moveFile": _apply_move_file,
    "renameSymbol": _apply_rename_symbol,
    "modifyImport": _apply_modify_import,
    "reorderImports": _apply_reorder_imports,
    "editStmtBlock": _apply_edit_stmt_block,
}
