"""Post-merge verification (reference ``semmerge/verify.py``).

Type-checks the merged tree with ``tsc --noEmit``. A missing toolchain
passes vacuously — the documented graceful-degradation contract
(reference ``semmerge/verify.py:28-30``; ``requirements.md:107``
[FBK-003]; ``runbook.md:57``). "Missing toolchain" includes the
half-installed case: ``npx`` present but ``tsc`` not installed makes
``npx`` print its *own* error and exit nonzero — that must be the
vacuous pass, not a failed merge. Real type failures are recognized by
``tsc``'s diagnostic format (``error TS####``), which every tsc
diagnostic carries; launcher noise never does.

The invocation runs under a process-group deadline
(``SEMMERGE_TYPECHECK_TIMEOUT`` seconds, default 300): a wedged npx/tsc
raises :class:`~semantic_merge_tpu.errors.DeadlineFault` into the CLI's
degradation ladder instead of hanging the merge driver forever.
"""
from __future__ import annotations

import pathlib
import re
import subprocess
from typing import FrozenSet, List, Optional, Set, Tuple

from ..utils.loggingx import logger
from ..utils.procs import env_seconds, run_with_deadline

#: Every real tsc diagnostic line carries an ``error TS####`` code;
#: npx/npm launcher failures (tsc uninstalled, registry errors) do not.
_TSC_DIAGNOSTIC = re.compile(r"\berror TS\d+")


def typecheck_ts(tree_path: pathlib.Path, *,
                 deadline: Optional[float] = None) -> Tuple[bool, List[str]]:
    tree_path = pathlib.Path(tree_path)
    if deadline is None:
        deadline = env_seconds("SEMMERGE_TYPECHECK_TIMEOUT", 300.0)
    try:
        proc = run_with_deadline(
            ["npx", "tsc", "-p", ".", "--noEmit"],
            timeout=deadline, stage="verify",
            cwd=tree_path, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
    except FileNotFoundError:
        logger.debug("TypeScript compiler not available; skipping type-check")
        return True, []
    if proc.returncode == 0:
        return True, []
    lines = (proc.stdout or "").splitlines()
    if not any(_TSC_DIAGNOSTIC.search(line) for line in lines):
        # Nonzero exit without a single tsc diagnostic: the launcher
        # failed (npx present, tsc uninstalled / npm error) — the
        # documented vacuous pass, not a type failure.
        logger.debug("tsc launcher failed without diagnostics "
                     "(toolchain incomplete); skipping type-check")
        return True, []
    return False, lines


def _file_set(tree: pathlib.Path) -> Set[str]:
    return {p.relative_to(tree).as_posix()
            for p in tree.rglob("*") if p.is_file()}


def untouched_parity(tree_a: pathlib.Path, tree_b: pathlib.Path, *,
                     exclude: FrozenSet[str] | Set[str] = frozenset(),
                     ) -> List[str]:
    """Byte-parity audit of two trees outside an excluded footprint —
    the resolution tier's never-worse gate: everything a resolution did
    *not* claim to touch must be identical to the conflict-free merge.

    Returns the sorted tree-relative (posix) paths that differ —
    present on one side only, or byte-unequal — excluding ``exclude``;
    an empty list means parity holds."""
    tree_a, tree_b = pathlib.Path(tree_a), pathlib.Path(tree_b)
    excluded = set(exclude)
    mismatched: List[str] = []
    for rel in sorted(_file_set(tree_a) | _file_set(tree_b)):
        if rel in excluded:
            continue
        fa, fb = tree_a / rel, tree_b / rel
        if not (fa.is_file() and fb.is_file()):
            mismatched.append(rel)
        elif fa.read_bytes() != fb.read_bytes():
            mismatched.append(rel)
    return mismatched
