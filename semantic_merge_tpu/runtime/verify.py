"""Post-merge verification (reference ``semmerge/verify.py``).

Type-checks the merged tree with ``tsc --noEmit``. A missing toolchain
passes vacuously — the documented graceful-degradation contract
(reference ``semmerge/verify.py:28-30``; ``requirements.md:107``
[FBK-003]; ``runbook.md:57``).
"""
from __future__ import annotations

import pathlib
import subprocess
from typing import List, Tuple

from ..utils.loggingx import logger


def typecheck_ts(tree_path: pathlib.Path) -> Tuple[bool, List[str]]:
    tree_path = pathlib.Path(tree_path)
    try:
        proc = subprocess.run(
            ["npx", "tsc", "-p", ".", "--noEmit"],
            cwd=tree_path, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
    except FileNotFoundError:
        logger.debug("TypeScript compiler not available; skipping type-check")
        return True, []
    return proc.returncode == 0, proc.stdout.splitlines()
