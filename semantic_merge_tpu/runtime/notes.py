"""Op-log persistence as git notes (reference ``semmerge/notes.py``).

Op logs are attached to the merged commits under the ``semmerge`` notes
ref after every successful merge, for traceability and rebase replay.
Failures are swallowed — notes are best-effort metadata, never a reason
to fail a merge (reference ``semmerge/notes.py:34-36``). Unlike the
reference, the logs can also be read back (``notes_get``), which powers
``semrebase`` replay.
"""
from __future__ import annotations

import os
import pathlib
import subprocess
import tempfile

from ..core.ops import OpLog
from ..utils import workdir

NOTES_REF = "semmerge"


def notes_put(commit: str, oplog: OpLog, namespace: str = NOTES_REF) -> None:
    fd, tmp_path = tempfile.mkstemp(prefix="semmerge_notes_")
    os.close(fd)
    tmp_file = pathlib.Path(tmp_path)
    try:
        tmp_file.write_bytes(oplog.to_json_bytes())
        subprocess.run(
            ["git", "notes", "--ref", namespace, "add", "-f", "-F", str(tmp_file), commit],
            check=True, cwd=workdir.current(),
        )
    except subprocess.CalledProcessError:
        pass  # Notes are optional; never fail the merge over them.
    finally:
        tmp_file.unlink(missing_ok=True)


def notes_get(commit: str, namespace: str = NOTES_REF) -> OpLog | None:
    try:
        proc = subprocess.run(
            ["git", "notes", "--ref", namespace, "show", commit],
            check=True, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            cwd=workdir.current(),
        )
    except subprocess.CalledProcessError:
        return None
    try:
        return OpLog.from_json(proc.stdout)
    except Exception:
        return None
