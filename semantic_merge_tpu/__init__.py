"""semantic_merge_tpu — a TPU-native semantic merge framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of the
jimmc414/semantic_merge reference engine (see SURVEY.md): git-integrated
three-way *semantic* merges of TypeScript repositories, where per-file
AST indexing, symbol diffing, op-log lifting, composition, and CRDT
ordering run as batched, sharded device programs instead of a per-file
Node.js worker + sequential Python loops.

Layer map (mirrors SURVEY.md §1, re-architected TPU-first):

- ``runtime/``  — host orchestration: git plumbing, notes, applier,
  formatter/typecheck hooks, tracing (reference L7/L5/L1).
- ``cli.py``    — the ``semmerge``/``semdiff`` orchestrator (reference L6).
- ``core/``     — pure data contracts: Op/OpLog/Target/Conflict, the
  deterministic id scheme, and string→integer encoding (reference L4 data).
- ``ops/``      — device compute: batched diff joins, vectorized lift,
  segmented-scan compose, sorted-CRDT reconciliation (reference L4 loops
  + the L2 worker hot path, lifted onto the TPU).
- ``frontend/`` — host-side TS/JS declaration scanner (Python + native
  C++), replacing the Node worker's parse/index stage (reference L2).
- ``backends/`` — the ``lang/`` plugin slot: ``ts_host`` is the CPU
  parity oracle, ``ts_tpu`` is the device path (reference L3).
- ``parallel/`` — mesh construction, shardings, collective joins.
- ``models/``   — the DeclAligner similarity matcher (the P1 learned
  matcher from the reference design docs) and its distributed trainer.
"""

__version__ = "0.1.0"
