"""Pluggable fleet member transport: unix sockets, TCP, optional mTLS.

Every router↔member round-trip — verb dispatch, control calls, health
heartbeats — and the client's fleet-socket dial goes through this seam.
Addresses select the transport::

    /run/semmerge.sock.m0      # plain path: AF_UNIX (the default)
    tcp://10.0.0.7:7633        # TCP: members on other hosts
    tcp://[::1]:7633           # bracketed IPv6

TLS is configured by environment (both sides of a fleet share it):

=========================  ============================================
env var                    meaning
=========================  ============================================
SEMMERGE_FLEET_TLS_CERT    PEM cert chain this endpoint presents
SEMMERGE_FLEET_TLS_KEY     its private key (defaults to the cert file)
SEMMERGE_FLEET_TLS_CA      CA bundle the *peer* must chain to — setting
                           it turns verification on in both directions
                           (mTLS); a fleet pins its own private CA, so
                           hostname checks are off (members are
                           addressed by IP/port, identity comes from
                           the CA signature)
=========================  ============================================

Robustness contract (the tentpole of the cross-host PR): per-call
connect/read deadlines (``SEMMERGE_FLEET_CONNECT_TIMEOUT``,
``SEMMERGE_FLEET_READ_TIMEOUT``), jittered exponential backoff between
bounded resends (``SEMMERGE_FLEET_RESENDS`` — safe because every fleet
request carries an idempotency key, so a resend of an
already-executed request replays the recorded response), and
application-level heartbeats (:func:`heartbeat`, a ``hello`` round
trip under ``SEMMERGE_FLEET_HEARTBEAT_TIMEOUT``) that detect half-open
connections TCP keepalive would sit on for minutes. Transport-shaped
failures raise :class:`~semantic_merge_tpu.errors.TransportFault`
(exit 21 under ``SEMMERGE_FLEET=require``; under ``auto`` every caller
degrades through the existing ladder instead).

The ``net:*`` fault stages (``utils/faults.py``) are wired here:
``net:connect`` fires before each dial, ``net:read`` before each reply
read, ``net:partition`` at both seams (a half-open link fails reads
and fresh dials alike), and ``net:slow`` injects
``SEMMERGE_FAULT_NET_SLOW_S`` (default 0.2 s) of latency per dial when
given a verbatim kind token (``net:slow:lag``); its ``fault``/``raise``
kinds raise like any other stage.

Import-light: stdlib + the error taxonomy + the fault harness — the
client dials through this module before jax exists in the process.
"""
from __future__ import annotations

import contextlib
import os
import random
import socket
import ssl
import time
from typing import Any, Dict, Optional, Tuple

from ..errors import TransportFault, fault_boundary
from ..service import protocol
from ..utils import faults
from ..utils.procs import env_seconds

#: Address prefix selecting the TCP transport.
TCP_PREFIX = "tcp://"

ENV_TLS_CERT = "SEMMERGE_FLEET_TLS_CERT"
ENV_TLS_KEY = "SEMMERGE_FLEET_TLS_KEY"
ENV_TLS_CA = "SEMMERGE_FLEET_TLS_CA"

_ERRORS_HELP = "Fleet transport failures, by operation"
_RESENDS_HELP = "Idempotency-keyed transport resends after a failed leg"
_HEARTBEATS_HELP = "Application-level member heartbeats, by outcome"

#: Documented ``fleet_transport_errors_total`` op label values.
OPS = ("dial", "read", "control", "heartbeat")
#: Documented ``fleet_heartbeats_total`` outcome label values.
HEARTBEAT_OUTCOMES = ("ok", "connect", "timeout", "error")


# ----------------------------------------------------------------------
# addresses


def is_tcp(address: str) -> bool:
    """True when ``address`` selects the TCP transport."""
    return str(address).startswith(TCP_PREFIX)


def tcp_endpoint(address: str) -> Tuple[str, int]:
    """``(host, port)`` of a ``tcp://host:port`` address (bracketed
    IPv6 accepted). Raises ``ValueError`` on anything else."""
    if not is_tcp(address):
        raise ValueError(f"not a tcp:// address: {address!r}")
    rest = address[len(TCP_PREFIX):]
    host, sep, port = rest.rpartition(":")
    if not sep or not host or not port.isdigit():
        raise ValueError(f"malformed tcp address: {address!r}")
    if host.startswith("[") and host.endswith("]"):
        host = host[1:-1]
    if not host:
        raise ValueError(f"malformed tcp address: {address!r}")
    return host, int(port)


def describe(address: str) -> str:
    """Short log-friendly form of an address."""
    return address if is_tcp(address) else os.path.basename(address) or \
        address


def bound_address(sock: socket.socket, address: str) -> str:
    """The concrete address a listener bound — resolves a ``:0``
    ephemeral TCP port to the kernel-assigned one so a member can
    advertise something dialable."""
    if not is_tcp(address):
        return address
    host, port = tcp_endpoint(address)
    if port != 0:
        return address
    actual = sock.getsockname()[1]
    rendered = f"[{host}]" if ":" in host else host
    return f"{TCP_PREFIX}{rendered}:{actual}"


# ----------------------------------------------------------------------
# knobs


def connect_timeout() -> float:
    return env_seconds("SEMMERGE_FLEET_CONNECT_TIMEOUT", 5.0)


def read_timeout(default: float) -> float:
    return env_seconds("SEMMERGE_FLEET_READ_TIMEOUT", default)


def heartbeat_timeout() -> float:
    return env_seconds("SEMMERGE_FLEET_HEARTBEAT_TIMEOUT", 2.0)


def resends() -> int:
    raw = os.environ.get("SEMMERGE_FLEET_RESENDS", "").strip()
    try:
        return max(0, int(raw)) if raw else 2
    except ValueError:
        return 2


def backoff_s(attempt: int, base: float = 0.05, cap: float = 2.0) -> float:
    """Full-jitter exponential backoff: ``uniform(0, min(cap,
    base * 2^attempt))`` — resending peers decorrelate instead of
    hammering a recovering member in lockstep."""
    return random.uniform(0.0, min(cap, base * (2.0 ** attempt)))


# ----------------------------------------------------------------------
# TLS


def _tls_env() -> Tuple[str, str, str]:
    cert = os.environ.get(ENV_TLS_CERT, "").strip()
    key = os.environ.get(ENV_TLS_KEY, "").strip() or cert
    ca = os.environ.get(ENV_TLS_CA, "").strip()
    return cert, key, ca


def tls_enabled() -> bool:
    """True when any fleet TLS material is configured."""
    cert, _key, ca = _tls_env()
    return bool(cert or ca)


def client_context() -> Optional[ssl.SSLContext]:
    """The dial-side TLS context, or ``None`` for plaintext. With a CA
    configured the server must chain to it; with a cert configured this
    endpoint presents it (the server's mTLS requirement)."""
    cert, key, ca = _tls_env()
    if not (cert or ca):
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    if ca:
        ctx.verify_mode = ssl.CERT_REQUIRED
        ctx.load_verify_locations(cafile=ca)
    else:
        ctx.verify_mode = ssl.CERT_NONE
    if cert:
        ctx.load_cert_chain(certfile=cert, keyfile=key)
    return ctx


def server_context() -> Optional[ssl.SSLContext]:
    """The listen-side TLS context, or ``None`` for plaintext. Needs a
    cert to serve; with a CA configured every client must present a
    cert chaining to it (mTLS)."""
    cert, key, ca = _tls_env()
    if not cert:
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(certfile=cert, keyfile=key)
    if ca:
        ctx.verify_mode = ssl.CERT_REQUIRED
        ctx.load_verify_locations(cafile=ca)
    return ctx


# ----------------------------------------------------------------------
# fault seams


def _slow_s() -> float:
    raw = os.environ.get("SEMMERGE_FAULT_NET_SLOW_S", "").strip()
    try:
        return float(raw) if raw else 0.2
    except ValueError:
        return 0.2


def check_dial_faults() -> None:
    """The ``net:connect`` / ``net:slow`` / ``net:partition`` injection
    seams, fired before every dial. Plain ``raise`` kinds classify into
    :class:`TransportFault` at the boundary."""
    with fault_boundary("net:connect"):
        faults.check("net:connect")
    with fault_boundary("net:slow"):
        token = faults.check("net:slow")
    if token is not None:
        time.sleep(_slow_s())
    with fault_boundary("net:partition"):
        faults.check("net:partition")


def check_read_faults() -> None:
    """The ``net:read`` / ``net:partition`` seams, fired before every
    reply read."""
    with fault_boundary("net:read"):
        faults.check("net:read")
    with fault_boundary("net:partition"):
        faults.check("net:partition")


# ----------------------------------------------------------------------
# metrics (lazy: the client imports this module pre-everything)


def _count_error(op: str) -> None:
    from ..obs import metrics as obs_metrics
    obs_metrics.REGISTRY.counter("fleet_transport_errors_total",
                                 _ERRORS_HELP).inc(1, op=op)


def count_resend() -> None:
    from ..obs import metrics as obs_metrics
    obs_metrics.REGISTRY.counter("fleet_transport_resends_total",
                                 _RESENDS_HELP).inc(1)


def _count_heartbeat(outcome: str) -> None:
    from ..obs import metrics as obs_metrics
    obs_metrics.REGISTRY.counter("fleet_heartbeats_total",
                                 _HEARTBEATS_HELP).inc(1, outcome=outcome)


# ----------------------------------------------------------------------
# dial / listen


def dial(address: str, timeout: Optional[float] = None,
         tls: bool = True) -> Optional[socket.socket]:
    """Connect to a member address under the connect deadline. Returns
    the connected (TLS-wrapped when configured) socket, or ``None``
    when nothing usable is listening — absent path, refused, connect
    timeout, failed TLS handshake. Injected ``net:*`` faults raise
    :class:`TransportFault` instead."""
    check_dial_faults()
    t = timeout if timeout is not None else connect_timeout()
    if is_tcp(address):
        host, port = tcp_endpoint(address)
        try:
            sock = socket.create_connection((host, port), timeout=t)
        except OSError:
            _count_error("dial")
            return None
        ctx = client_context() if tls else None
        if ctx is not None:
            try:
                sock = ctx.wrap_socket(sock, server_hostname=host)
            except (OSError, ssl.SSLError):
                _count_error("dial")
                with contextlib.suppress(OSError):
                    sock.close()
                return None
        return sock
    if not os.path.exists(address):
        return None
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(t)
    try:
        sock.connect(address)
    except OSError:
        _count_error("dial")
        with contextlib.suppress(OSError):
            sock.close()
        return None
    return sock


def listen(address: str, backlog: int = 128) -> socket.socket:
    """Bind + listen on a TCP address (TLS-wrapped when a server cert
    is configured — accepted connections handshake on first I/O).
    Raises ``OSError`` on bind failure; unix paths stay with their
    owner's stale-socket dance (``daemon._bind``)."""
    host, port = tcp_endpoint(address)
    family = socket.AF_INET6 if ":" in host else socket.AF_INET
    sock = socket.socket(family, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind((host, port))
    sock.listen(backlog)
    ctx = server_context()
    if ctx is not None:
        sock = ctx.wrap_socket(sock, server_side=True)
    return sock


# ----------------------------------------------------------------------
# round trips


def roundtrip(address: str, payload: Dict[str, Any], *,
              connect_deadline: Optional[float] = None,
              read_deadline: Optional[float] = None) -> Dict[str, Any]:
    """One dial → write → read. Raises :class:`TransportFault` on any
    transport-shaped failure, with ``cause`` naming the seam that died:
    ``connect`` (nothing answered the dial), ``read-timeout`` (the
    connection is up but the reply never came — the half-open shape),
    ``eof`` (peer closed mid-request), or the exception class name."""
    sock = dial(address, timeout=connect_deadline)
    if sock is None:
        raise TransportFault(f"dial failed: {describe(address)}",
                             stage="transport", cause="connect")
    try:
        sock.settimeout(read_deadline if read_deadline is not None
                        else read_timeout(connect_timeout()))
        rfile = sock.makefile("r", encoding="utf-8")
        wfile = sock.makefile("w", encoding="utf-8")
        try:
            protocol.write_message(wfile, payload)
            check_read_faults()
            resp = protocol.read_message(rfile)
        except socket.timeout as exc:
            _count_error("read")
            raise TransportFault(
                f"read deadline expired: {describe(address)}",
                stage="transport", cause="read-timeout") from exc
        except (OSError, ValueError, protocol.ProtocolError) as exc:
            _count_error("read")
            raise TransportFault(str(exc), stage="transport",
                                 cause=type(exc).__name__) from exc
    finally:
        with contextlib.suppress(OSError):
            sock.close()
    if resp is None:
        _count_error("read")
        raise TransportFault(f"peer closed: {describe(address)}",
                             stage="transport", cause="eof")
    return resp


def call(address: str, method: str, params: Dict[str, Any], *,
         timeout: Optional[float] = None,
         read_deadline: Optional[float] = None,
         retries: Optional[int] = None) -> Optional[Dict[str, Any]]:
    """Resilient control round-trip: bounded resends with jittered
    exponential backoff (control verbs are idempotent), ``None`` after
    the budget is spent or on a non-result answer."""
    budget = resends() if retries is None else max(0, retries)
    for attempt in range(budget + 1):
        if attempt:
            count_resend()
            time.sleep(backoff_s(attempt - 1))
        try:
            resp = roundtrip(
                address, {"id": 0, "method": method, "params": params},
                connect_deadline=timeout,
                read_deadline=read_deadline if read_deadline is not None
                else timeout)
        except TransportFault:
            _count_error("control")
            continue
        result = resp.get("result")
        return result if isinstance(result, dict) else None
    return None


def heartbeat(address: str,
              timeout: Optional[float] = None) -> Dict[str, Any]:
    """Application-level liveness probe: one ``hello`` round trip under
    the heartbeat deadline. Returns the hello result; raises
    :class:`TransportFault` whose ``cause`` distinguishes a dead member
    (``connect``) from a half-open/partitioned one (``read-timeout`` —
    the dial succeeds upstream of the break, the answer never comes)."""
    t = timeout if timeout is not None else heartbeat_timeout()
    try:
        resp = roundtrip(address,
                         {"id": 0, "method": "hello", "params": {}},
                         connect_deadline=t, read_deadline=t)
    except TransportFault as exc:
        _count_heartbeat("connect" if exc.cause == "connect"
                         else "timeout" if exc.cause == "read-timeout"
                         else "error")
        raise
    result = resp.get("result")
    if not isinstance(result, dict) or not result.get("ok"):
        _count_heartbeat("error")
        raise TransportFault(f"malformed hello from {describe(address)}",
                             stage="transport", cause="handshake")
    _count_heartbeat("ok")
    return result
