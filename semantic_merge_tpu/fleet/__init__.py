"""Fault-tolerant daemon fleet — a routing tier over N merge daemons.

ROADMAP's routing-tier item: one supervised daemon (PR 9) is a single
point of failure and a single queue; the fleet puts a lightweight
router in front of N supervised member daemons with consistent-hash
repo affinity (``hashring``), a durable dispatch WAL (``wal``), and
health-aware failover + hedged reads (``router``).

Postures (``SEMMERGE_FLEET``):

- ``off`` (default) — no fleet anywhere; the client path is
  byte-identical to the single-daemon service stack.
- ``auto`` — the client prefers an already-running fleet router on the
  service socket, and falls back to the plain ``SEMMERGE_DAEMON``
  posture when none is listening. Never worse than fleet-less.
- ``require`` — the client must reach a fleet router; failure is
  :class:`~semantic_merge_tpu.errors.FleetFault` (exit 19).

The package is import-light (stdlib only at import time) — the router
process never imports jax; member daemons carry the heavy runtime.
"""
from __future__ import annotations

from ..utils import reqenv

#: Posture env var (``off`` | ``auto`` | ``require``).
ENV_POSTURE = "SEMMERGE_FLEET"
#: Documented ``FleetFault`` exit code (see ``errors.EXIT_CODES``).
FLEET_EXIT = 19


def mode() -> str:
    """The effective fleet posture (overlay-aware)."""
    return reqenv.posture(ENV_POSTURE, default="off")
