"""The fleet router: N supervised member daemons behind one socket.

The router binds the *standard* service socket (so every existing
client transparently talks to the fleet) and spawns N member daemons
on derived socket paths (``<socket>.m0``, ``<socket>.m1``, …), each
under a per-member respawn policy
(:class:`~semantic_merge_tpu.service.supervisor.MemberSupervisor`).
Like the supervisor, the router process is deliberately boring — no
jax, no engine imports; nothing in it can fail the way a member does.

Request flow::

    client conn thread → WAL journal → rendezvous rank → member dispatch
                                  ↘ (transport failure) failover to next
                                  ↘ (idle, non-inplace) hedge to second

- **Affinity**: requests hash by resolved request cwd
  (:func:`fleet.hashring.repo_key`), so per-repo state — the inplace
  lockfile, decl caches, warm compiled programs — concentrates on one
  member. Failover order and hedge targets come from the same ranking.
- **Membership**: a health thread ticks every member's supervisor,
  probes liveness (the member's loopback ``/healthz`` when its
  ephemeral telemetry port is known, the application-level transport
  heartbeat otherwise), ejects failed or draining members from the
  ring (counting the keys whose owner moved —
  ``fleet_rehash_moves_total``) and re-admits them when they come
  back. Membership is *elastic*: besides the router-spawned local
  members, remote daemons (usually on other hosts, over the
  ``tcp://`` transport — :mod:`fleet.transport`) announce themselves
  with a ``join`` handshake carrying capacity and affinity epoch, are
  probed by the same heartbeats (a half-open link — dial succeeds,
  reads never answer — ejects with reason ``partition``), and depart
  with ``leave``/drain. Every ring change triggers an incremental
  *affinity handoff*: keys whose rendezvous owner moved are prewarmed
  onto the new owner (bounded by ``SEMMERGE_FLEET_HANDOFF_MAX``) so
  post-churn requests land warm — ``fleet_affinity_misses_total``
  over routed requests is the fleetwan bench's rehash miss rate.
- **Durability**: every verb request is journaled to the router's WAL
  before first dispatch and acked after the response is written
  toward the client; a router restart replays unacked entries to
  their rehashed owners. Idempotency keys (router-minted when the
  client sent none) plus the PR 4 inplace journal + repo lockfile
  collapse at-least-once dispatch into exactly-once effects.
- **Hedging**: a non-``--inplace`` request may be hedged to the
  second-ranked member after a p99-derived delay
  (``SEMMERGE_FLEET_HEDGE=off`` disables); first response wins and
  the loser's connection is closed.

Typed wire errors from a member (``exit_code`` present) pass through
to the client unchanged — the member is the authority on
request-shaped failures; the router only converts *transport* loss
into failover. A router drain (SIGTERM or the ``drain`` control verb)
closes admission with retryable ``FleetFault`` rejections
(``retry_after_ms`` attached), finishes in-flight dispatches, then
SIGTERMs the members so they drain too.
"""
from __future__ import annotations

import contextlib
import json
import os
import queue
import signal
import socket
import sys
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..errors import (FleetFault, MergeFault, TransportFault,
                      fault_boundary)
from ..obs import agg as obs_agg
from ..obs import export as obs_export
from ..obs import flight as obs_flight
from ..obs import metrics as obs_metrics
from ..obs import sampling as obs_sampling
from ..obs import slo as obs_slo
from ..obs import spans as obs_spans
from ..service import protocol, telemetry
from ..service.supervisor import MemberSupervisor
from ..utils import faults
from ..utils.loggingx import logger
from ..utils.procs import env_seconds
from . import hashring, transport, wal as fleet_wal

_MEMBERS_HELP = "Fleet members currently in the routing ring"
_FAILOVERS_HELP = "Fleet failovers (member ejections/re-dispatches), by reason"
_REHASH_HELP = "Repo keys whose owner moved on a membership change"
_HEDGES_HELP = "Hedged dispatches issued for slow primaries"
_HEDGE_WINS_HELP = "Hedged dispatches where the hedge answered first"
_REPLAY_HELP = "WAL entries replayed after a router restart"
_HANDOFFS_HELP = "Affinity handoffs (prewarms of moved keys), by reason"
_MISSES_HELP = "Routed requests that landed on a cold (non-warm) member"
_JOINS_HELP = "Member join handshakes accepted"
_DRAINING_HELP = ("Members alive but draining (1=draining) — "
                  "deliberate departures, not failures")

#: Health-probe failures before a member is ejected from the ring.
_EJECT_AFTER = 3


def _label_member(exposition: str, member: str) -> str:
    """Inject ``member="<id>"`` into every sample line of a Prometheus
    text exposition (comments pass through; lines that already carry a
    ``member`` label — the fleet rollups — are left alone)."""
    out = []
    for line in exposition.splitlines():
        if not line or line.startswith("#") or 'member="' in line:
            out.append(line)
            continue
        brace = line.rfind("}")
        if brace != -1 and "{" in line:
            out.append(f'{line[:brace]},member="{member}"{line[brace:]}')
        else:
            space = line.find(" ")
            if space == -1:
                out.append(line)
            else:
                out.append(f'{line[:space]}{{member="{member}"}}'
                           f'{line[space:]}')
    return "\n".join(out)


def _dedupe_comments(exposition: str) -> str:
    """Drop repeated ``# HELP``/``# TYPE`` lines — concatenating N
    member scrapes repeats them, and strict parsers reject that."""
    seen: set = set()
    out = []
    for line in exposition.splitlines():
        if line.startswith("#"):
            if line in seen:
                continue
            seen.add(line)
        out.append(line)
    return "\n".join(out)


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


class _MemberTransport(Exception):
    """A member connection died mid-request (crash, SIGKILL, garbage,
    partition) — the failover trigger, never surfaced to the client
    directly. ``reason`` feeds the failover counter/span:
    ``transport`` for connect-shaped loss, ``partition`` when the
    connection was up but the read deadline expired (half-open)."""

    def __init__(self, message: str, reason: str = "transport") -> None:
        super().__init__(message)
        self.reason = reason


class _Member:
    """Router-side view of one member daemon — a local child under a
    :class:`MemberSupervisor`, or a remote (``sup=None``) daemon that
    announced itself over the transport and is supervised elsewhere."""

    def __init__(self, member_id: str, address: str,
                 sup: Optional[MemberSupervisor] = None,
                 capacity: int = 1, epoch: int = 0) -> None:
        self.id = member_id
        self.address = address
        self.sup = sup
        self.remote = sup is None
        self.in_ring = False
        self.draining = False
        # When the router last *initiated* a drain of this member —
        # health probes that started before this instant carry a
        # pre-drain heartbeat and must not flip the member back.
        self.drain_ts = 0.0
        self.dead = False
        self.fail_streak = 0
        self.last_fault: Optional[str] = None
        self.capacity = capacity
        self.epoch = epoch
        self.metrics_port: Optional[int] = None
        self.dispatches = 0

    @property
    def socket_path(self) -> str:
        return self.address

    def state(self) -> str:
        """``ready`` (serving, in ring), ``draining`` (alive but
        refusing new work — NOT a failure), ``dead`` (crashed, ejected,
        or partitioned), or ``starting`` (known, not yet admitted)."""
        if self.dead:
            return "dead"
        if self.draining:
            return "draining"
        if self.in_ring:
            return "ready"
        return "starting"

    def view(self) -> Dict[str, Any]:
        return {"id": self.id, "socket": self.address,
                "pid": self.sup.pid if self.sup is not None else None,
                "in_ring": self.in_ring,
                "draining": self.draining,
                "state": self.state(),
                "remote": self.remote,
                "capacity": self.capacity,
                "epoch": self.epoch,
                "last_fault": self.last_fault,
                "restarts": self.sup.restarts if self.sup is not None
                else None,
                "last_rc": self.sup.last_rc if self.sup is not None
                else None,
                "metrics_port": self.metrics_port,
                "dispatches": self.dispatches}


class FleetRouter:
    """One ``semmerge fleet`` process. Construct, then
    :meth:`serve_forever`."""

    def __init__(self, socket_path: Optional[str] = None,
                 members: Optional[int] = None,
                 workers: Optional[int] = None,
                 queue_size: Optional[int] = None,
                 wal_dir: Optional[str] = None) -> None:
        self._socket_path = protocol.socket_path(socket_path)
        n = members if members is not None else \
            _env_int("SEMMERGE_FLEET_MEMBERS", 3)
        # 0 local members is a pure-remote fleet: every member arrives
        # over the transport with a join handshake.
        self._n = max(0, n)
        self._workers = workers
        self._queue_size = queue_size
        self._wal = fleet_wal.WriteAheadLog(
            wal_dir or os.environ.get("SEMMERGE_FLEET_WAL_DIR", "").strip()
            or fleet_wal.default_dir(self._socket_path))
        self._members: List[_Member] = []
        self._ring_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._in_flight = 0
        self._served = 0
        self._replayed = 0
        self._stop = threading.Event()
        self._draining = False
        self._t0 = time.time()
        self._seen_keys: "deque[str]" = deque(maxlen=1024)
        self._seen_set: set = set()
        self._latencies: "deque[float]" = deque(maxlen=256)
        self._hedge_on = os.environ.get(
            "SEMMERGE_FLEET_HEDGE", "").strip().lower() not in (
                "off", "0", "no", "false")
        self._hedge_default_ms = _env_int("SEMMERGE_FLEET_HEDGE_MS", 250)
        self._hedge_min_ms = _env_int("SEMMERGE_FLEET_HEDGE_MIN_MS", 50)
        self._hedge_cap_ms = _env_int("SEMMERGE_FLEET_HEDGE_CAP_MS", 2000)
        self._ready_timeout = env_seconds("SEMMERGE_FLEET_READY_TIMEOUT",
                                          60.0)
        self._health_interval = env_seconds(
            "SEMMERGE_FLEET_HEALTH_INTERVAL", 0.5)
        self._request_timeout = env_seconds("SEMMERGE_FLEET_TIMEOUT", 600.0)
        # Cross-host transport knobs (fleet/transport.py): per-call
        # connect deadline, bounded idempotency-keyed resends, and the
        # application-level heartbeat deadline that declares half-open
        # connections dead.
        self._connect_timeout = transport.connect_timeout()
        self._resends = transport.resends()
        self._heartbeat_timeout = transport.heartbeat_timeout()
        self._handoff_max = _env_int("SEMMERGE_FLEET_HANDOFF_MAX", 256)
        # Warm-affinity tracking: key → member ids that have served it.
        # A dispatch to a non-warm member is an affinity miss; ring
        # changes hand moved keys off to their new owners (prewarm).
        self._warm: Dict[str, set] = {}
        self._affinity_epoch = 0
        self._remote_seq = 0
        self._telemetry: Optional[telemetry.TelemetryServer] = None
        # Trace stitching: one router-side recorder per request grafts
        # the router's own fleet spans together with the span trees the
        # members ship back (SEMMERGE_FLEET_STITCH=off goes dark — the
        # tracecost bench's control arm).
        self._stitch = os.environ.get(
            "SEMMERGE_FLEET_STITCH", "on").strip().lower() != "off"
        self._trace_dir = os.environ.get(
            "SEMMERGE_FLEET_TRACE_DIR", "").strip() or None
        # PR 20: the trace dir is a byte-budgeted rotating store
        # (SEMMERGE_TRACE_BUDGET_MB / SEMMERGE_TRACE_KEEP) instead of
        # append-forever; the router mints/merges one sampling verdict
        # per trace (member decisions arrive in wire meta and can only
        # be upgraded here) and keeps 1 s/1 m routed-latency rollups.
        self._trace_store = (obs_sampling.TraceStore(self._trace_dir)
                             if self._trace_dir else None)
        self._sampler = obs_sampling.SamplingPolicy(minted_by="router")
        self._window = obs_agg.WindowAggregator()
        # Sealing a stitched trace (artifact write + OTLP serialize)
        # happens off the response path: requests hand their recorder
        # to a bounded background queue; a full queue drops the trace
        # (counted) rather than stall the reply.
        self._trace_q: "queue.Queue[Optional[Tuple[str, Any]]]" = \
            queue.Queue(maxsize=256)
        self._sealer: Optional[threading.Thread] = None
        if self._stitch:
            self._sealer = threading.Thread(target=self._trace_sealer,
                                            daemon=True,
                                            name="fleet-trace-sealer")
            self._sealer.start()
        # Router-level SLOs: same engine/knobs as the member daemons,
        # observed over routed (end-to-end) latencies.
        self._slo = obs_slo.from_env()

    # ------------------------------------------------------------------
    # lifecycle

    def member_argv(self, member_sock: str) -> List[str]:
        argv = [sys.executable, "-m", "semantic_merge_tpu", "serve",
                "--socket", member_sock, "--idle-exit", "0"]
        if self._workers is not None:
            argv += ["--workers", str(self._workers)]
        if self._queue_size is not None:
            argv += ["--queue", str(self._queue_size)]
        return argv

    def _member_env(self, member_id: str) -> Dict[str, str]:
        env = dict(os.environ)
        # Members are plain daemons: no fleet recursion, no inherited
        # fault injection (requests carry their own overlay), and an
        # ephemeral loopback telemetry port so the router can probe
        # /healthz without port bookkeeping.
        env["SEMMERGE_FLEET"] = "off"
        env["SEMMERGE_FLEET_MEMBER"] = member_id
        env["SEMMERGE_METRICS_PORT"] = "0"
        env.pop("SEMMERGE_FAULT", None)
        env.pop("SEMMERGE_METRICS", None)
        env.pop("SEMMERGE_SERVICE_SOCKET", None)
        return env

    def _member_socket(self, member_id: str) -> str:
        """Local members always speak AF_UNIX; when the router itself
        binds ``tcp://`` their sockets derive from the WAL directory
        instead of the (meaningless as a path) router address."""
        if transport.is_tcp(self._socket_path):
            return os.path.join(self._wal.directory, f"{member_id}.sock")
        return f"{self._socket_path}.{member_id}"

    def _bind(self) -> Optional[socket.socket]:
        path = self._socket_path
        if transport.is_tcp(path):
            try:
                return transport.listen(path)
            except OSError:
                probe = transport.dial(path, timeout=2.0)
                if probe is not None:
                    with contextlib.suppress(OSError):
                        probe.close()
                    return None
                raise
        if os.path.exists(path):
            probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            probe.settimeout(2.0)
            try:
                probe.connect(path)
            except OSError:
                logger.warning("replacing stale fleet socket %s", path)
                with contextlib.suppress(OSError):
                    os.unlink(path)
            else:
                probe.close()
                return None
            finally:
                with contextlib.suppress(OSError):
                    probe.close()
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.bind(path)
        with contextlib.suppress(OSError):
            os.chmod(path, 0o600)
        sock.listen(128)
        return sock

    def serve_forever(self) -> int:
        sock = self._bind()
        if sock is None:
            print(f"semmerge fleet: something already listening on "
                  f"{self._socket_path}")
            return 0
        try:
            signal.signal(signal.SIGTERM, self._on_signal)
            signal.signal(signal.SIGINT, self._on_signal)
        except ValueError:
            pass  # not the main thread (test embedding)
        pending = self._wal.open()
        for i in range(self._n):
            self._reclaim_orphan(self._member_socket(f"m{i}"))
        for i in range(self._n):
            member_id = f"m{i}"
            member_sock = self._member_socket(member_id)
            sup = MemberSupervisor(member_id,
                                   self.member_argv(member_sock),
                                   env=self._member_env(member_id))
            self._members.append(_Member(member_id, member_sock, sup))
        threading.Thread(target=self._health_loop, daemon=True,
                         name="fleet-health").start()
        if pending:
            threading.Thread(target=self._replay, args=(pending,),
                             daemon=True, name="fleet-replay").start()
        obs_metrics.REGISTRY.gauge("fleet_members", _MEMBERS_HELP).set(0)
        self._telemetry = telemetry.maybe_start(self.status,
                                                self._federated_metrics)
        if self._telemetry is not None:
            logger.info("fleet telemetry on 127.0.0.1:%d",
                        self._telemetry.port)
        logger.info("fleet router listening on %s (%d members, wal %s)",
                    self._socket_path, self._n, self._wal.directory)
        sock.settimeout(0.5)
        try:
            while not self._stop.is_set():
                try:
                    conn, _addr = sock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                threading.Thread(target=self._handle_conn, args=(conn,),
                                 daemon=True).start()
        finally:
            self._teardown(sock)
        return 0

    def _reclaim_orphan(self, path: str) -> None:
        """Shut down a member left behind by a previous incarnation.

        A SIGKILLed router orphans its member daemons; they keep their
        sockets, so this incarnation's children would lose the bind
        race forever (a daemon spawned onto a live socket exits
        "already listening"). Members are stateless — the WAL and the
        idempotency layers own the durable story — so the clean
        reclaim is to shut the orphan down and let the fresh
        supervisor respawn onto the path.
        """
        if not os.path.exists(path):
            return
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(2.0)
        try:
            s.connect(path)
        except OSError:
            with contextlib.suppress(OSError):
                s.close()
            with contextlib.suppress(OSError):
                os.unlink(path)  # dead member's leftover
            return
        try:
            rfile = s.makefile("r", encoding="utf-8")
            wfile = s.makefile("w", encoding="utf-8")
            protocol.write_message(wfile, {"id": 0, "method": "shutdown",
                                           "params": {}})
            protocol.read_message(rfile)
        except (OSError, ValueError, protocol.ProtocolError):
            pass
        finally:
            with contextlib.suppress(OSError):
                s.close()
        logger.warning("reclaiming orphaned fleet member on %s", path)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and os.path.exists(path):
            time.sleep(0.1)  # the daemon unlinks its socket on exit

    def _on_signal(self, signum, frame) -> None:
        logger.info("fleet signal %d: draining", signum)
        self._draining = True
        self._stop.set()

    def _teardown(self, sock: socket.socket) -> None:
        self._draining = True
        with contextlib.suppress(OSError):
            sock.close()
        if not transport.is_tcp(self._socket_path):
            with contextlib.suppress(OSError):
                os.unlink(self._socket_path)
        drain = env_seconds("SEMMERGE_SERVICE_DRAIN_TIMEOUT", 30.0)
        deadline = time.monotonic() + drain if drain > 0 else None
        while True:
            with self._state_lock:
                busy = self._in_flight > 0
            if not busy:
                break
            if deadline is not None and time.monotonic() > deadline:
                logger.warning("fleet drain timeout: abandoning dispatches")
                break
            time.sleep(0.05)
        for m in self._members:
            if m.sup is not None:
                m.sup.terminate()
        child_deadline = time.monotonic() + (drain if drain > 0 else 30.0)
        for m in self._members:
            proc = m.sup.proc if m.sup is not None else None
            if proc is None:
                continue
            remain = child_deadline - time.monotonic()
            try:
                proc.wait(timeout=max(0.1, remain))
            except Exception:
                m.sup.kill()
                with contextlib.suppress(Exception):
                    proc.wait(timeout=5)
        if self._sealer is not None:
            # Flush queued traces (FIFO ahead of the sentinel), then
            # give the OTLP exporter its drain window.
            with contextlib.suppress(queue.Full):
                self._trace_q.put_nowait(None)
            self._sealer.join(timeout=10.0)
            exporter = obs_export.maybe_exporter()
            if exporter is not None:
                exporter.close()
        self._wal.close()
        if self._telemetry is not None:
            self._telemetry.stop()
        metrics_path = os.environ.get("SEMMERGE_METRICS")
        if metrics_path:
            with contextlib.suppress(OSError):
                obs_metrics.dump(metrics_path)
        if os.environ.get(obs_flight.ENV_DIR):
            obs_flight.dump(None, "daemon-drain")
        logger.info("fleet router stopped (%d requests routed)",
                    self._served)

    # ------------------------------------------------------------------
    # membership / health

    def _ring(self) -> List[str]:
        with self._ring_lock:
            return [m.id for m in self._members if m.in_ring]

    def _member_by_id(self, member_id: str) -> Optional[_Member]:
        for m in self._members:
            if m.id == member_id:
                return m
        return None

    def _set_ring(self, member: _Member, up: bool, reason: str) -> None:
        with self._ring_lock:
            if member.in_ring == up:
                return
            before = [m.id for m in self._members if m.in_ring]
            member.in_ring = up
            after = [m.id for m in self._members if m.in_ring]
            seen = list(self._seen_set)
            self._affinity_epoch += 1
            if not up:
                # The member's warm state is suspect the moment it
                # leaves the ring (a crash respawns it cold); rejoin
                # re-warms through dispatches and handoffs.
                for warm in self._warm.values():
                    warm.discard(member.id)
        moved = hashring.moved_keys(seen, before, after)
        gauge = obs_metrics.REGISTRY.gauge("fleet_members", _MEMBERS_HELP)
        gauge.set(len(after))
        if moved:
            obs_metrics.REGISTRY.counter(
                "fleet_rehash_moves_total", _REHASH_HELP).inc(len(moved))
        if not up:
            obs_metrics.REGISTRY.counter(
                "fleet_failovers_total", _FAILOVERS_HELP).inc(
                    1, reason=reason)
            obs_spans.record("fleet.failover", 0.0, layer="fleet",
                             reason=reason, member=member.id)
            if moved:
                # Keys moved owners: any resident encoded snapshot this
                # process holds (co-located router+member deployments,
                # in-process test fleets) may now belong to a repo it no
                # longer serves authoritatively — invalidate them all
                # (lazy stale-epoch eviction on next lookup) so rehashed
                # owners re-encode from the repository of record.
                from ..service import residency
                residency.cache().bump_epoch()
            obs_flight.dump(
                None, "fleet-failover",
                extra={"fleet": {"member": member.id, "reason": reason,
                                 "ring": after,
                                 "rehash_moves": len(moved)}})
            logger.warning("fleet member %s ejected (%s); ring=%s, "
                           "%d keys rehashed", member.id, reason, after,
                           len(moved))
        else:
            logger.info("fleet member %s joined; ring=%s", member.id,
                        after)
        if moved and after and not self._draining:
            # Incremental affinity handoff, off the caller's path: the
            # moved keys' new owners get prewarmed so post-churn
            # requests land warm instead of cold.
            threading.Thread(
                target=self._handoff,
                args=(sorted(moved), list(after),
                      "join" if up else reason),
                daemon=True, name="fleet-handoff").start()

    def _handoff(self, moved: List[str], ring: List[str],
                 reason: str) -> None:
        """Prewarm each moved key onto its new rendezvous owner
        (bounded by ``SEMMERGE_FLEET_HANDOFF_MAX``) — the incremental
        rebalance that drives the post-churn rehash miss rate under
        the fleetwan gate instead of letting every moved key fault in
        cold."""
        for key in moved[:self._handoff_max]:
            if self._stop.is_set() or self._draining:
                return
            owner_id = hashring.owner(key, ring)
            owner = self._member_by_id(owner_id) if owner_id else None
            if owner is None or not owner.in_ring:
                continue
            with self._ring_lock:
                if owner.id in self._warm.get(key, set()):
                    continue
            t0 = time.perf_counter()
            result = self._member_call(owner, "prewarm", {"cwd": key},
                                       timeout=10.0)
            ok = bool(result and result.get("ok"))
            if ok:
                with self._ring_lock:
                    self._warm.setdefault(key, set()).add(owner.id)
            obs_metrics.REGISTRY.counter(
                "fleet_handoffs_total", _HANDOFFS_HELP).inc(
                    1, reason=reason)
            obs_spans.record("fleet.handoff", time.perf_counter() - t0,
                             layer="fleet", member=owner.id,
                             reason=reason, ok=ok)

    def _probe(self, member: _Member) -> Tuple[bool, bool]:
        """(alive, draining) — /healthz over the member's loopback
        telemetry port when known, the application-level transport
        heartbeat otherwise. A degraded (503) health answer is still
        *alive*: SLO burn is not a membership event. A heartbeat
        failure stamps ``member.last_fault`` so the eject can
        distinguish a dead member (``connect``) from a partitioned
        half-open one (``read-timeout``)."""
        if member.metrics_port:
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{member.metrics_port}/healthz")
                with urllib.request.urlopen(req, timeout=2.0) as resp:
                    body = json.loads(resp.read().decode("utf-8"))
                return True, bool(body.get("draining"))
            except urllib.error.HTTPError as exc:
                if exc.code == 503:  # degraded-but-serving
                    return True, False
                member.metrics_port = None
            except Exception:
                member.metrics_port = None  # port gone: re-discover
        t0 = time.perf_counter()
        try:
            hello = transport.heartbeat(member.address,
                                        timeout=self._heartbeat_timeout)
        except TransportFault as exc:
            member.last_fault = str(exc.cause or "connect")
            obs_spans.record(
                "fleet.heartbeat", time.perf_counter() - t0,
                layer="fleet", member=member.id,
                outcome="timeout" if exc.cause == "read-timeout"
                else "connect" if exc.cause == "connect" else "error")
            return False, False
        if member.last_fault is not None:
            member.last_fault = None
            obs_spans.record("fleet.heartbeat",
                             time.perf_counter() - t0, layer="fleet",
                             member=member.id, outcome="ok")
        return True, bool(hello.get("draining"))

    def _discover_port(self, member: _Member) -> None:
        status = self._member_call(member, "status", {}, timeout=5.0)
        if status and isinstance(status.get("metrics_port"), int):
            member.metrics_port = status["metrics_port"]

    def _member_call(self, member: _Member, method: str,
                     params: Dict[str, Any],
                     timeout: float) -> Optional[Dict[str, Any]]:
        """One control round-trip to a member over the transport
        (bounded jittered resends inside); ``None`` on any failure."""
        return transport.call(member.address, method, params,
                              timeout=min(timeout,
                                          self._connect_timeout),
                              read_deadline=timeout)

    def _health_loop(self) -> None:
        metrics_interval = env_seconds("SEMMERGE_OTLP_METRICS_INTERVAL",
                                       10.0)
        last_export = time.monotonic()
        while not self._stop.wait(self._health_interval):
            exporter = obs_export.maybe_exporter()
            if exporter is not None and \
                    time.monotonic() - last_export >= metrics_interval:
                last_export = time.monotonic()
                exporter.export_metrics(obs_metrics.REGISTRY.to_dict())
            if self._slo is not None:
                try:
                    verdict = self._slo.evaluate(consume_edges=True)
                except Exception:
                    verdict = {}
                for r in verdict.get("newly_tripped") or []:
                    logger.warning(
                        "fleet SLO burn: %s (fast %sx, slow %sx)",
                        r.get("objective"), r.get("burn_fast"),
                        r.get("burn_slow"))
            for member in list(self._members):
                if self._draining:
                    return
                if member.sup is not None:
                    event = member.sup.ensure()
                    if event == "died":
                        member.metrics_port = None
                        member.fail_streak = 0
                        member.dead = True
                        self._set_ring(member, False, "crash")
                        continue
                    if event == "spawned":
                        member.fail_streak = 0
                        continue
                    if not member.sup.running():
                        continue
                t_probe = time.monotonic()
                alive, draining = self._probe(member)
                if alive:
                    member.fail_streak = 0
                    member.dead = False
                    if member.metrics_port is None:
                        self._discover_port(member)
                    if draining:
                        member.draining = True
                        self._set_ring(member, False, "drain")
                    elif t_probe > member.drain_ts:
                        # A probe that began before the drain verb ran
                        # read a pre-drain heartbeat; acting on it
                        # would undo a deliberate drain. The next tick
                        # sees the member's real (draining) answer.
                        member.draining = False
                        self._set_ring(member, True, "join")
                else:
                    member.fail_streak += 1
                    if member.fail_streak >= _EJECT_AFTER:
                        member.dead = True
                        if member.in_ring:
                            # A half-open link (dial ok, reads dead) is
                            # a partition; a refused dial is a death.
                            self._set_ring(
                                member, False,
                                "partition"
                                if member.last_fault == "read-timeout"
                                else "health")

    def _await_ring(self, timeout: float) -> List[str]:
        deadline = time.monotonic() + timeout
        while True:
            ring = self._ring()
            if ring or time.monotonic() > deadline or self._stop.is_set():
                return ring
            time.sleep(0.05)

    # ------------------------------------------------------------------
    # connection handling

    def _handle_conn(self, conn: socket.socket) -> None:
        rfile = conn.makefile("r", encoding="utf-8")
        wfile = conn.makefile("w", encoding="utf-8")
        try:
            while True:
                msg = protocol.read_message(rfile)
                if msg is None:
                    break
                req_id = msg.get("id")
                method = msg.get("method")
                params = msg.get("params") or {}
                if method == "hello":
                    protocol.write_message(wfile, {
                        "id": req_id,
                        "result": {"ok": True, "pid": os.getpid(),
                                   "version": protocol.PROTOCOL_VERSION,
                                   "fleet": True,
                                   "members_up": len(self._ring()),
                                   "draining": self._draining}})
                    continue
                if method == "status":
                    protocol.write_message(wfile, {"id": req_id,
                                                   "result": self.status()})
                    continue
                if method == "metrics":
                    protocol.write_message(wfile, {
                        "id": req_id,
                        "result": {
                            "prometheus": self._federated_metrics(),
                            "metrics": obs_metrics.REGISTRY.to_dict(),
                            "health": self.status(),
                            "federated": True,
                        }})
                    continue
                if method == "member_status":
                    # The fleet aggregation surface behind `semmerge
                    # stats --fleet` / `serve --status --fleet`: router
                    # status plus every member's own status block, one
                    # round-trip, no per-member socket bookkeeping.
                    protocol.write_message(wfile, {
                        "id": req_id,
                        "result": {
                            "router": self.status(),
                            "members": {
                                m.id: self._member_status_block(m)
                                for m in list(self._members)},
                        }})
                    continue
                if method == "join":
                    protocol.write_message(wfile, {
                        "id": req_id,
                        "result": self._join_verb(params)})
                    continue
                if method == "leave":
                    protocol.write_message(wfile, {
                        "id": req_id,
                        "result": self._leave_verb(params)})
                    continue
                if method == "drain":
                    protocol.write_message(wfile, {
                        "id": req_id,
                        "result": self._drain_verb(params)})
                    continue
                if method == "shutdown":
                    protocol.write_message(wfile, {"id": req_id,
                                                   "result": {"ok": True}})
                    self._draining = True
                    self._stop.set()
                    break
                if method == "profile":
                    # Profiling is member work: forward to the first
                    # ring member (traffic flows through all of them).
                    ring = self._ring()
                    target = self._member_by_id(ring[0]) if ring else None
                    result = (self._member_call(target, "profile", params,
                                                timeout=120.0)
                              if target is not None else None)
                    protocol.write_message(wfile, {
                        "id": req_id,
                        "result": result or
                        {"ok": False, "error": "no fleet member available"}})
                    continue
                if method not in protocol.VERBS:
                    protocol.write_message(wfile, {
                        "id": req_id,
                        "error": {"message": f"unknown method {method!r}"}})
                    continue
                self._serve_verb(req_id, method, params, wfile)
        except (protocol.ProtocolError, OSError, ValueError):
            pass  # client went away or spoke garbage
        finally:
            with contextlib.suppress(OSError):
                conn.close()

    def _serve_verb(self, req_id, method: str, params: Dict[str, Any],
                    wfile) -> None:
        if self._draining:
            fault = FleetFault("fleet router is draining",
                               stage="fleet:route", cause="draining")
            protocol.write_message(wfile, {
                "id": req_id,
                "error": protocol.fault_error(fault, retry_after_ms=500)})
            return
        with self._state_lock:
            self._in_flight += 1
        try:
            response = self._dispatch(method, dict(params))
        except MergeFault as fault:
            response = {"error": protocol.fault_error(
                fault, trace_id=params.get("trace_id"))}
        finally:
            with self._state_lock:
                self._in_flight -= 1
                self._served += 1
        response["id"] = req_id
        protocol.write_message(wfile, response)

    # ------------------------------------------------------------------
    # dispatch: WAL → route → failover/hedge

    def _dispatch(self, method: str,
                  params: Dict[str, Any]) -> Dict[str, Any]:
        # The router mints missing idempotency/trace ids: the WAL entry
        # and every retried dispatch must share one key for the member
        # idempotency cache (and inplace journal) to collapse replays.
        idem = str(params.get("idempotency_key") or os.urandom(16).hex())
        params["idempotency_key"] = idem
        trace_id = str(params.get("trace_id") or os.urandom(8).hex())
        params["trace_id"] = trace_id
        key = hashring.repo_key(str(params.get("cwd") or "/"))
        with self._ring_lock:
            if key not in self._seen_set:
                if len(self._seen_keys) == self._seen_keys.maxlen:
                    evicted = self._seen_keys[0]
                    self._seen_set.discard(evicted)
                    self._warm.pop(evicted, None)
                self._seen_keys.append(key)
                self._seen_set.add(key)
        rec = obs_spans.SpanRecorder(detailed=False) if self._stitch \
            else None
        t_dispatch = time.monotonic()
        with obs_spans.request_scope(trace_id, rec):
            with fault_boundary("fleet:route"):
                faults.check("fleet:route")
                t0 = time.perf_counter()
                self._wal.record_request(idem, method, params, trace_id)
                obs_spans.record("fleet.wal_fsync",
                                 time.perf_counter() - t0, layer="fleet",
                                 t_start=t0)
                response = self._route(method, params, key, idem, rec)
        self._wal.ack(idem)
        if rec is not None:
            decision = self._mint_sampling(
                trace_id, method, response, rec,
                time.monotonic() - t_dispatch)
            try:
                self._trace_q.put_nowait((trace_id, rec, decision))
            except queue.Full:
                obs_metrics.REGISTRY.counter(
                    "fleet_trace_dropped_total",
                    "Stitched traces dropped on a full sealer queue."
                ).inc(1)
        return response

    def _mint_sampling(self, trace_id: str, method: str,
                       response: Dict[str, Any],
                       rec: obs_spans.SpanRecorder,
                       elapsed: float) -> obs_sampling.Decision:
        """Settle the trace's final keep/drop verdict. The winning
        member minted one at its own terminal outcome and shipped it in
        wire ``meta``; the router adds the criteria only it can see
        (end-to-end latency against its rolling p99, failovers,
        transport errors) and may *upgrade* drop→keep — never the
        reverse — so every process agrees about this trace id."""
        result = response.get("result") \
            if isinstance(response, dict) else None
        meta = result.get("meta") if isinstance(result, dict) else None
        member_dec = obs_sampling.Decision.from_meta(
            meta.get(obs_sampling.META_KEY)) \
            if isinstance(meta, dict) else None
        rows = rec.span_dicts()
        flags = obs_sampling.outcome_flags(rows)
        error = flags["error"] or not isinstance(result, dict)
        failover = any(r.get("name") == "fleet.failover" for r in rows)
        local = self._sampler.decide(
            trace_id, method, elapsed, error=error,
            degraded=flags["degraded"],
            breaker=flags["breaker"] or failover,
            resolver=flags["resolver"])
        final = member_dec.upgrade(local) if member_dec is not None \
            else local
        if isinstance(meta, dict):
            meta[obs_sampling.META_KEY] = final.to_meta()
        self._window.observe(method, elapsed, error=error)
        return final

    def _route(self, method: str, params: Dict[str, Any], key: str,
               idem: str,
               rec: Optional[obs_spans.SpanRecorder] = None
               ) -> Dict[str, Any]:
        """Rank → dispatch → failover until a member answers."""
        hedge_ok = self._hedge_on and "--inplace" not in (
            params.get("argv") or [])
        tried: set = set()
        attempts = 0
        max_attempts = max(2 * self._n, 4)
        while True:
            ring = self._ring() or self._await_ring(self._ready_timeout)
            candidates = [m for m in hashring.rank(key, ring)
                          if m not in tried] or hashring.rank(key, ring)
            if not candidates:
                raise FleetFault(
                    "no fleet member available for dispatch",
                    stage="fleet:route", cause="no-members")
            target = self._member_by_id(candidates[0])
            hedge_target = (self._member_by_id(candidates[1])
                            if hedge_ok and len(candidates) > 1 else None)
            t0 = time.monotonic()
            t0_pc = time.perf_counter()
            try:
                response, winner, hedged_won = self._send(
                    target, hedge_target, method, params, rec,
                    attempts + 1)
            except _MemberTransport as dead:
                attempts += 1
                tried.add(target.id)
                target.dead = True
                self._set_ring(target, False, dead.reason)
                obs_metrics.REGISTRY.counter(
                    "fleet_failovers_total", _FAILOVERS_HELP).inc(
                        1, reason=dead.reason)
                obs_spans.record("fleet.failover",
                                 time.monotonic() - t0, layer="fleet",
                                 t_start=t0_pc, reason=dead.reason,
                                 member=target.id, attempt=attempts)
                if attempts >= max_attempts:
                    raise FleetFault(
                        f"dispatch failed on {attempts} members",
                        stage="fleet:failover", cause=dead.reason)
                continue
            dt = time.monotonic() - t0
            self._latencies.append(dt)
            winner.dispatches += 1
            with self._ring_lock:
                warm = self._warm.setdefault(key, set())
                cold = winner.id not in warm
                warm.add(winner.id)
            if cold:
                obs_metrics.REGISTRY.counter(
                    "fleet_affinity_misses_total", _MISSES_HELP).inc(1)
            obs_spans.record("fleet.route", dt, layer="fleet",
                             t_start=t0_pc, verb=method, member=winner.id,
                             attempt=attempts + 1)
            if hedged_won:
                obs_metrics.REGISTRY.counter(
                    "fleet_hedge_wins_total", _HEDGE_WINS_HELP).inc(1)
            if self._slo is not None:
                self._slo.observe(method, dt,
                                  error="error" in response)
            return response

    def _hedge_delay_s(self) -> float:
        lat = sorted(self._latencies)
        if len(lat) >= 20:
            p99 = lat[min(len(lat) - 1, int(0.99 * (len(lat) - 1)))]
            ms = p99 * 1000.0
        else:
            ms = float(self._hedge_default_ms)
        return min(max(ms, float(self._hedge_min_ms)),
                   float(self._hedge_cap_ms)) / 1000.0

    def _send(self, target: _Member, hedge_target: Optional[_Member],
              method: str, params: Dict[str, Any],
              rec: Optional[obs_spans.SpanRecorder] = None,
              attempt: int = 1,
              ) -> Tuple[Dict[str, Any], _Member, bool]:
        """Dispatch to ``target``, optionally hedging to
        ``hedge_target`` after the p99-derived delay. Returns
        ``(response, winning member, hedge_won)``; raises
        :class:`_MemberTransport` only when every attempted leg died.

        When ``rec`` is set (stitching on), each leg records a
        ``fleet.relay`` span directly into it (``record_into`` — leg
        threads don't inherit the request scope) and the *winning* leg
        grafts the member-shipped span tree (``result.meta.spans``)
        under its relay anchor before releasing the dispatch — so the
        stitched tree is complete the moment ``done`` fires."""
        self._wal.record_dispatch(params["idempotency_key"], target.id)
        box: Dict[str, Any] = {}
        done = threading.Event()
        lock = threading.Lock()
        conns: Dict[str, socket.socket] = {}

        def leg(member: _Member, is_hedge: bool) -> None:
            t0 = time.perf_counter()
            try:
                resp = self._member_verb(member, method, params, conns,
                                         abandoned=done.is_set)
            except _MemberTransport:
                if rec is not None:
                    obs_spans.record_into(
                        rec, "fleet.relay", time.perf_counter() - t0,
                        t_start=t0, layer="fleet", member=member.id,
                        attempt=attempt, outcome="transport")
                with lock:
                    box.setdefault("dead", []).append(member.id)
                    if len(box.get("dead", [])) >= legs_total[0]:
                        done.set()
                return
            dt = time.perf_counter() - t0
            with lock:
                won = "resp" not in box
                if won:
                    box["resp"] = (resp, member, is_hedge)
            if rec is not None:
                obs_spans.record_into(
                    rec, "fleet.relay", dt, t_start=t0, layer="fleet",
                    member=member.id, attempt=attempt,
                    outcome="ok" if won else "late")
                if won:
                    self._graft_member_spans(rec, resp, member, attempt,
                                             t0)
            if won:
                done.set()

        legs_total = [1]
        threading.Thread(target=leg, args=(target, False),
                         daemon=True).start()
        hedge_launched = False
        if hedge_target is not None:
            t_hw = time.perf_counter()
            primary_done = done.wait(self._hedge_delay_s())
            if rec is not None:
                obs_spans.record_into(
                    rec, "fleet.hedge_wait",
                    time.perf_counter() - t_hw, t_start=t_hw,
                    layer="fleet")
            if not primary_done:
                with lock:
                    launch_hedge = "resp" not in box and \
                        len(box.get("dead", [])) == 0
                if launch_hedge:
                    hedge_launched = True
                    legs_total[0] = 2
                    obs_metrics.REGISTRY.counter(
                        "fleet_hedges_total", _HEDGES_HELP).inc(1)
                    self._wal.record_dispatch(params["idempotency_key"],
                                              hedge_target.id)
                    threading.Thread(target=leg,
                                     args=(hedge_target, True),
                                     daemon=True).start()
        if not done.wait(self._request_timeout):
            for c in conns.values():
                with contextlib.suppress(OSError):
                    c.close()
            raise _MemberTransport("request timed out on every leg")
        with lock:
            if "resp" not in box:
                raise _MemberTransport("all dispatch legs died")
            resp, winner, is_hedge = box["resp"]
        # Cancel the loser: closing its connection is the only
        # cancellation the wire offers; the member's own admission/
        # deadline machinery bounds the abandoned work.
        for member_id, c in list(conns.items()):
            if member_id != winner.id:
                with contextlib.suppress(OSError):
                    c.close()
        if hedge_launched:
            loser = target if is_hedge else hedge_target
            obs_spans.record("fleet.hedge", 0.0, layer="fleet",
                             member=loser.id, won=False, outcome="lost")
        if is_hedge:
            obs_spans.record("fleet.hedge", 0.0, layer="fleet",
                             member=winner.id, won=True, outcome="won")
        return resp, winner, is_hedge

    def _graft_member_spans(self, rec: obs_spans.SpanRecorder,
                            resp: Dict[str, Any], member: _Member,
                            attempt: int, t0: float) -> None:
        """Pull the member-shipped span tree off the wire response and
        graft it into the stitched recorder, anchored at the relay
        start (member ``perf_counter`` epochs mean nothing here) and
        stamped with member id + attempt. The rows are *moved* out of
        ``result.meta`` — the client gets the lean response it always
        got; the stitched artifact owns the tree."""
        result = resp.get("result")
        meta = result.get("meta") if isinstance(result, dict) else None
        rows = meta.pop("spans", None) if isinstance(meta, dict) else None
        if rows:
            rec.absorb_dicts(rows, t_base=max(t0 - rec.epoch, 0.0),
                             member=member.id, attempt=attempt)

    def _member_verb(self, member: _Member, method: str,
                     params: Dict[str, Any],
                     conns: Dict[str, socket.socket],
                     abandoned=None) -> Dict[str, Any]:
        """One verb round-trip over the member transport; raises
        :class:`_MemberTransport` once the bounded resend budget
        (``SEMMERGE_FLEET_RESENDS``, jittered exponential backoff
        between tries) is spent. A well-formed ``result`` *or typed*
        ``error`` frame is a final answer and passes through. Resends
        are safe because every fleet request carries an idempotency
        key — a member that already executed the first send replays
        its recorded response instead of executing twice. ``abandoned``
        (the hedge race's ``done``) stops resends once another leg has
        settled the request."""
        last_cause = "connect"
        for resend in range(self._resends + 1):
            if resend:
                if abandoned is not None and abandoned():
                    break  # the race is settled; don't re-dispatch
                transport.count_resend()
                time.sleep(transport.backoff_s(resend - 1))
            try:
                conn = transport.dial(member.address,
                                      timeout=self._connect_timeout)
            except TransportFault as exc:
                last_cause = str(exc.cause or "connect")
                continue
            if conn is None:
                last_cause = "connect"
                continue
            conn.settimeout(self._request_timeout)
            conns[member.id] = conn
            try:
                rfile = conn.makefile("r", encoding="utf-8")
                wfile = conn.makefile("w", encoding="utf-8")
                protocol.write_message(wfile, {"id": 1, "method": method,
                                               "params": params})
                transport.check_read_faults()
                resp = protocol.read_message(rfile)
            except socket.timeout:
                last_cause = "read-timeout"
                continue
            except TransportFault as exc:
                last_cause = str(exc.cause or "transport")
                continue
            except (OSError, ValueError, protocol.ProtocolError) as exc:
                last_cause = type(exc).__name__
                continue
            finally:
                conns.pop(member.id, None)
                with contextlib.suppress(OSError):
                    conn.close()
            if resp is None:
                last_cause = "eof"
                continue
            if "result" in resp:
                return {"result": resp["result"]}
            error = resp.get("error")
            if isinstance(error, dict) and "exit_code" in error:
                return {"error": error}  # typed: the final answer
            last_cause = "malformed"
        raise _MemberTransport(
            f"member {member.id} unreachable ({last_cause})",
            reason="partition" if last_cause == "read-timeout"
            else "transport")

    # ------------------------------------------------------------------
    # replay

    def _replay(self, pending: List[Dict[str, Any]]) -> None:
        """Re-dispatch entries journaled by a previous router
        incarnation but never acked. The client that sent them saw a
        transport failure and is retrying (or gave up); replay makes
        the *effect* durable either way. Idempotency keys make the
        collision of both paths harmless."""
        if not self._await_ring(self._ready_timeout):
            logger.warning("WAL replay: no members came up; %d entries "
                           "stay open", len(pending))
            return
        for rec in pending:
            if self._stop.is_set():
                return
            params = rec.get("params") or {}
            verb = rec.get("verb")
            key = hashring.repo_key(str(params.get("cwd") or "/"))
            idem = rec.get("key")
            try:
                with fault_boundary("fleet:replay"):
                    self._route(verb, dict(params), key, idem)
            except MergeFault as fault:
                logger.warning("WAL replay of %s failed: %s", idem,
                               fault.describe())
                continue
            self._wal.ack(idem)
            self._replayed += 1
            obs_metrics.REGISTRY.counter(
                "fleet_wal_replayed_total", _REPLAY_HELP).inc(1)
            logger.info("WAL replay settled %s (%s)", idem, verb)

    # ------------------------------------------------------------------
    # observability plane: stitched traces + federated telemetry

    def _trace_sealer(self) -> None:
        """Drain the sealing queue: one stitched trace at a time, off
        the response path. A ``None`` sentinel (teardown) stops the
        thread after everything queued ahead of it is sealed."""
        while True:
            item = self._trace_q.get()
            if item is None:
                return
            trace_id, rec, decision = item
            try:
                self._finish_trace(trace_id, rec, decision)
            except Exception:
                logger.exception("trace seal failed for %s", trace_id)

    def _finish_trace(self, trace_id: str, rec: obs_spans.SpanRecorder,
                      decision: Optional[obs_sampling.Decision] = None
                      ) -> None:
        """Seal one stitched trace: persist the artifact through the
        byte-budgeted store when ``SEMMERGE_FLEET_TRACE_DIR`` is set,
        ship it OTLP-ward when an exporter is configured — both only
        for *kept* traces (a dropped verdict frees the disk and the
        collector alike). Best-effort on both paths — a full disk or a
        dead collector must never fail a routed merge."""
        rows = rec.span_dicts()
        if not rows:
            return
        if decision is not None and not decision.keep:
            return
        if self._trace_store is not None:
            artifact = {"schema": 1, "kind": "fleet-trace",
                        "trace_id": trace_id, "router_pid": os.getpid(),
                        "socket": self._socket_path, "spans": rows}
            self._trace_store.write(trace_id, artifact,
                                    decision=decision)
        exporter = obs_export.maybe_exporter()
        if exporter is not None:
            exporter.export_trace(trace_id, rows)

    def _federated_metrics(self) -> str:
        """The fleet's one scrape surface: the router's own registry
        (re-labelled ``member="router"``) concatenated with every
        live member's ``/metrics`` scrape re-labelled by member id,
        plus ``fleet_member_up`` rollups. Scrape failures count in
        ``fleet_scrape_errors_total`` and drop that member's block —
        a wedged member must not wedge the fleet scrape."""
        self._window.publish()
        up = obs_metrics.REGISTRY.gauge(
            "fleet_member_up", "Ring membership by member (1=in ring)")
        draining = obs_metrics.REGISTRY.gauge(
            "fleet_member_draining", _DRAINING_HELP)
        for m in list(self._members):
            # A draining member is alive and deliberate — it must NOT
            # read as a failure in the rollups (fleet_member_up alerts
            # fire on dead members, not on drains).
            state = m.state()
            up.set(1.0 if state in ("ready", "draining") else 0.0,
                   member=m.id)
            draining.set(1.0 if state == "draining" else 0.0,
                         member=m.id)
        parts = [_label_member(
            obs_metrics.REGISTRY.render_prometheus(), "router")]
        for m in list(self._members):
            port = m.metrics_port
            if not port:
                continue
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/metrics")
                with urllib.request.urlopen(req, timeout=2.0) as resp:
                    text = resp.read().decode("utf-8")
            except Exception:
                obs_metrics.REGISTRY.counter(
                    "fleet_scrape_errors_total",
                    "Failed member /metrics scrapes").inc(1, member=m.id)
                continue
            parts.append(_label_member(text, m.id))
        return _dedupe_comments("\n".join(p for p in parts if p)) + "\n"

    # ------------------------------------------------------------------
    # control verbs

    def _next_remote_id(self) -> str:
        # caller holds _ring_lock
        self._remote_seq += 1
        return f"r{self._remote_seq}"

    def _join_verb(self, params: Dict[str, Any]) -> Dict[str, Any]:
        """Admit (or refresh) a remote member: validate the announced
        address with a heartbeat, add it to the ring, and hand moved
        keys off. Idempotent — members re-announce periodically, which
        doubles as rejoin after a healed partition or router restart."""
        address = str(params.get("address") or "").strip()
        if not address:
            return {"ok": False, "error": "join needs an address"}
        try:
            capacity = max(1, int(params.get("capacity") or 1))
        except (TypeError, ValueError):
            capacity = 1
        try:
            epoch = int(params.get("epoch") or 0)
        except (TypeError, ValueError):
            epoch = 0
        want_id = str(params.get("member") or "").strip()
        try:
            transport.heartbeat(address,
                                timeout=self._heartbeat_timeout)
        except TransportFault as exc:
            return {"ok": False,
                    "error": f"join probe failed ({exc.cause}): {exc}"}
        with self._ring_lock:
            member = next((m for m in self._members
                           if m.address == address), None)
            fresh = member is None
            if fresh:
                member_id = want_id or self._next_remote_id()
                if any(m.id == member_id for m in self._members):
                    return {"ok": False,
                            "error": f"member id {member_id!r} taken"}
                member = _Member(member_id, address, sup=None,
                                 capacity=capacity, epoch=epoch)
                self._members.append(member)
            else:
                member.capacity, member.epoch = capacity, epoch
            member.dead = False
            member.draining = False
            member.fail_streak = 0
            member.last_fault = None
        if fresh:
            obs_metrics.REGISTRY.counter("fleet_joins_total",
                                         _JOINS_HELP).inc(1)
            obs_spans.record("fleet.join", 0.0, layer="fleet",
                             member=member.id,
                             address=transport.describe(address),
                             capacity=capacity)
            logger.info("fleet member %s joined from %s (capacity=%d)",
                        member.id, transport.describe(address),
                        capacity)
        self._set_ring(member, True, "join")
        return {"ok": True, "member": member.id, "fresh": fresh,
                "ring": self._ring(), "epoch": self._affinity_epoch}

    def _leave_verb(self, params: Dict[str, Any]) -> Dict[str, Any]:
        """Remove a remote member that announced its departure. Local
        (supervised) members drain instead — the supervisor owns their
        lifecycle."""
        ident = str(params.get("member") or params.get("address")
                    or "").strip()
        with self._ring_lock:
            member = next((m for m in self._members
                           if m.id == ident or m.address == ident),
                          None)
        if member is None:
            return {"ok": False, "error": f"unknown member {ident!r}"}
        if member.sup is not None:
            return {"ok": False,
                    "error": "local members leave via drain/shutdown"}
        member.draining = True
        self._set_ring(member, False, "leave")
        with self._ring_lock:
            self._members = [m for m in self._members
                             if m.id != member.id]
        # The label series must not keep reporting the departed member
        # as up/draining forever.
        obs_metrics.REGISTRY.gauge(
            "fleet_member_up",
            "Ring membership by member (1=in ring)").set(
                0.0, member=member.id)
        obs_metrics.REGISTRY.gauge(
            "fleet_member_draining",
            _DRAINING_HELP).set(0.0, member=member.id)
        logger.info("fleet member %s left (%s)", member.id,
                    transport.describe(member.address))
        return {"ok": True, "member": member.id, "ring": self._ring()}

    def _member_status_block(self, m: _Member) -> Dict[str, Any]:
        """One member's ``member_status`` entry: its own status payload
        (when it answers) merged with the router-side ``state`` —
        ``draining`` is a deliberate departure, ``dead`` a failure; the
        aggregation must not lump them."""
        status = self._member_call(m, "status", {}, timeout=5.0)
        block: Dict[str, Any] = dict(status) \
            if isinstance(status, dict) else {"ok": False}
        block["state"] = m.state()
        block["router_view"] = m.view()
        return block

    def _drain_verb(self, params: Dict[str, Any]) -> Dict[str, Any]:
        member_id = params.get("member")
        if member_id:
            member = self._member_by_id(str(member_id))
            if member is None:
                return {"ok": False,
                        "error": f"unknown member {member_id!r}"}
            # Block health-probe downgrades outright while the drain
            # verb is in flight — any probe that starts before the
            # member acks may still read a pre-drain heartbeat — then
            # stamp the ack time so only genuinely-later probes (the
            # member undraining itself) can return it to the ring.
            member.drain_ts = float("inf")
            member.draining = True
            self._set_ring(member, False, "drain")
            result = self._member_call(member, "drain", {}, timeout=5.0)
            member.drain_ts = time.monotonic()
            return {"ok": True, "member": member.id,
                    "member_ack": result}
        self._draining = True
        self._stop.set()
        return {"ok": True, "draining": True}

    def status(self) -> Dict[str, Any]:
        with self._state_lock:
            in_flight, served = self._in_flight, self._served
        members = list(self._members)
        return {
            "ok": True,
            "fleet": True,
            "pid": os.getpid(),
            "version": protocol.PROTOCOL_VERSION,
            "socket": self._socket_path,
            "uptime_s": round(time.time() - self._t0, 3),
            "draining": self._draining,
            "in_flight": in_flight,
            "served_total": served,
            "members": [m.view() for m in members],
            "members_up": len(self._ring()),
            "members_draining": sum(1 for m in members
                                    if m.state() == "draining"),
            "members_dead": sum(1 for m in members
                                if m.state() == "dead"),
            "affinity_epoch": self._affinity_epoch,
            "transport": {
                "tls": transport.tls_enabled(),
                "connect_timeout_s": self._connect_timeout,
                "heartbeat_timeout_s": self._heartbeat_timeout,
                "resends": self._resends,
                "handoff_max": self._handoff_max,
            },
            "wal": {"dir": self._wal.directory,
                    "open": self._wal.open_count(),
                    "replayed": self._replayed},
            "hedge": {"enabled": self._hedge_on,
                      "delay_ms": round(self._hedge_delay_s() * 1000.0,
                                        3)},
            "stitch": self._stitch,
            "slo": self._slo.status() if self._slo is not None else None,
            "window": self._window.window(),
            "sampling": self._sampler.stats(),
            "trace_store": (self._trace_store.stats()
                            if self._trace_store is not None else None),
            "metrics": obs_metrics.REGISTRY.to_dict(),
        }
